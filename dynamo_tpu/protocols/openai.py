"""OpenAI-compatible API types.

Fills the role of the reference's vendored ``lib/async-openai`` fork plus its
``nvext`` extension (reference: lib/llm/src/protocols/openai/nvext.rs).
Pydantic models with ``extra="allow"`` so unknown client fields pass through
(the reference's BYOT stance).
"""

from __future__ import annotations

import time
import uuid
from typing import Any, Literal

from pydantic import BaseModel, ConfigDict, Field


class NvExt(BaseModel):
    """Framework extension field (reference: nvext.rs) — annotations request
    server-side events like ttft breakdown; use_raw_prompt skips templating."""

    model_config = ConfigDict(extra="allow")
    annotations: list[str] | None = None
    use_raw_prompt: bool | None = None
    greed_sampling: bool | None = None


class ChatMessage(BaseModel):
    model_config = ConfigDict(extra="allow")
    role: str
    content: str | list[dict[str, Any]] | None = None
    name: str | None = None
    tool_calls: list[dict[str, Any]] | None = None
    tool_call_id: str | None = None
    reasoning_content: str | None = None

    def text_content(self) -> str:
        if self.content is None:
            return ""
        if isinstance(self.content, str):
            return self.content
        # multimodal content parts; concatenate text parts
        return "".join(p.get("text", "") for p in self.content if isinstance(p, dict) and p.get("type") == "text")


class ChatCompletionRequest(BaseModel):
    model_config = ConfigDict(extra="allow")
    model: str
    messages: list[ChatMessage]
    temperature: float | None = None
    top_p: float | None = None
    top_k: int | None = None  # extension (vLLM-compatible)
    n: int = 1
    stream: bool = False
    stream_options: dict[str, Any] | None = None
    stop: str | list[str] | None = None
    max_tokens: int | None = None
    max_completion_tokens: int | None = None
    min_tokens: int | None = None
    presence_penalty: float | None = None
    frequency_penalty: float | None = None
    repetition_penalty: float | None = None
    logprobs: bool | None = None
    top_logprobs: int | None = None
    seed: int | None = None
    user: str | None = None
    tools: list[dict[str, Any]] | None = None
    tool_choice: str | dict[str, Any] | None = None
    response_format: dict[str, Any] | None = None
    ignore_eos: bool | None = None
    nvext: NvExt | None = None

    def stop_list(self) -> list[str]:
        if self.stop is None:
            return []
        return [self.stop] if isinstance(self.stop, str) else list(self.stop)

    def effective_max_tokens(self) -> int | None:
        return self.max_completion_tokens or self.max_tokens


class CompletionRequest(BaseModel):
    model_config = ConfigDict(extra="allow")
    model: str
    prompt: str | list[str] | list[int] | list[list[int]]
    suffix: str | None = None
    temperature: float | None = None
    top_p: float | None = None
    top_k: int | None = None
    n: int = 1
    stream: bool = False
    stream_options: dict[str, Any] | None = None
    stop: str | list[str] | None = None
    max_tokens: int | None = None
    min_tokens: int | None = None
    presence_penalty: float | None = None
    frequency_penalty: float | None = None
    repetition_penalty: float | None = None
    logprobs: int | None = None
    echo: bool = False
    seed: int | None = None
    user: str | None = None
    response_format: dict[str, Any] | None = None
    ignore_eos: bool | None = None
    nvext: NvExt | None = None

    def stop_list(self) -> list[str]:
        if self.stop is None:
            return []
        return [self.stop] if isinstance(self.stop, str) else list(self.stop)


class EmbeddingRequest(BaseModel):
    model_config = ConfigDict(extra="allow")
    model: str
    input: str | list[str] | list[int] | list[list[int]]
    encoding_format: Literal["float", "base64"] = "float"
    dimensions: int | None = None
    user: str | None = None


class Usage(BaseModel):
    prompt_tokens: int = 0
    completion_tokens: int = 0
    total_tokens: int = 0


def _gen_id(prefix: str) -> str:
    return f"{prefix}-{uuid.uuid4().hex}"


def now_s() -> int:
    return int(time.time())


class ChatChoiceDelta(BaseModel):
    role: str | None = None
    content: str | None = None
    tool_calls: list[dict[str, Any]] | None = None
    reasoning_content: str | None = None


class ChatChunkChoice(BaseModel):
    index: int = 0
    delta: ChatChoiceDelta
    finish_reason: str | None = None
    logprobs: dict[str, Any] | None = None


class ChatCompletionChunk(BaseModel):
    id: str
    object: Literal["chat.completion.chunk"] = "chat.completion.chunk"
    created: int = Field(default_factory=now_s)
    model: str = ""
    choices: list[ChatChunkChoice] = Field(default_factory=list)
    usage: Usage | None = None


class ChatChoice(BaseModel):
    index: int = 0
    message: ChatMessage
    finish_reason: str | None = None
    logprobs: dict[str, Any] | None = None


class ChatCompletionResponse(BaseModel):
    id: str = Field(default_factory=lambda: _gen_id("chatcmpl"))
    object: Literal["chat.completion"] = "chat.completion"
    created: int = Field(default_factory=now_s)
    model: str = ""
    choices: list[ChatChoice] = Field(default_factory=list)
    usage: Usage = Field(default_factory=Usage)


class CompletionChoice(BaseModel):
    index: int = 0
    text: str = ""
    finish_reason: str | None = None
    logprobs: dict[str, Any] | None = None


class ResponsesRequest(BaseModel):
    """POST /v1/responses, minimal surface (reference route:
    http/service/openai.rs:1165)."""

    model: str = ""
    input: str | list[dict] = ""
    instructions: str | None = None
    max_output_tokens: int | None = None
    temperature: float | None = None
    top_p: float | None = None
    stream: bool = False


class ResponseOutputText(BaseModel):
    type: Literal["output_text"] = "output_text"
    text: str = ""
    annotations: list = Field(default_factory=list)


class ResponseMessage(BaseModel):
    type: Literal["message"] = "message"
    id: str = ""
    role: Literal["assistant"] = "assistant"
    status: str = "completed"
    content: list[ResponseOutputText] = Field(default_factory=list)


class ResponsesUsage(BaseModel):
    input_tokens: int = 0
    output_tokens: int = 0
    total_tokens: int = 0


class ResponsesResponse(BaseModel):
    id: str = Field(default_factory=lambda: _gen_id("resp"))
    object: Literal["response"] = "response"
    created_at: int = Field(default_factory=now_s)
    status: str = "completed"
    model: str = ""
    output: list[ResponseMessage] = Field(default_factory=list)
    usage: ResponsesUsage | None = None


class CompletionResponse(BaseModel):
    id: str = Field(default_factory=lambda: _gen_id("cmpl"))
    object: Literal["text_completion"] = "text_completion"
    created: int = Field(default_factory=now_s)
    model: str = ""
    choices: list[CompletionChoice] = Field(default_factory=list)
    usage: Usage | None = None


class ModelInfo(BaseModel):
    id: str
    object: Literal["model"] = "model"
    created: int = Field(default_factory=now_s)
    owned_by: str = "dynamo_tpu"


class ModelList(BaseModel):
    object: Literal["list"] = "list"
    data: list[ModelInfo] = Field(default_factory=list)


class EmbeddingData(BaseModel):
    object: Literal["embedding"] = "embedding"
    index: int
    # list for encoding_format="float", base64 string of f32 LE bytes else
    embedding: list[float] | str


class EmbeddingResponse(BaseModel):
    object: Literal["list"] = "list"
    data: list[EmbeddingData] = Field(default_factory=list)
    model: str = ""
    usage: Usage = Field(default_factory=Usage)


class ErrorInfo(BaseModel):
    message: str
    type: str = "invalid_request_error"
    code: int | str | None = None


class ErrorResponse(BaseModel):
    error: ErrorInfo
