"""Internal engine-facing protocol types.

Fills the role of the reference's internal protocol layer
(reference: lib/llm/src/protocols/common/llm_backend.rs:1-192):
``PreprocessedRequest`` is what flows from the preprocessor to an engine
(token ids + sampling/stop/output options), ``LLMEngineOutput`` is what an
engine streams back (token deltas), ``BackendOutput`` is post-detokenize.
"""

from __future__ import annotations

import enum
import uuid
from dataclasses import dataclass, field
from typing import Any


class FinishReason(str, enum.Enum):
    STOP = "stop"            # eos or stop-sequence hit
    LENGTH = "length"        # max_tokens reached
    CANCELLED = "cancelled"  # client disconnected / context stopped
    ERROR = "error"

    def __str__(self) -> str:  # serialize as plain string
        return self.value


@dataclass
class StopConditions:
    """Reference: common::StopConditions."""

    max_tokens: int | None = None
    stop: list[str] = field(default_factory=list)           # string stop sequences
    stop_token_ids: list[int] = field(default_factory=list)  # exact token stops
    min_tokens: int | None = None
    ignore_eos: bool = False

    def to_dict(self) -> dict:
        return {
            "max_tokens": self.max_tokens,
            "stop": self.stop,
            "stop_token_ids": self.stop_token_ids,
            "min_tokens": self.min_tokens,
            "ignore_eos": self.ignore_eos,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "StopConditions":
        return cls(**{k: d[k] for k in cls.__dataclass_fields__ if k in d})  # type: ignore[arg-type]


@dataclass
class SamplingOptions:
    """Reference: common::SamplingOptions."""

    temperature: float | None = None
    top_p: float | None = None
    top_k: int | None = None
    frequency_penalty: float | None = None
    presence_penalty: float | None = None
    repetition_penalty: float | None = None
    seed: int | None = None
    logprobs: int | None = None
    n: int = 1
    # Structured output (OpenAI response_format): None = unconstrained,
    # {} = json_object mode, non-empty dict = json_schema subset
    # (engine/guided.py).
    guided_json: dict | None = None

    def to_dict(self) -> dict:
        return {k: getattr(self, k) for k in self.__dataclass_fields__}

    @classmethod
    def from_dict(cls, d: dict) -> "SamplingOptions":
        return cls(**{k: d[k] for k in cls.__dataclass_fields__ if k in d})  # type: ignore[arg-type]


def tensor_to_wire(arr) -> dict:
    """ONE envelope for tensors riding the msgpack data plane
    ({data, shape, dtype} — the nixl_connect tensor-transfer role). Both
    directions live here so encoders, frontends, and engines can never
    drift on the format."""
    import numpy as np

    a = np.ascontiguousarray(arr, np.float32)
    return {"data": a.tobytes(), "shape": list(a.shape), "dtype": "float32"}


def tensor_from_wire(d: dict):
    import numpy as np

    return np.frombuffer(
        d["data"], np.dtype(d.get("dtype", "float32"))
    ).reshape(d["shape"]).astype(np.float32)


@dataclass
class PreprocessedRequest:
    """Tokenized request handed to an engine.

    Reference: lib/llm/src/protocols/common/preprocessor.rs (PreprocessedRequest)
    — token_ids plus resolved sampling/stop options and eos ids from the model
    card; ``request_id`` propagates for tracing; ``kv_transfer_params`` carries
    the disaggregation handshake (reference: vllm kv_transfer_params pattern,
    components/src/dynamo/vllm/handlers.py:236-241).
    """

    token_ids: list[int]
    model: str = ""
    request_id: str = field(default_factory=lambda: uuid.uuid4().hex)
    stop_conditions: StopConditions = field(default_factory=StopConditions)
    sampling_options: SamplingOptions = field(default_factory=SamplingOptions)
    eos_token_ids: list[int] = field(default_factory=list)
    annotations: dict[str, Any] = field(default_factory=dict)
    kv_transfer_params: dict[str, Any] | None = None
    # router hint: precomputed block hashes (filled by KV router when available)
    estimated_prefix_hit_blocks: int = 0
    # Multimodal embedding spans: [{"pos": int, "data": bytes,
    # "shape": [K, H], "dtype": "float32"}] — encoder outputs injected at
    # prompt positions pos..pos+K-1 (their token ids are digest-salted
    # placeholders). msgpack-friendly, so the spans ride the SAME data
    # plane as the request — the nixl_connect tensor-transfer role
    # (reference: lib/bindings/python/src/dynamo/nixl_connect).
    mm_embeddings: list[dict] | None = None

    def to_dict(self) -> dict:
        return {
            "token_ids": self.token_ids,
            "model": self.model,
            "request_id": self.request_id,
            "stop_conditions": self.stop_conditions.to_dict(),
            "sampling_options": self.sampling_options.to_dict(),
            "eos_token_ids": self.eos_token_ids,
            "annotations": self.annotations,
            "kv_transfer_params": self.kv_transfer_params,
            "estimated_prefix_hit_blocks": self.estimated_prefix_hit_blocks,
            "mm_embeddings": self.mm_embeddings,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "PreprocessedRequest":
        return cls(
            token_ids=list(d["token_ids"]),
            model=d.get("model", ""),
            request_id=d.get("request_id") or uuid.uuid4().hex,
            stop_conditions=StopConditions.from_dict(d.get("stop_conditions") or {}),
            sampling_options=SamplingOptions.from_dict(d.get("sampling_options") or {}),
            eos_token_ids=list(d.get("eos_token_ids") or []),
            annotations=dict(d.get("annotations") or {}),
            kv_transfer_params=d.get("kv_transfer_params"),
            estimated_prefix_hit_blocks=d.get("estimated_prefix_hit_blocks", 0),
            mm_embeddings=d.get("mm_embeddings"),
        )


@dataclass
class LLMEngineOutput:
    """One streamed engine delta (a batch of new tokens for one request).

    Reference: common::llm_backend::LLMEngineOutput. Engines emit token deltas
    per step (possibly >1 token for chunked prefill or spec decode); the
    detokenizer backend turns these into text deltas.
    """

    token_ids: list[int] = field(default_factory=list)
    finish_reason: FinishReason | None = None
    cum_log_probs: float | None = None
    log_probs: list[float] | None = None
    # Disagg: prefill response carries transfer params back to decode.
    kv_transfer_params: dict[str, Any] | None = None
    error: str | None = None
    # Tracing: the final delta ships the worker-side closed spans back to
    # the frontend (obs/tracer.py), so one /debug/traces endpoint shows
    # the cross-process timeline. Absent on all intermediate deltas.
    spans: list[dict] | None = None

    def to_dict(self) -> dict:
        d: dict[str, Any] = {"token_ids": self.token_ids}
        if self.finish_reason is not None:
            d["finish_reason"] = str(self.finish_reason)
        if self.cum_log_probs is not None:
            d["cum_log_probs"] = self.cum_log_probs
        if self.log_probs is not None:
            d["log_probs"] = self.log_probs
        if self.kv_transfer_params is not None:
            d["kv_transfer_params"] = self.kv_transfer_params
        if self.error is not None:
            d["error"] = self.error
        if self.spans is not None:
            d["spans"] = self.spans
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "LLMEngineOutput":
        fr = d.get("finish_reason")
        return cls(
            token_ids=list(d.get("token_ids") or []),
            finish_reason=FinishReason(fr) if fr else None,
            cum_log_probs=d.get("cum_log_probs"),
            log_probs=d.get("log_probs"),
            kv_transfer_params=d.get("kv_transfer_params"),
            error=d.get("error"),
            spans=d.get("spans"),
        )


@dataclass
class BackendOutput:
    """Post-detokenization delta: text plus the tokens that produced it."""

    text: str = ""
    token_ids: list[int] = field(default_factory=list)
    finish_reason: FinishReason | None = None
    cum_log_probs: float | None = None
    log_probs: list[float] | None = None  # per token in token_ids
