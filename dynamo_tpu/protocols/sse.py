"""Server-Sent Events codec (reference: lib/llm/src/protocols/codec.rs)."""

from __future__ import annotations

from typing import Any, AsyncIterator

DONE_EVENT = b"data: [DONE]\n\n"


def encode_sse(data: str) -> bytes:
    return f"data: {data}\n\n".encode()


def encode_sse_json(obj: Any) -> bytes:
    # pydantic models expose model_dump_json; fall back to json.dumps
    if hasattr(obj, "model_dump_json"):
        payload = obj.model_dump_json(exclude_none=True)
    else:
        import json

        payload = json.dumps(obj, separators=(",", ":"))
    return encode_sse(payload)


class SseDecoder:
    """Incremental SSE parser (client side — used by tests and the batch input)."""

    def __init__(self) -> None:
        self._buf = b""

    def feed(self, chunk: bytes) -> list[str]:
        self._buf += chunk
        events: list[str] = []
        while b"\n\n" in self._buf:
            raw, self._buf = self._buf.split(b"\n\n", 1)
            data_lines = [ln[5:].strip() for ln in raw.split(b"\n") if ln.startswith(b"data:")]
            if data_lines:
                events.append(b"\n".join(data_lines).decode())
        return events


async def decode_sse_stream(byte_iter: AsyncIterator[bytes]) -> AsyncIterator[str]:
    dec = SseDecoder()
    async for chunk in byte_iter:
        for ev in dec.feed(chunk):
            yield ev
