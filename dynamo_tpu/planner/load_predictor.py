"""Load predictors over a sliding metric window.

Reference: components/src/dynamo/planner/utils/load_predictor.py
(constant / ARIMA / Prophet behind one add_data_point/predict_next
interface). Same interface here; the heavy statistical models are replaced
by closed-form numpy fits, which match the planner's short horizons (one
adjustment interval ahead).
"""

from __future__ import annotations

from collections import deque

import numpy as np


class BasePredictor:
    def __init__(self, window_size: int = 50):
        self.window: deque[float] = deque(maxlen=window_size)

    def add_data_point(self, value: float) -> None:
        if value is not None and not np.isnan(value):
            self.window.append(float(value))

    def predict_next(self) -> float:
        raise NotImplementedError


class ConstantPredictor(BasePredictor):
    """Next value = last value."""

    def predict_next(self) -> float:
        return self.window[-1] if self.window else 0.0


class MovingAveragePredictor(BasePredictor):
    """Next value = mean of the window."""

    def predict_next(self) -> float:
        return float(np.mean(self.window)) if self.window else 0.0


class LinearTrendPredictor(BasePredictor):
    """Least-squares line through the window, evaluated one step ahead.
    Clamped at zero (a downward trend can't predict negative load)."""

    def predict_next(self) -> float:
        n = len(self.window)
        if n == 0:
            return 0.0
        if n < 3:
            return self.window[-1]
        x = np.arange(n, dtype=np.float64)
        slope, intercept = np.polyfit(x, np.asarray(self.window), 1)
        return float(max(slope * n + intercept, 0.0))


LOAD_PREDICTORS = {
    "constant": ConstantPredictor,
    "moving_average": MovingAveragePredictor,
    "linear": LinearTrendPredictor,
}


def make_predictor(kind: str, window_size: int = 50) -> BasePredictor:
    try:
        return LOAD_PREDICTORS[kind](window_size=window_size)
    except KeyError:
        raise ValueError(f"unknown load predictor {kind!r} "
                         f"(have: {sorted(LOAD_PREDICTORS)})") from None
