"""Planner connectors: apply replica decisions to the world.

Reference: components/src/dynamo/planner/ — KubernetesConnector patches
DynamoGraphDeployment replicas; VirtualConnector writes decisions to etcd
for an external orchestrator (virtual_connector.py). Here:

- :class:`VirtualConnector` writes the decision JSON to the coordinator KV
  (``planner/decisions/{namespace}``) with a monotonically increasing
  revision; any orchestrator can watch that prefix.
- :class:`ProcessConnector` applies decisions directly by spawning/stopping
  local worker processes — the no-K8s path used by tests and single-host
  deployments (each "replica" is one ``python -m
  dynamo_tpu.components.worker`` process).
"""

from __future__ import annotations

import asyncio
import json
import signal
import subprocess
import sys
import time

from dynamo_tpu.transports.client import CoordinatorClient
from dynamo_tpu.utils.logging import get_logger

log = get_logger("planner")

DECISIONS_PREFIX = "planner/decisions"


class VirtualConnector:
    def __init__(self, client: CoordinatorClient, namespace: str = "dynamo"):
        self.client = client
        self.namespace = namespace
        self.revision = 0
        self._seeded = False

    @property
    def key(self) -> str:
        return f"{DECISIONS_PREFIX}/{self.namespace}"

    async def _seed_revision(self) -> None:
        """Resume the revision counter from the stored decision so a planner
        restart never regresses it (an orchestrator deduplicating by revision
        would ignore fresh decisions otherwise)."""
        existing = await self.read()
        if existing and isinstance(existing.get("revision"), int):
            self.revision = max(self.revision, existing["revision"])
        self._seeded = True

    async def apply(self, prefill_replicas: int, decode_replicas: int,
                    reason: str = "") -> None:
        if not self._seeded:
            await self._seed_revision()
        self.revision += 1
        await self.client.put(self.key, json.dumps({
            "revision": self.revision,
            "prefill_replicas": prefill_replicas,
            "decode_replicas": decode_replicas,
            "reason": reason,
            "ts": time.time(),
        }).encode())

    async def read(self) -> dict | None:
        value = await self.client.get(self.key)
        return json.loads(value) if value else None


class ProcessConnector:
    """Scale worker fleets by (de)spawning local processes.

    ``prefill_args``/``decode_args`` are full argv tails for
    ``python -m dynamo_tpu.components.worker``; scale-down stops the
    most-recently started replica (SIGTERM → graceful drain)."""

    def __init__(self, prefill_args: list[str] | None, decode_args: list[str]):
        self.prefill_args = prefill_args
        self.decode_args = decode_args
        self.prefill_procs: list[subprocess.Popen] = []
        self.decode_procs: list[subprocess.Popen] = []

    def _spawn(self, args: list[str]) -> subprocess.Popen:
        cmd = [sys.executable, "-u", "-m", "dynamo_tpu.components.worker", *args]
        log.info("spawning worker: %s", " ".join(args))
        return subprocess.Popen(cmd)

    @staticmethod
    def _stop(proc: subprocess.Popen, grace: float = 15.0) -> None:
        if proc.poll() is None:
            proc.send_signal(signal.SIGTERM)
            try:
                proc.wait(grace)
            except subprocess.TimeoutExpired:
                proc.kill()

    def _reap(self, procs: list[subprocess.Popen]) -> None:
        procs[:] = [p for p in procs if p.poll() is None]

    async def apply(self, prefill_replicas: int, decode_replicas: int,
                    reason: str = "") -> None:
        loop = asyncio.get_running_loop()
        await loop.run_in_executor(None, self._apply_sync,
                                   prefill_replicas, decode_replicas)

    def _apply_sync(self, prefill_replicas: int, decode_replicas: int) -> None:
        for procs, args, target in (
            (self.prefill_procs, self.prefill_args, prefill_replicas),
            (self.decode_procs, self.decode_args, decode_replicas),
        ):
            if args is None:
                continue
            self._reap(procs)
            while len(procs) < target:
                procs.append(self._spawn(args))
            while len(procs) > target:
                self._stop(procs.pop())

    def shutdown(self) -> None:
        for procs in (self.prefill_procs, self.decode_procs):
            while procs:
                self._stop(procs.pop())
