"""Planner connectors: apply replica decisions to the world.

Reference: components/src/dynamo/planner/ — KubernetesConnector patches
DynamoGraphDeployment replicas; VirtualConnector writes decisions to etcd
for an external orchestrator (virtual_connector.py). Here:

- :class:`VirtualConnector` writes the decision JSON to the coordinator KV
  (``planner/decisions/{namespace}``) with a monotonically increasing
  revision; any orchestrator can watch that prefix.
- :class:`ProcessConnector` applies decisions directly by spawning/stopping
  local worker processes — the no-K8s path used by tests and single-host
  deployments (each "replica" is one ``python -m
  dynamo_tpu.components.worker`` process).
"""

from __future__ import annotations

import asyncio
import json
import re
import signal
import subprocess
import sys
import threading
import time
from dataclasses import dataclass, field

from dynamo_tpu.runtime.drain import DrainRequest, drain_key
from dynamo_tpu.transports.client import CoordinatorClient
from dynamo_tpu.utils.logging import get_logger
from dynamo_tpu.utils.metrics import MetricsRegistry

log = get_logger("planner")

DECISIONS_PREFIX = "planner/decisions"


class ConnectorMetrics:
    """The dynamo_connector_* family (names cross-checked by
    tools/lint_metrics.py CONNECTOR_METRICS)."""

    def __init__(self, registry: MetricsRegistry | None = None):
        self.bind(registry or MetricsRegistry())

    def bind(self, registry: MetricsRegistry) -> None:
        self.registry = registry
        self.replicas_spawned = registry.counter(
            "connector_replicas_spawned",
            "Worker processes the planner connector started")
        self.replicas_retired = registry.counter(
            "connector_replicas_retired",
            "Worker processes the planner connector retired (drained "
            "or force-stopped)")
        self.sigkill_escalations = registry.counter(
            "connector_sigkill_escalations",
            "Retirements that escalated to SIGKILL after the drain AND "
            "the abort signal both timed out (last resort)")
        self.drain_seconds = registry.histogram(
            "connector_drain_seconds",
            "Seconds from drain initiation to worker process exit",
            buckets=(0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0))


_metrics: ConnectorMetrics | None = None


def get_connector_metrics() -> ConnectorMetrics:
    global _metrics
    if _metrics is None:
        _metrics = ConnectorMetrics()
    return _metrics


def install_connector_metrics(registry: MetricsRegistry) -> ConnectorMetrics:
    """Re-home the singleton into a runtime registry (planner /metrics)."""
    m = get_connector_metrics()
    m.bind(registry)
    return m


class VirtualConnector:
    def __init__(self, client: CoordinatorClient, namespace: str = "dynamo"):
        self.client = client
        self.namespace = namespace
        self.revision = 0
        self._seeded = False

    @property
    def key(self) -> str:
        return f"{DECISIONS_PREFIX}/{self.namespace}"

    async def _seed_revision(self) -> None:
        """Resume the revision counter from the stored decision so a planner
        restart never regresses it (an orchestrator deduplicating by revision
        would ignore fresh decisions otherwise)."""
        existing = await self.read()
        if existing and isinstance(existing.get("revision"), int):
            self.revision = max(self.revision, existing["revision"])
        self._seeded = True

    async def apply(self, prefill_replicas: int, decode_replicas: int,
                    reason: str = "") -> None:
        if not self._seeded:
            await self._seed_revision()
        self.revision += 1
        await self.client.put(self.key, json.dumps({
            "revision": self.revision,
            "prefill_replicas": prefill_replicas,
            "decode_replicas": decode_replicas,
            "reason": reason,
            "ts": time.time(),
        }).encode())

    async def read(self) -> dict | None:
        value = await self.client.get(self.key)
        return json.loads(value) if value else None


_READY_RE = re.compile(r"WORKER_READY instance=([0-9a-f]{16})")


@dataclass
class Replica:
    """One worker process the connector owns. The stdout reader thread
    tees the child's lines through (so harnesses can still wait on
    WORKER_READY/WORKER_DRAINED) while capturing the instance id the
    drain handshake needs."""

    proc: subprocess.Popen
    instance_id: int | None = None
    _reader: threading.Thread | None = field(default=None, repr=False)

    def start_reader(self) -> None:
        if self.proc.stdout is None:
            return

        def pump() -> None:
            for line in self.proc.stdout:
                m = _READY_RE.search(line)
                if m:
                    self.instance_id = int(m.group(1), 16)
                sys.stdout.write(line)
                sys.stdout.flush()

        self._reader = threading.Thread(target=pump, daemon=True)
        self._reader.start()

    def alive(self) -> bool:
        return self.proc.poll() is None


class ProcessConnector:
    """Scale worker fleets by (de)spawning local processes.

    ``prefill_args``/``decode_args`` are full argv tails for
    ``python -m dynamo_tpu.components.worker``; scale-down retires the
    most-recently started replicas, CONCURRENTLY (a 4→1 decision costs
    one drain window, not three).

    Retirement ladder (runtime/drain.py protocol on the worker side):

    1. **initiate** — write the coordinator drain key (carries the
       decision's reason + this connector's deadline) when a client and
       the replica's instance id are known; otherwise SIGTERM. Both start
       the same graceful drain.
    2. **abort** — past ``drain_deadline`` + margin, send SIGTERM: the
       worker treats a signal during an active drain as "abort" (skip
       waiting + evacuation, bounded fast exit).
    3. **SIGKILL** — logged last resort, counted in
       ``dynamo_connector_sigkill_escalations_total``.
    """

    def __init__(self, prefill_args: list[str] | None, decode_args: list[str],
                 client: CoordinatorClient | None = None,
                 namespace: str = "dynamo", drain_deadline: float = 30.0,
                 abort_grace: float = 5.0):
        self.prefill_args = prefill_args
        self.decode_args = decode_args
        self.client = client
        self.namespace = namespace
        self.drain_deadline = drain_deadline
        self.abort_grace = abort_grace
        self.prefill_procs: list[Replica] = []
        self.decode_procs: list[Replica] = []

    def _spawn(self, args: list[str]) -> Replica:
        cmd = [sys.executable, "-u", "-m", "dynamo_tpu.components.worker", *args]
        log.info("spawning worker: %s", " ".join(args))
        rep = Replica(subprocess.Popen(
            cmd, stdout=subprocess.PIPE, text=True, bufsize=1))
        rep.start_reader()
        get_connector_metrics().replicas_spawned.inc()
        return rep

    async def _wait(self, rep: Replica, timeout: float) -> bool:
        """Await process exit off-loop; True when it exited in time."""
        loop = asyncio.get_running_loop()
        try:
            await loop.run_in_executor(None, rep.proc.wait, timeout)
            return True
        except subprocess.TimeoutExpired:
            return False

    async def _retire(self, rep: Replica, reason: str) -> None:
        """Drain one replica to exit (see the class-level ladder)."""
        m = get_connector_metrics()
        t0 = time.monotonic()
        if not rep.alive():
            m.replicas_retired.inc()
            return
        initiated = False
        if self.client is not None and rep.instance_id is not None:
            # Planner-initiated handshake: the worker's drain-key watcher
            # picks this up within its poll interval. Do NOT also signal —
            # a signal landing after the key would read as "abort".
            try:
                req = DrainRequest(reason=reason,
                                   deadline_s=self.drain_deadline,
                                   ts=time.time())
                await asyncio.wait_for(self.client.put(
                    drain_key(self.namespace, rep.instance_id),
                    req.to_bytes()), 3.0)
                initiated = True
            except Exception:
                log.warning("drain key write failed; falling back to SIGTERM")
        if not initiated:
            try:
                rep.proc.send_signal(signal.SIGTERM)
            except ProcessLookupError:
                pass
        if not await self._wait(rep, self.drain_deadline + 10.0):
            log.warning("replica pid=%d ignored the drain window; sending "
                        "the abort signal", rep.proc.pid)
            try:
                rep.proc.send_signal(signal.SIGTERM)
            except ProcessLookupError:
                pass
            if not await self._wait(rep, self.abort_grace):
                log.error("replica pid=%d survived drain AND abort; "
                          "SIGKILL as last resort", rep.proc.pid)
                m.sigkill_escalations.inc()
                rep.proc.kill()
                await self._wait(rep, 5.0)
        m.replicas_retired.inc()
        m.drain_seconds.observe(time.monotonic() - t0)

    def _reap(self, procs: list[Replica]) -> int:
        """Drop exited replicas (crashes); returns how many were reaped."""
        dead = [r for r in procs if not r.alive()]
        for r in dead:
            log.warning("replica pid=%d exited on its own (rc=%s); reaping",
                        r.proc.pid, r.proc.returncode)
        procs[:] = [r for r in procs if r.alive()]
        return len(dead)

    async def apply(self, prefill_replicas: int, decode_replicas: int,
                    reason: str = "") -> None:
        retiring: list = []
        for procs, args, target in (
            (self.prefill_procs, self.prefill_args, prefill_replicas),
            (self.decode_procs, self.decode_args, decode_replicas),
        ):
            if args is None:
                continue
            self._reap(procs)
            while len(procs) < target:
                procs.append(self._spawn(args))
            while len(procs) > target:
                retiring.append(self._retire(procs.pop(), reason))
        if retiring:
            await asyncio.gather(*retiring)

    async def shutdown(self, reason: str = "planner shutdown") -> None:
        retiring = []
        for procs in (self.prefill_procs, self.decode_procs):
            while procs:
                retiring.append(self._retire(procs.pop(), reason))
        if retiring:
            await asyncio.gather(*retiring)
