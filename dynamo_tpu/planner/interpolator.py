"""Performance interpolators over profiled sweep data.

Reference: components/src/dynamo/planner/utils/perf_interpolation.py —
PrefillInterpolator (TTFT + throughput/gpu vs ISL, quadratic fit over npz
sweep data) and DecodeInterpolator (ITL + throughput/gpu over a
(concurrency, context_length) grid). Same math, TPU units: throughput is
tokens/s *per chip* and a "replica" is one engine instance spanning
``chips_per_replica`` chips (its TP×EP mesh), so replica math divides by
the mesh size exactly like the reference divides by engine_num_gpu.

Data comes from a dict/npz of 1-D sweep arrays (the profiler writes the
same keys) — no fixed file format dependency.
"""

from __future__ import annotations

import numpy as np


class PrefillInterpolator:
    """Fit TTFT(isl) and prefill throughput/chip(isl) from sweep samples.

    Quadratic in log-space would over-fit the handful of sweep points the
    profiler produces; piecewise-linear interpolation with edge clamping
    (np.interp semantics) is monotone and safe to extrapolate flat.
    """

    def __init__(self, isl: np.ndarray, ttft_s: np.ndarray, thpt_per_chip: np.ndarray):
        order = np.argsort(isl)
        self.isl = np.asarray(isl, np.float64)[order]
        self.ttft_s = np.asarray(ttft_s, np.float64)[order]
        self.thpt = np.asarray(thpt_per_chip, np.float64)[order]
        if len(self.isl) == 0:
            raise ValueError("empty prefill sweep")

    @classmethod
    def from_data(cls, data: dict) -> "PrefillInterpolator":
        return cls(data["prefill_isl"], data["prefill_ttft_s"],
                   data["prefill_thpt_per_chip"])

    def interpolate_ttft(self, isl: float) -> float:
        return float(np.interp(isl, self.isl, self.ttft_s))

    def interpolate_thpt_per_chip(self, isl: float) -> float:
        return float(np.interp(isl, self.isl, self.thpt))


class DecodeInterpolator:
    """ITL and decode throughput/chip over a (concurrency, context) grid."""

    def __init__(self, concurrency: np.ndarray, context: np.ndarray,
                 itl_s: np.ndarray, thpt_per_chip: np.ndarray):
        # grids: itl_s[i, j] for concurrency[i] × context[j]
        self.concurrency = np.asarray(concurrency, np.float64)
        self.context = np.asarray(context, np.float64)
        self.itl_s = np.asarray(itl_s, np.float64)
        self.thpt = np.asarray(thpt_per_chip, np.float64)
        assert self.itl_s.shape == (len(self.concurrency), len(self.context))
        assert self.thpt.shape == self.itl_s.shape

    @classmethod
    def from_data(cls, data: dict) -> "DecodeInterpolator":
        return cls(data["decode_concurrency"], data["decode_context"],
                   data["decode_itl_s"], data["decode_thpt_per_chip"])

    def _interp_context(self, grid: np.ndarray, context: float) -> np.ndarray:
        """Interpolate each concurrency row at the given context length."""
        return np.array([np.interp(context, self.context, row) for row in grid])

    def interpolate_itl(self, concurrency: float, context: float) -> float:
        col = self._interp_context(self.itl_s, context)
        return float(np.interp(concurrency, self.concurrency, col))

    def interpolate_thpt_per_chip(self, concurrency: float, context: float) -> float:
        col = self._interp_context(self.thpt, context)
        return float(np.interp(concurrency, self.concurrency, col))

    def find_best_throughput_per_chip(self, itl_s: float, context: float) -> tuple[float, float]:
        """Highest throughput/chip whose ITL stays within the SLA at this
        context length → (throughput_per_chip, concurrency). Falls back to
        the lowest-concurrency point if even that misses the SLA
        (reference: find_best_throughput_per_gpu)."""
        itl_col = self._interp_context(self.itl_s, context)
        thpt_col = self._interp_context(self.thpt, context)
        ok = itl_col <= itl_s
        if not ok.any():
            i = int(np.argmin(itl_col))
            return float(thpt_col[i]), float(self.concurrency[i])
        i = int(np.argmax(np.where(ok, thpt_col, -np.inf)))
        return float(thpt_col[i]), float(self.concurrency[i])


def synthetic_profile(
    base_ttft_s: float = 0.1,
    prefill_rate_tokps: float = 8000.0,
    base_itl_s: float = 0.01,
    chips_per_replica: int = 1,
) -> dict:
    """An analytic profile for tests/dryruns: linear TTFT in ISL, ITL that
    degrades with concurrency and context. Stands in for a real sweep until
    the profiler has run on hardware."""
    isl = np.array([128, 512, 2048, 8192], np.float64)
    conc = np.array([1, 4, 16, 64], np.float64)
    ctx = np.array([256, 1024, 4096, 16384], np.float64)
    itl = base_itl_s * (1 + 0.02 * conc[:, None]) * (1 + ctx[None, :] / 32768)
    # tokens/s/chip for decode: concurrency / itl, per chip
    thpt = (conc[:, None] / itl) / chips_per_replica
    return {
        "prefill_isl": isl,
        "prefill_ttft_s": base_ttft_s + isl / prefill_rate_tokps,
        "prefill_thpt_per_chip": np.full_like(isl, prefill_rate_tokps / chips_per_replica),
        "decode_concurrency": conc,
        "decode_context": ctx,
        "decode_itl_s": itl,
        "decode_thpt_per_chip": thpt,
    }
