"""Prometheus scraping for the planner.

Reference: components/src/dynamo/planner/utils/prometheus.py — the planner
observes the frontend's Prometheus metrics. Here we scrape a ``/metrics``
endpoint directly (no Prometheus server in the loop) and diff counters
across intervals to recover per-interval rates.

Two sources:

* ``FrontendScraper`` — one frontend's own exposition.
* ``AggregatorScraper`` — the fleet aggregator's re-exposition
  (``dynamo_tpu/obs/fleet.py``): the same families, but rolled up across
  every discovered frontend under ``instance="_fleet"`` labels, plus the
  aggregator's SLO gauges, so ``Planner.plan()`` sees fleet-wide rates and
  its decisions can carry the SLO snapshot that justified them.
"""

from __future__ import annotations

import aiohttp

from dynamo_tpu.planner.planner_core import Metrics
from dynamo_tpu.utils.logging import get_logger
from dynamo_tpu.utils.metrics import (  # shared parser — inverts expose()
    Sample,
    metrics_url,
    parse_prometheus,
)

__all__ = ["Sample", "parse_prometheus", "FrontendScraper",
           "AggregatorScraper", "FLEET_INSTANCE"]

log = get_logger("planner")

# Label value the aggregator uses for fleet rollup series (obs/fleet.py):
# per-target series carry instance="host:port"; the cross-instance sums
# carry instance=FLEET_INSTANCE so the two never double-count.
FLEET_INSTANCE = "_fleet"


def _sum_for(sample: Sample, name: str, model: str | None = None,
             **where: str) -> float:
    want = set(where.items())
    total = 0.0
    for (n, labels), v in sample.items():
        if n != name:
            continue
        if model is not None and ("model", model) not in labels:
            continue
        if not want <= set(labels):
            continue
        total += v
    return total


class FrontendScraper:
    """Diffs the frontend's counters into per-interval Metrics."""

    # Extra label constraints applied to every sum (subclasses narrow this).
    _where: dict[str, str] = {}

    def __init__(self, metrics_url_: str, model: str | None = None):
        self.url = metrics_url_
        self.model = model
        self._prev: Sample | None = None
        self.last_sample: Sample | None = None  # most recent full scrape

    async def fetch(self) -> Sample:
        async with aiohttp.ClientSession() as s:
            async with s.get(self.url, timeout=aiohttp.ClientTimeout(total=10)) as resp:
                resp.raise_for_status()
                return parse_prometheus(await resp.text())

    def _delta(self, cur: Sample, name: str) -> float:
        now = _sum_for(cur, name, self.model, **self._where)
        before = (_sum_for(self._prev, name, self.model, **self._where)
                  if self._prev else 0.0)
        return max(now - before, 0.0)  # counter reset → treat as fresh

    async def observe_interval(self) -> Metrics:
        cur = await self.fetch()
        self.last_sample = cur
        if self._prev is None:
            # First scrape: only establish the baseline. Diffing against zero
            # would report all-time cumulative totals as one interval's load
            # (an attach to a long-running frontend could spuriously scale to
            # max_replicas and pollute the predictor window).
            self._prev = cur
            return Metrics()  # all-default: num_req=0 → planner skips it
        n_req = self._delta(cur, "dynamo_frontend_model_requests_total")
        in_tok = self._delta(cur, "dynamo_frontend_input_tokens_total")
        out_tok = self._delta(cur, "dynamo_frontend_output_tokens_total")
        ttft_sum = self._delta(cur, "dynamo_frontend_time_to_first_token_seconds_sum")
        ttft_cnt = self._delta(cur, "dynamo_frontend_time_to_first_token_seconds_count")
        itl_sum = self._delta(cur, "dynamo_frontend_inter_token_latency_seconds_sum")
        itl_cnt = self._delta(cur, "dynamo_frontend_inter_token_latency_seconds_count")
        self._prev = cur
        return Metrics(
            num_req=n_req,
            isl=in_tok / n_req if n_req else 0.0,
            osl=out_tok / n_req if n_req else 0.0,
            ttft_s=ttft_sum / ttft_cnt if ttft_cnt else None,
            itl_s=itl_sum / itl_cnt if itl_cnt else None,
        )


class AggregatorScraper(FrontendScraper):
    """Fleet-wide rates from the aggregator's rollup series.

    The aggregator re-serves every discovered target's families with
    ``instance`` labels and adds cross-instance rollups under
    ``instance="_fleet"``; restricting sums to the rollup keeps the math
    identical to FrontendScraper while covering every frontend at once."""

    _where = {"instance": FLEET_INSTANCE}

    def __init__(self, fleet_url: str, model: str | None = None):
        super().__init__(metrics_url(fleet_url), model)

    def slo_snapshot(self) -> dict[str, dict[str, float]]:
        """SLO state from the last scrape's gauges, keyed by SLO name:
        ``{"ttft_p95": {"budget_remaining": 0.82, "burn_rate_5m": 0.4,
        "burn_rate_1h": 0.2, ...}}``. Empty until observe_interval ran."""
        snap: dict[str, dict[str, float]] = {}
        for (name, labels), v in (self.last_sample or {}).items():
            d = dict(labels)
            slo = d.get("slo")
            if not slo:
                continue
            if name == "dynamo_slo_error_budget_remaining":
                snap.setdefault(slo, {})["budget_remaining"] = v
            elif name == "dynamo_slo_burn_rate" and "window" in d:
                snap.setdefault(slo, {})[f"burn_rate_{d['window']}"] = v
        return snap

    def slo_reason(self) -> str:
        """Compact one-line SLO snapshot for Decision.reason / connector
        apply(reason=...): ``slo[ttft_p95 budget=0.82 burn5m=0.40; ...]``."""
        snap = self.slo_snapshot()
        parts = []
        for slo in sorted(snap):
            d = snap[slo]
            frag = f"{slo} budget={d.get('budget_remaining', 1.0):.2f}"
            for w in ("5m", "1h", "6h"):
                if f"burn_rate_{w}" in d:
                    frag += f" burn{w}={d[f'burn_rate_{w}']:.2f}"
            parts.append(frag)
        return f"slo[{'; '.join(parts)}]" if parts else ""

    def mem_reason(self) -> str:
        """Compact capacity-forecast stamp for Decision.reason:
        ``mem[ttx=42s posture=tight]``. Reads the mem-ledger gauges
        (obs/mem_ledger.py) from the last scrape — the worst (minimum)
        TTX and worst (maximum) posture across per-instance series. The
        ``_fleet`` rollup rows are skipped: summing gauges across workers
        would fabricate a TTX no worker reports. Empty when no worker
        exposes the family (ledger disabled fleet-wide)."""
        from dynamo_tpu.obs.mem_ledger import POSTURES, TTX_CAP_S

        min_ttx: float | None = None
        max_posture = 0
        for (name, labels), v in (self.last_sample or {}).items():
            if dict(labels).get("instance") == FLEET_INSTANCE:
                continue
            if name == "dynamo_mem_ttx_seconds":
                min_ttx = v if min_ttx is None else min(min_ttx, v)
            elif name == "dynamo_mem_capacity_posture":
                max_posture = max(max_posture, int(v))
        if min_ttx is None:
            return ""
        posture = POSTURES[min(max_posture, len(POSTURES) - 1)]
        ttx = ("inf" if min_ttx >= TTX_CAP_S
               else f"{min_ttx:.0f}s")
        return f"mem[ttx={ttx} posture={posture}]"
