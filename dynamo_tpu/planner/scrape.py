"""Prometheus scraping for the planner.

Reference: components/src/dynamo/planner/utils/prometheus.py — the planner
observes the frontend's Prometheus metrics. Here we scrape the frontend's
``/metrics`` endpoint directly (no Prometheus server in the loop) and diff
counters across intervals to recover per-interval rates.
"""

from __future__ import annotations

import aiohttp

from dynamo_tpu.planner.planner_core import Metrics
from dynamo_tpu.utils.logging import get_logger

log = get_logger("planner")

Sample = dict[tuple[str, frozenset], float]


def parse_prometheus(text: str) -> Sample:
    """Minimal Prometheus text parser: name{labels} value."""
    out: Sample = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        head, _, value = line.rpartition(" ")
        name, labels = head, {}
        if "{" in head:
            name, _, rest = head.partition("{")
            for pair in rest.rstrip("}").split(","):
                if "=" in pair:
                    k, _, v = pair.partition("=")
                    labels[k.strip()] = v.strip().strip('"')
        try:
            out[(name, frozenset(labels.items()))] = float(value)
        except ValueError:
            continue
    return out


def _sum_for(sample: Sample, name: str, model: str | None = None) -> float:
    total = 0.0
    for (n, labels), v in sample.items():
        if n != name:
            continue
        if model is not None and ("model", model) not in labels:
            continue
        total += v
    return total


class FrontendScraper:
    """Diffs the frontend's counters into per-interval Metrics."""

    def __init__(self, metrics_url: str, model: str | None = None):
        self.url = metrics_url
        self.model = model
        self._prev: Sample | None = None

    async def fetch(self) -> Sample:
        async with aiohttp.ClientSession() as s:
            async with s.get(self.url, timeout=aiohttp.ClientTimeout(total=10)) as resp:
                resp.raise_for_status()
                return parse_prometheus(await resp.text())

    def _delta(self, cur: Sample, name: str) -> float:
        now = _sum_for(cur, name, self.model)
        before = _sum_for(self._prev, name, self.model) if self._prev else 0.0
        return max(now - before, 0.0)  # counter reset → treat as fresh

    async def observe_interval(self) -> Metrics:
        cur = await self.fetch()
        if self._prev is None:
            # First scrape: only establish the baseline. Diffing against zero
            # would report all-time cumulative totals as one interval's load
            # (an attach to a long-running frontend could spuriously scale to
            # max_replicas and pollute the predictor window).
            self._prev = cur
            return Metrics()  # all-default: num_req=0 → planner skips it
        n_req = self._delta(cur, "dynamo_frontend_model_requests_total")
        in_tok = self._delta(cur, "dynamo_frontend_input_tokens_total")
        out_tok = self._delta(cur, "dynamo_frontend_output_tokens_total")
        ttft_sum = self._delta(cur, "dynamo_frontend_time_to_first_token_seconds_sum")
        ttft_cnt = self._delta(cur, "dynamo_frontend_time_to_first_token_seconds_count")
        itl_sum = self._delta(cur, "dynamo_frontend_inter_token_latency_seconds_sum")
        itl_cnt = self._delta(cur, "dynamo_frontend_inter_token_latency_seconds_count")
        self._prev = cur
        return Metrics(
            num_req=n_req,
            isl=in_tok / n_req if n_req else 0.0,
            osl=out_tok / n_req if n_req else 0.0,
            ttft_s=ttft_sum / ttft_cnt if ttft_cnt else None,
            itl_s=itl_sum / itl_cnt if itl_cnt else None,
        )
