"""SLA planner: observe load → predict → size the worker fleet.

Fills the role of the reference's planner component
(reference: components/src/dynamo/planner/ — planner_core.py decision loop,
utils/load_predictor.py predictors, utils/perf_interpolation.py
interpolators, kubernetes/virtual connectors):

- :mod:`load_predictor` — constant / moving-average / linear-trend
  predictors over the recent metric window (the reference's ARIMA/Prophet
  fill the same role; those libraries aren't in the image, and a linear
  trend covers the interpolation-scale horizons the planner uses).
- :mod:`interpolator` — TTFT/throughput-per-chip vs ISL (prefill) and
  ITL/throughput-per-chip vs (concurrency, context) (decode), fitted from
  profiled sweep data; on TPU the sweep axes are mesh shapes (TP×chips)
  instead of GPU counts.
- :mod:`planner_core` — replica calculation with SLA targets + correction
  factors for queueing (observed TTFT/ITL vs interpolated).
- :mod:`connector` — VirtualConnector (decisions → coordinator KV for an
  external orchestrator) and ProcessConnector (spawns/stops local worker
  processes — the zero-K8s analog of patching DynamoGraphDeployment
  replicas).
"""

from dynamo_tpu.planner.connector import ProcessConnector, VirtualConnector
from dynamo_tpu.planner.interpolator import DecodeInterpolator, PrefillInterpolator
from dynamo_tpu.planner.load_predictor import LOAD_PREDICTORS, make_predictor
from dynamo_tpu.planner.planner_core import Metrics, Planner, PlannerConfig

__all__ = [
    "DecodeInterpolator", "LOAD_PREDICTORS", "Metrics", "Planner",
    "PlannerConfig", "PrefillInterpolator", "ProcessConnector",
    "VirtualConnector", "make_predictor",
]
