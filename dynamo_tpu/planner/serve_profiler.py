"""SLA profiling THROUGH the serving stack (frontend + runtime included).

Fills the role of the reference's pre-deployment profiler driving a live
deployment (reference: benchmarks/profiler/profile_sla.py:71-393 — sweeps
run against the HTTP endpoint of a launched topology, not an in-process
engine). The in-process :class:`planner.profiler.SlaProfiler` isolates
engine capability; THIS profiler measures what a client actually sees —
preprocessing, routing, wire framing, SSE — so planner interpolations
built from it include every overhead between user and chip.

One topology is launched (benchmarks/serve_bench.launch_topology: agg |
distributed | disagg), then the operating-point grid sweeps over it with
the HTTP load generator:

- prefill points: concurrency 1, ``osl=1`` → TTFT(isl)
- decode points: concurrency × context grid → ITL and tok/s/chip

Output: the SAME npz schema as the in-process profiler
(prefill_isl/prefill_ttft_s/... — planner/interpolator.py consumes both
interchangeably), plus ``source='serve'`` metadata.
"""

from __future__ import annotations

import argparse
import asyncio
import sys
from pathlib import Path

import numpy as np

from dynamo_tpu.utils.logging import configure_logging, get_logger

log = get_logger("serve_profiler")

REPO_ROOT = Path(__file__).resolve().parent.parent.parent


def _bench_modules():
    """benchmarks/ lives at the repo root, not inside the package."""
    if str(REPO_ROOT) not in sys.path:
        sys.path.insert(0, str(REPO_ROOT))
    from benchmarks.loadgen import run_load
    from benchmarks.serve_bench import launch_topology, wait_http

    return run_load, launch_topology, wait_http


def profile_serving(ns: argparse.Namespace) -> dict:
    """Launch the topology once, sweep the grids over its HTTP endpoint."""
    run_load, launch_topology, wait_http = _bench_modules()
    from benchmarks.serve_bench import base_env

    env = base_env(ns.platform)
    procs, base_url, chips = launch_topology(ns, env)
    try:
        wait_http(base_url + "/v1/models", ns.start_timeout)

        ttft = np.zeros(len(ns.isl_grid))
        p_thpt = np.zeros_like(ttft)
        for i, isl in enumerate(ns.isl_grid):
            load = asyncio.run(run_load(
                base_url, ns.model, concurrency=1,
                num_requests=ns.prefill_requests, isl=isl, osl=1,
                warmup=ns.warmup))
            if load["failed"]:
                raise RuntimeError(
                    f"prefill point isl={isl} had failures: {load['errors']}")
            ttft[i] = load["ttft_avg_s"]
            p_thpt[i] = isl / ttft[i] / chips if ttft[i] > 0 else 0.0
            log.info("serve prefill isl=%d ttft=%.4fs", isl, ttft[i])

        itl = np.zeros((len(ns.conc_grid), len(ns.ctx_grid)))
        d_thpt = np.zeros_like(itl)
        for i, conc in enumerate(ns.conc_grid):
            for j, ctx in enumerate(ns.ctx_grid):
                load = asyncio.run(run_load(
                    base_url, ns.model, concurrency=conc,
                    num_requests=max(ns.decode_requests, 2 * conc),
                    isl=ctx, osl=ns.decode_steps, warmup=ns.warmup))
                if load["failed"]:
                    raise RuntimeError(f"decode point conc={conc} ctx={ctx} "
                                       f"had failures: {load['errors']}")
                itl[i, j] = load["itl_p50_s"]
                d_thpt[i, j] = load["output_tok_s"] / chips
                log.info("serve decode conc=%d ctx=%d itl=%.4fs thpt/chip=%.1f",
                         conc, ctx, itl[i, j], d_thpt[i, j])
    finally:
        for p in reversed(procs):
            p.stop()

    return {
        "prefill_isl": np.asarray(ns.isl_grid, np.float64),
        "prefill_ttft_s": ttft,
        "prefill_thpt_per_chip": p_thpt,
        "decode_concurrency": np.asarray(ns.conc_grid, np.float64),
        "decode_context": np.asarray(ns.ctx_grid, np.float64),
        "decode_itl_s": itl,
        "decode_thpt_per_chip": d_thpt,
        "source": np.asarray("serve"),
        "topology": np.asarray(ns.topology),
        "chips": np.asarray(chips, np.float64),
    }


def main(argv: list[str] | None = None) -> None:
    p = argparse.ArgumentParser("serve-profiler", description=__doc__)
    # topology knobs (shared with serve_bench.launch_topology)
    p.add_argument("--topology", choices=["agg", "distributed", "disagg"],
                   default="agg")
    p.add_argument("--platform", choices=["ambient", "cpu"], default="ambient")
    p.add_argument("--model", default="llama-3-8b-lite")
    p.add_argument("--workers", type=int, default=2)
    p.add_argument("--block-size", type=int, default=16)
    p.add_argument("--num-blocks", type=int, default=0)
    p.add_argument("--max-batch-size", type=int, default=32)
    p.add_argument("--max-model-len", type=int, default=4096)
    p.add_argument("--start-timeout", type=float, default=600.0)
    # sweep grids (mirror the in-process profiler CLI)
    p.add_argument("--isl-grid", type=int, nargs="+", default=[128, 512, 2048])
    p.add_argument("--conc-grid", type=int, nargs="+", default=[1, 8, 32])
    p.add_argument("--ctx-grid", type=int, nargs="+", default=[256, 1024])
    p.add_argument("--decode-steps", type=int, default=32)
    p.add_argument("--prefill-requests", type=int, default=4)
    p.add_argument("--decode-requests", type=int, default=8)
    p.add_argument("--warmup", type=int, default=1)
    p.add_argument("--output", default="serve_profile.npz")
    ns = p.parse_args(argv)

    configure_logging()
    data = profile_serving(ns)
    np.savez(ns.output, **data)
    print(f"serve profile written to {ns.output}")


if __name__ == "__main__":
    main()
