"""Planner decision loop: metrics → prediction → replica targets.

Reference: components/src/dynamo/planner/utils/planner_core.py — per
adjustment interval: record observed num_req/ISL/OSL, predict the next
interval's load, correct for queueing (observed TTFT/ITL vs the
interpolated no-queueing value), then size prefill and decode fleets:

    prefill_replicas = ceil(req_rate·ISL / (prefill_thpt_per_chip·chips))
    decode_replicas  = ceil(req_rate·OSL / (best_decode_thpt_per_chip·chips))

where best_decode_thpt is the highest throughput meeting the (corrected)
ITL SLA at the predicted context length.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field

from dynamo_tpu.planner.interpolator import DecodeInterpolator, PrefillInterpolator
from dynamo_tpu.planner.load_predictor import make_predictor
from dynamo_tpu.utils.logging import get_logger

log = get_logger("planner")


@dataclass
class Metrics:
    """One adjustment interval's observations (reference: planner_core.py
    Metrics)."""

    num_req: float = 0.0       # requests completed this interval
    isl: float = 0.0           # mean input sequence length
    osl: float = 0.0           # mean output sequence length
    ttft_s: float | None = None
    itl_s: float | None = None

    def is_valid(self) -> bool:
        return self.num_req > 0 and self.isl > 0 and self.osl > 0


@dataclass
class PlannerConfig:
    ttft_sla_s: float = 0.5
    itl_sla_s: float = 0.05
    adjustment_interval_s: float = 30.0
    chips_per_prefill_replica: int = 1
    chips_per_decode_replica: int = 1
    min_replicas: int = 1
    max_replicas: int = 64
    load_predictor: str = "moving_average"
    prediction_window: int = 20
    # Max total chips the fleet may use (0 = unbounded); prefill is trimmed
    # first when over budget, mirroring the reference's gpu-budget clamp.
    chip_budget: int = 0


@dataclass
class Decision:
    prefill_replicas: int
    decode_replicas: int
    reason: str = ""


@dataclass
class Planner:
    config: PlannerConfig
    prefill_interp: PrefillInterpolator
    decode_interp: DecodeInterpolator
    p_correction: float = 1.0
    d_correction: float = 1.0
    _predictors: dict = field(default_factory=dict)

    def __post_init__(self) -> None:
        for key in ("num_req", "isl", "osl"):
            self._predictors[key] = make_predictor(
                self.config.load_predictor, self.config.prediction_window)

    # ------------------------------------------------------------------
    def observe(self, m: Metrics) -> None:
        if not m.is_valid():
            return
        self._predictors["num_req"].add_data_point(m.num_req)
        self._predictors["isl"].add_data_point(m.isl)
        self._predictors["osl"].add_data_point(m.osl)
        # Correction factors: how much worse the observed latency is than
        # the no-queueing interpolation at this operating point
        # (reference: correct prediction factors, planner_core.py:424).
        if m.ttft_s:
            expected = self.prefill_interp.interpolate_ttft(m.isl)
            if expected > 0:
                self.p_correction = m.ttft_s / expected
        if m.itl_s:
            expected = self.decode_interp.interpolate_itl(1.0, m.isl + m.osl / 2)
            if expected > 0:
                self.d_correction = m.itl_s / expected

    def predict_load(self) -> tuple[float, float, float]:
        return (self._predictors["num_req"].predict_next(),
                self._predictors["isl"].predict_next(),
                self._predictors["osl"].predict_next())

    # ------------------------------------------------------------------
    def compute_replicas(self, num_req: float, isl: float, osl: float) -> Decision:
        cfg = self.config
        if num_req <= 0 or isl <= 0:
            return Decision(cfg.min_replicas, cfg.min_replicas, "no load")

        # Prefill: queueing bias scales required throughput linearly
        # (reference: min(1, p_correction) damping on the way down only).
        p_thpt_needed = (num_req * isl / cfg.adjustment_interval_s
                         * max(1.0, self.p_correction))
        p_cap = (self.prefill_interp.interpolate_thpt_per_chip(isl)
                 * cfg.chips_per_prefill_replica)
        num_p = math.ceil(p_thpt_needed / max(p_cap, 1e-9))

        # Decode: tighten the ITL target by the observed correction, find
        # the best operating point meeting it, then size for token rate.
        corrected_itl = cfg.itl_sla_s / max(self.d_correction, 1e-9) \
            if self.d_correction > 1 else cfg.itl_sla_s
        d_thpt_per_chip, conc = self.decode_interp.find_best_throughput_per_chip(
            corrected_itl, isl + osl / 2)
        d_thpt_needed = num_req * osl / cfg.adjustment_interval_s
        d_cap = d_thpt_per_chip * cfg.chips_per_decode_replica
        num_d = math.ceil(d_thpt_needed / max(d_cap, 1e-9))

        num_p = min(max(num_p, cfg.min_replicas), cfg.max_replicas)
        num_d = min(max(num_d, cfg.min_replicas), cfg.max_replicas)
        if cfg.chip_budget > 0:
            while (num_p * cfg.chips_per_prefill_replica
                   + num_d * cfg.chips_per_decode_replica > cfg.chip_budget
                   and (num_p > cfg.min_replicas or num_d > cfg.min_replicas)):
                if num_p > cfg.min_replicas:
                    num_p -= 1
                else:
                    num_d -= 1
        reason = (f"pred: {num_req:.1f} req × isl {isl:.0f} / osl {osl:.0f}; "
                  f"p_corr {self.p_correction:.2f} d_corr {self.d_correction:.2f}; "
                  f"decode op point conc={conc:.0f}")
        return Decision(num_p, num_d, reason)

    def plan(self) -> Decision:
        """One decision from the current prediction state."""
        num_req, isl, osl = self.predict_load()
        d = self.compute_replicas(num_req, isl, osl)
        log.info("plan: prefill=%d decode=%d (%s)",
                 d.prefill_replicas, d.decode_replicas, d.reason)
        return d
