"""Pre-deployment SLA profiler: sweep a live engine, fit the planner.

Reference: benchmarks/profiler/profile_sla.py:71-393 — the reference sweeps
TP configurations of vLLM engines with genai-perf and writes npz files the
planner's interpolators read (tests/planner/profiling_results/). Here the
sweep drives our own EngineCore in-process (no HTTP hop, no external load
generator) and produces exactly the data dict
``planner.interpolator.{Prefill,Decode}Interpolator.from_data`` consume:

    prefill_isl, prefill_ttft_s, prefill_thpt_per_chip,
    decode_concurrency, decode_context, decode_itl_s, decode_thpt_per_chip

Method notes:
- each grid point is measured after a warmup pass so XLA compiles (one per
  static bucket) never pollute timings;
- prefix caching is disabled so repeat sweeps measure real prefill;
- decode ITL is steady-state: ``steps`` all-decode engine steps over a
  full batch, timed after the first decode step compiled.

CLI: ``python -m dynamo_tpu.planner.profiler --model llama-3-8b-lite
--output profile.npz`` (run on the target chip); the planner component
loads the npz via ``--profile`` instead of its synthetic default.
"""

from __future__ import annotations

import argparse
import time

import numpy as np

from dynamo_tpu.engine.engine import EngineCore
from dynamo_tpu.protocols.common import (
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from dynamo_tpu.utils.config import EngineConfig
from dynamo_tpu.utils.logging import get_logger

log = get_logger("profiler")


def _request(ctx_len: int, max_tokens: int, rid: str, seed: int = 0) -> PreprocessedRequest:
    toks = [(7 * seed + 11 * j) % 31900 + 5 for j in range(ctx_len)]
    req = PreprocessedRequest(
        token_ids=toks,
        stop_conditions=StopConditions(max_tokens=max_tokens, ignore_eos=True),
        sampling_options=SamplingOptions(temperature=0.0),
    )
    req.request_id = rid
    return req


class SlaProfiler:
    """Sweep one engine configuration; the engine is shared across grid
    points so compiled buckets are reused (one compile per static shape)."""

    def __init__(self, engine_cfg: EngineConfig, chips: int = 1):
        self.core = EngineCore(engine_cfg)
        self.chips = max(chips, 1)
        self._uid = 0

    def _rid(self) -> str:
        self._uid += 1
        return f"prof-{self._uid}"

    def _drain(self) -> None:
        while self.core.has_work():
            self.core.step()

    # ------------------------------------------------------------------
    def measure_ttft(self, isl: int) -> float:
        """Seconds from enqueue to first sampled token (prefill all chunks)."""
        req = _request(isl, 1, self._rid(), seed=self._uid)
        t0 = time.perf_counter()
        self.core.add_request(req)
        got = False
        while not got and self.core.has_work():
            outs = self.core.step()
            got = any(o.token_ids for o in outs.values())
        ttft = time.perf_counter() - t0
        self._drain()
        return ttft

    def profile_prefill(self, isl_grid: list[int]) -> tuple[np.ndarray, np.ndarray]:
        ttfts, thpts = [], []
        for isl in isl_grid:
            self.measure_ttft(isl)            # warmup: compile this bucket
            ttft = self.measure_ttft(isl)
            ttfts.append(ttft)
            thpts.append(isl / ttft / self.chips)
            log.info("prefill isl=%d ttft=%.4fs thpt/chip=%.1f tok/s",
                     isl, ttft, thpts[-1])
        return np.asarray(ttfts), np.asarray(thpts)

    # ------------------------------------------------------------------
    def measure_itl(self, concurrency: int, context: int, steps: int) -> float:
        """Steady-state seconds per all-decode step at a (concurrency,
        context) operating point."""
        maxb = self.core.engine_cfg.max_batch_size
        if concurrency > maxb:
            # Admission is slot-gated: extra requests would just queue, the
            # warmup would run until most of the batch FINISHED, and the
            # timed window would measure a smaller tail cohort.
            log.warning("capping ITL concurrency %d to max_batch_size %d",
                        concurrency, maxb)
            concurrency = maxb
        # Token budget: the wait-for-steady-state warmup below runs mixed
        # prefill+decode steps in which early-admitted requests already
        # decode, so give each request enough headroom that `steps` decode
        # tokens are still left once the whole batch reaches steady state.
        warmup_steps = max(
            -(-concurrency * context // self.core.engine_cfg.max_tokens_per_step),
            concurrency // max(self.core.engine_cfg.max_batch_size, 1) + 1,
        )
        for _ in range(concurrency):
            self.core.add_request(
                _request(context, steps + 2 + warmup_steps, self._rid(), seed=self._uid))
        # Run until EVERY request has finished prefill (the scheduler mixes
        # prefill chunks into decode steps, so "first decode token seen" is
        # NOT steady state — at high concurrency most of the batch would
        # still be prefilling and the timed window would fold prefill-chunk
        # compute into the ITL). Steady state = num_prefill_tokens stops
        # growing and at least one decode token has landed.
        entered = self.core.metrics.num_decode_tokens
        while self.core.has_work():
            pre = self.core.metrics.num_prefill_tokens
            self.core.step()
            if (self.core.metrics.num_prefill_tokens == pre
                    and self.core.sched.num_waiting == 0
                    and self.core.metrics.num_decode_tokens > entered):
                # No prefill progressed this step AND nothing is queued
                # waiting for a batch slot — a decode-only step with waiting
                # requests would still see their prefills land inside the
                # timed window once slots free up.
                break
        base = self.core.metrics.num_decode_tokens
        t0 = time.perf_counter()
        while (self.core.metrics.num_decode_tokens - base < concurrency * steps
               and self.core.has_work()):
            self.core.step()
        dt = time.perf_counter() - t0
        measured = self.core.metrics.num_decode_tokens - base
        self._drain()
        if measured == 0:
            raise RuntimeError(
                f"ITL window measured zero decode tokens at concurrency="
                f"{concurrency}, context={context} — warmup consumed the "
                "whole workload; raise steps or lower context")
        return dt / max(measured // max(concurrency, 1), 1)

    def profile_decode(
        self, conc_grid: list[int], ctx_grid: list[int], steps: int = 16
    ) -> tuple[np.ndarray, np.ndarray]:
        itl = np.zeros((len(conc_grid), len(ctx_grid)))
        thpt = np.zeros_like(itl)
        for i, c in enumerate(conc_grid):
            # measure_itl caps at max_batch_size; throughput must use the
            # EFFECTIVE concurrency or points above the cap report inflated
            # capacity to the planner.
            c_eff = min(c, self.core.engine_cfg.max_batch_size)
            for j, ctx in enumerate(ctx_grid):
                self.measure_itl(c, ctx, 2)   # warmup buckets
                itl[i, j] = self.measure_itl(c, ctx, steps)
                thpt[i, j] = c_eff / itl[i, j] / self.chips
                log.info("decode conc=%d ctx=%d itl=%.4fs thpt/chip=%.1f",
                         c, ctx, itl[i, j], thpt[i, j])
        return itl, thpt

    # ------------------------------------------------------------------
    def run(self, isl_grid: list[int], conc_grid: list[int],
            ctx_grid: list[int], decode_steps: int = 16) -> dict:
        ttft, p_thpt = self.profile_prefill(isl_grid)
        itl, d_thpt = self.profile_decode(conc_grid, ctx_grid, decode_steps)
        return {
            "prefill_isl": np.asarray(isl_grid, np.float64),
            "prefill_ttft_s": ttft,
            "prefill_thpt_per_chip": p_thpt,
            "decode_concurrency": np.asarray(conc_grid, np.float64),
            "decode_context": np.asarray(ctx_grid, np.float64),
            "decode_itl_s": itl,
            "decode_thpt_per_chip": d_thpt,
        }


def save_profile(path: str, data: dict) -> None:
    np.savez(path, **data)


def load_profile(path: str) -> dict:
    with np.load(path) as z:
        return {k: z[k] for k in z.files}


def engine_config_for_sweep(model: str, isl_grid: list[int],
                            conc_grid: list[int], ctx_grid: list[int],
                            decode_steps: int, block_size: int = 16,
                            tp: int = 1) -> EngineConfig:
    """Size the engine to the sweep's largest operating point."""
    max_len = max(max(isl_grid) + 8, max(ctx_grid) + decode_steps + 8)
    max_conc = max(conc_grid)
    blocks_per_seq = -(-max_len // block_size) + 1
    return EngineConfig(
        # Profiling sweeps measure latency/throughput, not output quality —
        # random weights on a weights-less dir are fine here.
        allow_random_weights=True,
        model=model, block_size=block_size,
        num_blocks=max_conc * blocks_per_seq + 1,
        max_batch_size=max_conc, max_model_len=max_len,
        decode_bucket=tuple(sorted(set(conc_grid))),
        enable_prefix_caching=False, tp=tp,
    )


def main() -> None:
    p = argparse.ArgumentParser("sla-profiler")
    p.add_argument("--model", default="llama-3-8b-lite")
    p.add_argument("--output", default="profile.npz")
    p.add_argument("--isl-grid", type=int, nargs="+", default=[128, 512, 2048])
    p.add_argument("--conc-grid", type=int, nargs="+", default=[1, 8, 32])
    p.add_argument("--ctx-grid", type=int, nargs="+", default=[256, 1024, 4096])
    p.add_argument("--decode-steps", type=int, default=32)
    p.add_argument("--chips", type=int, default=1)
    p.add_argument("--tp", type=int, default=1)
    ns = p.parse_args()
    cfg = engine_config_for_sweep(ns.model, ns.isl_grid, ns.conc_grid,
                                  ns.ctx_grid, ns.decode_steps, tp=ns.tp)
    prof = SlaProfiler(cfg, chips=max(ns.chips, ns.tp))
    data = prof.run(ns.isl_grid, ns.conc_grid, ns.ctx_grid, ns.decode_steps)
    save_profile(ns.output, data)
    print(f"profile written to {ns.output}")


if __name__ == "__main__":
    main()
