"""Tokenizer abstraction + incremental streaming decode.

Fills the role of the reference's tokenizer wrapper
(reference: lib/llm/src/tokenizers.rs, tokenizers/hf.rs:72): a uniform
encode/decode interface over HF tokenizers, plus a ``DecodeStream`` that
incrementally detokenizes a token stream without re-emitting text (the
per-token hot loop in the response path).

``ByteTokenizer`` is a deterministic, dependency- and network-free tokenizer
(UTF-8 bytes + special tokens) used by tests, the mocker, and the tiny
reference models — filling the role llama.cpp/GGUF vocab plays for the
reference's zero-GPU test path (reference: lib/llm/src/gguf.rs).
"""

from __future__ import annotations

import os
import re
from pathlib import Path
from typing import Protocol, Sequence

# SentencePiece byte-fallback pieces: literal "<0xHH>" vocab entries that
# stand for one raw byte (llama-family vocabs keep 256 of them).
_SP_BYTE_RE = re.compile(r"^<0x([0-9A-Fa-f]{2})>$")


class BaseTokenizer(Protocol):
    bos_id: int | None
    eos_id: int
    vocab_size: int

    def encode(self, text: str, add_bos: bool = False) -> list[int]: ...
    def decode(self, ids: Sequence[int], skip_special: bool = True) -> str: ...
    def apply_chat_template(self, messages: list[dict], add_generation_prompt: bool = True,
                            tools: list[dict] | None = None) -> str: ...


class ByteTokenizer:
    """UTF-8 byte tokenizer: ids = byte + 4; specials pad=0 bos=1 eos=2 unk=3."""

    PAD, BOS, EOS, UNK = 0, 1, 2, 3
    OFFSET = 4

    def __init__(self, vocab_size: int = 512):
        self.bos_id: int | None = self.BOS
        self.eos_id = self.EOS
        self.pad_id = self.PAD
        self.vocab_size = max(vocab_size, 256 + self.OFFSET)

    def encode(self, text: str, add_bos: bool = False) -> list[int]:
        ids = [b + self.OFFSET for b in text.encode("utf-8")]
        return ([self.BOS] + ids) if add_bos else ids

    def decode(self, ids: Sequence[int], skip_special: bool = True) -> str:
        return self.decode_bytes(ids).decode("utf-8", errors="replace")

    def decode_bytes(self, ids: Sequence[int]) -> bytes:
        # ids beyond the byte range are vocab padding (models may round the
        # vocab up for sharding) — they decode to nothing.
        return bytes(i - self.OFFSET for i in ids if self.OFFSET <= i < self.OFFSET + 256)

    def apply_chat_template(self, messages: list[dict], add_generation_prompt: bool = True,
                            tools: list[dict] | None = None) -> str:
        # Minimal ChatML-style template (reference: minijinja templating in
        # lib/llm/src/preprocessor/prompt/; real models use their HF template).
        parts = []
        if tools:
            import json as _json

            parts.append(f"<|system|>\nAvailable tools: {_json.dumps(tools)}\n")
        for m in messages:
            content = m.get("content") or ""
            if isinstance(content, list):
                content = "".join(p.get("text", "") for p in content if isinstance(p, dict))
            parts.append(f"<|{m.get('role', 'user')}|>\n{content}\n")
        if add_generation_prompt:
            parts.append("<|assistant|>\n")
        return "".join(parts)


class HFTokenizer:
    """HuggingFace tokenizer wrapper (local files only; zero-egress env)."""

    def __init__(self, path: str):
        from transformers import AutoTokenizer

        self._tok = AutoTokenizer.from_pretrained(path, local_files_only=True, trust_remote_code=False)
        self.bos_id = self._tok.bos_token_id
        self.eos_id = self._tok.eos_token_id if self._tok.eos_token_id is not None else 0
        self.vocab_size = len(self._tok)

    def encode(self, text: str, add_bos: bool = False) -> list[int]:
        ids = self._tok.encode(text, add_special_tokens=False)
        if add_bos and self.bos_id is not None:
            ids = [self.bos_id] + ids
        return ids

    def decode(self, ids: Sequence[int], skip_special: bool = True) -> str:
        return self._tok.decode(list(ids), skip_special_tokens=skip_special)

    def apply_chat_template(self, messages: list[dict], add_generation_prompt: bool = True,
                            tools: list[dict] | None = None) -> str:
        try:
            return self._tok.apply_chat_template(
                messages, tokenize=False, add_generation_prompt=add_generation_prompt,
                tools=tools,
            )
        except Exception:
            return ByteTokenizer.apply_chat_template(self, messages, add_generation_prompt, tools)  # type: ignore[arg-type]


def load_tokenizer(name_or_path: str | None) -> BaseTokenizer:
    """Resolve a tokenizer: local HF dir if it exists, else built-in byte tokenizer."""
    if name_or_path and (
        Path(name_or_path).is_dir() or os.path.exists(os.path.join(str(name_or_path), "tokenizer.json"))
    ):
        return HFTokenizer(str(name_or_path))
    return ByteTokenizer()


class DecodeStream:
    """Incremental detokenizer for one response stream.

    Reference: DecodeStream in lib/llm/src/tokenizers.rs — the per-token hot
    loop of the response path.

    Algorithm: decode a *segment* of recent token ids and emit the text grown
    since the last emission. Emission is withheld while the segment's decode
    ends in U+FFFD (incomplete multi-byte sequence split across tokens). The
    segment is compacted at whitespace boundaries so cost stays O(segment),
    not O(stream), without risking tokenizer context-dependence (e.g.
    sentencepiece leading-space rules) splitting a word across segments.
    """

    _COMPACT_AFTER = 48  # tokens

    def __init__(self, tokenizer: BaseTokenizer, skip_special: bool = True):
        self._tok = tokenizer
        self._skip_special = skip_special
        self._seg_ids: list[int] = []
        self._seg_emitted = 0  # chars of decode(_seg_ids) already emitted

    def step(self, token_id: int) -> str:
        """Feed one token; return the new text to emit ("" if withheld)."""
        self._seg_ids.append(token_id)
        text = self._tok.decode(self._seg_ids, skip_special=self._skip_special)
        if text.endswith("�"):
            return ""  # incomplete multi-byte sequence — wait for more tokens
        delta = text[self._seg_emitted :]
        self._seg_emitted = len(text)
        if len(self._seg_ids) >= self._COMPACT_AFTER and delta[-1:].isspace():
            self._seg_ids.clear()
            self._seg_emitted = 0
        return delta

    def flush(self) -> str:
        """Emit any withheld tail (e.g. trailing invalid bytes) at stream end."""
        if not self._seg_ids:
            return ""
        text = self._tok.decode(self._seg_ids, skip_special=self._skip_special)
        delta = text[self._seg_emitted :]
        self._seg_ids.clear()
        self._seg_emitted = 0
        return delta


def _byte_decoder() -> dict[str, int]:
    """Inverse of the GPT-2 bytes→unicode table used by byte-level BPE
    vocabs: printable chars map to themselves, the rest to a private range."""
    bs = (list(range(ord("!"), ord("~") + 1))
          + list(range(ord("¡"), ord("¬") + 1))
          + list(range(ord("®"), ord("ÿ") + 1)))
    cs = bs[:]
    n = 0
    for b in range(256):
        if b not in bs:
            bs.append(b)
            cs.append(256 + n)
            n += 1
    return {chr(c): b for b, c in zip(bs, cs)}


def guided_vocab(tok, size: int | None = None) -> list[str]:
    """Token-id → text table for constrained decoding (engine/guided.py).

    Built from the tokenizer's own vocab in one pass instead of V per-id
    ``decode([i])`` round-trips: byte-level BPE pieces are mapped through
    the GPT-2 byte decoder (exact text, leading-space markers included),
    sentencepiece pieces get their ▁ marker substituted (including the
    ``<0xHH>`` byte-fallback pieces: ASCII bytes become their character,
    non-ASCII bytes — partial UTF-8 sequences — stay "" so the masker never
    matches half a codepoint), and special tokens decode to "" so the
    masker never trial-feeds control markup. ``size`` pads/truncates to the
    MODEL vocab (sharding may round it up)."""
    if isinstance(tok, ByteTokenizer):
        v = size or tok.vocab_size
        pieces = [""] * v
        for i in range(tok.OFFSET, min(tok.OFFSET + 256, v)):
            pieces[i] = bytes([i - tok.OFFSET]).decode("utf-8", errors="replace")
        return pieces
    inner = getattr(tok, "_tok", None)
    if inner is not None and hasattr(inner, "get_vocab"):
        vocab = inner.get_vocab()
        v = size or max(len(inner), max(vocab.values(), default=-1) + 1)
        # get_vocab() can miss ids (added tokens, holes); backfill the gaps
        # from convert_ids_to_tokens so those ids aren't silently "" =
        # always-allowed for every grammar.
        have = {idx for idx in vocab.values() if 0 <= idx < v}
        conv = getattr(inner, "convert_ids_to_tokens", None)
        if conv is not None and len(have) < v:
            for idx in range(v):
                if idx in have:
                    continue
                try:
                    piece = conv(idx)
                except (IndexError, KeyError, ValueError, OverflowError):
                    continue
                if isinstance(piece, str) and piece:
                    vocab.setdefault(piece, idx)
        pieces = [""] * v
        dec = _byte_decoder()
        special = set(getattr(inner, "all_special_ids", None) or [])
        for piece, idx in vocab.items():
            if not (0 <= idx < v) or idx in special:
                continue
            m = _SP_BYTE_RE.match(piece)
            if m is not None:
                b = int(m.group(1), 16)
                # A lone non-ASCII byte is a UTF-8 fragment — no text a
                # grammar could match; leave it disallowed rather than
                # emitting U+FFFD into every charset check.
                pieces[idx] = chr(b) if b < 0x80 else ""
                continue
            if all(ch in dec for ch in piece):
                pieces[idx] = bytes(dec[ch] for ch in piece).decode(
                    "utf-8", errors="replace")
            else:
                pieces[idx] = piece.replace("▁", " ")
        return pieces
    v = size or tok.vocab_size
    return [tok.decode([i]) for i in range(v)]
