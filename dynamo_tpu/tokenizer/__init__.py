from dynamo_tpu.tokenizer.base import (
    BaseTokenizer,
    ByteTokenizer,
    DecodeStream,
    HFTokenizer,
    guided_vocab,
    load_tokenizer,
)

__all__ = ["BaseTokenizer", "ByteTokenizer", "DecodeStream", "HFTokenizer",
           "guided_vocab", "load_tokenizer"]
