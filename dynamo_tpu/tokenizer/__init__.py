from dynamo_tpu.tokenizer.base import (
    BaseTokenizer,
    ByteTokenizer,
    DecodeStream,
    HFTokenizer,
    load_tokenizer,
)

__all__ = ["BaseTokenizer", "ByteTokenizer", "DecodeStream", "HFTokenizer", "load_tokenizer"]
