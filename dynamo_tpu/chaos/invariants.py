"""Post-scenario invariant checking: did the system degrade *correctly*?

A chaos scenario doesn't assert that nothing failed — failure is the
input. It asserts the system-wide postconditions that must hold no matter
what was injected:

* **No lost streams** — every client request either finished (a terminal
  ``finish_reason``) or surfaced a *typed* error (an HTTP error status or
  an error payload). A stream that just stops is an outage.
* **No leaked KV blocks** — after the fleet drains, every engine reports
  zero running/waiting requests and zero pinned device blocks
  (``kv_usage`` counts only refcounted/active blocks; parked prefix-cache
  blocks are evictable and don't count).
* **Rank-identical SPMD op streams** — multi-host engines must have
  applied the exact same op sequence on every rank; divergence means a
  future collective hangs.
* **Metrics balance** — ``qos_admitted_total`` must equal the terminal
  request count after admission (completed + failed), i.e.
  admitted + shed == every request accounted for. Requests rejected
  before admission (400/404 client errors) sit outside both sides.

The report is plain data (``to_dict``) so the deterministic-replay test
can assert two runs of the same seed produce *identical* reports.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Iterable, Mapping

from dynamo_tpu.utils.metrics import metric_sum, parse_prometheus

__all__ = ["parse_prometheus", "metric_sum", "StreamOutcome",
           "InvariantReport", "InvariantChecker",
           "ADMITTED_TERMINAL_STATUSES", "SHED_STATUSES",
           "CLIENT_ERROR_STATUSES", "GENERATE_ROUTES"]

# frontend_requests_total statuses on the chat/completions routes, split by
# where in the request lifecycle they are emitted (frontend/service.py):
# post-admission terminals count against qos_admitted_total; shed statuses
# mirror qos_rejected_total; client errors precede the QoS gate entirely.
ADMITTED_TERMINAL_STATUSES = {"200", "499", "500"}
SHED_STATUSES = {"429", "503", "504"}
CLIENT_ERROR_STATUSES = {"400", "404", "501", "502"}
GENERATE_ROUTES = {"chat", "completions"}


@dataclass
class StreamOutcome:
    """What one client request ended as, from the client's point of view."""

    request_id: str
    status: str            # "finished" | "error" | "lost"
    detail: str = ""

    def to_dict(self) -> dict:
        return {"request_id": self.request_id, "status": self.status,
                "detail": self.detail}


@dataclass
class InvariantReport:
    failures: list[str] = field(default_factory=list)
    checks: list[str] = field(default_factory=list)
    details: dict[str, Any] = field(default_factory=dict)

    @property
    def passed(self) -> bool:
        return not self.failures

    def ok(self, name: str) -> None:
        self.checks.append(name)

    def fail(self, msg: str) -> None:
        self.failures.append(msg)

    def to_dict(self) -> dict:
        return {"passed": self.passed, "checks": list(self.checks),
                "failures": list(self.failures), "details": dict(self.details)}


class InvariantChecker:
    """Accumulates scenario evidence, then renders one report."""

    def __init__(self) -> None:
        self.report = InvariantReport()

    # -- streams -----------------------------------------------------------
    def check_streams(self, outcomes: Iterable[StreamOutcome]) -> None:
        outcomes = list(outcomes)
        lost = [o for o in outcomes if o.status == "lost"]
        counts = {
            "finished": sum(o.status == "finished" for o in outcomes),
            "error": sum(o.status == "error" for o in outcomes),
            "lost": len(lost),
        }
        self.report.details["streams"] = counts
        if lost:
            for o in lost[:5]:
                self.report.fail(
                    f"stream lost: request {o.request_id} ended without a "
                    f"finish reason or typed error ({o.detail})")
        else:
            self.report.ok("no_lost_streams")

    # -- kv leaks ----------------------------------------------------------
    def check_block_leaks(self, engine_stats: Mapping[str, Any]) -> None:
        """``engine_stats`` is the frontend /engine_stats JSON: per model,
        ``workers`` maps worker id -> published engine stats. Single-worker
        fleets (no kv router) may publish no per-worker map; that is a skip,
        not a pass.

        Workers running the memory ledger (obs/mem_ledger.py) publish a
        ``mem`` block whose pin-owner audit replaces the old kv_usage
        heuristic: ``orphan_pins`` must be zero AND no owner class may
        still hold pinned blocks after drain. Workers without the block
        (DYN_MEM_LEDGER=0) fall back to the kv_usage walk."""
        leaks: list[str] = []
        seen = audited = 0
        for model, stats in engine_stats.items():
            for wid, m in (stats.get("workers") or {}).items():
                if not isinstance(m, Mapping):
                    continue
                seen += 1
                running = m.get("num_running", 0) or 0
                waiting = m.get("num_waiting", 0) or 0
                usage = m.get("kv_usage", 0.0) or 0.0
                if running or waiting:
                    leaks.append(
                        f"{model}/{wid}: {running} running + {waiting} "
                        "waiting after drain")
                    continue
                mem = m.get("mem")
                if isinstance(mem, Mapping) and mem.get("enabled"):
                    audited += 1
                    orphans = int(mem.get("orphan_pins", 0) or 0)
                    if orphans:
                        leaks.append(
                            f"{model}/{wid}: {orphans} orphan pin(s) at "
                            "last mem-ledger audit (leaked references)")
                    held = {
                        cls: n for cls, n in
                        (mem.get("device_blocks") or {}).items()
                        if cls not in ("free", "cached") and n}
                    # session pins legitimately survive a drain (retained
                    # turns are the feature, not a leak)
                    held.pop("session", None)
                    if held:
                        leaks.append(
                            f"{model}/{wid}: pinned blocks after drain "
                            f"by owner {held}")
                elif usage > 1e-9:
                    leaks.append(
                        f"{model}/{wid}: kv_usage={usage:.4f} with no "
                        "running requests (leaked pinned blocks)")
        self.report.details["block_leak_workers_checked"] = seen
        self.report.details["block_leak_workers_audited"] = audited
        for leak in leaks:
            self.report.fail(f"kv leak: {leak}")
        if not leaks and seen:
            self.report.ok("no_leaked_blocks")

    # -- SPMD op streams ---------------------------------------------------
    def check_op_streams(self, streams: Mapping[int, Iterable[Any]]) -> None:
        """``streams`` maps rank -> its applied op sequence. All ranks must
        have applied identical sequences (broadcast-then-apply contract of
        engine._emit_op); the first divergence is reported by index."""
        per_rank = {r: list(ops) for r, ops in streams.items()}
        self.report.details["op_stream_ranks"] = sorted(per_rank)
        if len(per_rank) < 2:
            return
        ranks = sorted(per_rank)
        ref_rank, ref = ranks[0], per_rank[ranks[0]]
        diverged = False
        for r in ranks[1:]:
            ops = per_rank[r]
            if ops == ref:
                continue
            diverged = True
            idx = next((i for i, (a, b) in enumerate(zip(ref, ops))
                        if a != b), min(len(ref), len(ops)))
            self.report.fail(
                f"SPMD op streams diverge: rank {r} differs from rank "
                f"{ref_rank} at op index {idx} "
                f"(lengths {len(ops)} vs {len(ref)})")
        if not diverged:
            self.report.ok("spmd_op_streams_identical")

    # -- warm resume -------------------------------------------------------
    def check_warm_resume(self, engine_stats: Mapping[str, Any],
                          minimum: int = 1) -> None:
        """After a drained worker evacuated its retained sessions
        (runtime/drain.py), surviving workers must have resumed at least
        ``minimum`` session turns from the remote records — retirement
        converts would-be full recomputes into pull-to-warm imports.
        ``engine_stats`` is the frontend /engine_stats JSON."""
        resumes = hits = 0
        for stats in engine_stats.values():
            for m in (stats.get("workers") or {}).values():
                if isinstance(m, Mapping):
                    resumes += int(m.get("session_remote_resumes", 0) or 0)
                    hits += int(m.get("session_hits", 0) or 0)
        self.report.details["warm_resume"] = {
            "session_remote_resumes": resumes, "session_hits": hits}
        if resumes < minimum:
            self.report.fail(
                f"no warm resume: {resumes} session turn(s) resumed from "
                f"evacuated records (needed >= {minimum})")
        else:
            self.report.ok("sessions_resumed_warm")

    # -- checkpoint resume -------------------------------------------------
    def check_ckpt_resume(self, engine_stats: Mapping[str, Any],
                          minimum: int = 1) -> None:
        """After an unplanned worker kill, surviving workers must have
        warm-resumed at least ``minimum`` checkpointed streams
        (kvbm/stream_ckpt.py): resumes >= kills for checkpointed streams —
        the crash cost recompute, never the stream. ``engine_stats`` is the
        frontend /engine_stats JSON."""
        resumes = writes = 0
        for stats in engine_stats.values():
            for m in (stats.get("workers") or {}).values():
                if isinstance(m, Mapping):
                    resumes += int(m.get("stream_ckpt_resumes", 0) or 0)
                    writes += int(m.get("stream_ckpt_writes", 0) or 0)
        self.report.details["ckpt_resume"] = {
            "stream_ckpt_resumes": resumes, "stream_ckpt_writes": writes}
        if resumes < minimum:
            self.report.fail(
                f"no checkpoint resume: {resumes} stream(s) warm-resumed "
                f"from checkpoints (needed >= {minimum})")
        else:
            self.report.ok("streams_resumed_from_ckpt")

    # -- metrics balance ---------------------------------------------------
    def check_metrics_balance(self, metrics_text: str) -> None:
        """shed + completed + failed == admitted + shed, from the frontend's
        /metrics exposition (chat/completions routes only)."""
        samples = parse_prometheus(metrics_text)
        admitted = metric_sum(samples, "dynamo_qos_admitted_total")
        shed = metric_sum(samples, "dynamo_qos_rejected_total")
        completed = failed = shed_http = 0.0
        for (name, labels), v in samples.items():
            if name != "dynamo_frontend_requests_total":
                continue
            d = dict(labels)
            if d.get("route") not in GENERATE_ROUTES:
                continue
            status = d.get("status", "")
            if status == "200":
                completed += v
            elif status in ADMITTED_TERMINAL_STATUSES:
                failed += v
            elif status in SHED_STATUSES:
                shed_http += v
        self.report.details["metrics_balance"] = {
            "admitted": admitted, "completed": completed, "failed": failed,
            "shed": shed, "shed_http": shed_http,
        }
        if admitted != completed + failed:
            self.report.fail(
                f"metrics imbalance: qos_admitted_total={admitted:g} but "
                f"completed({completed:g}) + failed({failed:g}) = "
                f"{completed + failed:g}")
        else:
            self.report.ok("metrics_admitted_balance")
        if shed_http > shed:
            # every shed HTTP response must have a matching QoS rejection
            # (the reverse can differ: non-generate routes also reject)
            self.report.fail(
                f"metrics imbalance: {shed_http:g} shed HTTP responses but "
                f"only {shed:g} qos_rejected_total")
        else:
            self.report.ok("metrics_shed_balance")

    # -- fleet rollup ------------------------------------------------------
    def check_fleet_rollup(self, aggregator_text: str) -> None:
        """Same admitted-vs-terminal balance, but read from the fleet
        aggregator's rollup series (``instance="_fleet"``): after targets
        died and recovered mid-scenario the aggregator's fleet view must
        still account for every admitted request."""
        samples = parse_prometheus(aggregator_text)
        fleet = {"instance": "_fleet"}
        admitted = metric_sum(samples, "dynamo_qos_admitted_total", **fleet)
        completed = failed = 0.0
        for (name, labels), v in samples.items():
            if name != "dynamo_frontend_requests_total":
                continue
            d = dict(labels)
            if d.get("instance") != "_fleet":
                continue
            if d.get("route") not in GENERATE_ROUTES:
                continue
            status = d.get("status", "")
            if status == "200":
                completed += v
            elif status in ADMITTED_TERMINAL_STATUSES:
                failed += v
        scrape_errors = metric_sum(samples, "dynamo_fleet_scrape_errors_total")
        self.report.details["fleet_rollup"] = {
            "admitted": admitted, "completed": completed, "failed": failed,
            "scrape_errors": scrape_errors,
        }
        if admitted != completed + failed:
            self.report.fail(
                f"fleet rollup imbalance: qos_admitted_total={admitted:g} "
                f"but completed({completed:g}) + failed({failed:g}) = "
                f"{completed + failed:g}")
        else:
            self.report.ok("fleet_rollup_admitted_balance")

    def finish(self) -> InvariantReport:
        return self.report
