"""Chaos harness: drive a mocker fleet through scripted failure scenarios.

Spawns a real coordinator + mocker workers + frontend as subprocesses
(the zero-accelerator e2e shape of tests/test_e2e_mockers.py), injects
faults — either by manipulating processes directly (SIGKILL, restart) or
by shipping a ChaosPlan to the children via ``DYN_CHAOS_PLAN`` /
``DYN_CHAOS_SEED`` — then drives client load and hands the evidence to
the :class:`~dynamo_tpu.chaos.invariants.InvariantChecker`.

Scenarios return a :class:`ScenarioResult` whose ``report`` is plain data,
so ``tools/chaos_run.py`` can print it and the deterministic-replay test
can compare two runs byte-for-byte. Used by both ``tools/chaos_run.py``
and ``tests/test_chaos.py`` — the logic lives here so the CLI and the
pytest suite cannot drift apart.
"""

from __future__ import annotations

import concurrent.futures
import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Callable

from dynamo_tpu.chaos.invariants import (
    InvariantChecker,
    InvariantReport,
    StreamOutcome,
)
from dynamo_tpu.chaos.plan import ChaosPlan
from dynamo_tpu.utils.logging import get_logger

log = get_logger("chaos.harness")

REPO = Path(__file__).resolve().parent.parent.parent

_BASE_ENV = {
    "PYTHONPATH": str(REPO),
    "PYTHONUNBUFFERED": "1",
    "JAX_PLATFORMS": "cpu",
    "PALLAS_AXON_POOL_IPS": "",  # keep the TPU tunnel plugin out of tests
    "DYN_LOG": "info",
}


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class Proc:
    """Subprocess with readiness-line gating + captured logs (the
    ManagedProcess shape of tests/utils_process.py, importable from the
    package so tools/chaos_run.py works outside pytest)."""

    def __init__(self, args: list[str], name: str, env: dict | None = None):
        self.name = name
        self.args = [sys.executable, "-u", *args]
        self.env = {**os.environ, **_BASE_ENV, **(env or {})}
        self.proc: subprocess.Popen | None = None
        self._lines: list[str] = []

    def start(self) -> "Proc":
        self.proc = subprocess.Popen(
            self.args, env=self.env, cwd=REPO,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        threading.Thread(target=self._drain, daemon=True).start()
        return self

    def _drain(self) -> None:
        assert self.proc and self.proc.stdout
        for line in self.proc.stdout:
            self._lines.append(line)

    def wait_for_line(self, needle: str, timeout: float = 30.0) -> str:
        deadline = time.time() + timeout
        scanned = 0
        while time.time() < deadline:
            lines = self._lines
            while scanned < len(lines):
                if needle in lines[scanned]:
                    return lines[scanned]
                scanned += 1
            if self.proc.poll() is not None and scanned >= len(self._lines):
                raise RuntimeError(
                    f"{self.name} exited rc={self.proc.returncode}:\n"
                    + "".join(self._lines[-50:]))
            time.sleep(0.02)
        raise TimeoutError(f"{self.name}: no {needle!r} within {timeout}s:\n"
                           + "".join(self._lines[-50:]))

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    def kill_hard(self) -> None:
        if self.alive():
            self.proc.kill()

    def stop(self, grace: float = 5.0) -> None:
        if self.proc is None or self.proc.poll() is not None:
            return
        self.proc.send_signal(signal.SIGTERM)
        try:
            self.proc.wait(grace)
        except subprocess.TimeoutExpired:
            self.proc.kill()
            self.proc.wait(5)

    def logs(self) -> str:
        return "".join(self._lines)


def http_json(url: str, payload: dict | None = None, timeout: float = 30.0,
              headers: dict | None = None):
    req = urllib.request.Request(
        url,
        data=json.dumps(payload).encode() if payload is not None else None,
        headers={"content-type": "application/json", **(headers or {})})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read())


@dataclass
class FleetConfig:
    workers: int = 2
    router_mode: str = "kv"
    speedup_ratio: float = 50.0
    block_size: int = 4
    num_blocks: int = 128
    max_model_len: int = 512
    migration_limit: int = 3
    lease_ttl_s: float | None = None          # None = runtime default
    chaos_plan: "ChaosPlan | None" = None     # shipped to WORKERS via env
    chaos_seed: int | None = None
    worker_env: dict[str, str] = field(default_factory=dict)
    frontend_env: dict[str, str] = field(default_factory=dict)
    worker_args: list[str] = field(default_factory=list)
    kv_store: bool = False                    # spawn a G4 remote block store
    aggregator: bool = False                  # spawn a fleet aggregator
    aggregator_env: dict[str, str] = field(default_factory=dict)
    scrape_interval_s: float = 0.5            # aggregator sweep cadence
    staleness_ttl_s: float = 2.0              # aggregator staleness window


class MockerFleet:
    """coordinator + N mocker workers + frontend, as real processes."""

    def __init__(self, cfg: FleetConfig):
        self.cfg = cfg
        self.coord_port = free_port()
        self.http_port = free_port()
        self.coord_url = f"tcp://127.0.0.1:{self.coord_port}"
        self.base = f"http://127.0.0.1:{self.http_port}"
        self.coordinator: Proc | None = None
        self.workers: list[Proc] = []
        self.frontend: Proc | None = None
        self.kv_store: Proc | None = None
        self.kv_port = free_port() if cfg.kv_store else 0
        self.aggregator: Proc | None = None
        self.agg_port = free_port() if cfg.aggregator else 0
        self.agg_base = f"http://127.0.0.1:{self.agg_port}"

    # -- lifecycle ---------------------------------------------------------
    def _common_env(self) -> dict[str, str]:
        env: dict[str, str] = {}
        if self.cfg.lease_ttl_s is not None:
            env["DYN_LEASE_TTL_S"] = str(self.cfg.lease_ttl_s)
        return env

    def _worker_env(self) -> dict[str, str]:
        env = {**self._common_env(), **self.cfg.worker_env}
        if self.cfg.aggregator:
            # scrape targets need the per-process status server up so
            # advertise_metrics() has a /metrics URL to publish
            env.setdefault("DYN_SYSTEM_ENABLED", "1")
        if self.cfg.chaos_plan is not None:
            env["DYN_CHAOS_PLAN"] = json.dumps(self.cfg.chaos_plan.to_dict())
        if self.cfg.chaos_seed is not None:
            env["DYN_CHAOS_SEED"] = str(self.cfg.chaos_seed)
        return env

    def start_worker(self, i: int) -> Proc:
        extra = (["--remote-kv-addr", f"127.0.0.1:{self.kv_port}"]
                 if self.cfg.kv_store else [])
        w = Proc(
            ["-m", "dynamo_tpu.components.worker", "--engine", "mocker",
             "--coordinator", self.coord_url,
             "--block-size", str(self.cfg.block_size),
             "--speedup-ratio", str(self.cfg.speedup_ratio),
             "--max-model-len", str(self.cfg.max_model_len),
             "--num-blocks", str(self.cfg.num_blocks),
             *extra, *self.cfg.worker_args],
            name=f"worker{i}", env=self._worker_env()).start()
        return w

    def start(self) -> "MockerFleet":
        self.coordinator = Proc(
            ["-m", "dynamo_tpu.transports.coordinator", "--host", "127.0.0.1",
             "--port", str(self.coord_port)], name="coordinator").start()
        self.coordinator.wait_for_line("COORDINATOR_READY", 20)
        if self.cfg.kv_store:
            self.kv_store = Proc(
                ["-m", "dynamo_tpu.components.kv_store", "--host", "127.0.0.1",
                 "--port", str(self.kv_port),
                 # register lease-bound so the frontend's stream-checkpoint
                 # lookup can discover the store (workers get the address
                 # explicitly via --remote-kv-addr)
                 "--coordinator", self.coord_url],
                name="kv_store", env=self._common_env()).start()
            self.kv_store.wait_for_line("KV_STORE_READY", 20)
        self.workers = [self.start_worker(i) for i in range(self.cfg.workers)]
        for w in self.workers:
            w.wait_for_line("WORKER_READY", 30)
        self.frontend = Proc(
            ["-m", "dynamo_tpu.components.frontend",
             "--coordinator", self.coord_url, "--host", "127.0.0.1",
             "--port", str(self.http_port),
             "--router-mode", self.cfg.router_mode,
             "--migration-limit", str(self.cfg.migration_limit)],
            name="frontend", env={**self._common_env(),
                                  **self.cfg.frontend_env}).start()
        self.frontend.wait_for_line("FRONTEND_READY", 30)
        if self.cfg.aggregator:
            self.aggregator = Proc(
                ["-m", "dynamo_tpu.components.aggregator",
                 "--coordinator", self.coord_url, "--host", "127.0.0.1",
                 "--port", str(self.agg_port),
                 "--scrape-interval", str(self.cfg.scrape_interval_s),
                 "--scrape-timeout", "2.0",
                 "--staleness-ttl", str(self.cfg.staleness_ttl_s)],
                name="aggregator",
                env={**self._common_env(),
                     **self.cfg.aggregator_env}).start()
            self.aggregator.wait_for_line("AGGREGATOR_READY", 30)
        deadline = time.time() + 15
        while time.time() < deadline:
            try:
                if http_json(self.base + "/v1/models")["data"]:
                    return self
            except Exception:
                pass
            time.sleep(0.1)
        raise TimeoutError("model never discovered:\n" + self.frontend.logs())

    def stop(self) -> None:
        if self.aggregator:
            self.aggregator.stop()
        if self.frontend:
            self.frontend.stop()
        for w in self.workers:
            w.stop()
        if self.kv_store:
            self.kv_store.stop()
        if self.coordinator:
            self.coordinator.stop()

    def __enter__(self) -> "MockerFleet":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- observation -------------------------------------------------------
    def metrics_text(self) -> str:
        with urllib.request.urlopen(self.base + "/metrics", timeout=10) as r:
            return r.read().decode()

    def engine_stats(self) -> dict:
        return http_json(self.base + "/engine_stats")

    def aggregator_metrics_text(self) -> str:
        with urllib.request.urlopen(self.agg_base + "/metrics",
                                    timeout=10) as r:
            return r.read().decode()

    def fleet_debug(self) -> dict:
        return http_json(self.agg_base + "/debug/fleet", timeout=10)

    def wait_fleet_fresh(self, n: int, timeout: float = 30.0) -> dict:
        """Wait until the aggregator reports >= n fresh scrape targets;
        returns the final /debug/fleet document."""
        deadline = time.time() + timeout
        info: dict = {}
        while time.time() < deadline:
            try:
                info = self.fleet_debug()
                fresh = sum(1 for t in info.get("targets", [])
                            if t.get("fresh"))
                if fresh >= n:
                    return info
            except Exception:
                pass
            time.sleep(0.2)
        raise TimeoutError(
            f"aggregator never reached {n} fresh targets: {info}")

    def wait_drained(self, timeout: float = 20.0) -> dict:
        """Wait until every published worker snapshot shows an idle engine;
        returns the final /engine_stats. Published metrics lag ~1s."""
        deadline = time.time() + timeout
        stats: dict = {}
        while time.time() < deadline:
            stats = self.engine_stats()
            busy = False
            for model in stats.values():
                for m in (model.get("workers") or {}).values():
                    if (m.get("num_running", 0) or m.get("num_waiting", 0)
                            or (m.get("kv_usage", 0.0) or 0.0) > 1e-9):
                        busy = True
            if not busy:
                return stats
            time.sleep(0.3)
        return stats

    # -- load --------------------------------------------------------------
    def drive_load(self, n: int = 12, max_tokens: int = 8,
                   concurrency: int = 4, timeout: float = 30.0,
                   interval_s: float = 0.0) -> list[StreamOutcome]:
        """Fire ``n`` completions; classify every outcome for the stream-
        accounting invariant. An HTTP error status is a TYPED error (the
        client was told); a transport-level failure or a response without a
        finish_reason is a LOST stream."""

        def one(i: int) -> StreamOutcome:
            rid = f"chaos-{i}"
            if interval_s:
                time.sleep(interval_s * i)
            try:
                r = http_json(self.base + "/v1/completions", {
                    "model": "tiny-llama",
                    "prompt": f"chaos prompt {i} " * 4,
                    "max_tokens": max_tokens, "ignore_eos": True,
                }, timeout=timeout, headers={"x-request-id": rid})
                fr = r["choices"][0].get("finish_reason")
                if fr:
                    return StreamOutcome(rid, "finished", fr)
                return StreamOutcome(rid, "lost", "no finish_reason")
            except urllib.error.HTTPError as exc:
                return StreamOutcome(rid, "error", f"http {exc.code}")
            except Exception as exc:  # noqa: BLE001 - transport-level loss
                return StreamOutcome(rid, "lost", f"{type(exc).__name__}: {exc}")

        with concurrent.futures.ThreadPoolExecutor(concurrency) as ex:
            return list(ex.map(one, range(n)))

    def complete(self, prompt: str, rid: str, session: str | None = None,
                 max_tokens: int = 8, timeout: float = 30.0,
                 ) -> tuple[StreamOutcome, str]:
        """One completion with optional session affinity; returns the
        classified outcome plus the generated text (so a follow-up turn
        can extend the conversation — the ByteTokenizer is prefix-stable,
        so ``prompt + text`` re-hashes to the same block chain)."""
        headers = {"x-request-id": rid}
        if session is not None:
            headers["x-session-id"] = session
        try:
            r = http_json(self.base + "/v1/completions", {
                "model": "tiny-llama", "prompt": prompt,
                "max_tokens": max_tokens, "ignore_eos": True,
            }, timeout=timeout, headers=headers)
            choice = r["choices"][0]
            fr = choice.get("finish_reason")
            if fr:
                return StreamOutcome(rid, "finished", fr), choice.get("text") or ""
            return StreamOutcome(rid, "lost", "no finish_reason"), ""
        except urllib.error.HTTPError as exc:
            return StreamOutcome(rid, "error", f"http {exc.code}"), ""
        except Exception as exc:  # noqa: BLE001 - transport-level loss
            return StreamOutcome(rid, "lost", f"{type(exc).__name__}: {exc}"), ""


@dataclass
class ScenarioResult:
    name: str
    report: InvariantReport
    outcomes: list[StreamOutcome]
    seed: int | None = None

    def to_dict(self) -> dict:
        return {"name": self.name, "seed": self.seed,
                "report": self.report.to_dict(),
                "outcomes": [o.to_dict() for o in self.outcomes]}


def _finish(name: str, fleet: MockerFleet,
            outcomes: list[StreamOutcome],
            seed: int | None = None,
            require_shed_zero: bool = False,
            aggregator_text: str | None = None) -> ScenarioResult:
    """Shared epilogue: drain, then run every fleet-level invariant."""
    checker = InvariantChecker()
    checker.check_streams(outcomes)
    stats = fleet.wait_drained()
    checker.check_block_leaks(stats)
    checker.check_metrics_balance(fleet.metrics_text())
    if aggregator_text is not None:
        checker.check_fleet_rollup(aggregator_text)
    if require_shed_zero:
        from dynamo_tpu.chaos.invariants import metric_sum, parse_prometheus

        shed = metric_sum(parse_prometheus(fleet.metrics_text()),
                          "dynamo_qos_rejected_total")
        if shed:
            checker.report.fail(f"unexpected shedding: {shed:g} rejected")
    return ScenarioResult(name, checker.finish(), outcomes, seed=seed)


# ---------------------------------------------------------------------------
# Scenarios. Each takes a seed so the chaos-plan-driven ones replay exactly.
# ---------------------------------------------------------------------------

def scenario_smoke(seed: int = 1234) -> ScenarioResult:
    """Tier-1 smoke (<30s): inject transient dispatch errors + delays into
    every worker via a seeded plan; Migration must absorb them all."""
    plan = ChaosPlan.from_dict({"seed": seed, "rules": [
        # A burst of retryable dispatch failures...
        {"point": "worker.dispatch", "kind": "error", "rate": 0.3, "count": 4},
        # ...plus jitter on the mocker step loop (never fatal).
        {"point": "mocker.step", "kind": "delay", "rate": 0.05,
         "delay_s": 0.01},
    ]})
    cfg = FleetConfig(workers=2, chaos_plan=plan, chaos_seed=seed)
    with MockerFleet(cfg) as fleet:
        outcomes = fleet.drive_load(n=10, concurrency=4)
        return _finish("smoke", fleet, outcomes, seed=seed)


def scenario_worker_kill(seed: int = 1234) -> ScenarioResult:
    """Kill one worker mid-decode (chaos kind=kill after a few dispatches);
    migration re-dispatches onto the survivor, no stream is lost."""
    plan = ChaosPlan.from_dict({"seed": seed, "rules": [
        # the 3rd dispatch on whichever worker gets there first dies hard
        {"point": "worker.dispatch", "kind": "kill", "rate": 1.0,
         "count": 1, "after": 2},
    ]})
    cfg = FleetConfig(workers=2, chaos_plan=plan, chaos_seed=seed,
                      lease_ttl_s=3.0, speedup_ratio=10.0)
    with MockerFleet(cfg) as fleet:
        outcomes = fleet.drive_load(n=10, max_tokens=24, concurrency=3,
                                    timeout=60.0, interval_s=0.3)
        return _finish("worker_kill", fleet, outcomes, seed=seed)


def scenario_coordinator_partition(seed: int = 1234) -> ScenarioResult:
    """Kill + restart the coordinator mid-serving: workers re-register,
    frontend watches reset+replay, requests succeed throughout recovery."""
    cfg = FleetConfig(workers=2, lease_ttl_s=3.0)
    with MockerFleet(cfg) as fleet:
        pre = fleet.drive_load(n=4, concurrency=2)
        fleet.coordinator.stop()
        time.sleep(1.0)
        fleet.coordinator = Proc(
            ["-m", "dynamo_tpu.transports.coordinator", "--host", "127.0.0.1",
             "--port", str(fleet.coord_port)], name="coordinator2").start()
        fleet.coordinator.wait_for_line("COORDINATOR_READY", 20)
        # data-plane connections survive the partition; serving continues
        # while control-plane state is re-declared
        mid = fleet.drive_load(n=4, concurrency=2, timeout=60.0)
        deadline = time.time() + 30
        while time.time() < deadline:
            try:
                if http_json(fleet.base + "/v1/models")["data"]:
                    break
            except Exception:
                pass
            time.sleep(0.5)
        post = fleet.drive_load(n=4, concurrency=2, timeout=60.0)
        return _finish("coordinator_partition", fleet, pre + mid + post,
                       seed=seed)


def scenario_lease_expiry_storm(seed: int = 1234) -> ScenarioResult:
    """Drop every worker's lease keepalives (chaos on transports.keepalive)
    with a short TTL: leases expire in waves, instances vanish via
    prefix-watch DELETEs, then re-register on the runtime's reconnect
    path. Requests riding through the storm must all terminate."""
    plan = ChaosPlan.from_dict({"seed": seed, "rules": [
        # every keepalive for ~2 TTLs fails, then the storm passes
        {"point": "transports.keepalive", "kind": "error", "rate": 1.0,
         "count": 4},
    ]})
    cfg = FleetConfig(workers=2, chaos_plan=plan, chaos_seed=seed,
                      lease_ttl_s=2.0)
    with MockerFleet(cfg) as fleet:
        outcomes = fleet.drive_load(n=12, concurrency=3, timeout=60.0,
                                    interval_s=0.5)
        # give re-registration time to settle before the drain check
        time.sleep(3.0)
        return _finish("lease_expiry_storm", fleet, outcomes, seed=seed)


def scenario_slow_rank_stall(seed: int = 1234) -> ScenarioResult:
    """One fleet under heavy per-step delay injection (the slow-rank/
    straggler shape): throughput drops but nothing times out, sheds, or
    leaks — slowness must degrade latency only."""
    plan = ChaosPlan.from_dict({"seed": seed, "rules": [
        {"point": "mocker.step", "kind": "delay", "rate": 0.5,
         "delay_s": 0.05},
    ]})
    cfg = FleetConfig(workers=2, chaos_plan=plan, chaos_seed=seed)
    with MockerFleet(cfg) as fleet:
        outcomes = fleet.drive_load(n=8, max_tokens=16, concurrency=4,
                                    timeout=60.0)
        return _finish("slow_rank_stall", fleet, outcomes, seed=seed,
                       require_shed_zero=True)


def scenario_aggregator_partition(seed: int = 1234) -> ScenarioResult:
    """Scrape targets dying/partitioned mid-interval: the aggregator must
    degrade the dead target to stale-labeled data with zero crashes while
    the rest of the fleet stays fresh, count every failed scrape in
    ``dynamo_fleet_scrape_errors_total``, and — after the worker comes
    back — its fleet qos_admitted rollup must re-balance against the
    terminal statuses."""
    agg_plan = ChaosPlan.from_dict({"seed": seed, "rules": [
        # a burst of injected scrape faults on top of the real partition
        {"point": "obs.fleet.scrape", "kind": "error", "rate": 0.2,
         "count": 6},
    ]})
    cfg = FleetConfig(
        workers=2, aggregator=True, speedup_ratio=10.0,
        scrape_interval_s=0.3, staleness_ttl_s=1.5,
        aggregator_env={"DYN_CHAOS_PLAN": json.dumps(agg_plan.to_dict()),
                        "DYN_CHAOS_SEED": str(seed)})
    with MockerFleet(cfg) as fleet:
        # discovery without static target lists: frontend + both workers
        fleet.wait_fleet_fresh(3)
        pre = fleet.drive_load(n=6, concurrency=3)

        victim = fleet.workers[1]
        victim.kill_hard()
        # the dead target must flip to stale without dropping the others
        deadline = time.time() + 20
        degraded: dict = {}
        while time.time() < deadline:
            degraded = fleet.fleet_debug()
            fresh = [t for t in degraded.get("targets", []) if t["fresh"]]
            stale = [t for t in degraded.get("targets", []) if not t["fresh"]]
            if stale and len(fresh) >= 2:
                break
            time.sleep(0.2)
        mid = fleet.drive_load(n=4, concurrency=2, timeout=60.0)

        fleet.workers[1] = fleet.start_worker(1)
        fleet.workers[1].wait_for_line("WORKER_READY", 30)
        fleet.wait_fleet_fresh(3)
        post = fleet.drive_load(n=4, concurrency=2, timeout=60.0)

        # the rollup is a scrape-time snapshot: wait for the sweep after
        # the last terminal status lands before judging the balance
        fleet.wait_drained()
        agg_text = ""
        deadline = time.time() + 15
        while time.time() < deadline:
            agg_text = fleet.aggregator_metrics_text()
            probe = InvariantChecker()
            probe.check_fleet_rollup(agg_text)
            if probe.report.passed:
                break
            time.sleep(max(cfg.scrape_interval_s, 0.2))

        res = _finish("aggregator_partition", fleet, pre + mid + post,
                      seed=seed, aggregator_text=agg_text)
        stale_seen = [t for t in degraded.get("targets", [])
                      if not t.get("fresh")]
        if not stale_seen:
            res.report.fail("dead worker never degraded to stale")
        else:
            res.report.ok("partition_degraded_to_stale")
        from dynamo_tpu.chaos.invariants import metric_sum, parse_prometheus

        errs = metric_sum(parse_prometheus(agg_text),
                          "dynamo_fleet_scrape_errors_total")
        if errs <= 0:
            res.report.fail("dynamo_fleet_scrape_errors_total never moved")
        else:
            res.report.ok("scrape_errors_counted")
        if not fleet.aggregator.alive():
            res.report.fail("aggregator crashed during the partition:\n"
                            + fleet.aggregator.logs()[-2000:])
        else:
            res.report.ok("aggregator_survived")
        return res


def _check_orphan_pins(res: ScenarioResult, stats: dict) -> None:
    """Mem-ledger leak audit (obs/mem_ledger.py): every worker publishing
    a ``mem`` stats block must report zero orphan pins at its last audit —
    a pin whose owner id no longer exists anywhere is a leaked device
    reference no drain can reclaim."""
    orphans: dict[str, int] = {}
    checked = 0
    for model, s in stats.items():
        for wid, m in (s.get("workers") or {}).items():
            if not isinstance(m, dict):
                continue
            mem = m.get("mem") or {}
            if not mem.get("enabled"):
                continue
            checked += 1
            n = int(mem.get("orphan_pins", 0) or 0)
            if n:
                orphans[f"{model}/{wid}"] = n
    res.report.details["orphan_pins_workers_checked"] = checked
    if orphans:
        res.report.fail(f"mem-ledger audit found orphan pins: {orphans}")
    elif checked:
        res.report.ok("orphan_pins_zero")


def scenario_retire_under_load(seed: int = 1234,
                               quick: bool = False) -> ScenarioResult:
    """Drain-aware retirement end to end (runtime/drain.py): a worker
    holding retained sessions AND live streams is retired while a fresh
    replica serves on. The drain must lose zero streams, evacuate every
    session to the G4 store, and turn N+1 of each session must land on
    the survivor as a warm resume (remote record hit), not a recompute.
    ``quick=True`` is the sub-30s tier-1 smoke shape."""
    n_sessions = 2 if quick else 4
    n_bg = 3 if quick else 8
    cfg = FleetConfig(
        workers=1, kv_store=True, speedup_ratio=50.0, lease_ttl_s=3.0,
        # TTL far beyond the scenario: retention must survive until the
        # drain evacuates it (pop_oldest ignores TTL); both workers drain
        # at the end, so no sweep is needed for the leak check either.
        worker_args=["--session-ttl", "120",
                     "--drain-deadline", "6" if quick else "12"])
    with MockerFleet(cfg) as fleet:
        outcomes: list[StreamOutcome] = []
        turn1: dict[str, str] = {}
        # Turn 1: every session lands on worker0 (the only worker).
        for s in range(n_sessions):
            sid = f"sess-{s}"
            prompt = f"retire scenario session {s} context " * 3
            o, text = fleet.complete(prompt, f"turn1-{s}", session=sid)
            outcomes.append(o)
            turn1[sid] = prompt + text

        # Scale up, then retire worker0 mid-traffic.
        fleet.workers.append(fleet.start_worker(1))
        fleet.workers[1].wait_for_line("WORKER_READY", 30)
        victim = fleet.workers[0]
        bg_out: list[StreamOutcome] = []
        bg = threading.Thread(target=lambda: bg_out.extend(
            fleet.drive_load(n=n_bg, max_tokens=16, concurrency=2,
                             timeout=60.0)))
        bg.start()
        time.sleep(0.2)  # let some streams land on the victim first
        victim.proc.send_signal(signal.SIGTERM)
        drained_line = victim.wait_for_line("WORKER_DRAINED", 40)
        bg.join(90)
        outcomes.extend(bg_out)
        victim.proc.wait(10)

        # Turn 2: the retired worker is gone — each session's next turn
        # must resume warm on the survivor from the evacuated record.
        for s in range(n_sessions):
            sid = f"sess-{s}"
            o, _ = fleet.complete(turn1[sid] + " and then", f"turn2-{s}",
                                  session=sid, timeout=60.0)
            outcomes.append(o)
        # the survivor's resume counters reach /engine_stats on its next
        # publish tick — poll briefly instead of racing one snapshot
        stats: dict = {}
        deadline = time.time() + 10
        while time.time() < deadline:
            stats = fleet.engine_stats()
            probe = InvariantChecker()
            probe.check_warm_resume(stats, minimum=n_sessions)
            if probe.report.passed:
                break
            time.sleep(0.25)

        # Retire the survivor too: its retained turn-2 pins evacuate and
        # release, so the leak check sees a fully quiesced fleet.
        survivor = fleet.workers[1]
        survivor.proc.send_signal(signal.SIGTERM)
        survivor_line = survivor.wait_for_line("WORKER_DRAINED", 40)
        survivor.proc.wait(10)

        res = _finish("retire_under_load", fleet, outcomes, seed=seed)
        warm = InvariantChecker()
        warm.report = res.report
        warm.check_warm_resume(stats, minimum=n_sessions)
        _check_orphan_pins(res, stats)

        def parse_drained(line: str) -> dict:
            try:
                return json.loads(line.split("WORKER_DRAINED", 1)[1].strip())
            except Exception:
                return {}

        report = parse_drained(drained_line)
        res.report.details["drain_report"] = report
        # Routers forget retired workers, so exit-time occupancy from the
        # terminal reports is the leak check for the two drained processes.
        leaked = [r for r in (report, parse_drained(survivor_line))
                  if r.get("final_kv_usage", 0) > 1e-9
                  or r.get("final_num_running", 0)]
        if leaked:
            res.report.fail(f"retired worker exited with pinned KV: {leaked}")
        else:
            res.report.ok("retired_workers_quiesced")
        if report.get("state") != "done":
            res.report.fail(f"drain did not complete: {report}")
        else:
            res.report.ok("drain_completed")
        if report.get("evacuated_sessions", 0) < n_sessions:
            res.report.fail(
                f"evacuated {report.get('evacuated_sessions', 0)} of "
                f"{n_sessions} retained sessions")
        else:
            res.report.ok("all_sessions_evacuated")
        if victim.proc.returncode != 0:
            res.report.fail(
                f"retired worker exited rc={victim.proc.returncode} "
                "(SIGKILL escalation?)")
        else:
            res.report.ok("retired_worker_clean_exit")
        return res


def scenario_worker_kill_mid_decode(seed: int = 1234,
                                    quick: bool = False) -> ScenarioResult:
    """Crash-consistent stream checkpoints end to end (kvbm/stream_ckpt.py):
    a worker is SIGKILLed at a seeded decode step while a stream is
    mid-generation. The stream must NOT be lost: Migration finds the
    checkpoint record in the G4 store and resumes on a fresh replica,
    token-identical to an unkilled run (the mocker's md5 token stream
    depends only on (request_id, index), so re-running the same request id
    unkilled is an exact control), recomputing at most one checkpoint
    interval. ``quick=True`` is the sub-30s tier-1 smoke shape."""
    kill_after = 8 if quick else 12
    ckpt_blocks = 1           # --stream-ckpt-blocks (base cadence)
    interval_blocks = ckpt_blocks * 2   # standard-priority QoS degradation
    plan = ChaosPlan.from_dict({"seed": seed, "rules": [
        # SIGKILL the victim at a seeded decode step: hit 1 is the
        # admission+prefill iteration, every later hit decodes one token.
        {"point": "mocker.step", "kind": "kill", "rate": 1.0,
         "count": 1, "after": kill_after},
    ]})
    cfg = FleetConfig(workers=1, kv_store=True, lease_ttl_s=3.0,
                      speedup_ratio=50.0, chaos_plan=plan, chaos_seed=seed,
                      worker_args=["--stream-ckpt-blocks", str(ckpt_blocks),
                                   # keep token ids byte-decodable so the
                                   # resumed-vs-control text check is non-vacuous
                                   "--vocab-size", "260"])
    with MockerFleet(cfg) as fleet:
        victim = fleet.workers[0]
        prompt = "ckpt victim stream context " * 3
        max_tokens = 24
        got: list[tuple[StreamOutcome, str]] = []
        t = threading.Thread(target=lambda: got.append(
            fleet.complete(prompt, "ckpt-victim", max_tokens=max_tokens,
                           timeout=90.0)))
        t.start()
        victim.proc.wait(30)  # the seeded SIGKILL mid-decode

        # Fresh replica WITHOUT the kill plan: the resume target.
        fleet.cfg.chaos_plan = None
        fleet.workers.append(fleet.start_worker(1))
        fleet.workers[1].wait_for_line("WORKER_READY", 30)
        bg: list[StreamOutcome] = []
        if not quick:
            bg = fleet.drive_load(n=6, max_tokens=8, concurrency=2,
                                  timeout=60.0)
        t.join(90)
        outcomes = ([got[0][0]] if got
                    else [StreamOutcome("ckpt-victim", "lost", "no response")])
        resumed_text = got[0][1] if got else ""
        # Control: the SAME request id, unkilled. Identical output proves
        # the resumed stream was token-exact, not merely completed.
        ctrl_o, ctrl_text = fleet.complete(prompt, "ckpt-victim",
                                           max_tokens=max_tokens,
                                           timeout=60.0)
        outcomes.append(ctrl_o)
        outcomes.extend(bg)

        # The survivor's resume counters reach /engine_stats on its next
        # publish tick — poll briefly instead of racing one snapshot.
        stats: dict = {}
        deadline = time.time() + 10
        while time.time() < deadline:
            stats = fleet.engine_stats()
            probe = InvariantChecker()
            probe.check_ckpt_resume(stats, minimum=1)
            if probe.report.passed:
                break
            time.sleep(0.25)
        frontend_logs = fleet.frontend.logs()

        res = _finish("worker_kill_mid_decode", fleet, outcomes, seed=seed)
        ck = InvariantChecker()
        ck.report = res.report
        ck.check_ckpt_resume(stats, minimum=1)
        _check_orphan_pins(res, stats)
        res.report.details["ckpt"] = {
            "resumed_text": resumed_text, "control_text": ctrl_text,
            "kill_after": kill_after, "interval_blocks": interval_blocks}
        if not resumed_text or resumed_text != ctrl_text:
            res.report.fail(
                "resumed stream output differs from the unkilled control "
                f"run: {resumed_text!r} vs {ctrl_text!r}")
        else:
            res.report.ok("resumed_output_identical")
        recomputed = sum(
            int(m.get("stream_ckpt_resume_recomputed", 0) or 0)
            for s in stats.values()
            for m in (s.get("workers") or {}).values()
            if isinstance(m, dict))
        # One interval of recompute, plus the partial trailing block that
        # by construction can never be checkpointed (only FULL committed
        # blocks flush).
        bound = (interval_blocks + 1) * cfg.block_size
        res.report.details["ckpt"]["recomputed_tokens"] = recomputed
        # bg streams run unkilled (resume count 1), so the whole recompute
        # budget belongs to the victim stream.
        if recomputed > bound:
            res.report.fail(
                f"checkpoint resume recomputed {recomputed} tokens, more "
                f"than one interval (bound {bound})")
        else:
            res.report.ok("recompute_bounded_by_interval")
        if "quarantined" in frontend_logs:
            res.report.ok("killed_instance_quarantined")
        else:
            res.report.fail(
                "frontend never quarantined the killed instance")
        return res


def scenario_scale_during_partition(seed: int = 1234) -> ScenarioResult:
    """Scale-down while the coordinator is PARTITIONED away: the retiring
    worker cannot delete its membership keys or write its status — the
    drain must still complete locally within its bounded windows and exit
    rc 0 (no SIGKILL), and because every registration is lease-bound the
    dead worker's keys vanish on lease expiry: never a half-deregistered
    ghost. Traffic mid-partition migrates off the refusing worker."""
    cfg = FleetConfig(workers=2, lease_ttl_s=3.0, speedup_ratio=50.0,
                      worker_args=["--drain-deadline", "6"])
    with MockerFleet(cfg) as fleet:
        pre = fleet.drive_load(n=4, concurrency=2)
        # Published snapshots must show idle BEFORE the partition: during
        # it no publishes flow, so the frontend's last view of the retiring
        # worker has to be a quiesced one.
        fleet.wait_drained()

        fleet.coordinator.kill_hard()
        victim = fleet.workers[1]
        victim.proc.send_signal(signal.SIGTERM)
        # Streams the stale frontend still routes at the draining worker
        # are refused (typed ERR) and migrate to the survivor.
        mid = fleet.drive_load(n=4, concurrency=2, timeout=60.0)
        drained_line = victim.wait_for_line("WORKER_DRAINED", 45)
        victim.proc.wait(15)

        fleet.coordinator = Proc(
            ["-m", "dynamo_tpu.transports.coordinator", "--host", "127.0.0.1",
             "--port", str(fleet.coord_port)], name="coordinator2").start()
        fleet.coordinator.wait_for_line("COORDINATOR_READY", 20)
        deadline = time.time() + 30
        while time.time() < deadline:
            try:
                if http_json(fleet.base + "/v1/models")["data"]:
                    break
            except Exception:
                pass
            time.sleep(0.5)
        post = fleet.drive_load(n=4, concurrency=2, timeout=60.0)

        res = _finish("scale_during_partition", fleet, pre + mid + post,
                      seed=seed)
        try:
            report = json.loads(
                drained_line.split("WORKER_DRAINED", 1)[1].strip())
        except Exception:
            report = {}
        res.report.details["drain_report"] = report
        if report.get("state") not in ("done", "aborted"):
            res.report.fail(f"drain neither completed nor cleanly "
                            f"aborted: {report}")
        else:
            res.report.ok("drain_bounded_under_partition")
        if victim.proc.returncode != 0:
            res.report.fail(
                f"partitioned drain exited rc={victim.proc.returncode} "
                "(escalation instead of a bounded local drain)")
        else:
            res.report.ok("clean_exit_under_partition")
        return res


SCENARIOS: dict[str, Callable[[int], ScenarioResult]] = {
    "smoke": scenario_smoke,
    "worker_kill": scenario_worker_kill,
    "coordinator_partition": scenario_coordinator_partition,
    "lease_expiry_storm": scenario_lease_expiry_storm,
    "slow_rank_stall": scenario_slow_rank_stall,
    "aggregator_partition": scenario_aggregator_partition,
    "retire_under_load": scenario_retire_under_load,
    "retire_under_load_smoke": lambda seed=1234: scenario_retire_under_load(
        seed, quick=True),
    "worker_kill_mid_decode": scenario_worker_kill_mid_decode,
    "worker_kill_mid_decode_smoke": lambda seed=1234:
        scenario_worker_kill_mid_decode(seed, quick=True),
    "scale_during_partition": scenario_scale_during_partition,
}


def run_scenario(name: str, seed: int = 1234) -> ScenarioResult:
    try:
        fn = SCENARIOS[name]
    except KeyError:
        raise ValueError(
            f"unknown scenario {name!r} (one of {sorted(SCENARIOS)})")
    log.info("chaos scenario %s (seed=%d)", name, seed)
    return fn(seed)
