"""Deterministic fault-injection engine behind the named fault points.

FoundationDB-style simulation testing needs one property above all:
**replayability** — the same seed must produce the same fault sequence.
Two design choices buy that here:

* one RNG per fault point, derived from ``sha256(seed, point_name)``.
  A point's fault schedule depends only on its own hit sequence, never on
  how calls at OTHER points interleave (worker threads, shard-server
  threads, and asyncio tasks all hit points concurrently — a shared RNG
  would make the schedule depend on thread scheduling).
* decisions happen under one lock and are appended to an ordered
  ``injection log``; tests replay a plan twice and assert the logs are
  identical.

Fault kinds map onto errors the stack already recovers from, so chaos
exercises the REAL recovery paths rather than synthetic ones:

* ``delay``       sleep ``delay_s`` (sync or async per call site)
* ``error``       raise :class:`ChaosInjectedError` (a ``ConnectionError``
                  subclass — retryable by Migration / ShardClient / kvbm
                  circuit breaker, like any transport fault)
* ``disconnect``  raise ``ConnectionResetError`` (peer-died shape)
* ``hang``        sleep ``hang_s`` (wedge: flushed out by canaries and
                  client timeouts, not by an exception)
* ``kill``        SIGKILL the current process (crash, not clean shutdown)
"""

from __future__ import annotations

import fnmatch
import hashlib
import os
import random
import signal
import threading
from dataclasses import dataclass
from typing import Any, Mapping

from dynamo_tpu.chaos.plan import ChaosPlan
from dynamo_tpu.utils.logging import get_logger

log = get_logger("chaos")


class ChaosInjectedError(ConnectionError):
    """A fault injected by the chaos engine (kind=error).

    Subclasses ``ConnectionError`` so every retry/migration path that
    handles a real transport fault handles an injected one identically.
    """

    def __init__(self, point: str, message: str = ""):
        super().__init__(message or f"chaos: injected error at {point}")
        self.point = point


@dataclass(frozen=True)
class Injection:
    """One injected fault, as recorded in the engine's ordered log."""

    seq: int            # global order of injection within this engine
    point: str
    kind: str
    rule_index: int     # which plan rule fired
    hit: int            # the point-local hit number that drew the fault

    def key(self) -> tuple:
        return (self.seq, self.point, self.kind, self.rule_index, self.hit)


class ChaosEngine:
    """Interprets a :class:`ChaosPlan` deterministically.

    Thread-safe: fault points are hit from asyncio tasks, engine-core
    threads, and shard-server handler threads of the same process.
    """

    def __init__(self, plan: ChaosPlan):
        self.plan = plan
        self._lock = threading.Lock()
        self._rngs: dict[str, random.Random] = {}
        self._hits: dict[str, int] = {}          # point -> hits seen
        self._injected: dict[int, int] = {}      # rule index -> times fired
        self._seq = 0
        self.log: list[Injection] = []

    def _rng(self, point: str) -> random.Random:
        rng = self._rngs.get(point)
        if rng is None:
            digest = hashlib.sha256(
                f"{self.plan.seed}:{point}".encode()).digest()
            rng = self._rngs[point] = random.Random(
                int.from_bytes(digest[:8], "big"))
        return rng

    def decide(self, point: str, ctx: Mapping[str, Any]) -> Injection | None:
        """Record a hit at ``point`` and return the fault to apply, if any.

        Exactly one RNG draw per hit (whether or not any rule is eligible)
        keeps a point's schedule a pure function of (seed, hit number) —
        adding a bounded rule can't shift the faults of a later rule.
        """
        with self._lock:
            hit = self._hits[point] = self._hits.get(point, 0) + 1
            draw = self._rng(point).random()
            for idx, rule in enumerate(self.plan.rules):
                if not fnmatch.fnmatchcase(point, rule.point):
                    continue
                if rule.match and any(ctx.get(k) != v
                                      for k, v in rule.match.items()):
                    continue
                if hit <= rule.after:
                    continue
                if (rule.count is not None
                        and self._injected.get(idx, 0) >= rule.count):
                    continue
                if draw >= rule.rate:
                    continue
                self._injected[idx] = self._injected.get(idx, 0) + 1
                inj = Injection(seq=self._seq, point=point, kind=rule.kind,
                                rule_index=idx, hit=hit)
                self._seq += 1
                self.log.append(inj)
                return inj
        return None

    def rule_for(self, inj: Injection):
        return self.plan.rules[inj.rule_index]

    def log_keys(self) -> list[tuple]:
        """The injected-fault sequence as comparable tuples (replay tests
        assert two runs of the same plan+seed produce equal lists)."""
        with self._lock:
            return [inj.key() for inj in self.log]

    def apply_terminal(self, inj: Injection) -> None:
        """Raise/kill for a decided fault. Sleep kinds are applied by the
        caller (sync vs async call sites need different sleeps)."""
        rule = self.rule_for(inj)
        if inj.kind == "error":
            raise ChaosInjectedError(inj.point, rule.message)
        if inj.kind == "disconnect":
            raise ConnectionResetError(
                rule.message or f"chaos: injected disconnect at {inj.point}")
        if inj.kind == "kill":
            log.warning("chaos: killing process at point %s (seq %d)",
                        inj.point, inj.seq)
            # SIGKILL, not sys.exit: a crash leaves no chance for cleanup
            # handlers to mask the failure mode under test.
            os.kill(os.getpid(), signal.SIGKILL)
