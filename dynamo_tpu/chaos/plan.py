"""ChaosPlan: the declarative fault-injection DSL.

A plan is a seed plus an ordered list of rules. Each rule targets one or
more named fault points (glob over the point name), picks a fault kind,
and bounds how often it fires:

```yaml
seed: 1234
rules:
  - point: worker.dispatch        # glob: "disagg.*" matches stage/pull/import
    kind: error                   # delay | error | disconnect | hang | kill
    rate: 0.25                    # per-hit injection probability
    count: 3                      # stop after this many injections (null = ∞)
    after: 2                      # let the first N matching hits through
    delay_s: 0.05                 # sleep length for kind=delay
    match: {endpoint: generate}   # ctx equality predicate (all keys must ==)
```

Interpretation is deterministic: the injector derives one RNG per fault
point from ``sha256(seed, point)``, so the same plan + seed replays the
identical fault sequence regardless of what other points do (see
injector.py). Plans load from YAML/JSON files, inline JSON strings, or
plain dicts — the env var ``DYN_CHAOS_PLAN`` accepts any of the three.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Mapping

FAULT_KINDS = ("delay", "error", "disconnect", "hang", "kill")


@dataclass
class ChaosRule:
    """One fault-injection rule; see the module docstring for field docs."""

    point: str                      # glob over fault-point names
    kind: str                       # one of FAULT_KINDS
    rate: float = 1.0               # per-hit injection probability
    count: int | None = None        # max injections (None = unbounded)
    after: int = 0                  # skip the first N matching hits
    delay_s: float = 0.05           # sleep for kind=delay
    hang_s: float = 300.0           # sleep for kind=hang
    message: str = ""               # carried on the raised error
    match: dict[str, Any] = field(default_factory=dict)

    def __post_init__(self) -> None:
        if self.kind not in FAULT_KINDS:
            raise ValueError(
                f"unknown fault kind {self.kind!r} (one of {FAULT_KINDS})")
        if not 0.0 <= self.rate <= 1.0:
            raise ValueError(f"rate must be in [0, 1], got {self.rate}")

    def to_dict(self) -> dict:
        d: dict[str, Any] = {"point": self.point, "kind": self.kind,
                             "rate": self.rate}
        if self.count is not None:
            d["count"] = self.count
        if self.after:
            d["after"] = self.after
        if self.kind == "delay":
            d["delay_s"] = self.delay_s
        if self.kind == "hang":
            d["hang_s"] = self.hang_s
        if self.message:
            d["message"] = self.message
        if self.match:
            d["match"] = dict(self.match)
        return d

    @classmethod
    def from_dict(cls, d: Mapping[str, Any]) -> "ChaosRule":
        known = {"point", "kind", "rate", "count", "after", "delay_s",
                 "hang_s", "message", "match"}
        extra = set(d) - known
        if extra:
            raise ValueError(f"unknown ChaosRule keys: {sorted(extra)}")
        return cls(
            point=str(d["point"]),
            kind=str(d["kind"]),
            rate=float(d.get("rate", 1.0)),
            count=None if d.get("count") is None else int(d["count"]),
            after=int(d.get("after", 0)),
            delay_s=float(d.get("delay_s", 0.05)),
            hang_s=float(d.get("hang_s", 300.0)),
            message=str(d.get("message", "")),
            match=dict(d.get("match") or {}),
        )


@dataclass
class ChaosPlan:
    """A seed + ordered rules. Rules are evaluated in order per hit; the
    first eligible rule injects (one fault per hit, like firewall rules)."""

    seed: int = 0
    rules: list[ChaosRule] = field(default_factory=list)

    def to_dict(self) -> dict:
        return {"seed": self.seed, "rules": [r.to_dict() for r in self.rules]}

    @classmethod
    def from_dict(cls, d: Mapping[str, Any]) -> "ChaosPlan":
        return cls(seed=int(d.get("seed", 0)),
                   rules=[ChaosRule.from_dict(r) for r in d.get("rules", [])])

    @classmethod
    def load(cls, spec: "str | Path | Mapping[str, Any]") -> "ChaosPlan":
        """Load from a dict, a YAML/JSON file path, or an inline JSON
        string (the shapes ``DYN_CHAOS_PLAN`` accepts)."""
        if isinstance(spec, Mapping):
            return cls.from_dict(spec)
        text = str(spec).strip()
        if text.startswith("{"):
            return cls.from_dict(json.loads(text))
        path = Path(text)
        raw = path.read_text()
        try:
            import yaml

            data = yaml.safe_load(raw)
        except ImportError:  # pragma: no cover - yaml ships in the image
            data = json.loads(raw)
        if not isinstance(data, Mapping):
            raise ValueError(f"chaos plan {path} is not a mapping")
        return cls.from_dict(data)
