"""Named fault points with deterministic, seeded fault injection.

Call sites thread a *fault point* through every layer that can fail:

    from dynamo_tpu import chaos
    ...
    await chaos.ainject("transports.request", op=body.get("op"))   # async
    chaos.inject("disagg.pull", addr=addr)                        # sync

With chaos disabled (the default) both calls are a module-level no-op:
one global ``None`` check, no allocation, no locking — safe to leave in
production paths. Chaos turns on when a process is started with
``DYN_CHAOS_PLAN`` (YAML/JSON file path or inline JSON; optionally
``DYN_CHAOS_SEED`` overriding the plan's seed) or when a harness calls
:func:`configure` directly. See docs/CHAOS.md for the fault-point
catalog, the plan DSL, and the seed-replay workflow.
"""

from __future__ import annotations

import os
import time
from typing import Any

from dynamo_tpu.chaos.injector import ChaosEngine, ChaosInjectedError, Injection
from dynamo_tpu.chaos.plan import FAULT_KINDS, ChaosPlan, ChaosRule

__all__ = [
    "FAULT_KINDS", "ChaosEngine", "ChaosInjectedError", "ChaosPlan",
    "ChaosRule", "Injection", "ainject", "configure", "configure_from_env",
    "enabled", "engine", "inject", "injection_log", "reset",
]

SEED_ENV = "DYN_CHAOS_SEED"
PLAN_ENV = "DYN_CHAOS_PLAN"

_engine: ChaosEngine | None = None


def configure(plan: "ChaosPlan | dict | str", seed: int | None = None) -> ChaosEngine:
    """Enable chaos for this process. ``plan`` is a ChaosPlan, a dict, a
    file path, or inline JSON; ``seed`` (if given) overrides the plan's."""
    global _engine
    if not isinstance(plan, ChaosPlan):
        plan = ChaosPlan.load(plan)
    if seed is not None:
        plan = ChaosPlan(seed=seed, rules=plan.rules)
    _engine = ChaosEngine(plan)
    return _engine


def configure_from_env(env: "dict[str, str] | None" = None) -> ChaosEngine | None:
    """Enable chaos iff DYN_CHAOS_PLAN is set (DYN_CHAOS_SEED optional)."""
    e = os.environ if env is None else env
    spec = e.get(PLAN_ENV)
    if not spec:
        return None
    seed_s = e.get(SEED_ENV)
    return configure(spec, seed=int(seed_s) if seed_s else None)


def reset() -> None:
    """Disable chaos (tests)."""
    global _engine
    _engine = None


def enabled() -> bool:
    return _engine is not None


def engine() -> ChaosEngine | None:
    return _engine


def injection_log() -> list[tuple]:
    """Ordered (seq, point, kind, rule, hit) tuples injected so far."""
    return _engine.log_keys() if _engine is not None else []


def _record(inj: Injection) -> None:
    from dynamo_tpu.chaos.metrics import get_chaos_metrics

    get_chaos_metrics().record(inj.point, inj.kind)


def inject(point: str, **ctx: Any) -> None:
    """Synchronous fault point. No-op unless chaos is configured."""
    eng = _engine
    if eng is None:
        return
    inj = eng.decide(point, ctx)
    if inj is None:
        return
    _record(inj)
    rule = eng.rule_for(inj)
    if inj.kind == "delay":
        time.sleep(rule.delay_s)
        return
    if inj.kind == "hang":
        time.sleep(rule.hang_s)
        return
    eng.apply_terminal(inj)


async def ainject(point: str, **ctx: Any) -> None:
    """Async fault point: sleeps cooperatively. No-op unless configured."""
    eng = _engine
    if eng is None:
        return
    inj = eng.decide(point, ctx)
    if inj is None:
        return
    _record(inj)
    rule = eng.rule_for(inj)
    if inj.kind == "delay":
        import asyncio

        await asyncio.sleep(rule.delay_s)
        return
    if inj.kind == "hang":
        import asyncio

        await asyncio.sleep(rule.hang_s)
        return
    eng.apply_terminal(inj)


# Subprocesses (workers, frontends, coordinators spawned by the harness)
# opt in purely through the environment; reading two env vars once at
# import keeps the disabled path a plain module-global None check.
configure_from_env()
