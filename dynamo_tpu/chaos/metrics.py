"""Prometheus counter for injected faults (dynamo_chaos_injected_total).

Same install idiom as disagg/metrics.py: a module singleton backed by a
private registry until a process re-homes it into its runtime registry
(workers/frontends call ``install_chaos_metrics`` when chaos is enabled),
so injected faults show up on /metrics next to the symptoms they cause.
Name is cross-checked by tools/lint_metrics.py RECOVERY_METRICS.
"""

from __future__ import annotations

from dynamo_tpu.utils.metrics import MetricsRegistry


class ChaosMetrics:
    def __init__(self, registry: MetricsRegistry | None = None):
        self.bind(registry or MetricsRegistry())

    def bind(self, registry: MetricsRegistry) -> None:
        self.registry = registry
        self.injected = registry.counter(
            "chaos_injected_total",
            "Faults injected by the chaos engine, by fault point and kind")

    def record(self, point: str, kind: str) -> None:
        self.injected.inc(1, point=point, kind=kind)


_metrics: ChaosMetrics | None = None


def get_chaos_metrics() -> ChaosMetrics:
    global _metrics
    if _metrics is None:
        _metrics = ChaosMetrics()
    return _metrics


def install_chaos_metrics(registry: MetricsRegistry) -> ChaosMetrics:
    m = get_chaos_metrics()
    m.bind(registry)
    return m
