"""Session-sticky KV retention: keep a conversation's KV across turns.

A ``session.id`` annotation rides the OpenAI request (``x-session-id``
header or ``session_id`` body field) through preprocessing to the engine
(same wire pattern as qos/deadline.py). When a stream carrying one
finishes, the engine does NOT let its committed blocks fall straight to
the LRU inactive pool — it takes a session-owned reference on the chain
(:class:`SessionStore`), so turn N+1's admission-time prefix match finds
the whole previous context on device and prefills only the new suffix.

Retention is bounded three ways, all deterministic across multi-host
ranks (decisions derive from annotations, pool state, and the
leader-stamped step clock — never per-rank wall time):

* **TTL** (``EngineConfig.session_ttl``): an idle session's pins are
  released after this many seconds of step time;
* **pressure**: if waiting requests can't admit because session pins
  hold the pool, the oldest sessions are released first;
* **capacity**: at most ``max_sessions`` entries, LRU.

Releasing a pin demotes the blocks to the normal inactive LRU — still
matchable; with ``session_tiers`` the engine first write-throughs the
chain into the KVBM host/disk ladder so a later turn can re-import it
even after device eviction (kvbm/offload.py).

The ``dynamo_session_*`` Prometheus family below is cross-checked by
tools/lint_metrics.py SESSION_METRICS.
"""

from __future__ import annotations

from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Any, Mapping

from dynamo_tpu.obs.mem_ledger import get_mem_ledger
from dynamo_tpu.utils.metrics import MetricsRegistry

SESSION_KEY = "session.id"
SESSION_HEADER = "x-session-id"


def session_id_from(headers: Mapping[str, str] | None = None,
                    body: Mapping[str, Any] | None = None) -> str | None:
    """Frontend-side extraction: header wins over body, blanks are None."""
    sid = None
    if headers is not None:
        sid = headers.get(SESSION_HEADER)
    if sid is None and body is not None:
        sid = body.get("session_id")
    if sid is None:
        return None
    sid = str(sid).strip()
    return sid or None


def session_id_of(annotations: Mapping[str, Any] | None) -> str | None:
    """Engine/router-side read of the preprocessed annotation."""
    if not annotations:
        return None
    sid = annotations.get(SESSION_KEY)
    if sid is None:
        return None
    sid = str(sid).strip()
    return sid or None


class SessionMetrics:
    """The dynamo_session_* family (names cross-checked by
    tools/lint_metrics.py SESSION_METRICS)."""

    def __init__(self, registry: MetricsRegistry | None = None):
        self.bind(registry or MetricsRegistry())

    def bind(self, registry: MetricsRegistry) -> None:
        self.registry = registry
        self.lookups = registry.counter(
            "session_lookups",
            "Admitted requests carrying a session.id annotation")
        self.hits = registry.counter(
            "session_hits",
            "Session lookups that claimed a retained previous turn")
        self.avoided_tokens = registry.counter(
            "session_avoided_tokens",
            "Prompt tokens whose prefill was skipped on a session turn "
            "(measured prefix-match blocks at admission, not estimated)")
        self.retained_blocks = registry.gauge(
            "session_retained_blocks",
            "Device KV blocks currently pinned by session retention")
        self.active = registry.gauge(
            "session_active",
            "Sessions currently holding retained KV on this engine")
        self.expired = registry.counter(
            "session_expired",
            "Sessions released by the TTL sweep, pool pressure, or the "
            "capacity cap")
        self.demoted_blocks = registry.counter(
            "session_demoted_blocks",
            "Session blocks write-staged down the KVBM tier ladder when "
            "their pins were released")
        self.remote_resumes = registry.counter(
            "session_remote_resumes",
            "Session turns resumed from a drain-evacuated remote record "
            "(pull-to-warm on a surviving worker, runtime/drain.py)")


_metrics: SessionMetrics | None = None


def get_session_metrics() -> SessionMetrics:
    global _metrics
    if _metrics is None:
        _metrics = SessionMetrics()
    return _metrics


def install_session_metrics(registry: MetricsRegistry) -> SessionMetrics:
    """Re-home the singleton into a runtime registry (worker /metrics)."""
    m = get_session_metrics()
    m.bind(registry)
    return m


@dataclass
class SessionEntry:
    """One retained turn: the committed hash chain and the pins holding it."""

    seq_hashes: tuple[int, ...]
    pinned: list[int] = field(default_factory=list)
    tokens: int = 0
    last_used: float = 0.0


class SessionStore:
    """Engine-core-thread-only registry of session pins over a PrefixPool.

    Every pin this store takes is released through exactly one of
    :meth:`claim`, :meth:`pop_expired`, :meth:`pop_oldest`, or
    :meth:`release_all` — the zero-leaked-pins invariant the e2e/chaos
    tests assert by comparing ``pool.num_free`` against baseline.
    """

    def __init__(self, pool, *, ttl: float, max_sessions: int = 256):
        self.pool = pool
        self.ttl = ttl
        self.max_sessions = max_sessions
        self._entries: "OrderedDict[str, SessionEntry]" = OrderedDict()
        # Memory ledger (obs/mem_ledger.py): session-owner pin taxonomy.
        # Pins tag/untag exactly with _entries membership, so the audit's
        # live set is simply the store's current session ids.
        self._mled = get_mem_ledger()

    def session_ids(self) -> list[str]:
        """Live session ids (the mem-ledger audit's live set)."""
        return list(self._entries)

    def __len__(self) -> int:
        return len(self._entries)

    @property
    def pinned_blocks(self) -> int:
        return sum(len(e.pinned) for e in self._entries.values())

    def _gauges(self) -> None:
        m = get_session_metrics()
        m.active.set(float(len(self._entries)))
        m.retained_blocks.set(float(self.pinned_blocks))

    def retain(self, session_id: str, seq_hashes: list[int],
               now: float | None) -> SessionEntry | None:
        """Pin the committed, device-resident prefix of ``seq_hashes``
        under ``session_id`` (replacing any prior entry for it). Returns
        the new entry, or None when nothing was committable. Evicted
        prior/overflow entries are returned to the caller via
        :meth:`pop_oldest` pressure — here they are just released."""
        stale = self._entries.pop(session_id, None)
        if stale is not None:
            if self._mled.enabled:
                self._mled.unpin("session", session_id)
            self.pool.release(stale.pinned)
            stale.pinned = []
        pinned = self.pool.match_prefix(list(seq_hashes))
        if not pinned:
            self._gauges()
            return None
        if self._mled.enabled:
            self._mled.pin("session", session_id, len(pinned))
        entry = SessionEntry(
            seq_hashes=tuple(seq_hashes[: len(pinned)]),
            pinned=pinned,
            tokens=len(pinned) * self.pool.block_size,
            last_used=now if now is not None else 0.0,
        )
        self._entries[session_id] = entry
        self._gauges()
        return entry

    def claim(self, session_id: str, now: float | None) -> SessionEntry | None:
        """Consume a retained turn for its next request. The store's pins
        are released here — the blocks park in the matchable inactive pool
        for the instant before the claiming request's own admission-time
        ``match_prefix`` re-references them (engine core is single-threaded,
        so nothing allocates in between)."""
        entry = self._entries.pop(session_id, None)
        if entry is None:
            return None
        if self._mled.enabled:
            self._mled.unpin("session", session_id)
        self.pool.release(entry.pinned)
        entry.pinned = []
        if now is not None:
            entry.last_used = now
        self._gauges()
        return entry

    def pop_expired(self, now: float | None) -> list[tuple[str, SessionEntry]]:
        """Remove entries idle past the TTL (leader step clock). The
        caller demotes/releases their pins (EngineCore._demote_session)."""
        if now is None or self.ttl <= 0:
            return []
        out = [(sid, e) for sid, e in self._entries.items()
               if now - e.last_used >= self.ttl]
        for sid, _ in out:
            del self._entries[sid]
            if self._mled.enabled:
                # Pin ownership passes to the caller's demote path, which
                # releases within the same engine step — the ledger drops
                # the session tag at store-exit time.
                self._mled.unpin("session", sid)
        if out:
            self._gauges()
        return out

    def pop_oldest(self) -> tuple[str, SessionEntry] | None:
        """Remove the LRU entry (pool-pressure / capacity valve)."""
        if not self._entries:
            return None
        sid, entry = self._entries.popitem(last=False)
        if self._mled.enabled:
            self._mled.unpin("session", sid)
        self._gauges()
        return sid, entry

    def release_all(self) -> int:
        """Drop every pin (engine wipe / fail_all). Returns blocks freed."""
        n = 0
        for sid, entry in self._entries.items():
            n += len(entry.pinned)
            if self._mled.enabled:
                self._mled.unpin("session", sid)
            self.pool.release(entry.pinned)
            entry.pinned = []
        self._entries.clear()
        self._gauges()
        return n

    def snapshot(self) -> dict:
        return {
            "sessions": len(self._entries),
            "pinned_blocks": self.pinned_blocks,
            "retained_tokens": sum(e.tokens for e in self._entries.values()),
            "ttl": self.ttl,
        }
