"""Engine error types (jax-free so the mocker/runtime paths import light)."""


class NoFreeBlocks(Exception):
    """Block pool exhausted (caller should preempt, queue, or reject)."""
