"""Batched, jittable token sampling.

All sampling params are per-request arrays so one compiled function serves a
mixed batch (greedy + temperature + top-k/p + penalties). Greedy is
``temperature <= 0``. Per-request PRNG keys make seeded requests reproducible
regardless of batch composition.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

NEG_INF = -1e30


@dataclass
class SamplingState:
    """Device-side per-slot sampling params (batch-indexed)."""

    temperature: jax.Array   # [B] f32; <=0 → greedy
    top_k: jax.Array         # [B] i32; 0 → disabled
    top_p: jax.Array         # [B] f32; 1.0 → disabled
    frequency_penalty: jax.Array  # [B] f32
    presence_penalty: jax.Array   # [B] f32
    repetition_penalty: jax.Array  # [B] f32; 1.0 → disabled
    keys: jax.Array          # [B, 2] uint32 per-request PRNG key data
    token_counts: jax.Array  # [B, V] i32 counts of emitted tokens (penalties)


def apply_penalties(logits: jax.Array, st: SamplingState) -> jax.Array:
    counts = st.token_counts.astype(jnp.float32)
    seen = counts > 0
    logits = logits - st.frequency_penalty[:, None] * counts
    logits = logits - st.presence_penalty[:, None] * seen
    rp = st.repetition_penalty[:, None]
    rep = jnp.where(logits > 0, logits / rp, logits * rp)
    logits = jnp.where(seen & (rp != 1.0), rep, logits)
    return logits


def sample(logits: jax.Array, st: SamplingState) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Sample one token per row.

    Returns (tokens [B] i32, logprobs [B] f32, new_keys [B,2]).
    """
    b, v = logits.shape
    logits = logits.astype(jnp.float32)
    logits = apply_penalties(logits, st)
    greedy = st.temperature <= 0.0

    temp = jnp.maximum(st.temperature, 1e-6)[:, None]
    scaled = logits / temp
    sort_idx = jnp.argsort(scaled, axis=-1, descending=True)
    sorted_logits = jnp.take_along_axis(scaled, sort_idx, axis=-1)
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(probs, axis=-1) - probs  # mass strictly before this rank
    rank = jnp.arange(v)[None, :]
    k = jnp.where(st.top_k <= 0, v, st.top_k)[:, None]
    keep = (rank < k) & (cum < st.top_p[:, None])
    keep = keep.at[:, 0].set(True)
    masked = jnp.where(keep, sorted_logits, NEG_INF)

    keys = jax.vmap(jax.random.wrap_key_data)(st.keys)
    def draw(key, row):
        new_key, sub = jax.random.split(key)
        return jax.random.categorical(sub, row), jax.random.key_data(new_key)

    sampled_rank, new_keys = jax.vmap(draw)(keys, masked)
    sampled = jnp.take_along_axis(sort_idx, sampled_rank[:, None], axis=-1)[:, 0]
    tokens = jnp.where(greedy, jnp.argmax(logits, axis=-1), sampled).astype(jnp.int32)

    logprobs_all = jax.nn.log_softmax(logits, axis=-1)
    lp = jnp.take_along_axis(logprobs_all, tokens[:, None], axis=-1)[:, 0]
    return tokens, lp, new_keys


def greedy_sample(logits: jax.Array) -> tuple[jax.Array, jax.Array]:
    """All-greedy, penalty-free batch: argmax + its logprob.

    Bit-identical to :func:`sample` when every row has temperature <= 0,
    frequency/presence penalty 0, and repetition penalty 1 (callers verify
    at dispatch) — penalties are then the identity, so argmax over raw
    logits selects the same token and ``log_softmax`` yields the same
    logprob. Skips the PRNG, the penalty-count gather/scatter, and the
    sorted top-k/p masking — per-step vocab-sized traffic that is pure
    waste for greedy serving."""
    toks = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    lp = jnp.take_along_axis(jax.nn.log_softmax(logits, axis=-1),
                             toks[:, None], axis=-1)[:, 0]
    return toks, lp


def record_tokens(token_counts: jax.Array, tokens: jax.Array, active: jax.Array) -> jax.Array:
    """Scatter-add sampled tokens into the penalty counts (inactive rows skipped)."""
    inc = active.astype(jnp.int32)
    return token_counts.at[jnp.arange(tokens.shape[0]), tokens].add(inc)
