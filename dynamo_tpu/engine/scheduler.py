"""Continuous-batching scheduler.

Design follows the reference's mocker scheduler (the only scheduler the
reference owns — reference: lib/llm/src/mocker/scheduler.rs:54-240, token
budgets + prefill costing), made real: requests move WAITING → (chunked
prefill) → RUNNING (decode) → FINISHED, with block allocation against the
PrefixPool, recompute-style preemption under block pressure, and prefix-cache
reuse feeding back into TTFT.

One step = decode rows AND at most a token-budgeted set of prefill chunks
(decode first): decode streams advance every step, so a long prompt's
prefill can stall ITL by at most one chunk's compute, not the whole prompt
(the reference's engines mix within token-budgeted steps the same way,
lib/llm/src/mocker/scheduler.rs:117-178). By default the engine dispatches
the whole plan as ONE ragged mixed-phase XLA launch: the step program is
already per-row ragged (per-row q_start/q_len ride the scalar-prefetch
path, so a decode row padded to the chunk ladder T costs DMA-elided grid
steps, not T× FLOPs), which removes the second launch's dispatch gap and
lets XLA overlap decode attention with prefill matmuls.
``--no-unified-step`` restores the legacy two-launch path; fused decode
windows (decode_window > 1) are decode-only scans and also keep it.
Static-shape buckets keep XLA compile counts bounded either way.

Chunk size is cost-model-driven when ``prefill_chunk == 0``: the engine
resolves a per-QoS-class cap (costmodel.auto_prefill_chunk — largest chunk
whose predicted mixed-step time keeps decode ITL inside the SLO ladder)
and passes it here as ``chunk_by_qos``; plan() caps each seq's chunk by
its own class, so interactive traffic takes small chunks while batch
prompts chew through large ones.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Iterable

from dynamo_tpu.engine.errors import NoFreeBlocks
from dynamo_tpu.engine.prefix_pool import PrefixPool
from dynamo_tpu.engine.session import session_id_of
from dynamo_tpu.obs.mem_ledger import get_mem_ledger
from dynamo_tpu.obs.sched_ledger import get_sched_ledger
from dynamo_tpu.protocols.common import FinishReason, PreprocessedRequest
from dynamo_tpu.qos.deadline import NO_SPEC_KEY, deadline_of, expired, priority_of
from dynamo_tpu.qos.wdrr import WdrrQueue
from dynamo_tpu.tokens import TokenBlockSequence


class Phase(enum.Enum):
    WAITING = "waiting"
    RUNNING = "running"   # prompt partially/fully computed; decoding when fully
    FINISHED = "finished"


@dataclass
class Seq:
    req: PreprocessedRequest
    block_size: int
    tokens: list[int] = field(default_factory=list)   # prompt + generated
    prompt_len: int = 0
    num_computed: int = 0          # tokens whose KV is resident
    block_ids: list[int] = field(default_factory=list)
    committed_blocks: int = 0      # prefix of block_ids committed to the pool
    phase: Phase = Phase.WAITING
    finish_reason: FinishReason | None = None
    slot: int = -1                 # persistent sampling-state slot
    slot_initialized: bool = False  # sampling state (seed, counts) reset done
    block_seq: TokenBlockSequence = field(init=False)
    prefix_hit_blocks: int = 0     # engine-local prefix cache hits (stats)
    # Count of dispatched-but-unmaterialized sampled steps whose token for
    # this seq lives only on device (pipelined step loop). While > 0, the
    # next decode input reads slot_toks instead of seq.tokens — a bool is
    # not enough: with step N in flight, step N-1's finalize must not make
    # step N+1's dispatch read the (not yet appended) host token.
    inflight_samples: int = 0
    # A speculative verify step is in flight: the scheduler must not plan
    # this seq again until finalize accepts/rolls back (engine/spec.py).
    verify_inflight: bool = False
    # Structured output: a TokenMasker (engine/guided.py) constraining each
    # sampled token to the request's JSON grammar. Guided seqs decode
    # unpipelined in their own masked batches.
    guided: object | None = None
    # Multimodal embedding spans [(pos, np.ndarray[K, H])]: encoder outputs
    # injected at prompt positions during prefill (engine dispatch). Spans
    # are retained for the seq's whole life — preemption recomputes the
    # prefill from position 0 and needs them again. mm_end (max span end)
    # lets decode dispatches skip the span scan with one comparison.
    mm_spans: list = field(default_factory=list)
    mm_end: int = 0
    # QoS: priority class feeds the WDRR waiting queue; deadline_ts is an
    # absolute wall-clock deadline after which the seq is cancelled (before
    # prefill via expire_waiting, mid-decode via the engine's stop check).
    qos_priority: str = "standard"
    deadline_ts: float | None = None
    # Session-sticky KV retention (engine/session.py): the session.id
    # annotation, and whether this seq's avoided-prefill tokens have been
    # counted (once, on its first planned chunk — preemption must not
    # double-count the re-admission match).
    session_id: str | None = None
    session_counted: bool = False
    # Crash-consistent stream checkpoints (kvbm/stream_ckpt.py): committed
    # blocks covered by the last enqueued checkpoint (-1 = none yet; the
    # first fires at prefill completion), and whether this seq's
    # warm-resume metrics were counted (once, on its first planned chunk —
    # preemption must not double-count).
    ckpt_blocks: int = -1
    ckpt_counted: bool = False
    # Tracing (obs/tracer.py): the wire TraceContext parsed off the
    # request annotations, the one currently-open phase span
    # (engine.queue → engine.prefill → engine.decode), and the token
    # count inside the open decode-window span. The engine owns all
    # transitions; the scheduler never touches these.
    trace_ctx: object | None = None
    trace_span: object | None = None
    trace_tokens: int = 0

    def __post_init__(self) -> None:
        self.tokens = list(self.req.token_ids)
        self.prompt_len = len(self.tokens)
        self.block_seq = TokenBlockSequence.from_tokens(self.tokens, self.block_size)
        ann = getattr(self.req, "annotations", None)
        self.qos_priority = priority_of(ann, self.qos_priority)
        self.deadline_ts = deadline_of(ann)
        self.session_id = session_id_of(ann)

    @property
    def request_id(self) -> str:
        return self.req.request_id

    @property
    def num_output_tokens(self) -> int:
        return len(self.tokens) - self.prompt_len

    def prefill_target(self) -> int:
        """Tokens that must be (re)computed before decode can proceed.

        Fresh request: the whole prompt (then sample the first token).
        Preempt-resumed request: everything except the final already-sampled
        token — that token is the next decode input; re-sampling mid-stream
        positions would duplicate output the client already saw.
        """
        return max(self.prompt_len, len(self.tokens) - 1)

    @property
    def in_decode(self) -> bool:
        return self.phase is Phase.RUNNING and self.num_computed >= self.prefill_target()

    def blocks_needed(self, upto_tokens: int) -> int:
        return -(-upto_tokens // self.block_size)  # ceil div


def _spec_eligible(seq: "Seq") -> bool:
    from dynamo_tpu.engine.spec import greedy_eligible

    ann = getattr(seq.req, "annotations", None)
    if ann and ann.get(NO_SPEC_KEY):
        # QoS degradation: under pressure, speculative width is the first
        # throughput knob to go — draft compute serves latency, not capacity.
        return False
    return greedy_eligible(seq.req.sampling_options)


@dataclass
class PrefillWork:
    seq: Seq
    start: int    # first token index of this chunk (== seq.num_computed)
    length: int   # chunk length


@dataclass
class StepPlan:
    prefill: list[PrefillWork] = field(default_factory=list)
    decode: list[Seq] = field(default_factory=list)
    # Decode steps fused into this dispatch (power of two). Every decode seq
    # has blocks allocated for `decode_window` more tokens.
    decode_window: int = 1

    @property
    def empty(self) -> bool:
        return not self.prefill and not self.decode


class Scheduler:
    def __init__(
        self,
        pool: PrefixPool,
        max_batch_size: int,
        prefill_chunk: int,
        max_model_len: int,
        max_tokens_per_step: int = 8192,
        decode_window: int = 1,
        spec_lookahead: int = 0,
        qos_weights: dict[str, int] | None = None,
        chunk_by_qos: dict[str, int] | None = None,
    ):
        self.pool = pool
        self.max_batch_size = max_batch_size
        self.prefill_chunk = prefill_chunk
        # Per-QoS chunk caps (SLO-driven auto mode): each seq's prefill
        # chunk is additionally capped by its own class. None/empty =
        # uniform prefill_chunk for everyone.
        self.chunk_by_qos = dict(chunk_by_qos) if chunk_by_qos else {}
        self.max_model_len = max_model_len
        self.max_tokens_per_step = max_tokens_per_step
        self.decode_window = max(decode_window, 1)
        # Speculative verify chunks write KV for up to spec_k proposed
        # positions ahead — block growth must cover them (engine/spec.py).
        self.spec_lookahead = spec_lookahead
        # Weighted deficit-round-robin over priority classes instead of a
        # plain FIFO: interactive traffic admits ahead of batch without
        # starving it (WdrrQueue is deque-compatible; preempted seqs resume
        # ahead of all lanes via appendleft).
        self.waiting: WdrrQueue = WdrrQueue(
            key_fn=lambda s: s.qos_priority, weights=qos_weights)
        self.running: list[Seq] = []
        self._slot_free: list[int] = list(range(max_batch_size - 1, -1, -1))
        self.preemption_count = 0
        # Scheduling ledger (obs/sched_ledger.py): admission-block causes
        # and preemption recompute accounting. Every hook is gated on
        # .enabled so DYN_SCHED_LEDGER=0 adds zero work to the plan path.
        self._sled = get_sched_ledger()
        # Memory ledger (obs/mem_ledger.py): stream-owned pin taxonomy and
        # per-QoS block consumption rates (TTX forecast). Same zero-work
        # gating contract under DYN_MEM_LEDGER=0.
        self._mled = get_mem_ledger()

    # ------------------------------------------------------------------
    def add(self, seq: Seq) -> None:
        if seq.prompt_len >= self.max_model_len:
            seq.phase = Phase.FINISHED
            seq.finish_reason = FinishReason.ERROR
            return
        # A prompt that can't fit even into an *empty* pool would wait
        # forever — reject it up front (+1: decode needs room to grow).
        if seq.blocks_needed(seq.prompt_len + 1) > self.pool.num_blocks - 1:
            seq.phase = Phase.FINISHED
            seq.finish_reason = FinishReason.ERROR
            return
        self.waiting.append(seq)

    def has_work(self) -> bool:
        return bool(self.waiting or self.running)

    @property
    def num_waiting(self) -> int:
        return len(self.waiting)

    @property
    def num_running(self) -> int:
        return len(self.running)

    # ------------------------------------------------------------------
    def _try_admit(self, seq: Seq) -> bool:
        """Admit a waiting seq: match cached prefix, allocate prompt blocks,
        claim a sampling slot. Returns False under resource pressure."""
        if not self._slot_free:
            if self._sled.enabled:
                self._sled.record_block("batch_full")
            return False
        # Match at most prefill_target-1 tokens so at least one token is
        # computed (we need last-position state before decode can continue).
        matchable = (seq.prefill_target() - 1) // seq.block_size
        matched = self.pool.match_prefix(seq.block_seq.sequence_hashes()[:matchable])
        need = seq.blocks_needed(len(seq.tokens)) - len(matched)
        # Watermark: keep one free/evictable block per running seq so the
        # decode-growth loop doesn't immediately hit pressure and preempt the
        # seq we just admitted (admit→evict→re-admit thrash under mixed
        # prefill+decode stepping). The preempted-resume path (front of the
        # waiting deque with committed prefix) still re-admits once decoders
        # drain.
        if need + len(self.running) > self.pool.num_free:
            self.pool.release(matched)
            if self._sled.enabled:
                self._sled.record_block("no_free_blocks")
            return False
        try:
            fresh = self.pool.allocate(need)
        except NoFreeBlocks:
            self.pool.release(matched)
            if self._sled.enabled:
                self._sled.record_block("no_free_blocks")
            return False
        seq.block_ids = matched + fresh
        seq.committed_blocks = len(matched)
        seq.num_computed = len(matched) * seq.block_size
        seq.prefix_hit_blocks = len(matched)
        if self._mled.enabled:
            self._mled.pin("stream", seq.request_id, len(seq.block_ids))
            self._mled.record_alloc(seq.qos_priority, len(fresh))
        seq.slot = self._slot_free.pop()
        seq.slot_initialized = False
        seq.phase = Phase.RUNNING
        self.running.append(seq)
        return True

    def _grow_for_decode(self, seq: Seq, tokens_ahead: int = 1) -> bool:
        """Ensure block capacity for `tokens_ahead` more tokens; False if
        allocation failed."""
        need = seq.blocks_needed(seq.num_computed + tokens_ahead)
        if need > len(seq.block_ids):
            grow = need - len(seq.block_ids)
            try:
                seq.block_ids.extend(self.pool.allocate(grow))
            except NoFreeBlocks:
                return False
            if self._mled.enabled:
                self._mled.pin("stream", seq.request_id, grow)
                self._mled.record_alloc(seq.qos_priority, grow)
        return True

    def preempt(self, seq: Seq, cause: str = "blocks") -> None:
        """Recompute-style preemption: release blocks, requeue at the front.
        (Reference pattern: vLLM recompute preemption, mirrored by the mocker.)"""
        if self._sled.enabled:
            # Every resident-KV token released here must be recomputed
            # through prefill from position 0 on re-admission.
            self._sled.record_preempt(seq.num_computed, cause)
        if self._mled.enabled:
            self._mled.unpin("stream", seq.request_id)
            self._mled.record_release(seq.qos_priority, len(seq.block_ids))
        self.pool.release(seq.block_ids)
        seq.block_ids = []
        seq.committed_blocks = 0
        seq.num_computed = 0
        seq.phase = Phase.WAITING
        if seq.slot >= 0:
            self._slot_free.append(seq.slot)
            seq.slot = -1
        self.running.remove(seq)
        self.waiting.appendleft(seq)
        self.preemption_count += 1

    def finish(self, seq: Seq, reason: FinishReason) -> None:
        seq.phase = Phase.FINISHED
        seq.finish_reason = reason
        if seq in self.running:
            self.running.remove(seq)
        elif seq in self.waiting:
            self.waiting.remove(seq)
        if self._mled.enabled and seq.block_ids:
            self._mled.unpin("stream", seq.request_id)
            self._mled.record_release(seq.qos_priority, len(seq.block_ids))
        self.pool.release(seq.block_ids)
        seq.block_ids = []
        if seq.slot >= 0:
            self._slot_free.append(seq.slot)
            seq.slot = -1

    def expire_waiting(self, now: float | None = None) -> list[Seq]:
        """Cancel waiting seqs whose deadline has passed — before any
        prefill compute is spent on them. Returns the cancelled seqs so
        the engine can emit their terminal outputs."""
        stale = [s for s in self.waiting if expired(s.deadline_ts, now)]
        for seq in stale:
            self.finish(seq, FinishReason.CANCELLED)
        return stale

    # ------------------------------------------------------------------
    def plan(self) -> StepPlan:
        plan = StepPlan()
        # Admit as many waiting seqs as resources allow.
        while self.waiting and len(self.running) < self.max_batch_size:
            if not self._try_admit(self.waiting[0]):
                if self._sled.enabled and sum(
                        1 for d in self.waiting.depths().values() if d) > 1:
                    # The blocked head also gates every other non-empty
                    # WDRR lane behind its lane commitment — seqs that
                    # might have admitted had the round-robin pointer sat
                    # elsewhere.
                    self._sled.record_block("wdrr_gate")
                break
            self.waiting.popleft()
        if (self._sled.enabled and self.waiting
                and len(self.running) >= self.max_batch_size):
            self._sled.record_block("batch_full")

        # Decode batch first (every decodable stream advances every step);
        # grow blocks, preempting from the back on pressure.
        # Window: fuse up to decode_window steps into one dispatch. Shrink to
        # (a) fit every seq under max_model_len (the block table must cover
        # every fused position) and (b) the useful horizon — past the point
        # every stream will have hit max_tokens, fused steps are pure waste
        # (their tokens are discarded at finalize).
        cands = [s for s in self.running
                 if s.in_decode and s.num_computed < self.max_model_len]
        w = self.decode_window
        if w > 1 and cands:
            cap = min(self.max_model_len - s.num_computed for s in cands)
            useful = 1
            for s in cands:
                mt = s.req.stop_conditions.max_tokens
                # decode positions already computed (incl. in-flight windows)
                out_est = max(s.num_computed - s.prefill_target(), 0)
                useful = max(useful, (mt - out_est) if mt is not None else cap)
            w = max(1, min(w, cap, useful))
            w = 1 << (w.bit_length() - 1)  # pow2 bucket bounds compile count
        decodable: list[Seq] = []
        for seq in list(self.running):
            if not seq.in_decode:
                continue
            if seq.verify_inflight:
                # A dispatched-but-unfinalized verify step owns this seq's
                # next positions; replanning it before the accept/rollback
                # lands would read garbage state.
                continue
            if self.spec_lookahead and _spec_eligible(seq):
                # Only verify-eligible seqs reserve lookahead blocks —
                # sampled/penalized seqs never speculate, and over-reserving
                # for them would trigger preemptions for capacity nobody uses.
                grow_ahead = max(w, 1 + self.spec_lookahead)
            else:
                grow_ahead = w
            if seq.num_computed >= self.max_model_len:
                # At capacity: the finalize of an in-flight step will finish
                # this seq (pipelined stepping plans ahead of stop checks);
                # decoding past max_model_len would outgrow the block table.
                continue
            while not self._grow_for_decode(seq, grow_ahead):
                # preempt the most recently admitted other seq
                victims = [s for s in reversed(self.running) if s is not seq]
                if not victims:
                    break
                victim = victims[0]
                self.preempt(victim, cause=(
                    "qos" if victim.qos_priority != seq.qos_priority
                    else "blocks"))
                if victim in decodable:
                    decodable.remove(victim)
            else:
                decodable.append(seq)
                continue
            # could not grow even after preemption: preempt seq itself
            self.preempt(seq)
        plan.decode = decodable[: self.max_batch_size]
        plan.decode_window = w if plan.decode else 1

        # Prefill chunks for seqs short of their target, within what's left
        # of the step token budget after the decode rows (a fused window
        # computes window tokens per row).
        budget = self.max_tokens_per_step - len(plan.decode) * plan.decode_window
        for seq in self.running:
            target = seq.prefill_target()
            if seq.num_computed < target and budget > 0:
                cap = self.chunk_by_qos.get(seq.qos_priority, self.prefill_chunk)
                chunk = min(target - seq.num_computed, cap, budget)
                plan.prefill.append(PrefillWork(seq=seq, start=seq.num_computed, length=chunk))
                budget -= chunk
        return plan

    # ------------------------------------------------------------------
    def commit_computed_blocks(self, seq: Seq) -> None:
        """Commit every fully-computed block (emits stored events via pool).

        Bounded by len(tokens) as well as num_computed: under pipelined
        stepping num_computed runs ahead of the appended tokens, and a block
        can only be committed once every token value in it is known (the
        hash chain needs the values)."""
        n_full = min(seq.num_computed, len(seq.tokens)) // seq.block_size
        hashes = seq.block_seq.sequence_hashes()
        while seq.committed_blocks < n_full:
            i = seq.committed_blocks
            parent = hashes[i - 1] if i > 0 else None
            self.pool.commit(seq.block_ids[i], hashes[i], parent)
            seq.committed_blocks += 1
