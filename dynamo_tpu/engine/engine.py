"""The first-party JAX engine: model runner + engine core + async facade.

Fills the role vLLM's AsyncLLM plays under the reference framework
(reference worker wrapper: components/src/dynamo/vllm/main.py,
handlers.py) — but the engine itself is ours, TPU-first:

- ``ModelRunner``: owns params, paged KV cache, and per-slot sampling state
  on device; compiles one XLA program per (batch, chunk, blocktable) bucket;
  cache/state buffers are donated so steps update in place.
- ``EngineCore``: synchronous scheduler + step loop (directly testable).
- ``AsyncJaxEngine``: thread-hosted step loop bridging to asyncio streams —
  the object a worker process serves via serve_endpoint.
"""

from __future__ import annotations

import asyncio
import collections
import queue as thread_queue
import threading
import time
from pathlib import Path
from dataclasses import dataclass, field
from functools import partial
from typing import TYPE_CHECKING, Any, AsyncIterator, Callable

if TYPE_CHECKING:
    from dynamo_tpu.kvbm.offload import OffloadManager

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from dynamo_tpu import chaos
from dynamo_tpu.engine.cache import (
    KVCacheSpec,
    allocate_cache,
    register_device_tier,
)
from dynamo_tpu.engine.prefix_pool import PrefixPool
from dynamo_tpu.engine.sampling import (
    SamplingState,
    greedy_sample as _greedy_sample,
    record_tokens,
    sample,
)
from dynamo_tpu.engine.scheduler import Phase, PrefillWork, Scheduler, Seq, StepPlan
from dynamo_tpu.engine.session import SessionStore, get_session_metrics
from dynamo_tpu.kvbm.stream_ckpt import (
    CKPT_DRAWS_KEY,
    CKPT_GENERATED_KEY,
    build_ckpt_record,
    get_stream_ckpt_metrics,
)
from dynamo_tpu.models import llama
from dynamo_tpu.models.config import ModelConfig, resolve_model_config
from dynamo_tpu.obs.compile_ledger import (
    WARMUP_MODES,
    BucketSig,
    enumerate_buckets,
    get_compile_ledger,
)
from dynamo_tpu.obs.profiler import StepPerfProfiler, phase as _perf_phase
from dynamo_tpu.obs.mem_ledger import get_mem_ledger, live_ids_of
from dynamo_tpu.obs.sched_ledger import HolStall, get_sched_ledger, step_geometry
from dynamo_tpu.obs.tracer import get_tracer, trace_context_of
from dynamo_tpu.parallel.mesh import MeshConfig, make_mesh
from dynamo_tpu.protocols.common import FinishReason, LLMEngineOutput, PreprocessedRequest
from dynamo_tpu.router.events import KvCacheEvent
from dynamo_tpu.utils.config import EngineConfig
from dynamo_tpu.utils.logging import get_logger

log = get_logger("engine")


def _bucket(n: int, buckets: tuple[int, ...]) -> int:
    for b in buckets:
        if n <= b:
            return b
    return n


def _pow2_bucket(n: int, lo: int, hi: int) -> int:
    b = lo
    while b < n and b < hi:
        b *= 2
    return b


@jax.jit
def _advance_key_data(data: jax.Array, n: jax.Array) -> jax.Array:
    """Key data after ``n`` sampler draws from ``data`` — replays
    sampling.sample()'s per-draw chain (``new_key = split(key)[0]``) in one
    fori_loop, so checkpoint resume restores a mid-stream PRNG state with a
    single tiny dispatch (``n`` is a traced operand: one compile serves
    every resume depth)."""
    key = jax.random.wrap_key_data(data)
    key = lax.fori_loop(0, n, lambda _, k: jax.random.split(k)[0], key)
    return jax.random.key_data(key)


def _derived_seed(request_id: str) -> int:
    """Stable per-request sampler seed for requests that set none. Making
    every stream's key a pure function of (seed, draws) is what lets a
    checkpoint resume restore sampler state exactly — including for
    unseeded requests, whose resume re-derives this same value from the
    (unchanged) request id."""
    import hashlib

    return int.from_bytes(
        hashlib.sha256(request_id.encode()).digest()[:4], "big")


@dataclass
class EngineMetrics:
    """Engine-side stats published to the router/planner
    (reference: ForwardPassMetrics, lib/llm/src/kv_router/publisher.rs:686)."""

    num_steps: int = 0
    num_prefill_tokens: int = 0
    num_decode_tokens: int = 0
    num_requests_finished: int = 0
    num_preemptions: int = 0
    prefix_hit_blocks: int = 0
    prefix_lookup_blocks: int = 0
    # Speculative decoding (reference surface: SpecDecodeStats in
    # ForwardPassMetrics): proposed = tokens offered for verification,
    # accepted = proposals that matched the true greedy path.
    spec_proposed: int = 0
    spec_accepted: int = 0
    # QoS: requests cancelled because their deadline passed (either while
    # waiting — before any prefill — or mid-decode via the stop check).
    deadline_cancelled: int = 0
    # Session turns that resumed from a drain-evacuated remote record
    # (pull-to-warm after another worker retired, runtime/drain.py).
    session_remote_resumes: int = 0
    # Streams resumed warm from a crash checkpoint (kvbm/stream_ckpt.py):
    # the migration operator replays the stream on a survivor with the
    # stream_ckpt.* annotations stamped.
    stream_ckpt_resumes: int = 0
    # KV-cache footprint (set once at engine construction): total device
    # bytes of the paged cache and whether int8 KV quantization is on —
    # exported as dynamo_engine_kv_cache_bytes / dynamo_engine_kv_quant_enabled.
    kv_cache_bytes: int = 0
    kv_quant_enabled: bool = False

    def snapshot(self, sched: Scheduler, pool: PrefixPool) -> dict:
        return {
            "kv_cache_bytes": self.kv_cache_bytes,
            "kv_quant_enabled": self.kv_quant_enabled,
            "num_waiting": sched.num_waiting,
            "num_running": sched.num_running,
            "kv_usage": pool.usage,
            "kv_total_blocks": pool.num_blocks,
            "num_steps": self.num_steps,
            "prefill_tokens": self.num_prefill_tokens,
            "decode_tokens": self.num_decode_tokens,
            "requests_finished": self.num_requests_finished,
            "preemptions": self.num_preemptions,
            "prefix_hit_rate": self.prefix_hit_blocks / max(self.prefix_lookup_blocks, 1),
            "spec_proposed": self.spec_proposed,
            "spec_accepted": self.spec_accepted,
            "deadline_cancelled": self.deadline_cancelled,
            "session_remote_resumes": self.session_remote_resumes,
            "stream_ckpt_resumes": self.stream_ckpt_resumes,
        }


@dataclass
class PendingStep:
    """A dispatched-but-unmaterialized engine step: per batch,
    (kind, rows, sample_rows, device tokens, device logprobs)."""

    batches: list[tuple[str, list, list[bool], Any, Any]] = field(default_factory=list)
    # Scheduling-ledger context captured at plan time (decode_window,
    # token-budget utilization, HOL victim list) — consumed by
    # _record_step at finalize. None when DYN_SCHED_LEDGER=0.
    sched: Any = None
    # Unified steps: the leading decode-row count of the "mixed" batch
    # (rows [0:n] are decode/guided, the rest prefill chunks) — captured
    # at plan time because prefill_target() moves as finalize appends
    # tokens, so a finalize-time re-derivation would misclassify.
    mixed_dec_rows: int = 0


class ModelRunner:
    """Device-state owner + bucketed compiled step functions."""

    def __init__(
        self,
        cfg: ModelConfig,
        engine_cfg: EngineConfig,
        mesh=None,
        params=None,
        rng_seed: int = 0,
    ):
        self.cfg = cfg
        self.engine_cfg = engine_cfg
        self.mesh = mesh
        # Replicated placement for host-built step inputs and sampling state.
        # On a mesh this makes every array an explicit global array — required
        # under multi-host jax (each process holds the full replicated value),
        # and a no-op-equivalent on one host.
        from jax.sharding import NamedSharding, PartitionSpec

        self._repl = (NamedSharding(mesh, PartitionSpec())
                      if mesh is not None else None)
        key = jax.random.key(rng_seed)
        if params is not None:
            self.params = params
        else:
            from dynamo_tpu.models.loader import has_weights, load_params

            if engine_cfg.model.endswith(".gguf"):
                from dynamo_tpu.models.gguf import load_params_gguf

                _, self.params = load_params_gguf(engine_cfg.model, mesh=mesh)
            elif has_weights(engine_cfg.model):
                self.params = load_params(cfg, engine_cfg.model, mesh=mesh)
            else:
                from dynamo_tpu.models.config import MODEL_PRESETS

                if engine_cfg.model not in MODEL_PRESETS:
                    # A real model PATH without safetensors (typo, or a
                    # .bin-only snapshot): serving random weights would look
                    # like a working server producing garbage. Fail fast
                    # unless explicitly allowed (reference contrast: vLLM
                    # refuses unloadable checkpoints the same way).
                    if not engine_cfg.allow_random_weights:
                        raise ValueError(
                            f"{engine_cfg.model!r} has no *.safetensors "
                            "weights to load; convert the checkpoint, fix "
                            "the path, or pass --allow-random-weights to "
                            "serve RANDOM weights (tests/benches only)")
                    log.warning(
                        "%s has no *.safetensors weights: engine will serve "
                        "RANDOM weights (--allow-random-weights)",
                        engine_cfg.model)
                self.params = llama.init_params(cfg, key)
        if mesh is not None:
            # Explicitly place params per their logical-axis rules: on one
            # host this pins the TP/EP layout (instead of leaving GSPMD to
            # re-shard uncommitted arrays per bucket); on multi-host it is
            # mandatory — every process must contribute its shard of the
            # global param arrays. Random init is seed-deterministic, so all
            # processes hold identical host values to shard from. Leaves the
            # loader already placed pass through untouched (global_put
            # returns correctly-sharded arrays as-is).
            from dynamo_tpu.parallel.mesh import shard_params

            self.params = shard_params(
                self.params, llama.param_logical_axes(cfg), mesh)
        if engine_cfg.quantization == "int8":
            # After placement: the elementwise quantize preserves the mesh
            # sharding, so TP/EP layouts carry over (models/quant.py).
            # (The value itself was validated with the other config checks
            # in EngineCore, before any weight IO.)
            from dynamo_tpu.models.quant import quantize_params_int8

            self.params = quantize_params_int8(self.params, cfg)
        num_blocks = engine_cfg.num_blocks or self._auto_num_blocks()
        self.spec = KVCacheSpec.for_model(cfg, num_blocks, engine_cfg.block_size,
                                          kv_dtype=engine_cfg.kv_dtype)
        self.cache_k, self.cache_v = allocate_cache(self.spec, mesh)
        maxb = engine_cfg.max_batch_size
        # Row maxb is the trash row: padding/non-sampling rows write their
        # sampling-state updates there so real slots are never clobbered by
        # duplicate scatter indices and PRNG keys only advance on real samples.
        self.counts = self._place(jnp.zeros((maxb + 1, cfg.vocab_size), jnp.int32))
        base = jax.random.split(jax.random.key(engine_cfg.seed), maxb + 1)
        self.keys = self._place(jax.vmap(jax.random.key_data)(base).astype(jnp.uint32))
        # Per-slot latest sampled token, ON DEVICE: lets the next decode step
        # consume this step's token without a host round-trip — the core of
        # the pipelined (host/device-overlapped) step loop. Row maxb = trash.
        self.slot_toks = self._place(jnp.zeros((maxb + 1,), jnp.int32))
        self._step_fns: dict[tuple[int, int, int], Callable] = {}
        self.max_nblk = -(-engine_cfg.max_model_len // engine_cfg.block_size)
        # Compile ledger (obs/compile_ledger.py): every cache miss below is
        # a trace+compile that blocks the engine-core thread; the ledger
        # times it, attributes the victim request, and feeds warmup
        # coverage. Disabled (warmup_mode=off) the gate is one bool read.
        self._ledger = get_compile_ledger()
        from dynamo_tpu.ops.paged_attention import select_attn_impl

        self.attn_impl = select_attn_impl(engine_cfg.attn_impl)
        if (self.attn_impl in ("pallas", "pallas_interpret") and mesh is not None
                and mesh.shape.get("model", 1) > 1
                and cfg.num_kv_heads % mesh.shape["model"] != 0):
            log.warning(
                "num_kv_heads=%d does not divide tp=%d: pallas attention will "
                "fall back to the dense gather path", cfg.num_kv_heads,
                mesh.shape["model"])
        # Context-parallel ring prefill gate (ops/ring_attention.py promoted
        # to a serving mode): None = ring off (sp=1 mesh, or the knob set to
        # -1); otherwise the minimum prompt tokens before a fresh
        # full-prompt batch rides the seq-sharded ring path. 0 = auto — the
        # cost model's ring-vs-chunked break-even for this model on this
        # device (obs/costmodel.py).
        self.ring_threshold: int | None = None
        sp = mesh.shape.get("seq", 1) if mesh is not None else 1
        if sp > 1 and engine_cfg.ring_prefill_threshold >= 0:
            if engine_cfg.ring_prefill_threshold > 0:
                self.ring_threshold = engine_cfg.ring_prefill_threshold
            else:
                from dynamo_tpu.obs.costmodel import (
                    hw_spec_for,
                    ring_prefill_break_even_tokens,
                )

                self.ring_threshold = ring_prefill_break_even_tokens(
                    cfg, hw_spec_for(jax.devices()[0].device_kind), sp=sp,
                    chunk=engine_cfg.prefill_chunk,
                    block_size=engine_cfg.block_size,
                    kv_dtype=engine_cfg.kv_dtype,
                    quantization=engine_cfg.quantization,
                    max_tokens=engine_cfg.max_model_len)
            from dynamo_tpu.obs.ring_prefill import get_ring_prefill_metrics

            get_ring_prefill_metrics().threshold_tokens.set(
                float(self.ring_threshold))
            log.info("ring prefill engaged: sp=%d threshold=%d tokens%s",
                     sp, self.ring_threshold,
                     "" if engine_cfg.ring_prefill_threshold
                     else " (cost-model auto)")

    def _place(self, x):
        """Replicate onto the mesh (global array) or leave as-is off-mesh."""
        if self._repl is None:
            return jnp.asarray(x)
        from dynamo_tpu.parallel.mesh import global_put

        return global_put(x, self._repl)

    def _auto_num_blocks(self) -> int:
        """Size the device KV pool from free memory (TPU) or a small default."""
        ec = self.engine_cfg
        try:
            stats = jax.devices()[0].memory_stats() or {}
            limit = stats.get("bytes_limit", 0)
            in_use = stats.get("bytes_in_use", 0)
            budget = int((limit - in_use) * 0.85)
        except Exception:
            budget = 0
        spec = KVCacheSpec.for_model(self.cfg, 1, ec.block_size,
                                     kv_dtype=ec.kv_dtype)
        if budget > 0:
            n = max(budget // spec.bytes_per_block(), 16)
        else:
            n = 512
        cap = (ec.max_model_len // ec.block_size) * ec.max_batch_size + 1
        return int(min(n, cap))

    # ------------------------------------------------------------------
    def _build_step_fn(self, b: int, t: int, nblk: int, sp_prefill: bool = False,
                       fast_greedy: bool = False, mm: bool = False,
                       masked: bool = False):
        cfg = self.cfg
        trash_row = self.engine_cfg.max_batch_size

        attn_impl = self.attn_impl
        moe_impl = "ep" if self.engine_cfg.ep > 1 else "dense"
        mesh = self.mesh
        pp_micro = self.engine_cfg.pp_microbatches
        attn_splits = self.engine_cfg.attn_num_splits

        def step(params, ck, cv, counts, keys, slot_toks, tokens, q_start, q_len,
                 bt, slots, temp, top_k, top_p, fp, pp, rp, do_sample, from_slot,
                 *mm_args):
            # Device-fed decode input: rows whose previous token was sampled
            # by an in-flight step read it from slot_toks instead of the host
            # tokens array (which holds 0 for them) — XLA's execution order
            # guarantees the producing step has run.
            first = jnp.where(from_slot, slot_toks[slots], tokens[:, 0])
            tokens = tokens.at[:, 0].set(first)
            rest = list(mm_args)
            emb_override = rest.pop(0) if mm else None
            emb_mask = rest.pop(0) if mm else None
            logit_mask = rest.pop(0) if masked else None
            hidden, ck, cv = llama.forward(params, cfg, tokens, q_start, q_len, bt, ck, cv,
                                           attn_impl=attn_impl, moe_impl=moe_impl,
                                           mesh=mesh, sp_prefill=sp_prefill,
                                           embed_override=emb_override,
                                           embed_mask=emb_mask,
                                           pp_microbatches=pp_micro,
                                           attn_num_splits=attn_splits)
            logits = llama.logits_from_hidden(params, cfg, hidden).astype(jnp.float32)
            if masked:
                # Structured output (engine/guided.py): the grammar's
                # per-row allow-mask, additive in log space. The model
                # program is untouched — only the sampling input shifts.
                logits = logits + logit_mask
            write_slots = jnp.where(do_sample, slots, trash_row)
            with _perf_phase("sampling"):
                if fast_greedy:
                    # Whole batch greedy + penalty-free (host-verified at
                    # dispatch): argmax over raw logits is bit-identical to
                    # the general path and skips its PRNG, penalty-count
                    # gathers, and sorted top-k/p masking — the per-step
                    # vocab-sized traffic that isn't the model itself.
                    toks, lps = _greedy_sample(logits)
                else:
                    st = SamplingState(
                        temperature=temp, top_k=top_k, top_p=top_p,
                        frequency_penalty=fp, presence_penalty=pp,
                        repetition_penalty=rp,
                        keys=keys[slots], token_counts=counts[slots],
                    )
                    toks, lps, new_keys = sample(logits, st)
                    new_counts = record_tokens(st.token_counts, toks, do_sample)
                    # Only sampling rows persist state; others write to trash.
                    counts = counts.at[write_slots].set(new_counts)
                    keys = keys.at[write_slots].set(new_keys)
            slot_toks = slot_toks.at[write_slots].set(toks)
            return ck, cv, counts, keys, slot_toks, toks, lps

        return jax.jit(step, donate_argnums=(1, 2, 3, 4, 5),
                       **self._jit_shardings())

    def _jit_shardings(self) -> dict:
        """Pin step-output shardings on a mesh: cache keeps its TP layout;
        sampling state and sampled tokens come back fully replicated so the
        host can materialize them on EVERY process (multi-host finalize) and
        the next dispatch feeds them straight back without resharding."""
        if self.mesh is None:
            return {}
        from jax.sharding import NamedSharding, PartitionSpec as P

        from dynamo_tpu.parallel.mesh import kv_cache_spec, kv_scale_spec

        repl = NamedSharding(self.mesh, P())
        cache = NamedSharding(self.mesh, kv_cache_spec())
        if self.spec.quantized:
            # Quantized caches are {"q","s"} pytrees; shard each leaf.
            cache = {"q": cache, "s": NamedSharding(self.mesh, kv_scale_spec())}
        return {"out_shardings": (cache, cache, repl, repl, repl, repl, repl)}

    def _build_window_fn(self, b: int, nblk: int, w: int,
                         fast_greedy: bool = False):
        """Fused decode window: ``w`` single-token steps in ONE compiled
        dispatch, `lax.scan`-sequenced on device with each step's sampled
        token feeding the next — zero host round trips inside the window.
        This is the TPU answer to per-token dispatch latency (the reference's
        engines decode step-by-step because their scheduler lives next to
        the GPU; ours may sit a network tunnel away from the chip). Stop
        conditions lag ≤ w-1 tokens; finalize discards overrun, so emitted
        streams are bit-identical to w=1 (tests/test_engine.py windowed
        equivalence tests)."""
        cfg = self.cfg
        trash_row = self.engine_cfg.max_batch_size
        attn_impl = self.attn_impl
        moe_impl = "ep" if self.engine_cfg.ep > 1 else "dense"
        mesh = self.mesh
        pp_micro = self.engine_cfg.pp_microbatches
        attn_splits = self.engine_cfg.attn_num_splits

        def step(params, ck, cv, counts, keys, slot_toks, tokens, q_start, q_len,
                 bt, slots, temp, top_k, top_p, fp, pp, rp, do_sample, from_slot):
            first = jnp.where(from_slot, slot_toks[slots], tokens[:, 0])
            write_slots = jnp.where(do_sample, slots, trash_row)

            def body(carry, j):
                ck, cv, counts, keys, slot_toks, cur = carry
                hidden, ck, cv = llama.forward(
                    params, cfg, cur[:, None], q_start + j, q_len, bt, ck, cv,
                    attn_impl=attn_impl, moe_impl=moe_impl, mesh=mesh,
                    pp_microbatches=pp_micro, attn_num_splits=attn_splits)
                logits = llama.logits_from_hidden(params, cfg, hidden).astype(jnp.float32)
                with _perf_phase("sampling"):
                    if fast_greedy:
                        # See _build_step_fn: bit-identical for all-greedy
                        # penalty-free batches, minus the sampling machinery.
                        toks, lps = _greedy_sample(logits)
                    else:
                        st = SamplingState(
                            temperature=temp, top_k=top_k, top_p=top_p,
                            frequency_penalty=fp, presence_penalty=pp,
                            repetition_penalty=rp, keys=keys[slots],
                            token_counts=counts[slots],
                        )
                        toks, lps, new_keys = sample(logits, st)
                        new_counts = record_tokens(st.token_counts, toks,
                                                   do_sample)
                        counts = counts.at[write_slots].set(new_counts)
                        keys = keys.at[write_slots].set(new_keys)
                slot_toks = slot_toks.at[write_slots].set(toks)
                return (ck, cv, counts, keys, slot_toks, toks), (toks, lps)

            (ck, cv, counts, keys, slot_toks, _), (toks_w, lps_w) = lax.scan(
                body, (ck, cv, counts, keys, slot_toks, first),
                jnp.arange(w, dtype=jnp.int32))
            return ck, cv, counts, keys, slot_toks, toks_w.T, lps_w.T  # [B, W]

        return jax.jit(step, donate_argnums=(1, 2, 3, 4, 5),
                       **self._jit_shardings())

    def step_fn(self, b: int, t: int, nblk: int, sp_prefill: bool = False,
                window: int = 1, fast_greedy: bool = False, mm: bool = False,
                masked: bool = False):
        key = (b, t, nblk, sp_prefill, window, fast_greedy, mm, masked)
        if key not in self._step_fns:
            log.info("compiling step fn B=%d T=%d NBLK=%d sp_prefill=%s W=%d "
                     "greedy=%s mm=%s masked=%s", b, t, nblk, sp_prefill,
                     window, fast_greedy, mm, masked)
            if window > 1:
                self._step_fns[key] = self._build_window_fn(
                    b, nblk, window, fast_greedy)
            else:
                self._step_fns[key] = self._build_step_fn(
                    b, t, nblk, sp_prefill, fast_greedy, mm, masked)
        return self._step_fns[key]

    def used_fast_greedy(self) -> bool:
        """Whether any compiled step so far took the argmax-only greedy
        variant — THE accessor for the compile-cache key layout (step keys
        are (b, t, nblk, sp, window, fast_greedy, mm); 'verify'/'embed'
        entries are string-prefixed and excluded)."""
        return any(not isinstance(k[0], str) and k[5]
                   for k in self._step_fns)

    def reset_slot(self, slot: int, seed: int | None, *, advance: int = 0,
                   resume_tokens: "list[int] | None" = None) -> None:
        """Initialize a seq's persistent sampling state. ``advance`` replays
        that many sampler draws on the fresh key (sample()'s split chain is
        a pure function of (seed, draws), so a checkpoint-resumed stream's
        n+1'th draw is bit-identical to the unkilled run's at
        decode_window=1); ``resume_tokens`` rebuilds the penalty counts
        from the already-generated ledger riding the resume prompt."""
        self.counts = self.counts.at[slot].set(0)
        if resume_tokens:
            toks = jnp.asarray(resume_tokens, jnp.int32)
            self.counts = self.counts.at[slot, toks].add(1)
        if seed is not None:
            k = jax.random.key_data(jax.random.key(seed)).astype(jnp.uint32)
            if advance > 0:
                k = _advance_key_data(k, jnp.int32(advance)).astype(jnp.uint32)
            self.keys = self.keys.at[slot].set(k)

    def dispatch(
        self,
        rows: list[tuple[Seq, int, int]],  # (seq, start, length) per row
        sample_rows: list[bool],
        window: int = 1,
        masks: list | None = None,  # per-row bool[V] allow-masks (guided)
        mixed: bool = False,
    ) -> tuple[jax.Array, jax.Array]:
        """Enqueue one bucketed step on the device WITHOUT blocking; returns
        device arrays (tokens [B] or [B, window], logprobs likewise) still
        being computed. The caller overlaps host work (scheduling, output
        assembly for earlier steps) with the device, then materializes via
        ``np.asarray``. ``window > 1`` (decode rows only) fuses that many
        steps into the dispatch — the caller must have grown each seq's
        block table to cover ``window`` more tokens. ``mixed`` marks a
        unified ragged step (decode rows packed with prefill-chunk rows):
        the batch buckets over the DECODE row ladder while t takes the
        prefill chunk ladder — same ragged step program, different bucket
        geometry (legacy prefill's (1,2,4,8) row ladder can't hold a full
        decode batch)."""
        ec = self.engine_cfg
        n = len(rows)
        t_max = max(length for _, _, length in rows)
        if t_max == 1:
            # Degenerate mixed batches (every live row is one token) ARE
            # the decode program — classify them as such so the ledger
            # matches the program actually minted.
            b, t, mixed = _bucket(n, ec.decode_bucket), 1, False
        elif mixed:
            window = 1
            b, t = _bucket(n, ec.decode_bucket), _pow2_bucket(t_max, 16, ec.prefill_chunk)
        else:
            window = 1  # windows are a decode-dispatch concept
            b, t = _bucket(n, (1, 2, 4, 8)), _pow2_bucket(t_max, 16, ec.prefill_chunk)
        # Block-table width from the batch's max KV coverage — NOT the max
        # allocated table length: every query/context position this step
        # touches is < start + length (+ window-1 for fused decode windows),
        # so blocks past that are pure waste (the Pallas kernel still burns
        # one HBM DMA per table entry per step, and the dense path gathers
        # them). Pow2-bucketed to bound the number of compiled programs.
        bsz = ec.block_size
        nblk_need = max(
            min(len(s.block_ids),
                -(-(start + length + window - 1) // bsz))
            for s, start, length in rows)
        nblk = min(_pow2_bucket(max(nblk_need, 1), 4, self.max_nblk), self.max_nblk)
        # Sequence-parallel prefill: a batch of fresh full-prompt chunks
        # (every row starts at 0) on a seq>1 mesh rides ring attention —
        # but only past the ring-vs-chunked threshold (explicit knob or
        # cost-model break-even, resolved in __init__). Shorter prompts
        # take the dense path: identical program to an sp=1 engine, so
        # staying below threshold costs zero extra ops.
        sp_capable = (
            t > 1
            and self.mesh is not None
            and self.mesh.shape.get("seq", 1) > 1
            and all(start == 0 for _, start, _ in rows)
        )
        sp_prefill = (
            sp_capable
            and self.ring_threshold is not None
            and t_max >= self.ring_threshold
        )
        if t > 1 and self.ring_threshold is not None:
            from dynamo_tpu.obs.ring_prefill import get_ring_prefill_metrics

            rpm = get_ring_prefill_metrics()
            if sp_prefill:
                rpm.invocations.inc()
                rpm.tokens.inc(sum(length for _, _, length in rows))
            else:
                rpm.bypassed.inc()

        masked = masks is not None and any(m is not None for m in masks)
        tokens = np.zeros((b, t), np.int32)
        q_start = np.zeros((b,), np.int32)
        q_len = np.zeros((b,), np.int32)
        bt = np.zeros((b, nblk), np.int32)
        slots = np.zeros((b,), np.int32)
        fast_greedy = True  # padding rows (temp 0, rp 1) are greedy-compatible
        temp = np.zeros((b,), np.float32)
        top_k = np.zeros((b,), np.int32)
        top_p = np.ones((b,), np.float32)
        fp = np.zeros((b,), np.float32)
        pp = np.zeros((b,), np.float32)
        rp = np.ones((b,), np.float32)
        do_sample = np.zeros((b,), bool)
        from_slot = np.zeros((b,), bool)

        for i, (seq, start, length) in enumerate(rows):
            # Decode rows only (start at/after the prefill target): a
            # length-1 resume-prefill chunk must read its host token, not
            # the in-flight sampled one.
            if (seq.inflight_samples > 0 and length == 1
                    and start >= seq.prefill_target()):
                # The input token was sampled by a still-in-flight step; the
                # compiled step reads it from slot_toks on device.
                from_slot[i] = True
            else:
                chunk = seq.tokens[start : start + length]
                tokens[i, : len(chunk)] = chunk
            q_start[i] = start
            q_len[i] = length
            ids = seq.block_ids[:nblk]  # beyond-coverage blocks never read
            bt[i, : len(ids)] = ids
            slots[i] = max(seq.slot, 0)
            so = seq.req.sampling_options
            temp[i] = so.temperature if so.temperature is not None else 1.0
            top_k[i] = so.top_k or 0
            top_p[i] = so.top_p if so.top_p is not None else 1.0
            fp[i] = so.frequency_penalty or 0.0
            pp[i] = so.presence_penalty or 0.0
            rp[i] = so.repetition_penalty or 1.0
            do_sample[i] = sample_rows[i]
            if temp[i] > 0.0 or fp[i] != 0.0 or pp[i] != 0.0 or rp[i] != 1.0:
                fast_greedy = False

        # Multimodal: chunks intersecting an embedding span carry the
        # encoder outputs for those positions. NOT gated on t>1 — a
        # length-1 prefill tail (chunk budget, prefix-cache hit leaving one
        # token) can land inside a span, and serving the placeholder
        # embedding there would poison the digest-keyed prefix cache.
        # Decode/window rows start at/after the prompt end, so they never
        # intersect and mm stays False for them naturally.
        emb_override = None
        for i, (seq, start, length) in enumerate(rows):
            if not seq.mm_spans or start >= seq.mm_end:
                continue  # decode rows skip the span scan with one compare
            for pos, emb in seq.mm_spans:
                lo = max(pos, start)
                hi = min(pos + emb.shape[0], start + length)
                if lo >= hi:
                    continue
                if emb_override is None:
                    emb_override = np.zeros(
                        (b, t, self.cfg.hidden_size), np.float32)
                    emb_mask = np.zeros((b, t), bool)
                emb_override[i, lo - start:hi - start] = \
                    emb[lo - pos:hi - pos]
                emb_mask[i, lo - start:hi - start] = True
        mm = emb_override is not None

        if masked:
            fast_greedy = False
            logit_mask = np.zeros((b, self.cfg.vocab_size), np.float32)
            for i, m in enumerate(masks):
                if m is not None:
                    logit_mask[i, ~m] = -1e30
        led = self._ledger
        cold = led.enabled and (
            (b, t, nblk, sp_prefill, window, fast_greedy, mm, masked)
            not in self._step_fns)
        fn = self.step_fn(b, t, nblk, sp_prefill, window, fast_greedy, mm,
                          masked)
        place = self._place
        extra = ((place(emb_override), place(emb_mask)) if mm else ())
        if masked:
            extra = (*extra, place(logit_mask))
        if cold:
            # jit compiles lazily: the cache miss pays its trace+compile
            # wall INSIDE the fn(...) call below (only execution stays
            # async), so timing the call measures the engine-thread stall.
            led.mark_inflight(True)
            t_compile = time.perf_counter()
        (self.cache_k, self.cache_v, self.counts, self.keys, self.slot_toks,
         toks, lps) = fn(
            self.params, self.cache_k, self.cache_v, self.counts, self.keys,
            self.slot_toks,
            place(tokens), place(q_start), place(q_len),
            place(bt), place(slots), place(temp),
            place(top_k), place(top_p), place(fp),
            place(pp), place(rp), place(do_sample),
            place(from_slot), *extra,
        )
        if cold:
            dt = time.perf_counter() - t_compile
            led.mark_inflight(False)
            kind = ("window" if window > 1
                    else "decode" if t == 1
                    else "mixed" if mixed else "prefill")
            led.record(
                BucketSig(kind, b, t, nblk, fast_greedy,
                          ec.kv_dtype or "bfloat16"),
                dt,
                trace_ctx=next((s.trace_ctx for s, _, _ in rows
                                if s.trace_ctx is not None), None))
        return toks, lps

    def run(
        self,
        rows: list[tuple[Seq, int, int]],
        sample_rows: list[bool],
    ) -> tuple[np.ndarray, np.ndarray]:
        """Dispatch one step and block for host results (tokens, logprobs)."""
        toks, lps = self.dispatch(rows, sample_rows)
        n = len(rows)
        return np.asarray(toks)[:n], np.asarray(lps)[:n]

    # -- speculative verify --------------------------------------------
    def _build_verify_fn(self, b: int, t: int, nblk: int):
        """One forward over a [B, t] chunk of (current token + proposed
        continuation), returning the ARGMAX token and its logprob at EVERY
        position — the speculative-decoding verify step (engine/spec.py).
        Greedy-only by contract (callers gate on greedy+penalty-free rows),
        so no sampling state is read or written; KV for all positions is
        written (rejected positions are overwritten by later true tokens)."""
        cfg = self.cfg
        attn_impl = self.attn_impl
        moe_impl = "ep" if self.engine_cfg.ep > 1 else "dense"
        mesh = self.mesh
        attn_splits = self.engine_cfg.attn_num_splits

        def verify(params, ck, cv, tokens, q_start, q_len, bt):
            hidden, ck, cv = llama.forward(
                params, cfg, tokens, q_start, q_len, bt, ck, cv,
                attn_impl=attn_impl, moe_impl=moe_impl, mesh=mesh,
                return_all_hidden=True, attn_num_splits=attn_splits)
            logits = llama.logits_from_hidden(params, cfg, hidden).astype(jnp.float32)
            toks = jnp.argmax(logits, axis=-1).astype(jnp.int32)      # [B, t]
            lps = jnp.take_along_axis(jax.nn.log_softmax(logits, axis=-1),
                                      toks[..., None], axis=-1)[..., 0]
            return ck, cv, toks, lps

        kw = {}
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from dynamo_tpu.parallel.mesh import kv_cache_spec, kv_scale_spec

            repl = NamedSharding(self.mesh, P())
            cache = NamedSharding(self.mesh, kv_cache_spec())
            if self.spec.quantized:
                cache = {"q": cache,
                         "s": NamedSharding(self.mesh, kv_scale_spec())}
            kw["out_shardings"] = (cache, cache, repl, repl)
        return jax.jit(verify, donate_argnums=(1, 2), **kw)

    def dispatch_verify(self, rows: list[tuple[Seq, int, int]],
                        chunks: list[list[int]]) -> tuple[jax.Array, jax.Array]:
        """Enqueue one verify step; chunk tokens are EXPLICIT (the proposals
        are not in seq.tokens yet). Returns ([B, t] argmax tokens, lps)."""
        ec = self.engine_cfg
        n = len(rows)
        t_max = max(len(c) for c in chunks)
        b = _bucket(n, ec.decode_bucket)
        # clamp: _pow2_bucket's hi stops further doubling but doesn't cap
        # the result — a 5-token chunk must not mint (and pay for) T=8
        t = min(_pow2_bucket(t_max, 2, ec.spec_k + 1), ec.spec_k + 1)
        # Same coverage-based table-width bucketing as dispatch(): the
        # verify chunk reads nothing past start + len(chunk).
        bsz = ec.block_size
        nblk_need = max(
            min(len(seq.block_ids), -(-(start + len(c)) // bsz))
            for (seq, start, _), c in zip(rows, chunks))
        nblk = min(_pow2_bucket(max(nblk_need, 1), 4, self.max_nblk), self.max_nblk)

        tokens = np.zeros((b, t), np.int32)
        q_start = np.zeros((b,), np.int32)
        q_len = np.zeros((b,), np.int32)
        bt = np.zeros((b, nblk), np.int32)
        for i, (seq, start, length) in enumerate(rows):
            tokens[i, : len(chunks[i])] = chunks[i]
            q_start[i] = start
            q_len[i] = len(chunks[i])
            ids = seq.block_ids[:nblk]
            bt[i, : len(ids)] = ids

        key = ("verify", b, t, nblk)
        led = self._ledger
        cold = led.enabled and key not in self._step_fns
        if key not in self._step_fns:
            log.info("compiling verify fn B=%d T=%d NBLK=%d", b, t, nblk)
            self._step_fns[key] = self._build_verify_fn(b, t, nblk)
        fn = self._step_fns[key]
        place = self._place
        if cold:
            led.mark_inflight(True)
            t_compile = time.perf_counter()
        self.cache_k, self.cache_v, toks, lps = fn(
            self.params, self.cache_k, self.cache_v,
            place(tokens), place(q_start), place(q_len), place(bt))
        if cold:
            dt = time.perf_counter() - t_compile
            led.mark_inflight(False)
            led.record(
                BucketSig("verify", b, t, nblk, True,
                          ec.kv_dtype or "bfloat16"),
                dt,
                trace_ctx=next((s.trace_ctx for s, _, _ in rows
                                if s.trace_ctx is not None), None))
        return toks, lps

    # -- embeddings ----------------------------------------------------
    def _build_embed_fn(self, b: int, t: int):
        """Prefill-only forward returning the final-norm hidden state at the
        last prompt token (the /v1/embeddings pooling; reference route:
        lib/llm/src/http/service/openai.rs:1132). Uses a TRANSIENT cache
        built inside the jit — embedding calls never touch (or contend with)
        the serving KV pool."""
        cfg = self.cfg
        ec = self.engine_cfg
        nblk = -(-t // ec.block_size) + 1

        def embed(params, tokens, q_len):
            shape = (cfg.num_layers, nblk + 1, ec.block_size,
                     cfg.num_kv_heads, cfg.head_dim)
            ck = jnp.zeros(shape, jnp.dtype(cfg.dtype))
            cv = jnp.zeros(shape, jnp.dtype(cfg.dtype))
            bt = jnp.tile(jnp.arange(1, nblk + 1, dtype=jnp.int32)[None, :],
                          (tokens.shape[0], 1))
            q_start = jnp.zeros((tokens.shape[0],), jnp.int32)
            hidden, _, _ = llama.forward(
                params, cfg, tokens, q_start, q_len, bt, ck, cv,
                attn_impl="dense", mesh=self.mesh)
            return hidden.astype(jnp.float32)

        kw = {}
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            kw["out_shardings"] = NamedSharding(self.mesh, P())
        return jax.jit(embed, **kw)

    def embed(self, token_lists: list[list[int]]) -> np.ndarray:
        """Embed a batch of token sequences → [N, H] float32 (last-token
        pooled, final-norm space)."""
        out = np.zeros((len(token_lists), self.cfg.hidden_size), np.float32)
        t_max = max(len(ts) for ts in token_lists)
        if t_max > self.engine_cfg.max_model_len:
            raise ValueError(
                f"embedding input of {t_max} tokens exceeds max_model_len="
                f"{self.engine_cfg.max_model_len}")
        t = _pow2_bucket(t_max, 16, self.engine_cfg.max_model_len)
        # Bounded bucket ladder: client batch sizes must not mint unbounded
        # compile-cache entries (each compile blocks the engine-core thread).
        b = _bucket(len(token_lists), (1, 2, 4, 8, 16, 32, 64))
        key = ("embed", b, t, 0, 0)
        led = self._ledger
        cold = led.enabled and key not in self._step_fns
        if key not in self._step_fns:
            log.info("compiling embed fn B=%d T=%d", b, t)
            self._step_fns[key] = self._build_embed_fn(b, t)
        fn = self._step_fns[key]
        tokens = np.zeros((b, t), np.int32)
        q_len = np.zeros((b,), np.int32)
        for i, ts in enumerate(token_lists):
            tokens[i, : len(ts)] = ts
            q_len[i] = len(ts)
        if cold:
            led.mark_inflight(True)
            t_compile = time.perf_counter()
        hidden = np.asarray(fn(self.params, self._place(tokens), self._place(q_len)))
        if cold:
            led.mark_inflight(False)
            led.record(
                BucketSig("embed", b, t, 0, True,
                          self.engine_cfg.kv_dtype or "bfloat16"),
                time.perf_counter() - t_compile)
        out[:] = hidden[: len(token_lists)]
        return out

    # -- AOT bucket warmup ---------------------------------------------
    def warmup(self, sigs: list[BucketSig], deadline_s: float = 0.0) -> dict:
        """Precompile the enumerated bucket lattice (obs/compile_ledger.py)
        by executing each program once with padding inputs: q_len=0 rows
        compute nothing meaningful, do_sample=False routes sampling-state
        writes to the trash row, and KV writes land in pool block 0 —
        which every real prefill rewrites before anything reads it. jit
        caches executables per call signature, so this mints exactly the
        cache entries serving dispatches would otherwise compile lazily
        (and the ledger's inventory ends equal to the enumeration).
        ``deadline_s`` bounds the total wall (0 = unbounded); lattice
        entries past the deadline stay cold and count against coverage."""
        led = self._ledger
        t0 = time.perf_counter()
        compiled = cached = failed = skipped = 0
        for sig in sigs:
            if deadline_s > 0 and time.perf_counter() - t0 >= deadline_s:
                skipped += 1
                continue
            try:
                hit = self._warm_one(sig)
            except Exception:
                log.warning("warmup compile failed for %s", sig,
                            exc_info=True)
                failed += 1
                continue
            cached += 1 if hit else 0
            compiled += 0 if hit else 1
        summary = {"compiled": compiled, "cached": cached, "failed": failed,
                   "deadline_skipped": skipped,
                   "seconds": round(time.perf_counter() - t0, 3),
                   "coverage": round(led.coverage(), 4)}
        log.info("bucket warmup: %s", summary)
        return summary

    def _warm_one(self, sig: BucketSig) -> bool:
        """Compile+execute one bucket signature with padding inputs.
        Returns True when the program was already cached (no compile)."""
        b, t, nblk = sig.b, sig.t, sig.nblk
        place = self._place
        t0 = time.perf_counter()
        if sig.kind == "embed":
            key = ("embed", b, t, 0, 0)
            if key in self._step_fns:
                return True
            self._step_fns[key] = self._build_embed_fn(b, t)
            np.asarray(self._step_fns[key](
                self.params, place(np.zeros((b, t), np.int32)),
                place(np.zeros((b,), np.int32))))
        elif sig.kind == "verify":
            key = ("verify", b, t, nblk)
            if key in self._step_fns:
                return True
            self._step_fns[key] = self._build_verify_fn(b, t, nblk)
            self.cache_k, self.cache_v, toks, _lps = self._step_fns[key](
                self.params, self.cache_k, self.cache_v,
                place(np.zeros((b, t), np.int32)),
                place(np.zeros((b,), np.int32)),
                place(np.zeros((b,), np.int32)),
                place(np.zeros((b, nblk), np.int32)))
            np.asarray(toks)
        else:
            window = (self.engine_cfg.decode_window
                      if sig.kind == "window" else 1)
            key = (b, t, nblk, False, window, sig.greedy, False, False)
            if key in self._step_fns:
                return True
            fn = self.step_fn(b, t, nblk, False, window, sig.greedy,
                              False, False)
            zi = np.zeros((b,), np.int32)
            zf = np.zeros((b,), np.float32)
            ones = np.ones((b,), np.float32)
            zb = np.zeros((b,), bool)
            (self.cache_k, self.cache_v, self.counts, self.keys,
             self.slot_toks, toks, _lps) = fn(
                self.params, self.cache_k, self.cache_v, self.counts,
                self.keys, self.slot_toks,
                place(np.zeros((b, t), np.int32)), place(zi), place(zi),
                place(np.zeros((b, nblk), np.int32)), place(zi),
                place(zf), place(zi), place(ones),
                place(zf), place(zf), place(ones),
                place(zb), place(zb))
            np.asarray(toks)
        self._ledger.record(sig, time.perf_counter() - t0, source="warmup")
        return False


class EngineCore:
    """Synchronous engine: scheduler + runner + output assembly."""

    def __init__(
        self,
        engine_cfg: EngineConfig,
        mesh=None,
        params=None,
        event_sink: Callable[[KvCacheEvent], None] | None = None,
    ):
        if engine_cfg.sp > 1 and engine_cfg.ring_prefill_threshold >= 0 and (
            engine_cfg.prefill_chunk < engine_cfg.max_model_len
            or engine_cfg.max_tokens_per_step < engine_cfg.max_model_len
        ):
            # Sequence-parallel engines prefill whole prompts as ONE
            # seq-sharded chunk (ring attention needs the chunk to be the
            # entire context); chunking — whether by prefill_chunk or by the
            # scheduler's per-step token budget — would push later chunks
            # (start != 0) onto the dense path and waste the sp axis. Copy
            # the config rather than mutating the caller's.
            import dataclasses as _dc
            log.info(
                "sp=%d: raising prefill_chunk %d and max_tokens_per_step %d -> "
                "max_model_len %d", engine_cfg.sp, engine_cfg.prefill_chunk,
                engine_cfg.max_tokens_per_step, engine_cfg.max_model_len)
            engine_cfg = _dc.replace(
                engine_cfg,
                prefill_chunk=max(engine_cfg.prefill_chunk, engine_cfg.max_model_len),
                max_tokens_per_step=max(engine_cfg.max_tokens_per_step,
                                        engine_cfg.max_model_len),
            )
        self.engine_cfg = engine_cfg
        if engine_cfg.spec_ngram > 0:
            if engine_cfg.decode_window > 1:
                raise ValueError(
                    "spec_ngram and decode_window>1 are mutually exclusive "
                    "(both amortize dispatches over future tokens; pick one)")
            if engine_cfg.pp > 1:
                raise ValueError("spec_ngram requires pp=1 (forward_pp has "
                                 "no all-positions output)")
        if engine_cfg.pp > 1 and (engine_cfg.tp > 1 or engine_cfg.ep > 1
                                  or engine_cfg.sp > 1):
            raise ValueError(
                "pp>1 currently composes only with dp; tp/ep/sp must be 1 "
                "(the PP stage block is not head/expert/sequence-sharded — "
                "see models/llama.forward_pp)")
        if engine_cfg.quantization not in ("none", "", "int8"):
            # Validate here, before any weight IO — a typo must fail in
            # milliseconds, not after loading/sharding a 70B checkpoint.
            raise ValueError(
                f"unknown quantization {engine_cfg.quantization!r} "
                "(supported: none, int8)")
        if engine_cfg.kv_dtype not in ("bfloat16", "", "int8", "int4"):
            raise ValueError(
                f"unknown kv_dtype {engine_cfg.kv_dtype!r} "
                "(supported: bfloat16 [model-precision cache], int8, int4)")
        if engine_cfg.attn_num_splits < 0:
            raise ValueError(
                f"attn_num_splits must be >= 0 (0 = auto), "
                f"got {engine_cfg.attn_num_splits}")
        if engine_cfg.warmup_mode not in WARMUP_MODES:
            raise ValueError(
                f"unknown warmup_mode {engine_cfg.warmup_mode!r} "
                f"(supported: {', '.join(WARMUP_MODES)})")
        if engine_cfg.warmup_deadline < 0:
            raise ValueError(
                f"warmup_deadline must be >= 0 (0 = unbounded), "
                f"got {engine_cfg.warmup_deadline}")
        # Compile ledger gate (obs/compile_ledger.py): configured before
        # the runner exists so every compile this engine ever mints is
        # governed by the same mode; the enumerated lattice doubles as the
        # coverage denominator in lazy mode (grows organically) and the
        # precompile worklist in full mode (EngineCore.warmup).
        get_compile_ledger().configure(engine_cfg.warmup_mode)
        # Scheduling ledger gate (obs/sched_ledger.py): re-read the
        # DYN_SCHED_LEDGER env at engine construction so tests flipping
        # the env see the gate they set.
        self.sched_led = get_sched_ledger()
        self.sched_led.configure()
        self.model_cfg = resolve_model_config(engine_cfg.model)
        if engine_cfg.kv_dtype == "int4" and self.model_cfg.head_dim % 2:
            raise ValueError(
                f"kv_dtype=int4 packs two nibbles per byte along head_dim and "
                f"needs it even; model {engine_cfg.model!r} has head_dim="
                f"{self.model_cfg.head_dim}")
        # SLO-driven chunk sizing (prefill_chunk=0 = auto): resolve to
        # concrete per-QoS chunks BEFORE bucket enumeration and the
        # scheduler read the config — the prefill t ladder, warmup plan
        # and per-step token budget all key off ec.prefill_chunk, so auto
        # must not leave a 0 behind. The cap is the batch class's chunk
        # (largest SLO budget); interactive/standard refine downward
        # per-seq inside the scheduler.
        from dynamo_tpu.obs import costmodel as cm
        self._hw = cm.hw_spec_for(jax.devices()[0].device_kind)
        if engine_cfg.prefill_chunk <= 0:
            import dataclasses as _dc
            ladder_cap = min(engine_cfg.max_model_len,
                             engine_cfg.max_tokens_per_step)
            self.chunk_by_qos = {
                qos: cm.auto_prefill_chunk(
                    self.model_cfg, self._hw,
                    itl_slo_s=engine_cfg.itl_slo_ms / 1e3,
                    decode_rows=engine_cfg.max_batch_size,
                    decode_kv_len=max(engine_cfg.max_model_len // 2,
                                      engine_cfg.block_size),
                    block_size=engine_cfg.block_size,
                    max_chunk=ladder_cap,
                    kv_dtype=engine_cfg.kv_dtype or "bfloat16",
                    quantization=engine_cfg.quantization or "none",
                    qos_class=qos)
                for qos in cm.QOS_ITL_SLO_SCALE}
            resolved = max(self.chunk_by_qos.values())
            log.info("auto prefill chunk (itl_slo=%.1fms): %s -> cap %d",
                     engine_cfg.itl_slo_ms, self.chunk_by_qos, resolved)
            engine_cfg = _dc.replace(engine_cfg, prefill_chunk=resolved)
            self.engine_cfg = engine_cfg
        else:
            self.chunk_by_qos = {qos: engine_cfg.prefill_chunk
                                 for qos in cm.QOS_ITL_SLO_SCALE}
        self.sched_led.set_prefill_chunks(self.chunk_by_qos)
        # Unified ragged mixed-phase steps: one launch per iteration when
        # prefill work rides along. Fused decode windows keep the legacy
        # path (a window is a decode-only scan).
        self._unified = (engine_cfg.unified_step
                         and engine_cfg.decode_window == 1)
        if mesh is None and any(v != 1 for v in engine_cfg.mesh_shape().values()):
            mesh = make_mesh(MeshConfig(dp=engine_cfg.dp, pp=engine_cfg.pp,
                                        sp=engine_cfg.sp, tp=engine_cfg.tp,
                                        ep=engine_cfg.ep))
        self.runner = ModelRunner(self.model_cfg, engine_cfg, mesh=mesh, params=params,
                                  rng_seed=engine_cfg.seed)
        if engine_cfg.warmup_mode != "off":
            # Publish the reachable lattice so coverage is meaningful even
            # before (or without) a full warmup — a lazy engine's coverage
            # gauge climbs as traffic mints buckets.
            get_compile_ledger().set_plan(enumerate_buckets(engine_cfg))
        self.pool = PrefixPool(
            self.runner.spec.num_blocks,
            engine_cfg.block_size,
            event_sink=event_sink,
            enable_prefix_caching=engine_cfg.enable_prefix_caching,
        )
        self.sched = Scheduler(
            pool=self.pool,
            max_batch_size=engine_cfg.max_batch_size,
            prefill_chunk=engine_cfg.prefill_chunk,
            max_model_len=engine_cfg.max_model_len,
            max_tokens_per_step=engine_cfg.max_tokens_per_step,
            decode_window=engine_cfg.decode_window,
            spec_lookahead=(engine_cfg.spec_k if engine_cfg.spec_ngram > 0
                            else 0),
            chunk_by_qos=self.chunk_by_qos,
        )
        # Session-sticky KV retention (engine/session.py): finished streams
        # carrying a session.id keep their committed blocks pinned so the
        # next turn prefills only the suffix. Needs prefix caching — the
        # retained chain is claimed through the normal admission-time
        # match_prefix, which is also how avoided tokens get MEASURED.
        self.sessions: SessionStore | None = None
        if engine_cfg.session_ttl > 0 and engine_cfg.enable_prefix_caching:
            self.sessions = SessionStore(self.pool,
                                         ttl=engine_cfg.session_ttl)
        self.metrics = EngineMetrics(
            kv_cache_bytes=(self.runner.spec.bytes_per_block()
                            * self.runner.spec.num_blocks),
            kv_quant_enabled=self.runner.spec.quantized,
        )
        # Hardware counters: analytic FLOPs/bytes + MFU/BW-util per step
        # (obs/profiler.py). DYN_PERF_PROFILE=0 turns the whole thing into
        # a no-op dict lookup per step.
        self.perf = StepPerfProfiler(self.model_cfg, engine_cfg)
        self._seqs: dict[str, Seq] = {}
        self.default_eos: list[int] = []
        # Tracing: decode spans rotate every N generated tokens — one span
        # (one allocation) per N steps, never per token (obs/tracer.py).
        import os as _os
        self._trace_stride = max(
            int(_os.environ.get("DYN_TRACE_DECODE_STRIDE", "32")), 1)
        self._trace_last_preempt = 0
        # Deadline clock for the current step window. On multi-host engines
        # the leader stamps it over the op stream so every rank evaluates
        # deadline expiry against the SAME instant — per-rank wall clocks
        # would let ranks disagree on a cancellation and diverge.
        self._step_now: float | None = None
        # Structured output: token-id → text table + tokenizer EOS, built
        # lazily on the first guided request (engine/guided.py).
        self._guided_vocab: tuple[list[str], list[int]] | None = None
        self.kvbm: "OffloadManager | None" = None
        if (engine_cfg.host_kv_blocks > 0 or engine_cfg.disk_kv_path
                or engine_cfg.remote_kv_addr):
            from dynamo_tpu.kvbm.offload import OffloadManager
            from dynamo_tpu.kvbm.pools import DiskBlockPool, HostBlockPool

            # Multi-host engines: every rank runs this same construction in
            # SPMD lockstep (op-stream replay keeps decisions identical);
            # tiers then hold rank-LOCAL cache shards and extract/inject
            # touch only addressable memory (kvbm/distributed.py — the
            # reference's KvbmLeader/KvbmWorker split without the control
            # channel, distributed/leader.rs:126, worker.rs:143).
            transfer = None
            tier_spec, fp = self.runner.spec, engine_cfg.model
            disk_path = engine_cfg.disk_kv_path
            if jax.process_count() > 1:
                from dynamo_tpu.kvbm.distributed import (
                    ShardedBlockTransferEngine,
                    local_block_spec,
                )

                transfer = ShardedBlockTransferEngine(self.runner.mesh)
                tier_spec, shard_fp = local_block_spec(
                    self.runner.spec, self.runner.cache_k)
                fp = f"{engine_cfg.model}|{shard_fp}"
                if disk_path:
                    # Per-rank subdir: ranks colocated on one filesystem
                    # must not fight over one MANIFEST/arena.
                    disk_path = str(Path(disk_path) /
                                    f"rank{jax.process_index()}")
            # Cascade G2 host → G3 disk → G4 remote: each tier spills its
            # LRU victims to the next, lookups walk the chain top-down.
            remote = None
            if engine_cfg.remote_kv_addr:
                from dynamo_tpu.kvbm.remote import RemoteBlockPool

                remote = RemoteBlockPool(tier_spec, engine_cfg.remote_kv_addr,
                                         fingerprint=fp)
            disk = (DiskBlockPool(tier_spec, disk_path,
                                  engine_cfg.disk_kv_bytes,
                                  fingerprint=fp,
                                  overflow=remote)
                    if disk_path else None)
            tiers: list = []
            if engine_cfg.host_kv_blocks > 0:
                tiers.append(HostBlockPool(tier_spec, engine_cfg.host_kv_blocks,
                                           overflow=disk or remote))
            if disk is not None:
                tiers.append(disk)
            if remote is not None:
                tiers.append(remote)
            self.kvbm = OffloadManager(
                self.runner, self.pool, tiers, transfer=transfer,
                # The shared G4 store can't guarantee rank-identical
                # hit/miss (cross-engine LRU, connection hiccups), so
                # multi-host onboard plans are voted down to the mesh-wide
                # minimum (OffloadManager.vote_plans) instead of refused.
                vote_plans=(jax.process_count() > 1
                            and bool(engine_cfg.remote_kv_addr)),
                # Fleet-wide prefix cache: committed blocks publish to the
                # shared G4 store as they form, not only on eviction.
                publish_tier=(remote if engine_cfg.global_prefix_cache
                              else None),
                # Stream checkpoints park in the same shared store. Direct
                # remote writes are single-host only (same rule as
                # evacuate_sessions: a rank's KV shard in the SHARED store
                # would corrupt cross-worker reads); multi-host ranks all
                # see ckpt_tier=None, so enqueue stays rank-identical.
                ckpt_tier=(remote
                           if (engine_cfg.stream_ckpt_blocks > 0
                               and jax.process_count() == 1)
                           else None))
        # Memory & capacity ledger (obs/mem_ledger.py): re-read the
        # DYN_MEM_LEDGER env at construction (same contract as the sched
        # ledger above), publish this engine's device pool as the G1 tier
        # row, register every KVBM tier's occupancy callback, and hand the
        # audit a live-id source so orphaned pins reconcile against what
        # this engine actually holds. Tier callbacks and the live source
        # are pulled only at snapshot/audit time, never on the step path.
        self.mem_led = get_mem_ledger()
        self.mem_led.configure()
        register_device_tier(self.pool, self.runner.spec)
        if self.kvbm is not None:
            for tier in self.kvbm.tiers:
                self.mem_led.register_tier(tier.name, tier.occupancy)
        self._mem_source_key = f"engine:{id(self):x}"
        self.mem_led.register_live_source(self._mem_source_key,
                                          self._mem_live_ids)

    def _mem_live_ids(self) -> dict:
        """Per-owner-class live ids for the mem-ledger leak audit. A pin
        tagged under any class but absent from the matching set here is an
        orphan — a reference the engine no longer knows about."""
        staged = getattr(self, "_staged_pins", {})
        return live_ids_of(
            streams=self._seqs.keys(),
            sessions=(self.sessions.session_ids()
                      if self.sessions is not None else ()),
            **(self.kvbm.queue_live_ids() if self.kvbm is not None else {}),
            staging=staged.keys(),
        )

    def _guided_pieces(self) -> tuple[list[str], list[int]]:
        if self._guided_vocab is None:
            from dynamo_tpu.tokenizer import guided_vocab, load_tokenizer

            tok = load_tokenizer(self.engine_cfg.model)
            pieces = guided_vocab(tok, self.runner.cfg.vocab_size)
            eos = getattr(tok, "eos_id", None)
            self._guided_vocab = (pieces, [eos] if eos is not None else [])
        return self._guided_vocab

    # ------------------------------------------------------------------
    def warmup(self) -> dict:
        """AOT bucket warmup (obs/compile_ledger.py). Runs BEFORE the
        engine serves (the worker calls it between construction and
        readiness, on the thread that will become the engine-core owner's
        predecessor — no step loop is running yet, so device state has one
        owner throughout). ``off``/``lazy`` return immediately; ``full``
        precompiles the enumerated lattice under ``warmup_deadline``."""
        ec = self.engine_cfg
        led = get_compile_ledger()
        out: dict = {"mode": ec.warmup_mode,
                     "coverage": round(led.coverage(), 4)}
        if ec.warmup_mode != "off" and led.plan is not None:
            out["buckets"] = len(led.plan)
        if ec.warmup_mode == "full":
            out.update(self.runner.warmup(
                sorted(led.plan or enumerate_buckets(ec),
                       key=lambda s: (s.kind, s.b, s.t, s.nblk, s.greedy)),
                deadline_s=ec.warmup_deadline))
        return out

    # ------------------------------------------------------------------
    def add_request(self, req: PreprocessedRequest,
                    now: float | None = None) -> LLMEngineOutput | None:
        """Queue a request; returns an immediate error output if rejected.
        `now` pins the deadline-expiry clock (multi-host replay passes the
        leader's timestamp so all ranks make the same admit decision)."""
        if not req.token_ids:
            return LLMEngineOutput(
                finish_reason=FinishReason.ERROR, error="empty prompt (no token_ids)"
            )
        from dynamo_tpu.qos.deadline import deadline_of, expired

        if expired(deadline_of(getattr(req, "annotations", None)), now):
            # Already past deadline: never enters the scheduler, so no
            # prefill compute is ever dispatched for it.
            self.metrics.deadline_cancelled += 1
            return LLMEngineOutput(finish_reason=FinishReason.CANCELLED)
        seq = Seq(req=req, block_size=self.engine_cfg.block_size)
        if req.sampling_options.guided_json is not None:
            from dynamo_tpu.engine.guided import TokenMasker

            pieces, tok_eos = self._guided_pieces()
            eos_ids = list(req.eos_token_ids or self.default_eos or tok_eos)
            seq.guided = TokenMasker(pieces, eos_ids,
                                     req.sampling_options.guided_json)
        if req.mm_embeddings:
            if self.engine_cfg.sp > 1 or self.engine_cfg.pp > 1:
                return LLMEngineOutput(
                    finish_reason=FinishReason.ERROR,
                    error="multimodal requests require sp=1 and pp=1 "
                          "(the ring/pipeline prefill paths have no "
                          "embedding-override input yet)")
            from dynamo_tpu.protocols.common import tensor_from_wire

            try:
                seq.mm_spans = [(int(s["pos"]), tensor_from_wire(s))
                                for s in req.mm_embeddings]
            except Exception as exc:  # noqa: BLE001 - malformed client input
                return LLMEngineOutput(
                    finish_reason=FinishReason.ERROR,
                    error=f"bad mm_embeddings payload: {exc}")
            H = self.model_cfg.hidden_size
            for pos, emb in seq.mm_spans:
                if (emb.ndim != 2 or emb.shape[1] != H or pos < 0
                        or pos + emb.shape[0] > len(req.token_ids)):
                    return LLMEngineOutput(
                        finish_reason=FinishReason.ERROR,
                        error=f"mm span (pos={pos}, shape={emb.shape}) out of "
                              f"range for prompt len {len(req.token_ids)} / "
                              f"hidden {H}")
            seq.mm_end = max(pos + emb.shape[0] for pos, emb in seq.mm_spans)
        self.sched.add(seq)
        if seq.phase is Phase.FINISHED:  # rejected (too long for model or pool)
            return LLMEngineOutput(
                finish_reason=FinishReason.ERROR,
                error=f"prompt of {seq.prompt_len} tokens exceeds capacity "
                      f"(max_model_len={self.engine_cfg.max_model_len}, "
                      f"usable_kv_blocks={self.pool.num_blocks - 1})",
            )
        self._seqs[req.request_id] = seq
        seq.trace_ctx = trace_context_of(getattr(req, "annotations", None))
        if seq.trace_ctx is not None:
            # Admission wait starts now; step_begin ends it when the first
            # prefill chunk is planned (engine.queue → engine.prefill).
            seq.trace_span = get_tracer().start_span(
                "engine.queue", ctx=seq.trace_ctx,
                request_id=req.request_id, model=req.model,
                prompt_tokens=seq.prompt_len, priority=seq.qos_priority)
        if self.sessions is not None and seq.session_id is not None:
            # Turn N+1 of a retained session: release the store's pins so
            # the chain parks in the matchable inactive pool; this seq's
            # admission-time match_prefix re-references it an instant later
            # (single-threaded core — nothing allocates in between). The
            # avoided-token count is MEASURED from that match in step_begin,
            # not taken from the entry.
            sm = get_session_metrics()
            sm.lookups.inc()
            if self.sessions.claim(seq.session_id, self._step_now) is not None:
                sm.hits.inc()
            else:
                # No local turn retained: a drained worker may have parked
                # the session in the remote store. A record hit means the
                # kvbm.onboard below pulls the evacuated chain back warm —
                # count it as a (remote) session hit for the chaos
                # invariants and the dynamo_session_* family.
                remote = self._remote_tier()
                if (remote is not None
                        and getattr(remote, "get_session", None) is not None
                        and remote.get_session(seq.session_id)):
                    sm.hits.inc()
                    sm.remote_resumes.inc()
                    self.metrics.session_remote_resumes += 1
        if self.kvbm is not None:
            # Same matchable cap as the scheduler: leave ≥1 prompt token to
            # compute so decode has last-position state. Onboarding is an
            # optimization — a corrupt tier entry must not take down the
            # engine-core thread (add_request runs outside step()'s guard).
            cap = (seq.prefill_target() - 1) // seq.block_size
            try:
                self.kvbm.onboard(seq.block_seq.sequence_hashes()[:cap])
            except Exception:
                log.exception("kvbm onboard failed; continuing without reuse")
        self.metrics.prefix_lookup_blocks += max(len(seq.tokens) // seq.block_size, 1)
        return None

    def abort(self, request_id: str) -> None:
        seq = self._seqs.get(request_id)
        if seq is None or seq.phase is Phase.FINISHED:
            return
        self._reap_stream_ckpt(seq)
        self._trace_finish(seq, FinishReason.CANCELLED)
        self.sched.finish(seq, FinishReason.CANCELLED)

    def has_work(self) -> bool:
        return self.sched.has_work()

    # ------------------------------------------------------------------
    def _check_stop(self, seq: Seq, token: int) -> FinishReason | None:
        sc = seq.req.stop_conditions
        n_out = seq.num_output_tokens
        if seq.deadline_ts is not None:
            from dynamo_tpu.qos.deadline import expired

            if expired(seq.deadline_ts, self._step_now):
                # Mid-decode deadline: nobody is waiting for the rest of
                # this stream — stop burning decode steps on it.
                self.metrics.deadline_cancelled += 1
                return FinishReason.CANCELLED
        eos_ids = set(seq.req.eos_token_ids or self.default_eos)
        if token in (sc.stop_token_ids or []):
            return FinishReason.STOP
        if token in eos_ids and not sc.ignore_eos and (sc.min_tokens or 0) <= n_out:
            return FinishReason.STOP
        if sc.max_tokens is not None and n_out >= sc.max_tokens:
            return FinishReason.LENGTH
        if len(seq.tokens) >= self.engine_cfg.max_model_len:
            return FinishReason.LENGTH
        return None

    # -- crash-consistent stream checkpoints (kvbm/stream_ckpt.py) -------
    def _init_slot(self, seq: Seq) -> None:
        """Reset a seq's sampling slot — restoring mid-stream PRNG state
        and penalty counts when the request carries stream_ckpt.* resume
        annotations. Every stream gets a concrete seed (explicit or
        request-derived), so the key after n draws is a pure function of
        the request — the invariant that makes sampled resume bit-identical
        at decode_window=1."""
        so = seq.req.sampling_options
        seed = so.seed if so.seed is not None else _derived_seed(
            seq.request_id)
        ann = getattr(seq.req, "annotations", None) or {}
        gen = int(ann.get(CKPT_GENERATED_KEY) or 0)
        if gen <= 0:
            self.runner.reset_slot(seq.slot, seed)
            return
        gen = min(gen, seq.prompt_len)
        self.runner.reset_slot(
            seq.slot, seed,
            advance=int(ann.get(CKPT_DRAWS_KEY) or gen),
            # The resume prompt's trailing ledger: rebuild the penalty
            # counts the crashed worker had accumulated.
            resume_tokens=seq.tokens[seq.prompt_len - gen:seq.prompt_len])

    def _ckpt_interval(self, seq: Seq) -> int:
        """Committed-block cadence for this seq, QoS-degraded from the
        --stream-ckpt-blocks base: interactive streams checkpoint at the
        configured interval, standard at 2x, batch at 4x — crash exposure
        is a latency-SLO product, and batch recompute is cheap relative to
        the store traffic it saves. 0 = checkpointing off."""
        base = self.engine_cfg.stream_ckpt_blocks
        if base <= 0:
            return 0
        if seq.qos_priority == "interactive":
            return base
        return base * (4 if seq.qos_priority == "batch" else 2)

    def _maybe_stream_ckpt(self, seq: Seq) -> None:
        """Enqueue a StreamCheckpoint when due: once at prefill completion
        (the first emit's commit), then every interval committed blocks.
        The decision reads only the commit stream + config, so multi-host
        ranks stay in lockstep (the enqueue itself no-ops there —
        ckpt_tier is single-host, see EngineCore.__init__)."""
        k = self._ckpt_interval(seq)
        if (k <= 0 or self.kvbm is None or self.kvbm.ckpt_tier is None
                or seq.committed_blocks <= 0):
            return
        if 0 <= seq.ckpt_blocks and seq.committed_blocks - seq.ckpt_blocks < k:
            return
        start = max(seq.ckpt_blocks, 0)
        hashes = seq.block_seq.sequence_hashes()[: seq.committed_blocks]
        pairs = list(zip(seq.block_ids[start:seq.committed_blocks],
                         hashes[start:]))
        generated = seq.tokens[seq.prompt_len:]
        so = seq.req.sampling_options
        seed = so.seed if so.seed is not None else _derived_seed(
            seq.request_id)
        # Threefry key data is just the seed's two 32-bit words — the
        # record carries the full PRNG state (key + draw counter) without
        # touching the device.
        record = build_ckpt_record(
            seq.request_id, generated, hashes,
            key_data=[(seed >> 32) & 0xFFFFFFFF, seed & 0xFFFFFFFF],
            draws=len(generated), seed=seed, prompt_tokens=seq.prompt_len)
        span = None
        if seq.trace_ctx is not None:
            span = get_tracer().start_span(
                "engine.ckpt", ctx=seq.trace_ctx, request_id=seq.request_id,
                blocks=len(pairs), generated=len(generated))
        self.kvbm.enqueue_stream_ckpt(seq.request_id, record, pairs)
        if span is not None:
            get_tracer().end_span(span)
        seq.ckpt_blocks = seq.committed_blocks

    def _reap_stream_ckpt(self, seq: Seq) -> None:
        """Finish-time reap: a finished stream (any reason) must not be
        resumable. Only streams that ever checkpointed pay the store
        round-trip."""
        if self.kvbm is not None and seq.ckpt_blocks >= 0:
            self.kvbm.delete_stream_ckpt(seq.request_id)

    def ckpt_lag_blocks(self) -> int:
        """Committed blocks of live streams not yet covered by a
        checkpoint — the fleet's crash exposure, exported as
        dynamo_stream_ckpt_lag_blocks."""
        return sum(max(s.committed_blocks - max(s.ckpt_blocks, 0), 0)
                   for s in list(self._seqs.values())
                   if s.phase is not Phase.FINISHED)

    def step_begin(self) -> "PendingStep | None":
        """Plan one engine step and DISPATCH it to the device without
        blocking on results. Host-side state is advanced speculatively
        (positions, block growth — everything value-independent), so the
        caller may plan+dispatch the NEXT step while this one computes:
        the sampled tokens stay on device (slot_toks) and feed the next
        decode step directly. Value-dependent effects (token append, hash
        commit, stop conditions) happen in :meth:`step_finalize`, which
        lags by however many steps the caller keeps in flight.

        This is the host/device overlap the reference-class engines get
        from async scheduling — expressed TPU-style: the host never waits
        to build step N+1, and a finished/stopped stream costs at most one
        speculative row, discarded at finalize.
        """
        if self.sessions is not None:
            self._session_sweep()
        plan = self.sched.plan()
        if self.kvbm is not None:
            # Write back blocks evicted during planning before their slots
            # are rewritten by this step's KV scatter (batched: one bucketed
            # transfer instead of per-eviction round-trips).
            self.kvbm.flush_pending()
        self.metrics.num_preemptions = self.sched.preemption_count
        if plan.empty:
            return None
        self.metrics.num_steps += 1
        self._trace_plan(plan)
        if self.sessions is not None:
            # Avoided-token accounting: the blocks a session turn did NOT
            # recompute are exactly its admission-time prefix hit — a
            # measured quantity, counted once per seq on its first planned
            # chunk.
            for w in plan.prefill:
                seq = w.seq
                if seq.session_id is not None and not seq.session_counted:
                    seq.session_counted = True
                    if seq.prefix_hit_blocks:
                        get_session_metrics().avoided_tokens.inc(
                            seq.prefix_hit_blocks * seq.block_size)
        # Checkpoint-resume accounting mirrors the session pattern: the
        # recompute a crash actually cost is the resume prompt MINUS what
        # the admission onboard pulled back warm — measured once, on the
        # first planned chunk.
        for w in plan.prefill:
            seq = w.seq
            if seq.ckpt_counted:
                continue
            seq.ckpt_counted = True
            ann = getattr(seq.req, "annotations", None) or {}
            if int(ann.get(CKPT_GENERATED_KEY) or 0) > 0:
                self.metrics.stream_ckpt_resumes += 1
                sm = get_stream_ckpt_metrics()
                sm.resumes.inc(1)
                sm.resume_recomputed_tokens.inc(max(
                    seq.prefill_target()
                    - seq.prefix_hit_blocks * seq.block_size, 0))

        for seq in [w.seq for w in plan.prefill] + plan.decode:
            if not seq.slot_initialized and seq.slot >= 0:
                self._init_slot(seq)
                seq.slot_initialized = True

        # Unified mode: decode rows and the step's prefill-chunk rows pack
        # into ONE ragged "mixed" program (per-row live-token counts ride
        # the scalar-prefetch path, so padding costs DMA-elided grid steps,
        # not FLOPs). Legacy mode (--no-unified-step, or decode_window>1)
        # runs them as two bucketed programs, decode first — see the
        # scheduler module docstring.
        pending = PendingStep()
        batches: list[tuple[str, list, list[bool], int, list | None]] = []
        decode_seqs = plan.decode
        guided_rows: list = []
        if any(s.guided is not None for s in decode_seqs):
            rest = []
            for s in decode_seqs:
                if s.guided is None:
                    rest.append(s)
                elif s.inflight_samples == 0:
                    # Unpipelined by design: the mask for token t needs
                    # token t-1 materialized on the host.
                    guided_rows.append((s, s.num_computed, 1))
                # else: pause this cycle until the in-flight token lands
            decode_seqs = rest
        if self.engine_cfg.spec_ngram > 0 and decode_seqs:
            verify_rows, verify_chunks, decode_seqs = self._plan_verify(decode_seqs)
            if verify_rows:
                toks, lps = self.runner.dispatch_verify(verify_rows, verify_chunks)
                for seq, start, length in verify_rows:
                    seq.num_computed = start + length
                    seq.inflight_samples += 1
                    seq.verify_inflight = True
                pending.batches.append(
                    ("verify", verify_rows, verify_chunks, toks, lps))
        pf_rows, pf_sample_rows, pf_masks = [], [], None
        if plan.prefill:
            pf_rows = [(w.seq, w.start, w.length) for w in plan.prefill]
            # Sample only on the chunk completing a *fresh* prompt; a
            # preempt-resumed seq already holds its next token (the resume
            # prefill just rebuilds KV) so sampling would duplicate output.
            pf_sample_rows = [
                w.start + w.length >= w.seq.prefill_target()
                and len(w.seq.tokens) == w.seq.prompt_len
                for w in plan.prefill
            ]
            if any(w.seq.guided is not None and s for w, s in
                   zip(plan.prefill, pf_sample_rows)):
                # The FIRST sampled token must already obey the grammar.
                pf_masks = [
                    w.seq.guided.mask()
                    if (w.seq.guided is not None and pf_sample_rows[i])
                    else None
                    for i, w in enumerate(plan.prefill)]
        if self._unified and pf_rows:
            # One ragged launch: decode rows, guided decode rows (their
            # masks join per-row), then the prefill chunks. dispatch()
            # classifies a degenerate all-length-1 batch back to "decode".
            rows = ([(s, s.num_computed, 1) for s in decode_seqs]
                    + guided_rows + pf_rows)
            sample_rows = ([True] * (len(decode_seqs) + len(guided_rows))
                           + pf_sample_rows)
            pending.mixed_dec_rows = len(decode_seqs) + len(guided_rows)
            masks = None
            if guided_rows or pf_masks is not None:
                masks = ([None] * len(decode_seqs)
                         + [s.guided.mask() for s, _, _ in guided_rows]
                         + (pf_masks if pf_masks is not None
                            else [None] * len(pf_rows)))
            batches.append(("mixed", rows, sample_rows, 1, masks))
        else:
            if decode_seqs:
                rows = [(s, s.num_computed, 1) for s in decode_seqs]
                batches.append(("decode", rows, [True] * len(rows),
                                plan.decode_window, None))
            if guided_rows:
                batches.append(("decode", guided_rows,
                                [True] * len(guided_rows), 1,
                                [s.guided.mask() for s, _, _ in guided_rows]))
            if pf_rows:
                batches.append(("prefill", pf_rows, pf_sample_rows, 1,
                                pf_masks))

        for kind, rows, sample_rows, window, b_masks in batches:
            toks, lps = self.runner.dispatch(rows, sample_rows, window=window,
                                             masks=b_masks,
                                             mixed=(kind == "mixed"))
            # Value-independent bookkeeping, done at dispatch so the next
            # plan() sees advanced positions. Token metrics count at
            # finalize, so discarded speculative rows don't inflate them.
            advance = window if kind == "decode" else None
            for i, (seq, start, length) in enumerate(rows):
                seq.num_computed = start + (advance or length)
                if sample_rows[i]:
                    seq.inflight_samples += 1
            pending.batches.append((kind, rows, sample_rows, toks, lps))
        if self.sched_led.enabled:
            used = (len(plan.decode) * plan.decode_window
                    + sum(w.length for w in plan.prefill))
            hol = None
            if plan.prefill and plan.decode:
                # Every decode-ready stream in this step waits out the
                # prefill work before its token materializes; the culprit
                # is the request contributing the largest chunk. Under the
                # unified step the stall is NOT a whole separate launch —
                # only the chunk's marginal share of the mixed step's wall
                # (priced by the cost model) is charged to the victims.
                culprit = max(plan.prefill, key=lambda w: w.length)
                stall_share = None
                if self._unified:
                    from dynamo_tpu.obs import costmodel as cm
                    kw = dict(
                        decode_rows=len(plan.decode),
                        decode_kv_len=max(s.num_computed
                                          for s in plan.decode),
                        chunk_kv_len=max(w.start + w.length
                                         for w in plan.prefill),
                        block_size=self.engine_cfg.block_size,
                        kv_dtype=self.engine_cfg.kv_dtype or "bfloat16",
                        quantization=self.engine_cfg.quantization or "none")
                    mixed_s = cm.mixed_step_seconds(
                        self.model_cfg, self._hw,
                        chunk=sum(w.length for w in plan.prefill), **kw)
                    pure_s = cm.mixed_step_seconds(
                        self.model_cfg, self._hw, chunk=0, **kw)
                    if mixed_s > 0:
                        stall_share = max(mixed_s - pure_s, 0.0) / mixed_s
                hol = HolStall(
                    culprit=culprit.seq.request_id,
                    culprit_tokens=sum(w.length for w in plan.prefill),
                    victims=[(s.trace_ctx, s.request_id, s.qos_priority)
                             for s in plan.decode],
                    stall_share=stall_share)
            pending.sched = {
                "decode_window": plan.decode_window,
                "budget_util": used / max(self.sched.max_tokens_per_step, 1),
                "hol": hol,
            }
        return pending

    def _trace_plan(self, plan: StepPlan) -> None:
        """Advance per-seq phase spans from the step plan. Spans are
        observational only — multi-host ranks may record different wall
        times but never make different decisions off them. Untraced seqs
        (no obs.traceparent annotation) cost one None check here."""
        tr = None
        for w in plan.prefill:
            s = w.seq
            sp = s.trace_span
            if s.trace_ctx is None or (sp is not None
                                       and sp.name == "engine.prefill"):
                continue  # untraced, or a later chunk of the same prefill
            tr = tr or get_tracer()
            if sp is not None:
                # queue→prefill admit, or a preempt-resume out of decode.
                extra = ({"tokens": s.trace_tokens}
                         if sp.name == "engine.decode" and s.trace_tokens
                         else {})
                tr.end_span(sp, prefix_hit_blocks=s.prefix_hit_blocks,
                            **extra)
            s.trace_span = tr.start_span(
                "engine.prefill", ctx=s.trace_ctx, request_id=s.request_id,
                prompt_tokens=s.prompt_len,
                prefix_hit_blocks=s.prefix_hit_blocks)
            s.trace_tokens = 0
        for s in plan.decode:
            if s.trace_ctx is None:
                continue
            sp = s.trace_span
            if sp is not None and sp.name == "engine.decode":
                s.trace_tokens += plan.decode_window
                if s.trace_tokens >= self._trace_stride:
                    tr = tr or get_tracer()
                    tr.end_span(sp, tokens=s.trace_tokens,
                                batch=len(plan.decode))
                    s.trace_span = tr.start_span(
                        "engine.decode", ctx=s.trace_ctx,
                        request_id=s.request_id)
                    s.trace_tokens = 0
                continue
            tr = tr or get_tracer()
            if sp is not None:  # prefill complete: decode begins
                tr.end_span(sp)
            s.trace_span = tr.start_span(
                "engine.decode", ctx=s.trace_ctx, request_id=s.request_id,
                batch=len(plan.decode))
            s.trace_tokens = plan.decode_window

    def _trace_finish(self, seq: Seq, reason: FinishReason | None) -> None:
        sp = seq.trace_span
        if sp is None:
            return
        seq.trace_span = None
        status = "ok"
        if reason is FinishReason.CANCELLED:
            status = "cancelled"
        elif reason is FinishReason.ERROR:
            status = "error"
        attrs: dict = {"finish_reason": str(reason) if reason else "",
                       "output_tokens": seq.num_output_tokens}
        if sp.name == "engine.decode" and seq.trace_tokens:
            attrs["tokens"] = seq.trace_tokens
        get_tracer().end_span(sp, status=status, **attrs)

    def _record_step(self, t0: float, pending: "PendingStep") -> None:
        """Always-on step profile: one ring append per engine step."""
        n_pf = n_dec = 0
        for kind, rows, *_ in pending.batches:
            if kind == "prefill":
                n_pf += len(rows)
            elif kind == "mixed":
                n_dec += pending.mixed_dec_rows
                n_pf += len(rows) - pending.mixed_dec_rows
            else:
                n_dec += len(rows)
        pc = self.sched.preemption_count
        wall = time.perf_counter() - t0
        get_tracer().recorder.steps.record(
            time.time(), wall,
            num_prefill=n_pf, num_decode=n_dec,
            num_waiting=self.sched.num_waiting,
            num_preempted=pc - self._trace_last_preempt,
            occupancy=(self.sched.num_running
                       / max(self.engine_cfg.max_batch_size, 1)),
            **self.perf.measure(pending.batches, wall))
        self._trace_last_preempt = pc
        if self.sched_led.enabled:
            info = pending.sched or {}
            self.sched_led.record_step(
                wall_s=wall,
                decode_window=info.get("decode_window", 1),
                budget_util=info.get("budget_util", 0.0),
                queue_depths=self.sched.waiting.depths(),
                hol=info.get("hol"),
                **step_geometry(self.model_cfg, self.engine_cfg,
                                pending.batches,
                                mixed_dec_rows=pending.mixed_dec_rows))
        if self.mem_led.enabled:
            # Capacity forecast + leak audit cadence ride the step clock:
            # free-pool observations feed the per-QoS EWMA consumption
            # rates behind dynamo_mem_ttx_seconds, and maybe_audit is a
            # no-op until audit_interval_s has elapsed.
            self.mem_led.observe_device(
                free=self.pool.num_free_raw,
                cached=self.pool.num_inactive,
                total=self.pool.num_blocks - 1)
            self.mem_led.observe_free(self.pool.num_free, now=time.time())
            self.mem_led.maybe_audit(time.time())

    def _plan_verify(self, decode_seqs: list
                     ) -> tuple[list, list[list[int]], list]:
        """Partition decode seqs into speculative-verify rows and plain
        decode. A seq verifies when it is greedy + penalty-free (verify is
        argmax-exact only then), its last token is host-known (no in-flight
        device-fed sample), and the n-gram proposer finds a continuation
        (engine/spec.py).

        Pipelined entry: under the overlapped step loop a decode seq's last
        token is ALWAYS still in flight at plan time — so when the known
        prefix already shows a repetition signal (a proposal exists even
        without the pending token), the seq PAUSES one plan cycle (dropped
        from this step) so its token materializes and the next plan can
        verify. The bubble costs one cycle; an accepted run repays it with
        up to spec_k+1 tokens. No signal → plain pipelined decode, no
        bubble."""
        from dynamo_tpu.engine.spec import greedy_eligible, propose

        ec = self.engine_cfg
        verify_rows, verify_chunks, plain = [], [], []
        for seq in decode_seqs:
            if seq.guided is not None or not greedy_eligible(seq.req.sampling_options):
                plain.append(seq)
                continue
            # cap proposals to stay inside the model context
            k = min(ec.spec_k, ec.max_model_len - 1 - seq.num_computed)
            proposal = propose(seq.tokens, ec.spec_ngram, k) if k > 0 else []
            if seq.inflight_samples > 0:
                if not proposal:
                    plain.append(seq)   # no signal: stay fully pipelined
                # else: pause this cycle (dispatch nothing for this seq)
                continue
            if not proposal:
                plain.append(seq)
                continue
            start = seq.num_computed
            chunk = [seq.tokens[start], *proposal]
            verify_rows.append((seq, start, len(chunk)))
            verify_chunks.append(chunk)
            self.metrics.spec_proposed += len(proposal)
        return verify_rows, verify_chunks, plain

    def _emit_and_finish(self, seq, candidates: list[int], lps_row,
                         outputs: dict[str, LLMEngineOutput],
                         count_decode: bool) -> int:
        """THE finalize tail, shared by decode/window and verify batches so
        the greedy-equivalence guarantee can't drift between them: append
        candidate tokens until a stop fires, commit blocks, transfer
        prefix-hit stats, assemble the output, run finish bookkeeping.
        Returns the number of tokens emitted."""
        emitted: list[int] = []
        reason = None
        for token in candidates:
            seq.tokens.append(token)
            seq.block_seq.append(token)
            emitted.append(token)
            if seq.guided is not None:
                seq.guided.advance(token)
            reason = self._check_stop(seq, token)
            if reason is not None:
                break
        if count_decode:
            self.metrics.num_decode_tokens += len(emitted)
        self.sched.commit_computed_blocks(seq)
        if reason is None:
            # Checkpoint cadence rides the commit stream: first at prefill
            # completion (this seq's first emit), then every interval
            # committed blocks. Finishing streams skip straight to the reap.
            self._maybe_stream_ckpt(seq)
        if seq.prefix_hit_blocks:
            self.metrics.prefix_hit_blocks += seq.prefix_hit_blocks
            seq.prefix_hit_blocks = 0
        per_tok = [float(x) for x in lps_row[: len(emitted)]]
        out = LLMEngineOutput(
            token_ids=emitted,
            cum_log_probs=sum(per_tok),
            log_probs=per_tok,
        )
        if reason is not None:
            out.finish_reason = reason
            self._reap_stream_ckpt(seq)
            if (self.sessions is not None and seq.session_id is not None
                    and reason in (FinishReason.STOP, FinishReason.LENGTH)):
                # Retain BEFORE sched.finish releases the seq's refs: the
                # session pin increfs the committed chain while it is still
                # active, so there is no instant where turn N's KV is
                # evictable. Cancelled/errored streams never retain.
                self._retain_session(seq)
            self._trace_finish(seq, reason)
            self.sched.finish(seq, reason)
            self.metrics.num_requests_finished += 1
            del self._seqs[seq.request_id]
        outputs[seq.request_id] = out
        return len(emitted)

    def step_finalize(self, pending: "PendingStep") -> dict[str, LLMEngineOutput]:
        """Materialize a dispatched step's tokens and apply value-dependent
        effects: append tokens, commit full blocks (hash chain), evaluate
        stop conditions, assemble per-request outputs."""
        t0 = time.perf_counter()
        outputs: dict[str, LLMEngineOutput] = {}
        for kind, rows, sample_rows, toks_dev, lps_dev in pending.batches:
            if kind == "verify":
                self._finalize_verify(rows, sample_rows, toks_dev, lps_dev,
                                      outputs)
                continue
            n = len(rows)
            # Normalize to [n, W]: single-step dispatches return [B], fused
            # decode windows [B, W] — one finalize path serves both.
            toks = np.asarray(toks_dev)[:n].reshape(n, -1)
            lps = np.asarray(lps_dev)[:n].reshape(n, -1)
            width = toks.shape[1]
            for i, (seq, start, length) in enumerate(rows):
                if seq.phase is Phase.FINISHED:
                    # Finished (stop/abort) while this step was in flight:
                    # its speculative row is discarded.
                    continue
                # A mixed batch's leading rows are decode rows (the split
                # was captured at plan time); everything after them, and
                # every row of a plain prefill batch, counts as prefill.
                decode_row = (kind == "decode"
                              or (kind == "mixed"
                                  and i < pending.mixed_dec_rows))
                if not decode_row:
                    self.metrics.num_prefill_tokens += length
                if sample_rows[i]:
                    seq.inflight_samples -= 1
                if not sample_rows[i]:
                    # Intermediate prefill chunk: no token emitted. (A seq
                    # preempted while in flight is WAITING with num_computed
                    # reset to 0 — commit is then a no-op.)
                    self.sched.commit_computed_blocks(seq)
                    continue
                # Append window tokens until a stop fires; the rest of the
                # window is discarded (its KV lives in blocks this seq owns,
                # freed at finish).
                self._emit_and_finish(
                    seq, [int(x) for x in toks[i]], lps[i], outputs,
                    count_decode=decode_row)
        self._record_step(t0, pending)
        if self.kvbm is not None and not self.sched.has_work():
            # Engine going idle: this finalize's commits would otherwise sit
            # in the publish-on-commit queue until the next step_begin —
            # which may be a long time away on a drained worker.
            self.kvbm.drain_publish()
        return outputs

    def _finalize_verify(self, rows, chunks, toks_dev, lps_dev,
                         outputs: dict[str, LLMEngineOutput]) -> None:
        """Accept/rollback a speculative verify step (engine/spec.py).

        Position j's argmax is on the true greedy path iff every earlier
        proposal matched; accepted tokens append exactly as decode tokens
        would have, the rest of the chunk rolls back (its KV is stale but
        unreachable — later true tokens overwrite those positions)."""
        from dynamo_tpu.engine.spec import accept

        n = len(rows)
        toks = np.asarray(toks_dev)[:n]
        lps = np.asarray(lps_dev)[:n]
        for i, (seq, start, length) in enumerate(rows):
            seq.verify_inflight = False
            if seq.phase is Phase.FINISHED:
                continue  # finished (abort) while in flight: discard
            seq.inflight_samples -= 1
            emitted_all = accept(chunks[i], [int(x) for x in toks[i, :length]])
            # Untouched-state check: a preemption while in flight reset
            # num_computed — leave its bookkeeping alone, discard the step.
            in_flight_intact = seq.num_computed == start + length
            if in_flight_intact:
                # Roll back to the KV-valid bound BEFORE the commit inside
                # _emit_and_finish: KV at position start+j was computed from
                # input chunk[j], which is a true token only for
                # j < len(emitted_all). With the optimistic start+length
                # still in place, a rejection landing on a block boundary
                # would commit a block whose last slot holds KV from the
                # rejected proposal token — poisoning the shared prefix pool
                # for every later request (and G2+ offloads) with that chain.
                # (A stop firing mid-candidates finishes the seq inside
                # _emit_and_finish, so no tighter post-call restore is
                # needed: a live seq always emits all of emitted_all.)
                seq.num_computed = start + len(emitted_all)
            n_emitted = self._emit_and_finish(
                seq, emitted_all, lps[i], outputs, count_decode=True)
            self.metrics.spec_accepted += max(n_emitted - 1, 0)

    def set_step_time(self, now: float | None) -> None:
        """Pin the deadline clock for the next step window (op-stream
        replay passes the leader's timestamp; see _step_now)."""
        self._step_now = now

    def has_expired_waiting(self, now: float | None = None) -> bool:
        from dynamo_tpu.qos.deadline import expired

        return any(expired(s.deadline_ts, now) for s in self.sched.waiting)

    def reap_expired(self, now: float | None = None) -> dict[str, LLMEngineOutput]:
        """Cancel WAITING seqs whose deadline passed and emit their terminal
        outputs. Waiting seqs never flow through step batches, so without an
        explicit reap an expired queued request would only die on admission."""
        outs: dict[str, LLMEngineOutput] = {}
        for seq in self.sched.expire_waiting(now):
            self._seqs.pop(seq.request_id, None)
            self.metrics.deadline_cancelled += 1
            self._trace_finish(seq, FinishReason.CANCELLED)
            outs[seq.request_id] = LLMEngineOutput(finish_reason=FinishReason.CANCELLED)
        return outs

    # -- session-sticky KV retention (engine/session.py) ----------------
    def _retain_session(self, seq: Seq) -> None:
        """Pin a finishing stream's committed chain under its session id."""
        hashes = seq.block_seq.sequence_hashes()[: seq.committed_blocks]
        self.sessions.retain(seq.session_id, hashes, self._step_now)
        # Capacity cap: LRU sessions demote (not just drop) so a later turn
        # can still re-import from the KVBM ladder.
        while len(self.sessions) > self.sessions.max_sessions:
            popped = self.sessions.pop_oldest()
            if popped is None:  # pragma: no cover - len()>0 guarantees one
                break
            self._demote_session(*popped)

    def _session_sweep(self) -> None:
        """TTL + pool-pressure valve, run before each plan().

        TTL expiry uses the leader-stamped step clock, so multi-host ranks
        release the same sessions on the same step. The pressure valve
        mirrors the admission watermark: while the head-of-line waiting seq
        cannot admit because session pins hold the pool, release the oldest
        sessions first — retained turns must never starve live traffic.
        """
        for sid, entry in self.sessions.pop_expired(self._step_now):
            self._demote_session(sid, entry)
        sched = self.sched
        while len(self.sessions) and sched.waiting:
            head = sched.waiting[0]
            need = head.blocks_needed(len(head.tokens))
            if need + len(sched.running) <= self.pool.num_free:
                break
            if need + len(sched.running) > (self.pool.num_free
                                            + self.sessions.pinned_blocks):
                break  # releasing every pin still wouldn't admit; keep them
            popped = self.sessions.pop_oldest()
            if popped is None:  # pragma: no cover - len() checked above
                break
            self._demote_session(*popped)

    def _demote_session(self, session_id: str, entry) -> None:
        """Release a retained entry's pins, first write-staging the chain
        down the KVBM tier ladder (host→disk→remote) when session_tiers is
        on — so a post-eviction turn re-imports instead of recomputing."""
        sm = get_session_metrics()
        sm.expired.inc()
        if (self.engine_cfg.session_tiers and self.kvbm is not None
                and entry.pinned):
            try:
                staged = self.kvbm.stage_blocks(
                    list(zip(entry.pinned, entry.seq_hashes)))
                sm.demoted_blocks.inc(staged)
            except Exception:
                log.exception("session %s: tier demotion failed; releasing "
                              "pins to LRU", session_id)
        if self.mem_led.enabled and entry.pinned:
            self.mem_led.record_churn("device", "session_demote",
                                      len(entry.pinned))
        self.pool.release(entry.pinned)
        entry.pinned = []

    def _remote_tier(self):
        """The shared remote tier in the KVBM ladder, or None."""
        if self.kvbm is None:
            return None
        for tier in self.kvbm.tiers:
            if getattr(tier, "name", "") == "remote":
                return tier
        return None

    def evacuate_sessions(self, _args: dict | None = None) -> dict:
        """Drain step 4 (runtime/drain.py): push every retained session's
        device chain plus a resumable record to the shared remote store,
        then release the pins — turn N+1 on a surviving worker pulls the
        chain back warm instead of recomputing. Engine-core thread only
        (CORE_OPS "session_evacuate"). Multi-host engines fall back to the
        tier-ladder demotion: each rank holds only its KV shard, and a
        shard written to the SHARED store would corrupt cross-worker reads.
        """
        out = {"sessions": 0, "blocks": 0, "bytes": 0}
        if self.sessions is None:
            return out
        remote = self._remote_tier()
        direct = remote is not None and jax.process_count() == 1
        while True:
            popped = self.sessions.pop_oldest()
            if popped is None:
                break
            sid, entry = popped
            try:
                if direct and entry.pinned:
                    blocks = self.transfer.extract(
                        self.runner.cache_k, self.runner.cache_v, entry.pinned)
                    for h, block in zip(entry.seq_hashes, blocks):
                        remote.put(h, block)
                        out["blocks"] += 1
                        out["bytes"] += int(getattr(block, "nbytes", 0))
                    if remote.put_session(sid, list(entry.seq_hashes),
                                          entry.tokens):
                        out["sessions"] += 1
                elif (self.kvbm is not None and entry.pinned):
                    # No direct path: stage down the local ladder so at least
                    # a restart of THIS worker re-imports instead of
                    # recomputing. No resumable record — survivors can't
                    # reach these blocks.
                    self.kvbm.stage_blocks(
                        list(zip(entry.pinned, entry.seq_hashes)))
            except Exception:
                log.exception("session %s evacuation failed; its blocks fall "
                              "to the LRU", sid)
            self.pool.release(entry.pinned)
            entry.pinned = []
        return out

    def abort_class(self, priority: str | None = None) -> list[str]:
        """Abort every live request of one QoS class (None = all) — the
        drain run-down's early-stop valve (runtime/drain.py abort_batch /
        abort_all). Returns the aborted request ids so the async wrapper
        can emit their terminal CANCELLED outputs."""
        rids = [rid for rid, seq in self._seqs.items()
                if seq.phase is not Phase.FINISHED
                and (priority is None or seq.qos_priority == priority)]
        for rid in rids:
            self.abort(rid)
        return rids

    def step(self) -> dict[str, LLMEngineOutput]:
        """Run one engine step synchronously; returns per-request deltas."""
        now = time.time()
        self.set_step_time(now)
        outs = self.reap_expired(now)
        pending = self.step_begin()
        if pending is not None:
            outs.update(self.step_finalize(pending))
        return outs

    # -- disagg / KV-transfer primitives (engine-core thread only) ---------
    @property
    def transfer(self):
        if self.kvbm is not None:  # share jit caches with the offload path
            return self.kvbm.transfer
        if getattr(self, "_transfer", None) is None:
            if jax.process_count() > 1:
                # Multi-host cache arrays span processes: extract/inject must
                # stay shard-local (a plain np.asarray of the global array
                # would need non-addressable shards).
                from dynamo_tpu.kvbm.distributed import ShardedBlockTransferEngine

                self._transfer = ShardedBlockTransferEngine(self.runner.mesh)
            else:
                from dynamo_tpu.kvbm.transfer import BlockTransferEngine

                self._transfer = BlockTransferEngine()
        return self._transfer

    def export_blocks(self, seq_hashes: list[int]) -> list[tuple[int, int | None, np.ndarray]]:
        """Gather the device-resident prefix of a hash chain off the device.
        The prefill side of disaggregated serving (reference: the NIXL
        kv_transfer_params handoff, components/src/dynamo/vllm/handlers.py)."""
        ids, kept = [], []
        parent: int | None = None
        for h in seq_hashes:
            bid = self.pool.block_for_hash(h)
            if bid is None:
                break
            ids.append(bid)
            kept.append((h, parent))
            parent = h
        if not ids:
            return []
        blocks = self.transfer.extract(self.runner.cache_k, self.runner.cache_v, ids)
        return [(h, par, data) for (h, par), data in zip(kept, blocks)]

    def import_blocks(self, plan: list[tuple[int, int | None, np.ndarray]],
                      span_attrs: dict | None = None) -> int:
        """Inject externally-received blocks as matchable cache entries —
        the decode side of disaggregated serving. Hashes already on device
        are skipped (and MRU-protected)."""
        from dynamo_tpu.kvbm.offload import inject_and_commit, plan_onboard

        by_hash = {h: data for h, _, data in plan}
        filtered = plan_onboard(self.pool, [h for h, _, _ in plan], by_hash.get)
        flush = self.kvbm.flush_pending if self.kvbm is not None else None
        return inject_and_commit(self.runner, self.pool, self.transfer, filtered,
                                 flush=flush, span_attrs=span_attrs)

    def pin_blocks(self, seq_hashes: list[int]) -> list[int]:
        """Incref the device-resident prefix of a chain so it survives until
        a pending transfer pulls it; pair with unpin_blocks."""
        return self.pool.match_prefix(seq_hashes)

    def unpin_blocks(self, block_ids: list[int]) -> None:
        self.pool.release(block_ids)

    # -- sharded disagg handoff (named ops — replayable on multi-host) -----
    # These bodies run on EVERY rank of a multi-host engine via the op
    # stream (parallel/multihost.py), in SPMD lockstep: pool decisions are
    # deterministic, device work is the same XLA program everywhere, and
    # each rank touches only its addressable cache shard
    # (disagg/sharded.py module docstring has the full design).

    @property
    def staging(self):
        if getattr(self, "_staging", None) is None:
            from dynamo_tpu.disagg.sharded import StagingStore

            self._staging = StagingStore()
            self._staged_pins: dict[str, list[int]] = {}
        return self._staging

    def my_box(self) -> tuple[int, int, int, int]:
        """This rank's (layer, head) extents of the global cache."""
        from dynamo_tpu.engine.cache import cache_payload
        from dynamo_tpu.kvbm.distributed import local_box

        starts, stops = local_box(cache_payload(self.runner.cache_k))
        return (starts[0], stops[0], starts[3], stops[3])

    def start_shard_server(self, advertise_host: str, on_release=None) -> str:
        """Start (once) the per-rank shard server serving staged KV; returns
        the address to advertise in kv_transfer_params. Thread-safe to call
        off the engine-core thread: it only binds a socket and reads the
        (lock-guarded) staging store."""
        if getattr(self, "_shard_server", None) is None:
            from dynamo_tpu.disagg.sharded import ShardServer

            self._shard_server = ShardServer(self.staging, on_release=on_release)
        return f"{advertise_host}:{self._shard_server.port}"

    @staticmethod
    def _vote_min(n: int) -> int:
        from dynamo_tpu.parallel.multihost import vote_min

        return vote_min(n)

    def stage_export(self, xfer_id: str, seq_hashes: list[int]) -> int:
        """Pin the device-resident prefix of a chain and stage this rank's
        cache shard of it to host memory; returns hashes covered. The pin
        holds until release_export, the staging until then too — pulls are
        served from host memory, never re-touching device state.

        Multi-host: the covered count is voted down to the mesh-wide
        minimum (0 if any rank's extract failed), and pins beyond it are
        released — so pin state, staged hash lists, and therefore every
        future eviction decision stay rank-identical."""
        touch = self.staging  # ensure _staged_pins exists on every path
        block_ids = self.pool.match_prefix(seq_hashes)
        data = None
        try:
            if block_ids:
                # Sharded staging box-slices 6-d float data (disagg/
                # sharded.py) — quantized caches stage dequantized blocks;
                # the importer requantizes at its inject boundary.
                blocks = self.transfer.extract(
                    self.runner.cache_k, self.runner.cache_v, block_ids,
                    dequant=self.runner.spec.quantized)
                data = np.stack(blocks)
        except Exception as exc:  # noqa: BLE001 — vote handles divergence
            log.warning("stage_export extract failed: %s", exc)
            data = None
        n = self._vote_min(len(block_ids) if data is not None else 0)
        if n < len(block_ids):
            self.pool.release(block_ids[n:])
            block_ids = block_ids[:n]
        if n == 0:
            return 0
        covered = seq_hashes[:n]
        parents: list[int | None] = [None, *covered[:-1]]
        touch.fill(xfer_id, covered, parents, data[:n], self.my_box())
        self._staged_pins[xfer_id] = block_ids
        if self.mem_led.enabled:
            self.mem_led.pin("staging", xfer_id, len(block_ids))
        return n

    def release_export(self, xfer_id: str) -> None:
        """Unpin + unstage one transfer — final ack AND mid-stream abort.
        For a still-streaming transfer this also tears down the stream
        state, so pins already shipped, staged-but-unpulled, and
        not-yet-staged waves all release together (later kv_stage_wave ops
        for this id become no-ops)."""
        st = getattr(self, "_streams_by_xid", {}).pop(xfer_id, None)
        if st is not None:
            self._stream_exports.pop(st.request_id, None)
        self.staging.drop(xfer_id)
        ids = self._staged_pins.pop(xfer_id, None)
        if ids is not None and self.mem_led.enabled:
            self.mem_led.unpin("staging", xfer_id)
        if ids:
            self.pool.release(ids)

    # -- streamed (wave-granular) export ------------------------------
    # The prefill side of the chunk-streamed handoff: kv_stream_begin
    # declares the full expected chain once, the leader's step loop emits
    # one kv_stage_wave exec op after each finalize that commits new
    # blocks (AsyncJaxEngine._run), and kv_stream_end votes + trims. All
    # three are replayed ops, so pins/staging stay rank-identical; the
    # per-wave extract failure of a single rank is absorbed by pinning
    # regardless and voting the covered count down at stream end.

    def _ensure_streams(self) -> None:
        if getattr(self, "_stream_exports", None) is None:
            self._stream_exports: dict[str, _StreamExport] = {}
            self._streams_by_xid: dict[str, _StreamExport] = {}

    def stream_begin(self, xfer_id: str, request_id: str,
                     seq_hashes: list[int]) -> int:
        """Open a streamed export for ``request_id``'s chain. No device
        work — the staging entry just declares the expected hashes so
        early pulls can wait on waves."""
        touch = self.staging  # ensure _staged_pins exists on every path
        self._ensure_streams()
        st = _StreamExport(xfer_id=xfer_id, request_id=request_id,
                           hashes=list(seq_hashes))
        self._stream_exports[request_id] = st
        self._streams_by_xid[xfer_id] = st
        self._staged_pins.setdefault(xfer_id, [])
        parents: list[int | None] = [None, *st.hashes[:-1]]
        touch.begin(xfer_id, st.hashes, parents, self.my_box(),
                    str(jnp.dtype(self.runner.spec.dtype)))
        return len(st.hashes)

    def stream_wave_targets(self) -> list[tuple[str, int, int]]:
        """Leader-side wave detection (engine-core thread, after
        step_finalize): chains whose committed-block prefix grew past what
        has been staged. Also caches each stream's Seq while it is still
        registered, so the final wave (committed by the finalize that
        finishes the request) is still visible after _seqs drops it."""
        streams = getattr(self, "_stream_exports", None)
        if not streams:
            return []
        out: list[tuple[str, int, int]] = []
        for rid, st in list(streams.items()):
            if st.seq is None:
                st.seq = self._seqs.get(rid)
            if st.seq is None:
                continue
            avail = min(st.seq.committed_blocks, len(st.hashes))
            if avail > st.requested:
                out.append((st.xfer_id, st.requested, avail))
                st.requested = avail
        return out

    def stage_wave(self, xfer_id: str, start: int, stop: int) -> int:
        """Stage blocks [start, stop) of a streamed chain: pin the new
        wave, extract this rank's shard slice, append to staging. NO vote
        here — pin decisions derive from pool state (rank-identical by
        replay); a local extract failure freezes this rank's staged count
        and stream_end's vote trims everyone to the minimum. Returns the
        blocks staged so far on this rank."""
        st = getattr(self, "_streams_by_xid", {}).get(xfer_id)
        if st is None:  # released/aborted while the op was in flight
            return 0
        stop = min(stop, len(st.hashes))
        if stop <= start:
            return st.staged
        # Pin [start, stop) without double-pinning earlier waves:
        # match_prefix increfs the whole resident prefix, so drop the refs
        # below start. The committed prefix can't shrink between the
        # finalize that committed it and this op (no allocate in between),
        # so len(ids) == stop on every rank in the healthy case.
        ids = self.pool.match_prefix(st.hashes[:stop])
        if start:
            self.pool.release(ids[:start])
        keep = ids[start:]
        self._staged_pins.setdefault(xfer_id, []).extend(keep)
        if keep and self.mem_led.enabled:
            self.mem_led.pin("staging", xfer_id, len(keep))
        if st.failed:
            return st.staged
        if len(ids) < stop:
            log.warning("stage_wave %s: only %d/%d blocks resident; "
                        "freezing stream", xfer_id, len(ids), stop)
            st.failed = True
            return st.staged
        try:
            blocks = self.transfer.extract(
                self.runner.cache_k, self.runner.cache_v, keep,
                dequant=self.runner.spec.quantized,
                span_attrs={"phase": "stage", "xfer_id": xfer_id,
                            "start": start, "stop": stop})
            data = np.stack(blocks)
        except Exception as exc:  # noqa: BLE001 — stream_end's vote trims
            log.warning("stage_wave extract failed: %s", exc)
            st.failed = True
            return st.staged
        if st.staged != start or not self.staging.append(xfer_id, start, data):
            st.failed = True
            return st.staged
        st.staged = stop
        from dynamo_tpu.disagg.metrics import get_kv_metrics

        get_kv_metrics().record_wave("stage", int(data.nbytes))
        return st.staged

    def stream_end(self, xfer_id: str) -> int:
        """Close a streamed export: vote the mesh-wide minimum staged
        count, trim pins/staging beyond it, mark the staging entry
        complete. Returns the covered (pullable) block count."""
        st = getattr(self, "_streams_by_xid", {}).pop(xfer_id, None)
        if st is None:
            return 0
        self._stream_exports.pop(st.request_id, None)
        covered = self._vote_min(st.staged)
        pins = self._staged_pins.get(xfer_id, [])
        if len(pins) > covered:
            if self.mem_led.enabled:
                self.mem_led.unpin("staging", xfer_id, len(pins) - covered)
            self.pool.release(pins[covered:])
            self._staged_pins[xfer_id] = pins[:covered]
        self.staging.finalize(xfer_id, covered)
        return covered

    def _fetch_local(self, params: dict, start: int | None = None,
                     stop: int | None = None, clients: dict | None = None):
        """The network half of a pull: fetch + assemble this rank's box
        (the window [start, stop) of the chain; the whole transfer when
        stop is None). Touches no engine state — safe off the core thread.
        ``clients`` is a per-transfer addr→ShardClient cache so wave pulls
        reuse connections. Returns (hashes, parents, local_blocks) or None
        on any failure."""
        from dynamo_tpu.disagg.sharded import (
            ShardClient,
            assemble_local,
            box_intersection,
        )

        spec = self.runner.spec
        box = self.my_box()
        pieces: list[tuple[np.ndarray, tuple[int, int, int, int]]] = []
        hashes: list[int] = []
        parents: list[int | None] = []
        try:
            for sh in params.get("shards", []):
                inter = box_intersection(box, tuple(sh["box"]))
                if inter is None:
                    continue
                if clients is not None:
                    client = clients.get(sh["addr"])
                    if client is None:
                        client = clients[sh["addr"]] = ShardClient(sh["addr"])
                    h, p, flat, got = client.fetch(params["xfer_id"], inter,
                                                   start, stop)
                else:
                    client = ShardClient(sh["addr"], retries=2)
                    try:
                        h, p, flat, got = client.fetch(params["xfer_id"],
                                                       inter, start, stop)
                    finally:
                        client.close()
                if hashes and len(h) != len(hashes):
                    # Shards answered different windows (a partial serve
                    # racing finalize-trim) — the slices no longer tile.
                    raise RuntimeError(
                        f"shard windows diverge: {len(h)} vs {len(hashes)}")
                hashes, parents = h, p  # identical across shards (one chain)
                pieces.append((flat, got))
            local = (assemble_local(box, pieces, len(hashes), spec.block_size,
                                    spec.head_dim, jnp.dtype(spec.dtype))
                     if hashes else None)
        except Exception as exc:  # noqa: BLE001 — nondeterministic IO
            log.warning("shard pull failed: %s", exc)
            return None
        return (hashes, parents, local) if local is not None else None

    def _pull_state(self, xfer_id: str) -> dict:
        if not hasattr(self, "_pulls"):
            self._pulls: dict[str, dict] = {}
        return self._pulls.setdefault(
            xfer_id, {"clients": {}, "waves": {}, "last": None})

    def prefetch_remote(self, params: dict, start: int | None = None,
                        stop: int | None = None, tail: bool = False) -> None:
        """Start the pull's network half on a background thread so engine
        steps keep running while bytes move; import_remote joins it. As a
        replayed op, every rank overlaps ITS fetch with ITS serving — the
        op order stays identical, only the waiting moves off the step
        path. Wave pulls ([start, stop) windows) of one transfer chain on
        a single thread lineage so the per-shard connections are reused
        without cross-thread sharing."""
        state = self._pull_state(params["xfer_id"])
        prev = state["last"]
        slot: dict = {}

        def run() -> None:
            if prev is not None:
                prev["thread"].join()
            with get_tracer().span("kv.transfer", phase="pull",
                                   xfer_id=params["xfer_id"],
                                   start=start if start is not None else 0,
                                   stop=stop if stop is not None else -1,
                                   tail=tail) as sp:
                result = self._fetch_local(params, start, stop,
                                           state["clients"])
                if result is not None:
                    sp.attrs["bytes"] = int(result[2].nbytes)
                    sp.attrs["blocks"] = len(result[0])
            slot["result"] = result

        t = threading.Thread(target=run, name="kv-prefetch", daemon=True)
        slot["thread"] = t
        state["waves"][(start, stop)] = slot
        state["last"] = slot
        t.start()

    def import_remote(self, params: dict, start: int | None = None,
                      stop: int | None = None, final: bool = True) -> int:
        """Join the prefetch (or fetch inline), vote, and inject one
        window of the chain. On a multi-host engine every rank runs this
        as a replayed op; the mesh-wide vote makes fetch failure
        all-or-nothing so per-rank pool state can never diverge (divergent
        pools would mean divergent XLA programs → hung collectives).
        Returns blocks injected, or -1 when the pull failed on some rank
        (no state was mutated anywhere). ``final`` closes the transfer's
        pull state (shard connections) afterwards."""
        state = self._pull_state(params["xfer_id"])
        slot = state["waves"].pop((start, stop), None)
        if slot is not None:
            slot["thread"].join()
            fetched = slot["result"]
        else:
            fetched = self._fetch_local(params, start, stop, state["clients"])
        failed = self._vote_min(1 if fetched is not None else 0) == 0
        if failed:
            self.close_pull(params["xfer_id"])
            return -1
        hashes, parents, local = fetched
        plan = [(h, par, local[i])
                for i, (h, par) in enumerate(zip(hashes, parents))]
        n = self.import_blocks(
            plan, span_attrs={"phase": "import", "xfer_id": params["xfer_id"],
                              "start": start if start is not None else 0,
                              "stop": stop if stop is not None else len(hashes)})
        from dynamo_tpu.disagg.metrics import get_kv_metrics

        get_kv_metrics().record_wave("pull", int(local.nbytes))
        log.info("pulled %d KV blocks for box %s (injected %d)",
                 len(plan), self.my_box(), n)
        if final:
            self.close_pull(params["xfer_id"])
        return n

    def close_pull(self, xfer_id: str) -> None:
        """Tear down a transfer's pull state: close per-shard connections
        and drop pending wave results. Closing the sockets first makes any
        in-flight fetch thread fail fast, so the join is bounded."""
        state = getattr(self, "_pulls", {}).pop(xfer_id, None)
        if state is None:
            return
        for client in state["clients"].values():
            client.close()
        last = state["last"]
        if last is not None and last["thread"].is_alive():
            last["thread"].join(timeout=5.0)

    def run_op(self, name: str, args: dict):
        """Execute one named core op — the replayable subset of run_in_core
        (every rank of a multi-host engine runs the same op with the same
        args, so unlike a closure it CAN ride the op stream)."""
        return CORE_OPS[name](self, args)

    def embed(self, token_lists: list[list[int]]) -> "np.ndarray":
        """Last-token-pooled embeddings (engine-core thread only)."""
        return self.runner.embed(token_lists)

    def fail_all(self, error: str) -> list[str]:
        """Abort every in-flight request (engine-fatal path). Returns the
        request ids that were failed so callers can notify their streams."""
        rids = list(self._seqs)
        for rid in rids:
            self.abort(rid)
        self._seqs.clear()
        if self.sessions is not None:
            # Retained pins must not outlive the requests that made them —
            # a failed engine's pool is rebuilt from scratch anyway.
            self.sessions.release_all()
        return rids


@dataclass
class _StreamExport:
    """Per-request state of a streamed (wave-granular) KV export.

    ``requested`` is leader-only bookkeeping (how far wave detection has
    emitted ops); ``staged`` is this rank's locally-staged prefix, voted
    down to the mesh minimum at stream_end. ``seq`` is cached by the
    leader's wave detection so the final wave — committed by the finalize
    that also finishes the request — is still observable after the seq
    leaves ``_seqs``."""

    xfer_id: str
    request_id: str
    hashes: list[int]
    seq: "Seq | None" = None
    requested: int = 0
    staged: int = 0
    failed: bool = False


# The replayable core-op registry: names + msgpack-able args only, so a
# multi-host leader can broadcast them on the op stream and followers
# replay them in lockstep (the closure-based run_in_core can't cross
# process boundaries and stays single-host-only).
CORE_OPS: dict[str, Callable[["EngineCore", dict], Any]] = {
    "kv_stage": lambda core, a: core.stage_export(a["xfer_id"], a["hashes"]),
    "kv_release": lambda core, a: core.release_export(a["xfer_id"]),
    "kv_prefetch": lambda core, a: core.prefetch_remote(a["params"]),
    "kv_import": lambda core, a: core.import_remote(a["params"]),
    # Streamed (wave-granular) handoff — see EngineCore.stream_begin.
    "kv_stream_begin": lambda core, a: core.stream_begin(
        a["xfer_id"], a["request_id"], a["hashes"]),
    "kv_stage_wave": lambda core, a: core.stage_wave(
        a["xfer_id"], a["start"], a["stop"]),
    "kv_stream_end": lambda core, a: core.stream_end(a["xfer_id"]),
    "kv_prefetch_wave": lambda core, a: core.prefetch_remote(
        a["params"], a["start"], a["stop"], a.get("tail", False)),
    "kv_import_wave": lambda core, a: core.import_remote(
        a["params"], a["start"], a["stop"], a.get("final", False)),
    "kv_pull_abort": lambda core, a: core.close_pull(a["xfer_id"]),
    # Drain-aware retirement (runtime/drain.py): evacuate retained
    # sessions to the remote store; early-stop a QoS class's streams.
    "session_evacuate": lambda core, a: core.evacuate_sessions(a),
    "qos_abort_class": lambda core, a: core.abort_class(
        a.get("priority") if a else None),
}


class OpChannelDown(RuntimeError):
    """The multi-host op broadcast channel failed — the engine cannot
    continue (a rank's devices would be missing from every collective)."""


class AsyncJaxEngine:
    """Async facade: background step-loop thread + asyncio output streams.

    This is what a worker process serves via ``serve_endpoint`` — the analog
    of vLLM's AsyncLLM under the reference (components/src/dynamo/vllm/
    handlers.py generate())."""

    def __init__(self, core: EngineCore, op_sink: Callable[[dict], None] | None = None):
        self.core = core
        # Multi-host leader hook (parallel/multihost.py): every state-
        # changing op is broadcast to follower ranks BEFORE being applied
        # locally, so their engine state machines replay identically.
        self._op_sink = op_sink
        self._channel_down = False
        self._loop: asyncio.AbstractEventLoop | None = None
        self._inbox: thread_queue.Queue = thread_queue.Queue()
        self._streams: dict[str, asyncio.Queue] = {}
        self._wake = threading.Event()
        self._stop = False
        self._thread = threading.Thread(target=self._run, name="engine-core", daemon=True)
        self._started = False

    def start(self) -> None:
        if not self._started:
            self._loop = asyncio.get_running_loop()
            self._thread.start()
            self._started = True

    async def shutdown(self) -> None:
        self._stop = True
        self._wake.set()
        if self._started:
            await asyncio.get_running_loop().run_in_executor(None, self._thread.join, 5.0)
        # A dead engine must not keep vouching for its pins: drop its
        # live-id source so anything it leaked surfaces in the next audit.
        self.core.mem_led.unregister_live_source(self.core._mem_source_key)

    def _emit_op(self, op: dict) -> None:
        """Broadcast one op to follower ranks; a failed broadcast is fatal
        for the whole multi-host engine (its devices leave the collective
        group), so stop the loop and surface OpChannelDown."""
        if self._op_sink is None:
            return
        try:
            self._op_sink(op)
        except Exception as exc:
            log.exception("op-channel broadcast failed; stopping engine loop")
            self._channel_down = True
            self._stop = True
            raise OpChannelDown(str(exc)) from exc

    def _stage_stream_waves(self) -> None:
        """After each finalize: stage newly-committed prefill chunks of any
        open streamed exports as kv_stage_wave ops. Broadcast-then-apply
        like every state-changing op, and emitted at a fixed point of the
        loop (right after step_finalize), so followers replay the wave at
        the identical op-stream position — pool pins stay rank-identical.
        The overlap comes for free: the NEXT chunk's device step is already
        dispatched (pipelined step_begin) while this host-side extract+
        stage runs."""
        for xid, start, stop in self.core.stream_wave_targets():
            self._emit_op({"op": "exec", "name": "kv_stage_wave",
                           "args": {"xfer_id": xid, "start": start,
                                    "stop": stop}})
            staged = self.core.run_op(
                "kv_stage_wave", {"xfer_id": xid, "start": start, "stop": stop})
            listener = getattr(self.core, "_stream_listener", None)
            if listener is not None and staged:
                try:
                    listener(xid, staged)
                except Exception:  # noqa: BLE001 — advisory only
                    log.exception("stream wave listener failed")

    # ------------------------------------------------------------------
    def _run(self) -> None:
        # Pipelined step loop: keep ONE step in flight. Each iteration plans
        # and dispatches step N+1 BEFORE blocking on step N's tokens, so host
        # work (scheduling, numpy prep, output assembly, SSE handoff) runs
        # while the device computes — the overlap reference-class engines get
        # from async scheduling (see EngineCore.step_begin).
        pending: PendingStep | None = None
        while not self._stop:
            moved = False
            while True:
                try:
                    kind, payload = self._inbox.get_nowait()
                except thread_queue.Empty:
                    break
                moved = True
                if kind == "add":
                    # The admit timestamp rides the op so follower ranks
                    # evaluate deadline expiry at the leader's instant.
                    t_add = time.time()
                    try:
                        self._emit_op({"op": "add", "req": payload.to_dict(),
                                       "now": t_add})
                    except OpChannelDown as exc:
                        self._post(payload.request_id, LLMEngineOutput(
                            finish_reason=FinishReason.ERROR, error=str(exc)))
                        break
                    err = self.core.add_request(payload, now=t_add)
                    if err is not None:
                        self._post(payload.request_id, err)
                elif kind == "abort":
                    try:
                        self._emit_op({"op": "abort", "rid": payload})
                    except OpChannelDown:
                        break  # _stop is set; streams fail below
                    self.core.abort(payload)
                    self._post(payload, LLMEngineOutput(finish_reason=FinishReason.CANCELLED))
                elif kind == "exec_op":
                    # Named core op (CORE_OPS): broadcast first so followers
                    # replay it at the same point in the stream, then run
                    # locally. This is how disagg KV staging/import composes
                    # with multi-host engines.
                    name, args, fut, fut_loop = payload
                    try:
                        self._emit_op({"op": "exec", "name": name, "args": args})
                    except OpChannelDown as exc:
                        fut_loop.call_soon_threadsafe(self._resolve, fut, None, exc)
                        break
                    try:
                        result, exc = self.core.run_op(name, args), None
                    except Exception as e:
                        result, exc = None, e
                    try:
                        fut_loop.call_soon_threadsafe(self._resolve, fut, result, exc)
                    except RuntimeError:
                        log.warning("exec_op result dropped: caller loop closed")
                elif kind == "exec" and self._op_sink is not None:
                    # Closure-based core access can't ride the op stream —
                    # running it would desync the followers' SPMD programs.
                    # Refuse loudly; use run_op (named ops) instead.
                    fn, fut, fut_loop = payload
                    exc = RuntimeError(
                        "run_in_core is not supported on a multi-host leader; "
                        "use run_op with a registered named op")
                    try:
                        fut_loop.call_soon_threadsafe(self._resolve, fut, None, exc)
                    except RuntimeError:
                        pass
                elif kind == "exec":
                    # Arbitrary core access (KV export/import/pin for disagg)
                    # marshaled onto this thread — the only thread allowed to
                    # touch device state. The future resolves on the loop it
                    # was created on (the caller's), which may differ from
                    # self._loop — cross-loop set_result is not thread-safe.
                    fn, fut, fut_loop = payload
                    try:
                        result, exc = fn(self.core), None
                    except Exception as e:
                        result, exc = None, e
                    try:
                        fut_loop.call_soon_threadsafe(self._resolve, fut, result, exc)
                    except RuntimeError:
                        # Caller's loop closed before we resolved (e.g. a
                        # cancelled asyncio.run): the future's owner is gone;
                        # dropping the result must not kill this thread.
                        log.warning("exec result dropped: caller loop closed")
            if self._channel_down:
                # Op channel died mid-drain: fail everything in flight
                # (checked before the idle-continue so an idle engine still
                # reports the failure to its streams).
                self.core.fail_all("multi-host op channel down")
                for rid in list(self._streams):
                    self._post(rid, LLMEngineOutput(
                        finish_reason=FinishReason.ERROR,
                        error="multi-host op channel down"))
                break
            if not self.core.has_work() and pending is None:
                if not moved:
                    self._wake.wait(timeout=0.05)
                    self._wake.clear()
                continue
            try:
                # Chaos: inside the try so an error-kind injection exercises
                # the engine-fatal path (fail_all + drain), and a delay is a
                # straggling device step.
                chaos.inject("engine.step")
                if self.core.has_work() or pending is not None:
                    t_step = time.time()
                    if self.core.has_expired_waiting(t_step):
                        # Broadcast-then-apply, like every state-changing op:
                        # followers reap the same seqs at the same instant.
                        self._emit_op({"op": "reap", "now": t_step})
                        for rid, out in self.core.reap_expired(t_step).items():
                            self._post(rid, out)
                    self._emit_op({"op": "step", "now": t_step})
                    self.core.set_step_time(t_step)
                nxt = self.core.step_begin() if self.core.has_work() else None
                if pending is not None:
                    outputs = self.core.step_finalize(pending)
                    for rid, out in outputs.items():
                        self._post(rid, out)
                pending = nxt
                self._stage_stream_waves()
            except Exception as exc:
                # Engine-fatal: fail + drain all in-flight state so the loop
                # doesn't spin hot retrying the same failing step.
                log.exception("engine step failed; failing all in-flight requests")
                pending = None
                self.core.fail_all(str(exc))
                if self._op_sink is not None and not isinstance(exc, OpChannelDown):
                    # Followers must mirror the wipe or their replayed state
                    # machines diverge from ours. (If the channel itself died,
                    # _stop is already set and there is no one to tell.)
                    try:
                        self._emit_op({"op": "fail_all", "error": str(exc)})
                    except OpChannelDown:
                        pass
                for rid in list(self._streams):
                    self._post(rid, LLMEngineOutput(finish_reason=FinishReason.ERROR, error=str(exc)))
                continue

    @staticmethod
    def _resolve(fut: asyncio.Future, result, exc: Exception | None) -> None:
        if fut.cancelled():
            return
        if exc is not None:
            fut.set_exception(exc)
        else:
            fut.set_result(result)

    async def run_in_core(self, fn: Callable[[EngineCore], Any]) -> Any:
        """Run ``fn(core)`` on the engine-core thread and await its result."""
        self.start()
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()
        self._inbox.put(("exec", (fn, fut, loop)))
        self._wake.set()
        return await fut

    async def run_op(self, name: str, args: dict) -> Any:
        """Run a registered named core op (CORE_OPS) on the engine-core
        thread. On a multi-host leader the op is broadcast to followers
        first — this is the multi-host-safe replacement for run_in_core."""
        self.start()
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()
        self._inbox.put(("exec_op", (name, args, fut, loop)))
        self._wake.set()
        return await fut

    def _post(self, rid: str, out: LLMEngineOutput) -> None:
        loop, q = self._loop, self._streams.get(rid)
        if loop is None or q is None:
            return
        loop.call_soon_threadsafe(q.put_nowait, out)

    # ------------------------------------------------------------------
    async def generate(self, req: PreprocessedRequest) -> AsyncIterator[LLMEngineOutput]:
        self.start()
        q: asyncio.Queue = asyncio.Queue()
        self._streams[req.request_id] = q
        self._inbox.put(("add", req))
        self._wake.set()
        out: LLMEngineOutput | None = None
        try:
            while True:
                out = await q.get()
                yield out
                if out.finish_reason is not None:
                    break
        finally:
            self._streams.pop(req.request_id, None)
            if out is None or out.finish_reason is None:  # client bailed early
                self._inbox.put(("abort", req.request_id))
                self._wake.set()

    async def embed(self, token_lists: list[list[int]]) -> "np.ndarray":
        """Embeddings via the engine-core thread (serialized with steps —
        device state has one owner)."""
        return await self.run_in_core(lambda core: core.embed(token_lists))

    # -- drain-aware retirement (runtime/drain.py) ---------------------
    async def evacuate_sessions(self) -> dict:
        """Push retained session KV + resumable records to the remote
        store (multi-host-safe: rides the op stream)."""
        return await self.run_op("session_evacuate", {})

    async def abort_class(self, priority: str | None = None) -> int:
        """Early-stop every live stream of one QoS class (None = all),
        emitting their terminal CANCELLED outputs. Returns the count."""
        rids = await self.run_op("qos_abort_class", {"priority": priority})
        for rid in rids or []:
            self._post(rid, LLMEngineOutput(finish_reason=FinishReason.CANCELLED))
        return len(rids or [])

    @property
    def inflight(self) -> int:
        """Streams with a live output queue (drain run-down's gauge)."""
        return len(self._streams)

    def stats(self) -> dict:
        out = self.core.metrics.snapshot(self.core.sched, self.core.pool)
        if self.core.kvbm is not None:
            out["kvbm"] = self.core.kvbm.snapshot()
            if self.core.kvbm.ckpt_tier is not None:
                # Crash exposure refreshes on the stats poll cadence — a
                # gauge read between polls shows the last sweep's value.
                get_stream_ckpt_metrics().lag_blocks.set(
                    float(self.core.ckpt_lag_blocks()))
        if self.core.sessions is not None:
            out["session"] = self.core.sessions.snapshot()
        led = get_compile_ledger()
        if led.enabled:
            # Warmup coverage + compile stalls ride the published stats so
            # the planner and /debug/fleet can see cold-bucket workers.
            out["compile"] = led.snapshot()
        sled = get_sched_ledger()
        if sled.enabled:
            # Goodput, padding waste, and stall attribution ride the same
            # stats channel (bench stamps, planner feed, /debug/fleet).
            out["sched"] = sled.snapshot()
        mled = get_mem_ledger()
        if mled.enabled:
            # Tier occupancy, pin-owner totals, TTX posture, and the last
            # leak-audit verdict ride the same channel — chaos invariants
            # read orphan_pins from here (chaos/invariants.py).
            out["mem"] = mled.snapshot()
        return out


def build_engine(engine_cfg: EngineConfig, mesh=None, params=None,
                 event_sink=None, op_sink=None) -> AsyncJaxEngine:
    core = EngineCore(engine_cfg, mesh=mesh, params=params, event_sink=event_sink)
    return AsyncJaxEngine(core, op_sink=op_sink)
