from dynamo_tpu.engine.engine import AsyncJaxEngine, EngineCore

__all__ = ["AsyncJaxEngine", "EngineCore"]
