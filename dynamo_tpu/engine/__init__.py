"""JAX engine package. Imports are lazy so jax-free consumers (mocker,
runtime, router) can use the block-pool/scheduler modules without pulling
jax into the process."""


def __getattr__(name):
    if name in ("AsyncJaxEngine", "EngineCore", "build_engine"):
        from dynamo_tpu.engine import engine

        return getattr(engine, name)
    raise AttributeError(name)


__all__ = ["AsyncJaxEngine", "EngineCore", "build_engine"]
