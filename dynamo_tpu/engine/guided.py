"""Structured output: token-level constrained decoding for JSON.

Fills the role of the reference's guided decoding surface
(reference: ``response_format`` in lib/async-openai request types and the
nvext extensions, lib/llm/src/protocols/openai/nvext.rs — served through
vLLM/TRT-LLM's xgrammar/outlines backends). The TPU redesign keeps the
model program untouched: the grammar lives on the HOST as a JSON pushdown
automaton; each step it emits an allow-mask over the vocab, which rides
the dispatch as one additive logits operand (0 / -inf) — the compiled
step stays static-shaped and the MXU path identical.

Two request modes (protocols/openai.py ``response_format``):
- ``json_object`` — any syntactically valid JSON value.
- ``json_schema`` — additionally enforces a schema SUBSET: ``type`` on
  every node, object ``properties`` (key membership + per-key value
  schemas) with ``required`` completion gating, ``items`` for arrays,
  and string ``enum``. Unsupported keywords are ignored (the output is
  then a superset of the schema's language — never an invalid JSON).

Mechanics: ``JsonMachine`` consumes characters; a token is allowed iff
feeding its decoded text keeps the machine alive. ``TokenMasker`` builds
the per-step [V] allow-mask by trial-feeding every vocab piece, memoized
by the machine's state signature — the signature collapses equivalent
states (e.g. any position inside an unconstrained string), so steady-state
masking is a dict hit. EOS is allowed exactly when the machine is in an
accepting state (a complete top-level value).

Guided sequences decode UNPIPELINED (the mask for token t needs token
t-1 on the host) and are excluded from fused windows and speculative
verify — the engine partitions them into their own masked batches.
"""

from __future__ import annotations

import json
from typing import Any

import numpy as np

from dynamo_tpu.utils.logging import get_logger

log = get_logger("guided")

_WS = " \t\n\r"
_DIGITS = "0123456789"
# Structural whitespace is bounded per run (progress forcing): a random
# model under a grammar that admits unlimited inter-token whitespace would
# happily spend its whole budget on newlines and never complete a document.
# Two consecutive blanks cover every sane emission style; pretty-printers
# with deeper indentation are outside the guided-decode contract.
_MAX_WS = 2
# Modes where whitespace is structural (between tokens) rather than string
# content — only these count against the run bound.
_WS_MODES = frozenset(
    ("done", "value", "obj_open", "colon", "obj_post", "key_open", "arr_post"))
# ONE canonical empty schema: signatures key sub-schemas by object identity
# (the schema tree is shared across machine clones), so the fallback must
# be a stable singleton — a fresh {} per transition would defeat the mask
# cache and risk id-reuse collisions.
_EMPTY: dict = {}


class Reject(Exception):
    pass


class _Frame:
    """One container on the stack: an object or array, plus its schema."""

    __slots__ = ("kind", "schema", "seen", "pending_key")

    def __init__(self, kind: str, schema: dict | None):
        self.kind = kind                  # "obj" | "arr"
        self.schema = schema if schema else _EMPTY
        self.seen: tuple[str, ...] = ()   # object keys already emitted
        self.pending_key: str | None = None

    def clone(self) -> "_Frame":
        f = _Frame(self.kind, self.schema)
        f.seen, f.pending_key = self.seen, self.pending_key
        return f


def _value_starts(schema: dict | None) -> str:
    """Characters that may start a value of the schema's type(s)."""
    t = (schema or {}).get("type")
    if isinstance(t, list):
        return "".join(_value_starts({**schema, "type": x}) for x in t)
    if (schema or {}).get("enum") is not None:
        # string enums only (subset); values start with a quote
        return '"'
    return {
        None: '{["-0123456789tfn',
        "object": "{",
        "array": "[",
        "string": '"',
        "number": "-" + _DIGITS,
        "integer": "-" + _DIGITS,
        "boolean": "tf",
        "null": "n",
    }.get(t, '{["-0123456789tfn')


class JsonMachine:
    """Character-level JSON automaton with optional schema constraints.

    mode ∈ value | str | str_esc | key | key_esc | colon | obj_open |
    obj_post | arr_post | num | lit | done. ``feed`` mutates; use
    ``clone`` for trial runs.
    """

    __slots__ = ("mode", "stack", "schema", "partial", "lit_rest", "num_state",
                 "ws_run")

    def __init__(self, schema: dict | None = None):
        self.mode = "value"
        self.stack: list[_Frame] = []
        self.schema = schema if schema else _EMPTY  # schema of the value being read
        self.partial = ""                 # current string/key content
        self.lit_rest = ""                # remaining literal chars
        self.num_state = ""               # coarse number validity state
        self.ws_run = 0                   # consecutive structural whitespace

    def clone(self) -> "JsonMachine":
        m = JsonMachine.__new__(JsonMachine)
        m.mode, m.schema = self.mode, self.schema
        m.partial, m.lit_rest, m.num_state = self.partial, self.lit_rest, self.num_state
        m.ws_run = self.ws_run
        m.stack = [f.clone() for f in self.stack]
        return m

    # -- signature for mask memoization ---------------------------------
    def signature(self) -> tuple:
        """Collapses states with identical allowed-token sets. The partial
        string matters only under prefix constraints (keys / enums). Every
        frame on the stack contributes (kind, schema, seen): two stacks that
        agree only at the top can still differ on which closers are legal
        (e.g. an outer object with pending required keys vs. one without) —
        keying by the top frame alone reused wrong masks across them."""
        frames = tuple((f.kind, id(f.schema), f.seen) for f in self.stack)
        partial = self.partial if self._candidates() is not None else ""
        return (self.mode, id(self.schema), frames, partial,
                self.lit_rest, self.num_state, self.ws_run)

    # -- constraints ----------------------------------------------------
    def _candidates(self) -> list[str] | None:
        """Full-string candidates constraining the current string, if any."""
        if self.mode in ("key", "key_esc"):
            props = (self.stack[-1].schema or {}).get("properties")
            if isinstance(props, dict):
                seen = self.stack[-1].seen
                return [k for k in props if k not in seen]
            return None
        if self.mode in ("str", "str_esc"):
            enum = (self.schema or {}).get("enum")
            if isinstance(enum, list) and all(isinstance(x, str) for x in enum):
                return list(enum)
        return None

    def _key_value_schema(self, key: str) -> dict:
        props = (self.stack[-1].schema or _EMPTY).get("properties") or _EMPTY
        sub = props.get(key)
        return sub if isinstance(sub, dict) else _EMPTY

    # -- feeding --------------------------------------------------------
    def feed(self, ch: str) -> None:
        """Consume one character or raise Reject."""
        if ch in _WS and self.mode in _WS_MODES:
            if self.ws_run >= _MAX_WS:
                raise Reject
            self.ws_run += 1
            return
        self.ws_run = 0
        m = self.mode
        if m == "done":
            if ch in _WS:
                return
            raise Reject
        if m == "value":
            if ch in _WS:
                return
            if ch not in _value_starts(self.schema):
                raise Reject
            if ch == "{":
                self.stack.append(_Frame("obj", self.schema))
                self.mode = "obj_open"
            elif ch == "[":
                self.stack.append(_Frame("arr", self.schema))
                self.mode = "value"
                # empty array: ']' is legal where a first element may start
                self.schema = self._items_schema()
            elif ch == '"':
                self.mode, self.partial = "str", ""
            elif ch in "-" + _DIGITS:
                self.mode = "num"
                self.num_state = "int" if ch in _DIGITS else "sign"
            elif ch == "t":
                self.mode, self.lit_rest = "lit", "rue"
            elif ch == "f":
                self.mode, self.lit_rest = "lit", "alse"
            elif ch == "n":
                self.mode, self.lit_rest = "lit", "ull"
            return
        if m in ("str", "key"):
            cands = self._candidates()
            if ch == '"':
                if cands is not None and self.partial not in cands:
                    raise Reject
                if m == "key":
                    self.stack[-1].pending_key = self.partial
                    self.mode = "colon"
                else:
                    self._value_done()
                return
            if ch == "\\":
                # Constrained strings (keys / enums) exclude escapes (see
                # *_esc below); rejecting the backslash HERE keeps the next
                # mask non-empty — deferring to the esc mode would be a
                # dead end where every escape char is rejected.
                if cands is not None:
                    raise Reject
                self.mode = m + "_esc"
                return
            if ord(ch) < 0x20:
                raise Reject
            nxt = self.partial + ch
            if cands is not None and not any(c.startswith(nxt) for c in cands):
                raise Reject
            self.partial = nxt
            return
        if m in ("str_esc", "key_esc"):
            # \u escapes are excluded in v1 (validating the 4-hex tail
            # would need more states; a truncated \u would emit invalid
            # JSON) — the simple escapes cover the machine's guarantees.
            if ch not in '"\\/bfnrt':
                raise Reject
            # escapes inside constrained strings would need decoding to
            # match candidates — disallow there, allow in free strings
            if self._candidates() is not None:
                raise Reject
            self.mode = m[:-4]
            self.partial += "?"  # decoded char; content is free-form
            return
        if m == "obj_open":
            if ch in _WS:
                return
            if ch == "}":
                self._object_close()
                return
            if ch == '"':
                self.mode, self.partial = "key", ""
                return
            raise Reject
        if m == "colon":
            if ch in _WS:
                return
            if ch == ":":
                frame = self.stack[-1]
                frame.seen = (*frame.seen, frame.pending_key or "")
                self.schema = self._key_value_schema(frame.pending_key or "")
                frame.pending_key = None
                self.mode = "value"
                return
            raise Reject
        if m == "obj_post":
            if ch in _WS:
                return
            if ch == ",":
                # A keyed object with every property already emitted has no
                # legal next key — the comma itself is the dead end, reject
                # it so the mask still contains the closing brace.
                props = (self.stack[-1].schema or _EMPTY).get("properties")
                if isinstance(props, dict) and \
                        all(k in self.stack[-1].seen for k in props):
                    raise Reject
                self.mode = "key_open"
                return
            if ch == "}":
                self._object_close()
                return
            raise Reject
        if m == "key_open":
            if ch in _WS:
                return
            if ch == '"':
                self.mode, self.partial = "key", ""
                return
            raise Reject
        if m == "arr_post":
            if ch in _WS:
                return
            if ch == ",":
                self.mode = "value"
                self.schema = self._items_schema()
                return
            if ch == "]":
                self.stack.pop()
                self._value_done()
                return
            raise Reject
        if m == "num":
            ns = self.num_state
            if ch in _DIGITS:
                self.num_state = {"sign": "int", "dot": "frac", "exp": "expd",
                                  "expsign": "expd"}.get(ns, ns)
                return
            if ch == "." and ns == "int":
                self.num_state = "dot"
                return
            if ch in "eE" and ns in ("int", "frac"):
                self.num_state = "exp"
                return
            if ch in "+-" and ns == "exp":
                self.num_state = "expsign"
                return
            if ns in ("int", "frac", "expd"):
                # number complete; the delimiter belongs to the parent
                self._value_done()
                self.feed(ch)
                return
            raise Reject
        if m == "lit":
            if self.lit_rest and ch == self.lit_rest[0]:
                self.lit_rest = self.lit_rest[1:]
                if not self.lit_rest:
                    self._value_done()
                return
            raise Reject
        raise Reject  # pragma: no cover — unknown mode

    # ``]`` closes an empty array from "value" mode; special-case it.
    def _items_schema(self) -> dict:
        top = self.stack[-1] if self.stack else None
        if top is not None and top.kind == "arr":
            items = (top.schema or _EMPTY).get("items")
            return items if isinstance(items, dict) else _EMPTY
        return _EMPTY

    def _object_close(self) -> None:
        frame = self.stack[-1]
        req = (frame.schema or {}).get("required") or []
        if any(k not in frame.seen for k in req):
            raise Reject
        self.stack.pop()
        self._value_done()

    def _value_done(self) -> None:
        """A value finished; return to the parent context. Scalar scratch
        state is reset here: a stale num_state/lit_rest would otherwise leak
        into the signature of every later state at the same stack shape and
        alias distinct grammar states in the mask cache."""
        self.num_state = ""
        self.lit_rest = ""
        if not self.stack:
            self.mode = "done"
            return
        top = self.stack[-1]
        self.mode = "obj_post" if top.kind == "obj" else "arr_post"
        self.schema = top.schema

    def feed_str(self, s: str) -> None:
        for ch in s:
            # "]" while expecting a first array element closes the array
            if ch == "]" and self.mode == "value" and self.stack \
                    and self.stack[-1].kind == "arr":
                self.ws_run = 0
                self.stack.pop()
                self._value_done()
                continue
            self.feed(ch)

    @property
    def complete(self) -> bool:
        if self.mode == "done":
            return True
        # a bare top-level number can only complete at EOS
        return (self.mode == "num" and not self.stack
                and self.num_state in ("int", "frac", "expd"))


class TokenMasker:
    """Per-sequence grammar state + vocab mask computation.

    ``pieces`` is the engine-wide token-id → text table; masks are
    memoized per machine signature across ALL sequences via the shared
    ``cache`` (states recur heavily — e.g. every position inside a free
    string shares one signature)."""

    def __init__(self, pieces: list[str], eos_ids: list[int],
                 schema: dict | None, cache: dict | None = None):
        self.pieces = pieces
        self.eos_ids = [e for e in eos_ids if e is not None]
        self.machine = JsonMachine(schema)
        self.cache = cache if cache is not None else {}

    @classmethod
    def parse_schema(cls, response_format: dict | None) -> dict | None:
        """OpenAI response_format → schema dict (None = unconstrained)."""
        if not response_format:
            return None
        kind = response_format.get("type")
        if kind == "json_object":
            return {}
        if kind == "json_schema":
            js = response_format.get("json_schema") or {}
            schema = js.get("schema") if isinstance(js, dict) else None
            return schema if isinstance(schema, dict) else {}
        return None

    def mask(self) -> np.ndarray:
        """bool[V] — True where the token keeps the grammar alive."""
        sig = self.machine.signature()
        hit = self.cache.get(sig)
        if hit is not None:
            return hit
        v = len(self.pieces)
        out = np.zeros((v,), bool)
        complete = self.machine.complete
        for tid, piece in enumerate(self.pieces):
            if not piece:
                continue
            trial = self.machine.clone()
            try:
                trial.feed_str(piece)
            except Reject:
                continue
            out[tid] = True
        for e in self.eos_ids:
            if 0 <= e < v:
                out[e] = complete
        if not out.any():
            # Dead end (shouldn't happen for valid grammars): allow EOS so
            # the stream terminates instead of sampling from -inf logits.
            log.warning("guided mask is empty; allowing EOS")
            for e in self.eos_ids:
                if 0 <= e < v:
                    out[e] = True
        self.cache[sig] = out
        return out

    def advance(self, token_id: int) -> None:
        if token_id in self.eos_ids:
            return
        piece = self.pieces[token_id] if 0 <= token_id < len(self.pieces) else ""
        try:
            self.machine.feed_str(piece)
        except Reject:
            # The mask should have prevented this; log and freeze (all
            # further masks will allow EOS only via the dead-end path).
            log.error("guided decode emitted a rejected token %d %r",
                      token_id, piece)

    @property
    def complete(self) -> bool:
        return self.machine.complete


def validate_json_output(text: str, schema: dict | None = None) -> Any:
    """Test helper: parse and (subset-)check an emitted document."""
    doc = json.loads(text)

    def check(node, sch):
        if not isinstance(sch, dict):
            return
        t = sch.get("type")
        if t == "object":
            assert isinstance(node, dict)
            for k in sch.get("required") or []:
                assert k in node, f"missing required {k}"
            props = sch.get("properties") or {}
            for k, v in node.items():
                assert not props or k in props, f"unexpected key {k}"
                check(v, props.get(k, {}))
        elif t == "array":
            assert isinstance(node, list)
            for item in node:
                check(item, sch.get("items", {}))
        elif t == "string":
            assert isinstance(node, str)
            if sch.get("enum"):
                assert node in sch["enum"]
        elif t in ("number", "integer"):
            assert isinstance(node, (int, float)) and not isinstance(node, bool)
        elif t == "boolean":
            assert isinstance(node, bool)
        elif t == "null":
            assert node is None

    check(doc, schema)
    return doc
