"""Device block pool with prefix caching, refcounts, LRU eviction, KV events.

This is the engine-local (G1/device) incarnation of the reference's block
registry + managed pool (reference: lib/llm/src/block_manager/block/
registry.rs:478 sequence-hash dedup; pool/managed.rs inactive-pool eviction):

- Active blocks are refcounted (shared across requests via prefix matching).
- A block whose refcount drops to zero but which holds *committed* content
  (a full block with a sequence hash) parks in an LRU **inactive** pool —
  still matchable, evicted only on allocation pressure.
- Commit/evict emit BlockStored/BlockRemoved KV events that feed the
  KV-aware router (reference: kv_router/publisher.rs).
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Callable

from dynamo_tpu.engine.errors import NoFreeBlocks
from dynamo_tpu.obs.mem_ledger import get_mem_ledger
from dynamo_tpu.router.events import BlockRemoved, BlockStored, KvCacheEvent


class PrefixPool:
    def __init__(
        self,
        num_blocks: int,
        block_size: int,
        event_sink: Callable[[KvCacheEvent], None] | None = None,
        enable_prefix_caching: bool = True,
    ):
        self.num_blocks = num_blocks
        self.block_size = block_size
        self.enable_prefix_caching = enable_prefix_caching
        self._event_sink = event_sink
        # Called as evict_hook(block_id, seq_hash) *before* an evicted
        # committed block's id is reused — the KVBM offload manager's
        # write-back point (dynamo_tpu.kvbm.offload).
        self.evict_hook: Callable[[int, int], None] | None = None
        # Called as commit_hook(block_id, seq_hash, parent_hash) after a
        # block's content hash registers — the KVBM publish-on-commit point
        # (global prefix cache, dynamo_tpu.kvbm.offload). Fires only for
        # the canonical (first) commit of a hash, so publishers never see
        # duplicate-content blocks.
        self.commit_hook: Callable[[int, int, "int | None"], None] | None = None
        # block 0 reserved (trash)
        self._free: list[int] = list(range(num_blocks - 1, 0, -1))
        self._refcount: dict[int, int] = {}
        self._hash_of: dict[int, int] = {}          # block_id -> seq_hash (committed)
        self._by_hash: dict[int, int] = {}          # seq_hash -> block_id
        self._inactive: OrderedDict[int, None] = OrderedDict()  # block_id -> LRU order
        # Memory ledger (obs/mem_ledger.py): device-tier eviction churn is
        # recorded where it happens. _churn_cause distinguishes pressure
        # evictions from the deliberate clear() sweep.
        self._mled = get_mem_ledger()
        self._churn_cause = "allocation_pressure"

    # -- introspection -------------------------------------------------------
    @property
    def num_free(self) -> int:
        return len(self._free) + len(self._inactive)

    @property
    def num_free_raw(self) -> int:
        """Free-list blocks only (never-written or fully released)."""
        return len(self._free)

    @property
    def num_inactive(self) -> int:
        """Committed-but-unreferenced blocks parked in the LRU (matchable,
        evictable on allocation pressure)."""
        return len(self._inactive)

    @property
    def usage(self) -> float:
        return 1.0 - self.num_free / max(self.num_blocks - 1, 1)

    def cached_block_count(self) -> int:
        return len(self._by_hash)

    def has_hash(self, seq_hash: int) -> bool:
        return seq_hash in self._by_hash

    def block_for_hash(self, seq_hash: int) -> int | None:
        return self._by_hash.get(seq_hash)

    def touch(self, seq_hash: int) -> None:
        """Refresh an inactive cached block to MRU so an imminent allocation
        burst doesn't evict it (used by KVBM onboarding to protect the
        on-device part of a chain it is about to extend)."""
        bid = self._by_hash.get(seq_hash)
        if bid is not None and bid in self._inactive:
            self._inactive.move_to_end(bid)

    # -- events --------------------------------------------------------------
    def _emit(self, ev: KvCacheEvent) -> None:
        if self._event_sink is not None:
            self._event_sink(ev)

    # -- allocation ----------------------------------------------------------
    def allocate(self, n: int) -> list[int]:
        """Allocate n uncommitted blocks (refcount 1), evicting LRU inactive
        committed blocks if the free list runs dry."""
        if n > self.num_free:
            raise NoFreeBlocks(f"need {n} blocks, {self.num_free} free/evictable")
        out = []
        for _ in range(n):
            if self._free:
                bid = self._free.pop()
            else:
                bid = self._evict_one()
            self._refcount[bid] = 1
            out.append(bid)
        return out

    def _evict_one(self) -> int:
        bid, _ = self._inactive.popitem(last=False)  # oldest
        h = self._hash_of.pop(bid, None)
        if h is not None:
            if self.evict_hook is not None:
                self.evict_hook(bid, h)
            del self._by_hash[h]
            if self._mled.enabled:
                self._mled.record_churn("device", self._churn_cause, 1)
            self._emit(BlockRemoved(block_hashes=(h,)))
        return bid

    # -- prefix matching -----------------------------------------------------
    def match_prefix(self, seq_hashes: list[int]) -> list[int]:
        """Return block ids for the longest cached prefix of ``seq_hashes``,
        increffing each matched block (caller owns a reference)."""
        if not self.enable_prefix_caching:
            return []
        out: list[int] = []
        for h in seq_hashes:
            bid = self._by_hash.get(h)
            if bid is None:
                break
            self._ref(bid)
            out.append(bid)
        return out

    def _ref(self, bid: int) -> None:
        rc = self._refcount.get(bid, 0)
        if rc == 0 and bid in self._inactive:
            del self._inactive[bid]
        self._refcount[bid] = rc + 1

    # -- commit / release ----------------------------------------------------
    def commit(self, bid: int, seq_hash: int, parent_hash: int | None = None) -> None:
        """Register a now-full block's content hash (emits BlockStored).
        If the hash is already cached by another block, this block stays
        uncommitted (the canonical copy wins; dedup is at match time)."""
        if not self.enable_prefix_caching:
            return
        if seq_hash in self._by_hash:
            return
        self._by_hash[seq_hash] = bid
        self._hash_of[bid] = seq_hash
        if self.commit_hook is not None:
            self.commit_hook(bid, seq_hash, parent_hash)
        self._emit(BlockStored(block_hashes=(seq_hash,), parent_hash=parent_hash))

    def release(self, block_ids: list[int]) -> None:
        """Drop one reference per block; committed blocks park in the LRU
        inactive pool, uncommitted blocks return to the free list."""
        for bid in block_ids:
            rc = self._refcount.get(bid, 0)
            if rc <= 0:
                raise ValueError(f"double free of block {bid}")
            rc -= 1
            self._refcount[bid] = rc
            if rc == 0:
                del self._refcount[bid]
                if bid in self._hash_of:
                    self._inactive[bid] = None
                    self._inactive.move_to_end(bid)
                else:
                    self._free.append(bid)

    def clear(self) -> None:
        """Drop all cached (inactive) blocks — admin /clear_kv_blocks
        (reference: http/service/clear_kv_blocks.rs). A deliberate clear
        drops content outright (no write-back offload)."""
        hook, self.evict_hook = self.evict_hook, None
        self._churn_cause = "clear"
        try:
            while self._inactive:
                self._free.append(self._evict_one())
        finally:
            self.evict_hook = hook
            self._churn_cause = "allocation_pressure"
