"""Paged KV cache storage + device block allocator.

The device tier (G1) of the KV block story: cache tensors are
``[layers, num_blocks, block_size, kv_heads, head_dim]`` jax.Arrays, sharded
over the mesh "model" axis on kv_heads. Block 0 is reserved as the trash
block for padding writes (models/llama.py). Host/disk tiers and offload live
in dynamo_tpu.kvbm (reference: lib/llm/src/block_manager/).

With ``kv_dtype="int8"`` each cache becomes a two-leaf pytree
``{"q": int8 payload [L, NB, BS, KH, D], "s": float32 scales [L, NB, KH]}``
— symmetric per-(layer, block, kv_head) quantization, mirroring the
``{"q", "so"}`` weight-quant idiom in models/llama.py. Everything downstream
(scan over layers, donation, shard_map in_specs) treats the cache as a
pytree, so the plain-array fast path is structurally unchanged.

``kv_dtype="int4"`` keeps the same pytree but packs two signed nibbles per
byte along head_dim: ``{"q": uint8 [L, NB, BS, KH, D//2], "s": f32}`` —
the uint8 payload dtype IS the packed-int4 marker everywhere downstream
(kernel, kvbm, scatter), so no third leaf or flag is needed. A block costs
~0.25x its bf16 bytes, so auto-sizing fits ~4x the blocks.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding

from dynamo_tpu.engine.errors import NoFreeBlocks
from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.parallel.mesh import kv_cache_spec, kv_scale_spec

#: scales are float32 — 4 bytes per (layer, block, kv_head), k and v each
_SCALE_ITEMSIZE = 4


@dataclass
class KVCacheSpec:
    num_blocks: int
    block_size: int
    num_layers: int
    num_kv_heads: int
    head_dim: int
    dtype: str = "bfloat16"
    #: "int8" / "int4" enable quantized storage; any other value means the
    #: cache is stored at ``dtype`` (model precision) exactly as before.
    kv_dtype: str = "bfloat16"

    @classmethod
    def for_model(cls, cfg: ModelConfig, num_blocks: int, block_size: int,
                  kv_dtype: str = "bfloat16") -> "KVCacheSpec":
        return cls(
            num_blocks=num_blocks,
            block_size=block_size,
            num_layers=cfg.num_layers,
            num_kv_heads=cfg.num_kv_heads,
            head_dim=cfg.head_dim,
            dtype=cfg.dtype,
            kv_dtype=kv_dtype,
        )

    @property
    def quantized(self) -> bool:
        return self.kv_dtype in ("int8", "int4")

    @property
    def packed_int4(self) -> bool:
        return self.kv_dtype == "int4"

    @property
    def payload_dtype(self):
        """Storage dtype of the quantized payload leaf. uint8 is the packed
        int4 marker (two nibbles per byte); int8 means one byte per elem."""
        return jnp.uint8 if self.packed_int4 else jnp.int8

    @property
    def payload_head_dim(self) -> int:
        """Trailing payload dim: head_dim, halved when int4-packed."""
        if self.packed_int4:
            if self.head_dim % 2:
                raise ValueError(
                    f"kv_dtype=int4 needs an even head_dim, got {self.head_dim}")
            return self.head_dim // 2
        return self.head_dim

    @property
    def shape(self) -> tuple[int, int, int, int, int]:
        return (self.num_layers, self.num_blocks, self.block_size, self.num_kv_heads, self.head_dim)

    @property
    def payload_shape(self) -> tuple[int, int, int, int, int]:
        """Stored payload shape: == ``shape`` except int4 packs head_dim/2."""
        return (self.num_layers, self.num_blocks, self.block_size,
                self.num_kv_heads, self.payload_head_dim)

    @property
    def scale_shape(self) -> tuple[int, int, int]:
        """Quantization scale tensor [layers, blocks, kv_heads] (int8/int4)."""
        return (self.num_layers, self.num_blocks, self.num_kv_heads)

    def bytes_per_block(self) -> int:
        if self.quantized:
            payload = (2 * self.num_layers * self.block_size
                       * self.num_kv_heads * self.payload_head_dim)
            scales = 2 * self.num_layers * self.num_kv_heads * _SCALE_ITEMSIZE
            return payload + scales
        itemsize = jnp.dtype(self.dtype).itemsize
        # k + v, all layers
        return 2 * self.num_layers * self.block_size * self.num_kv_heads * self.head_dim * itemsize


def register_device_tier(pool, spec: KVCacheSpec, *, name: str = "device") -> None:
    """Register the device (G1) block pool as a tier row in the memory
    ledger (obs/mem_ledger.py). ``pool`` is a PrefixPool; resident means
    referenced-or-cached — everything not on the raw free list (block 0,
    never handed out, is excluded). Byte math comes from
    :meth:`KVCacheSpec.bytes_per_block`, so quantized specs report their
    packed footprint. Pulled only at snapshot/audit time, never per-step."""
    from dynamo_tpu.obs.mem_ledger import get_mem_ledger

    bytes_per_block = spec.bytes_per_block()

    def _occupancy() -> tuple[int, int]:
        resident = pool.num_blocks - 1 - pool.num_free_raw
        return resident, resident * bytes_per_block

    get_mem_ledger().register_tier(name, _occupancy)


def allocate_cache(spec: KVCacheSpec, mesh: Mesh | None = None):
    """Allocate zeroed K and V caches (sharded if a mesh is given).

    Returns plain arrays, or ``{"q", "s"}`` pytrees when ``spec.quantized``
    (payload and scales sharded with per-leaf out_shardings)."""
    if spec.quantized:
        def qzeros():
            return {"q": jnp.zeros(spec.payload_shape, spec.payload_dtype),
                    "s": jnp.zeros(spec.scale_shape, jnp.float32)}
        if mesh is not None:
            sh = {"q": NamedSharding(mesh, kv_cache_spec()),
                  "s": NamedSharding(mesh, kv_scale_spec())}
            qzeros = jax.jit(qzeros, out_shardings=sh)
        return qzeros(), qzeros()
    if mesh is not None:
        sharding = NamedSharding(mesh, kv_cache_spec())
        zeros = jax.jit(
            lambda: jnp.zeros(spec.shape, jnp.dtype(spec.dtype)), out_shardings=sharding
        )
        return zeros(), zeros()
    z = jnp.zeros(spec.shape, jnp.dtype(spec.dtype))
    return z, jnp.zeros_like(z)


def cache_payload(cache) -> jax.Array:
    """The int8 payload leaf of a quantized cache, or the array itself —
    use wherever shard/box geometry of the [L, NB, BS, KH, D] tensor is
    needed without caring about quantization."""
    return cache["q"] if isinstance(cache, dict) else cache


@dataclass
class BlockAllocator:
    """Free-list allocator over device block ids. Block 0 (trash) is never
    handed out. Eviction/reuse decisions live above (kvbm); this is the raw
    device pool (reference: block_manager/pool)."""

    num_blocks: int
    _free: list[int] = field(default_factory=list)

    def __post_init__(self) -> None:
        self._free = list(range(self.num_blocks - 1, 0, -1))  # pop() yields 1,2,3..

    @property
    def num_free(self) -> int:
        return len(self._free)

    def allocate(self, n: int) -> list[int]:
        if n > len(self._free):
            raise NoFreeBlocks(f"need {n} blocks, {len(self._free)} free")
        return [self._free.pop() for _ in range(n)]

    def free(self, blocks: list[int]) -> None:
        for b in blocks:
            if not 0 < b < self.num_blocks:
                raise ValueError(f"bad block id {b}")
        self._free.extend(blocks)
