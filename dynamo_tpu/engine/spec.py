"""N-gram speculative decoding (prompt-lookup) proposals.

The TPU-first rationale: a decode step's cost is dominated by reading
every parameter byte once (HBM-bound), so verifying k proposed tokens in
ONE forward pass multiplies tokens-per-weight-read by the acceptance
rate. Proposals come from the sequence itself — the trailing n-gram is
matched against earlier positions and the continuation after the most
recent match is proposed (the "prompt lookup" scheme; strong on code,
summaries, RAG — any output that re-quotes its context). Verification is
exact for greedy decoding: emitted streams are bit-identical to
step-by-step decode (tests/test_spec.py equivalence suite).

The reference orchestrates engines that implement speculative decoding
internally (mocker surface: SpecDecodeStats, lib/llm/src/kv_router/
publisher.rs ForwardPassMetrics); here the engine is first-party, so the
scheme lives in the engine (engine/engine.py "verify" batches).
"""

from __future__ import annotations


def greedy_eligible(so) -> bool:
    """Verify steps are argmax-exact only for greedy, penalty-free
    sampling options — THE eligibility rule, shared by the scheduler's
    block-growth sizing and the engine's verify planner."""
    return (
        so.temperature is not None and so.temperature <= 0
        and not so.frequency_penalty and not so.presence_penalty
        and (so.repetition_penalty or 1.0) == 1.0
    )


def propose(tokens: list[int], ngram: int, k: int) -> list[int]:
    """Up to ``k`` continuation tokens after the most recent earlier
    occurrence of the trailing ``ngram``-gram; [] when no match.

    The scan walks backwards so the MOST RECENT prior occurrence wins —
    repetitive generation (the common acceptance case) matches its own
    immediately-preceding copy."""
    n = len(tokens)
    if ngram <= 0 or k <= 0 or n < ngram + 1:
        return []
    tail = tokens[n - ngram:]
    # last position where a match could START, leaving >=1 continuation
    # token before the tail itself
    for start in range(n - ngram - 1, -1, -1):
        if tokens[start:start + ngram] == tail:
            return tokens[start + ngram: start + ngram + k]
    return []


def accept(chunk: list[int], argmax_out: list[int]) -> list[int]:
    """Greedy acceptance walk.

    ``chunk`` = [current_token, p1..pk] (the verify step's inputs);
    ``argmax_out[j]`` = the model's next-token prediction at position j.
    Position j's output is on the true decode path iff every earlier
    proposal matched: p_j == argmax_out[j-1]. Returns the emitted tokens
    (>=1: position 0's output is always valid — it is exactly what a
    plain decode step would have produced)."""
    emitted = [argmax_out[0]]
    for j in range(1, len(chunk)):
        if chunk[j] != argmax_out[j - 1]:
            break
        emitted.append(argmax_out[j])
    return emitted
