"""GGUF checkpoint support: parse the container, map llama tensors.

Fills the role of the reference's GGUF front door
(reference: lib/llm/src/gguf.rs:1-924 — container probe, metadata read,
llama-family tensor mapping for its in-process engines).

Container layout (GGUF v2/v3): magic ``GGUF`` + version, tensor count,
metadata KV section (typed values incl. nested arrays), tensor info table
(name, dims, ggml type, offset), alignment padding, then raw tensor data.
GGML stores dims innermost-first, so a torch/HF ``[out, in]`` matrix
appears as ``ne=[in, out]`` with identical row-major bytes — reading with
``reshape(dims[::-1])`` recovers the ``[out, in]`` view, after which the
same transpose convention as the safetensors loader applies.

Scope: F32/F16/BF16 tensors (zero-copy) plus Q8_0/Q4_0 GGML block
dequantization; llama-family metadata →
:class:`~dynamo_tpu.models.config.ModelConfig`. ``save_gguf`` writes the
same subset, used by tests and by tools that re-export checkpoints.
"""

from __future__ import annotations

import mmap
import struct
from pathlib import Path
from typing import Any, BinaryIO

import numpy as np

from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.utils.logging import get_logger

log = get_logger("gguf")

MAGIC = b"GGUF"

# metadata value types
_U8, _I8, _U16, _I16, _U32, _I32, _F32, _BOOL, _STR, _ARR, _U64, _I64, _F64 = range(13)
_SCALAR_FMT = {_U8: "<B", _I8: "<b", _U16: "<H", _I16: "<h", _U32: "<I",
               _I32: "<i", _F32: "<f", _BOOL: "<?", _U64: "<Q", _I64: "<q",
               _F64: "<d"}

# ggml tensor types we can read losslessly
GGML_F32, GGML_F16 = 0, 1
GGML_Q4_0, GGML_Q8_0 = 2, 8
GGML_BF16 = 30
try:
    import ml_dtypes

    _BF16 = np.dtype(ml_dtypes.bfloat16)
except Exception:  # pragma: no cover
    _BF16 = None
_TENSOR_DTYPES: dict[int, np.dtype] = {
    GGML_F32: np.dtype(np.float32),
    GGML_F16: np.dtype(np.float16),
}
if _BF16 is not None:
    _TENSOR_DTYPES[GGML_BF16] = _BF16

ALIGNMENT_KEY = "general.alignment"
DEFAULT_ALIGNMENT = 32


class GGUFReader:
    """mmap-backed reader: metadata dict + zero-copy tensor views."""

    def __init__(self, path: str | Path):
        self.path = Path(path)
        with open(self.path, "rb") as f:
            self._mm = mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_READ)
        self._pos = 0
        if self._read(4) != MAGIC:
            raise ValueError(f"{self.path}: not a GGUF file (bad magic)")
        self.version = self._scalar("<I")
        if self.version not in (2, 3):
            raise ValueError(f"unsupported GGUF version {self.version}")
        n_tensors = self._scalar("<Q")
        n_kv = self._scalar("<Q")
        self.metadata: dict[str, Any] = {}
        for _ in range(n_kv):
            key = self._string()
            self.metadata[key] = self._value(self._scalar("<I"))
        self._tensors: dict[str, tuple[tuple[int, ...], int, int]] = {}
        for _ in range(n_tensors):
            name = self._string()
            n_dims = self._scalar("<I")
            dims = tuple(self._scalar("<Q") for _ in range(n_dims))
            ggml_type = self._scalar("<I")
            offset = self._scalar("<Q")
            self._tensors[name] = (dims, ggml_type, offset)
        align = int(self.metadata.get(ALIGNMENT_KEY, DEFAULT_ALIGNMENT))
        self._data_base = -(-self._pos // align) * align

    # -- low-level parsing --------------------------------------------------
    def _read(self, n: int) -> bytes:
        out = self._mm[self._pos : self._pos + n]
        self._pos += n
        return out

    def _scalar(self, fmt: str):
        (v,) = struct.unpack(fmt, self._read(struct.calcsize(fmt)))
        return v

    def _string(self) -> str:
        n = self._scalar("<Q")
        return self._read(n).decode("utf-8", errors="replace")

    def _value(self, vtype: int):
        if vtype == _STR:
            return self._string()
        if vtype == _ARR:
            etype = self._scalar("<I")
            n = self._scalar("<Q")
            return [self._value(etype) for _ in range(n)]
        fmt = _SCALAR_FMT.get(vtype)
        if fmt is None:
            raise ValueError(f"unknown GGUF metadata value type {vtype}")
        return self._scalar(fmt)

    # -- public surface -----------------------------------------------------
    def names(self) -> list[str]:
        return list(self._tensors)

    def __contains__(self, name: str) -> bool:
        return name in self._tensors

    def tensor(self, name: str) -> np.ndarray:
        """Tensor in numpy convention (outermost dim first). F32/F16/BF16
        are zero-copy views; Q8_0/Q4_0 GGML blocks (32-element groups with
        an f16 scale) dequantize to float32 — serving re-quantizes to the
        engine's own per-channel int8 when ``quantization=int8`` is set
        (models/quant.py), so the HBM saving survives the round trip."""
        dims, ggml_type, offset = self._tensors[name]
        count = int(np.prod(dims)) if dims else 1
        shape = tuple(reversed(dims))  # GGML dims are innermost-first
        if ggml_type in (GGML_Q8_0, GGML_Q4_0):
            nblocks = count // 32
            bb = 34 if ggml_type == GGML_Q8_0 else 18
            raw = np.frombuffer(self._mm, dtype=np.uint8, count=nblocks * bb,
                                offset=self._data_base + offset)
            raw = raw.reshape(nblocks, bb)
            scale = raw[:, :2].copy().view(np.float16).astype(np.float32)
            if ggml_type == GGML_Q8_0:
                vals = raw[:, 2:].copy().view(np.int8).astype(np.float32)
            else:  # Q4_0: 16 bytes of nibbles, value = nibble - 8
                nib = raw[:, 2:]
                vals = np.concatenate(
                    [(nib & 0x0F).astype(np.int8), (nib >> 4).astype(np.int8)],
                    axis=1).astype(np.float32) - 8.0
            return (vals * scale).reshape(shape)
        dtype = _TENSOR_DTYPES.get(ggml_type)
        if dtype is None:
            raise ValueError(
                f"tensor {name!r} uses ggml type {ggml_type}; only "
                "F32/F16/BF16/Q8_0/Q4_0 GGUF tensors are supported — "
                "requantize or convert the checkpoint")
        arr = np.frombuffer(self._mm, dtype=dtype, count=count,
                            offset=self._data_base + offset)
        return arr.reshape(shape)

    def architecture(self) -> str:
        return str(self.metadata.get("general.architecture", ""))

    def config(self) -> ModelConfig:
        """llama-family metadata → engine ModelConfig."""
        arch = self.architecture()
        if arch != "llama":
            raise ValueError(f"unsupported GGUF architecture {arch!r}")
        md = self.metadata

        def req(key: str):
            if f"{arch}.{key}" not in md:
                raise ValueError(f"GGUF missing {arch}.{key}")
            return md[f"{arch}.{key}"]

        n_heads = int(req("attention.head_count"))
        emb = int(req("embedding_length"))
        vocab = int(md.get(f"{arch}.vocab_size")
                    or len(md.get("tokenizer.ggml.tokens", []) or [])
                    or self._tensors["token_embd.weight"][0][1])
        return ModelConfig(
            name=self.path.stem,
            vocab_size=vocab,
            hidden_size=emb,
            intermediate_size=int(req("feed_forward_length")),
            num_layers=int(req("block_count")),
            num_heads=n_heads,
            num_kv_heads=int(md.get(f"{arch}.attention.head_count_kv", n_heads)),
            head_dim=int(md.get(f"{arch}.attention.key_length", emb // n_heads)),
            rope_theta=float(md.get(f"{arch}.rope.freq_base", 10000.0)),
            rms_norm_eps=float(md.get(
                f"{arch}.attention.layer_norm_rms_epsilon", 1e-5)),
            max_position_embeddings=int(md.get(f"{arch}.context_length", 8192)),
            tie_word_embeddings="output.weight" not in self._tensors,
        )


def permute_qk(w: np.ndarray, n_heads: int) -> np.ndarray:
    """HF half-rotate layout → GGUF interleaved-rope layout for attn_q/attn_k
    rows (llama.cpp convert_hf_to_gguf permute): per head, rows reorder from
    [evens, odds] halves to interleaved pairs. ``w`` is [out, in]."""
    out, inn = w.shape
    return (w.reshape(n_heads, 2, out // n_heads // 2, inn)
             .swapaxes(1, 2).reshape(out, inn))


def unpermute_qk(w: np.ndarray, n_heads: int) -> np.ndarray:
    """Inverse of :func:`permute_qk`: GGUF checkpoints store Q/K in the
    interleaved-rope layout; the engine applies HF half-rotate rope
    (models/llama.rope), so loads must restore the HF row order — without
    this, every real llama.cpp-produced GGUF generates garbage."""
    out, inn = w.shape
    return (w.reshape(n_heads, out // n_heads // 2, 2, inn)
             .swapaxes(1, 2).reshape(out, inn))


# llama.cpp tensor names → (our layer param, transpose-to-[in,out])
_LAYER_SPECS = {
    "wq": ("attn_q.weight", True),
    "wk": ("attn_k.weight", True),
    "wv": ("attn_v.weight", True),
    "wo": ("attn_output.weight", True),
    "attn_norm": ("attn_norm.weight", False),
    "mlp_norm": ("ffn_norm.weight", False),
    "w_gate": ("ffn_gate.weight", True),
    "w_up": ("ffn_up.weight", True),
    "w_down": ("ffn_down.weight", True),
}


def load_params_gguf(path: str | Path, mesh=None) -> tuple[ModelConfig, dict]:
    """Read a llama-family GGUF into (config, engine params pytree)."""
    import jax.numpy as jnp

    from dynamo_tpu.models.llama import param_logical_axes
    from dynamo_tpu.parallel.mesh import global_put, param_sharding_rules

    reader = GGUFReader(path)
    cfg = reader.config()
    dtype = np.dtype(np.float32) if _BF16 is None else _BF16
    axes = param_logical_axes(cfg)

    def place(arr: np.ndarray, leaf_axes):
        arr = np.ascontiguousarray(arr, dtype=dtype)
        if mesh is not None:
            return global_put(arr, param_sharding_rules(mesh, leaf_axes))
        return jnp.asarray(arr)

    params: dict = {
        "embed": place(reader.tensor("token_embd.weight"), axes["embed"]),
        "final_norm": place(reader.tensor("output_norm.weight"), axes["final_norm"]),
        "layers": {},
    }
    if not cfg.tie_word_embeddings:
        params["lm_head"] = place(reader.tensor("output.weight").T, axes["lm_head"])
    L = cfg.num_layers
    unperm = {"wq": cfg.num_heads, "wk": cfg.num_kv_heads}
    for our, (suffix, transpose) in _LAYER_SPECS.items():
        def grab(i: int) -> np.ndarray:
            t = reader.tensor(f"blk.{i}.{suffix}")
            if our in unperm:
                t = unpermute_qk(np.asarray(t, np.float32), unperm[our])
            return t.T if transpose else t

        first = grab(0)
        out = np.empty((L, *first.shape), dtype=dtype)
        out[0] = first
        for i in range(1, L):
            out[i] = grab(i)
        params["layers"][our] = place(out, axes["layers"][our])
    log.info("loaded GGUF %s: %s (%d layers, vocab %d)",
             path, cfg.name, cfg.num_layers, cfg.vocab_size)
    return cfg, params


# ---------------------------------------------------------------------------
# Writer (tests + re-export tooling)
# ---------------------------------------------------------------------------

def _w_string(f: BinaryIO, s: str) -> None:
    b = s.encode()
    f.write(struct.pack("<Q", len(b)) + b)


def _w_value(f: BinaryIO, v: Any) -> None:
    if isinstance(v, bool):
        f.write(struct.pack("<I", _BOOL) + struct.pack("<?", v))
    elif isinstance(v, int):
        f.write(struct.pack("<I", _U64) + struct.pack("<Q", v))
    elif isinstance(v, float):
        f.write(struct.pack("<I", _F32) + struct.pack("<f", v))
    elif isinstance(v, str):
        f.write(struct.pack("<I", _STR))
        _w_string(f, v)
    elif isinstance(v, list):
        f.write(struct.pack("<I", _ARR))
        f.write(struct.pack("<I", _STR) + struct.pack("<Q", len(v)))
        for item in v:
            _w_string(f, str(item))
    else:
        raise TypeError(f"unsupported metadata value {type(v)}")


def save_gguf(path: str | Path, metadata: dict[str, Any],
              tensors: dict[str, Any]) -> None:
    """Write a GGUF v3 file. Values are numpy arrays (F32/F16/BF16) or
    pre-encoded quantized tensors as ``(numpy_shape, ggml_type, raw_bytes)``
    tuples (e.g. Q8_0 blocks)."""
    rev_types = {np.dtype(np.float32): GGML_F32, np.dtype(np.float16): GGML_F16}
    if _BF16 is not None:
        rev_types[_BF16] = GGML_BF16
    with open(path, "wb") as f:
        f.write(MAGIC)
        f.write(struct.pack("<I", 3))
        f.write(struct.pack("<Q", len(tensors)))
        f.write(struct.pack("<Q", len(metadata)))
        for k, v in metadata.items():
            _w_string(f, k)
            _w_value(f, v)
        offset = 0
        blobs: list[bytes] = []
        for name, arr in tensors.items():
            if isinstance(arr, tuple):
                shape, gtype, blob = arr
            else:
                arr = np.ascontiguousarray(arr)
                shape, gtype, blob = arr.shape, rev_types[np.dtype(arr.dtype)], arr.tobytes()
            _w_string(f, name)
            dims = tuple(reversed(shape))  # ggml: innermost first
            f.write(struct.pack("<I", len(dims)))
            for d in dims:
                f.write(struct.pack("<Q", d))
            f.write(struct.pack("<I", gtype))
            f.write(struct.pack("<Q", offset))
            pad = (-len(blob)) % DEFAULT_ALIGNMENT
            blobs.append(blob + b"\0" * pad)
            offset += len(blob) + pad
        f.write(b"\0" * ((-f.tell()) % DEFAULT_ALIGNMENT))
        for blob in blobs:
            f.write(blob)
