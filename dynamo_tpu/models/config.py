"""Model architecture configs.

The engine is first-party (the reference delegates model math to
vLLM/SGLang/TRT-LLM; here it is ours — SURVEY.md §7). One config dataclass
covers the dense Llama family (3-8B/70B), MoE (DeepSeek/gpt-oss-style), and
the tiny CPU-testable presets that fill the llama.cpp role in the
reference's zero-GPU test path (reference: lib/engines/llamacpp).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from pathlib import Path


@dataclass(frozen=True)
class ModelConfig:
    name: str = "tiny-llama"
    vocab_size: int = 512
    hidden_size: int = 64
    intermediate_size: int = 128
    num_layers: int = 2
    num_heads: int = 4
    num_kv_heads: int = 2
    head_dim: int = 16
    rope_theta: float = 10000.0
    rms_norm_eps: float = 1e-5
    max_position_embeddings: int = 8192
    tie_word_embeddings: bool = True
    dtype: str = "bfloat16"
    # MoE (0 experts = dense)
    num_experts: int = 0
    num_experts_per_tok: int = 0
    moe_intermediate_size: int = 0
    num_shared_experts: int = 0
    # Multimodal (vision encoder attached)
    vision: "VisionConfig | None" = None

    @property
    def is_moe(self) -> bool:
        return self.num_experts > 0

    @property
    def q_size(self) -> int:
        return self.num_heads * self.head_dim

    @property
    def kv_size(self) -> int:
        return self.num_kv_heads * self.head_dim

    @classmethod
    def from_hf_config(cls, path: str) -> "ModelConfig":
        """Read a local HF config.json (llama-family keys)."""
        cfg = json.loads((Path(path) / "config.json").read_text())
        n_heads = cfg["num_attention_heads"]
        # MoE keys across HF families: mixtral (num_local_experts),
        # deepseek/qwen-moe (n_routed_experts, num_experts).
        n_experts = (cfg.get("num_local_experts") or cfg.get("n_routed_experts")
                     or cfg.get("num_experts") or 0)
        return cls(
            num_experts=n_experts,
            num_experts_per_tok=cfg.get("num_experts_per_tok", 2 if n_experts else 0),
            moe_intermediate_size=cfg.get(
                "moe_intermediate_size",
                cfg["intermediate_size"] if n_experts else 0),
            num_shared_experts=cfg.get("n_shared_experts", 0) or 0,
            name=cfg.get("_name_or_path", Path(path).name),
            vocab_size=cfg["vocab_size"],
            hidden_size=cfg["hidden_size"],
            intermediate_size=cfg["intermediate_size"],
            num_layers=cfg["num_hidden_layers"],
            num_heads=n_heads,
            num_kv_heads=cfg.get("num_key_value_heads", n_heads),
            head_dim=cfg.get("head_dim", cfg["hidden_size"] // n_heads),
            rope_theta=cfg.get("rope_theta", 10000.0),
            rms_norm_eps=cfg.get("rms_norm_eps", 1e-5),
            max_position_embeddings=cfg.get("max_position_embeddings", 8192),
            tie_word_embeddings=cfg.get("tie_word_embeddings", False),
        )


@dataclass(frozen=True)
class VisionConfig:
    """ViT encoder config for multimodal models (reference role:
    multimodal encode workers, components/src/dynamo/sglang multimodal)."""

    image_size: int = 224
    patch_size: int = 14
    hidden_size: int = 64
    num_layers: int = 2
    num_heads: int = 4
    intermediate_size: int = 128
    projector_hidden: int = 64


MODEL_PRESETS: dict[str, ModelConfig] = {
    # CPU-testable tiny models (the llama.cpp-of-this-repo).
    "tiny-llama": ModelConfig(),
    "tiny-llama-big-vocab": ModelConfig(name="tiny-llama-big-vocab", vocab_size=32000),
    "tiny-moe": ModelConfig(
        name="tiny-moe",
        num_experts=8,
        num_experts_per_tok=2,
        moe_intermediate_size=64,
        num_shared_experts=1,
    ),
    # Real targets (shapes only; weights load from local checkpoints).
    "llama-3-8b": ModelConfig(
        name="llama-3-8b",
        vocab_size=128256,
        hidden_size=4096,
        intermediate_size=14336,
        num_layers=32,
        num_heads=32,
        num_kv_heads=8,
        head_dim=128,
        rope_theta=500000.0,
        max_position_embeddings=8192,
        tie_word_embeddings=False,
    ),
    # 8-layer cut of llama-3-8b: real layer shapes, fits one v5e chip with
    # ample KV cache headroom — used by bench.py and the compile-check entry.
    "llama-3-8b-lite": ModelConfig(
        name="llama-3-8b-lite",
        vocab_size=128256,
        hidden_size=4096,
        intermediate_size=14336,
        num_layers=8,
        num_heads=32,
        num_kv_heads=8,
        head_dim=128,
        rope_theta=500000.0,
        max_position_embeddings=8192,
        tie_word_embeddings=True,
    ),
    "llama-3-70b": ModelConfig(
        name="llama-3-70b",
        vocab_size=128256,
        hidden_size=8192,
        intermediate_size=28672,
        num_layers=80,
        num_heads=64,
        num_kv_heads=8,
        head_dim=128,
        rope_theta=500000.0,
        max_position_embeddings=8192,
        tie_word_embeddings=False,
    ),
    # DeepSeek-R1-style wide-EP target (GQA stand-in for MLA in v1).
    "deepseek-moe": ModelConfig(
        name="deepseek-moe",
        vocab_size=129280,
        hidden_size=7168,
        intermediate_size=18432,
        num_layers=61,
        num_heads=128,
        num_kv_heads=8,
        head_dim=128,
        num_experts=256,
        num_experts_per_tok=8,
        moe_intermediate_size=2048,
        num_shared_experts=1,
    ),
    # gpt-oss-120b-style MoE.
    "gpt-oss-120b": ModelConfig(
        name="gpt-oss-120b",
        vocab_size=201088,
        hidden_size=2880,
        intermediate_size=2880,
        num_layers=36,
        num_heads=64,
        num_kv_heads=8,
        head_dim=64,
        num_experts=128,
        num_experts_per_tok=4,
        moe_intermediate_size=2880,
    ),
}


def resolve_model_config(name_or_path: str) -> ModelConfig:
    if name_or_path in MODEL_PRESETS:
        return MODEL_PRESETS[name_or_path]
    p = Path(name_or_path)
    if p.is_file() and p.suffix == ".gguf":
        from dynamo_tpu.models.gguf import GGUFReader

        return GGUFReader(p).config()
    if p.is_dir() and (p / "config.json").exists():
        return ModelConfig.from_hf_config(name_or_path)
    raise ValueError(f"unknown model: {name_or_path!r} (presets: {sorted(MODEL_PRESETS)})")
