"""Checkpoint loading: HF safetensors → the engine's params pytree.

Fills the reference's LocalModel/hub role (reference:
lib/llm/src/local_model.rs:45 LocalModelBuilder, lib/llm/src/hub.rs HF
resolution, lib/llm/src/gguf.rs single-file weights) — but TPU-first:

- The safetensors container is parsed directly (8-byte header length +
  JSON header + raw little-endian data) over ``mmap``, so tensor reads are
  zero-copy views; no safetensors/torch dependency.
- HF llama-family tensor names map onto the stacked-layer pytree that
  ``models/llama.forward`` scans over: per-layer weights are gathered into
  one ``[L, ...]`` array per parameter (filled layer-by-layer from the
  mapped files to bound peak host memory), projections are transposed from
  HF's ``[out, in]`` to the engine's row-major ``x @ W`` layout.
- When a mesh is given, each finished parameter is placed with its
  logical-axis sharding (parallel/mesh.py rules) as it is built — the full
  replicated pytree never materializes on one device.

RoPE note: our ``rope`` uses the half-rotate convention, matching HF
transformers' llama checkpoints — weights need no permutation (the
interleaved→half-rotate permutation is only needed for Meta's original
distribution format, which HF checkpoints already incorporate).

MoE checkpoints (mixtral-style ``block_sparse_moe`` names) map onto the
stacked expert arrays; shared-expert variants use the dense-MLP names.
"""

from __future__ import annotations

import json
import mmap
from pathlib import Path
from typing import Any, Iterator

import numpy as np

try:  # jax ships ml_dtypes; bf16 numpy arrays view through it
    import ml_dtypes

    _BF16 = np.dtype(ml_dtypes.bfloat16)
except Exception:  # pragma: no cover
    _BF16 = None

from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.utils.logging import get_logger

log = get_logger("loader")

_ST_DTYPES: dict[str, np.dtype] = {
    "F64": np.dtype(np.float64),
    "F32": np.dtype(np.float32),
    "F16": np.dtype(np.float16),
    "I64": np.dtype(np.int64),
    "I32": np.dtype(np.int32),
    "I16": np.dtype(np.int16),
    "I8": np.dtype(np.int8),
    "U8": np.dtype(np.uint8),
    "BOOL": np.dtype(np.bool_),
}
if _BF16 is not None:
    _ST_DTYPES["BF16"] = _BF16


class SafetensorsFile:
    """Zero-copy reader for one .safetensors file (mmap-backed)."""

    def __init__(self, path: str | Path):
        self.path = Path(path)
        with open(self.path, "rb") as f:
            self._mm = mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_READ)
        header_len = int.from_bytes(self._mm[:8], "little")
        self.header: dict[str, Any] = json.loads(self._mm[8 : 8 + header_len])
        self.header.pop("__metadata__", None)
        self._base = 8 + header_len

    def names(self) -> list[str]:
        return list(self.header)

    def tensor(self, name: str) -> np.ndarray:
        meta = self.header[name]
        dtype = _ST_DTYPES[meta["dtype"]]
        shape = meta["shape"]
        start, _end = meta["data_offsets"]
        count = int(np.prod(shape)) if shape else 1
        arr = np.frombuffer(self._mm, dtype=dtype, count=count,
                            offset=self._base + start)
        return arr.reshape(shape)


def save_safetensors(path: str | Path, tensors: dict[str, np.ndarray]) -> None:
    """Write a .safetensors file (tests + checkpoint tooling)."""
    codes = {v: k for k, v in _ST_DTYPES.items()}
    header: dict[str, Any] = {}
    offset = 0
    blobs: list[bytes] = []
    for name, a in tensors.items():
        a = np.ascontiguousarray(a)
        code = codes[np.dtype(a.dtype)]
        header[name] = {
            "dtype": code,
            "shape": list(a.shape),
            "data_offsets": [offset, offset + a.nbytes],
        }
        blobs.append(a.tobytes())
        offset += a.nbytes
    hj = json.dumps(header).encode()
    with open(path, "wb") as f:
        f.write(len(hj).to_bytes(8, "little"))
        f.write(hj)
        for b in blobs:
            f.write(b)


class CheckpointReader:
    """Name→tensor access across a sharded checkpoint directory.

    Resolves ``model.safetensors.index.json`` (weight_map) when present,
    else unions all ``*.safetensors`` files in the directory."""

    def __init__(self, model_dir: str | Path):
        self.dir = Path(model_dir)
        self._files: dict[str, SafetensorsFile] = {}
        self._where: dict[str, str] = {}
        index = self.dir / "model.safetensors.index.json"
        if index.exists():
            weight_map = json.loads(index.read_text())["weight_map"]
            for name, fname in weight_map.items():
                self._where[name] = fname
        else:
            for p in sorted(self.dir.glob("*.safetensors")):
                for name in self._file(p.name).names():
                    self._where[name] = p.name
        if not self._where:
            raise FileNotFoundError(f"no safetensors weights under {self.dir}")

    def _file(self, fname: str) -> SafetensorsFile:
        if fname not in self._files:
            self._files[fname] = SafetensorsFile(self.dir / fname)
        return self._files[fname]

    def __contains__(self, name: str) -> bool:
        return name in self._where

    def names(self) -> list[str]:
        return list(self._where)

    def get(self, name: str) -> np.ndarray:
        return self._file(self._where[name]).tensor(name)


def has_weights(model_dir: str | Path) -> bool:
    p = Path(model_dir)
    return p.is_dir() and (
        (p / "model.safetensors.index.json").exists()
        or any(p.glob("*.safetensors"))
    )


# ---------------------------------------------------------------------------
# HF llama-family name mapping
# ---------------------------------------------------------------------------

def _np_dtype(name: str) -> np.dtype:
    if name == "bfloat16":
        if _BF16 is None:  # pragma: no cover
            raise RuntimeError("bfloat16 load requires ml_dtypes")
        return _BF16
    return np.dtype(name)


def _layer_specs(cfg: ModelConfig, family: str) -> dict[str, tuple[str, bool]]:
    """Our layer param name → (HF suffix under model.layers.{i}., transpose).

    Transpose=True: HF stores linear weights as [out_features, in_features];
    the engine computes ``x @ W`` with W as [in, out].
    """
    specs = {
        "wq": ("self_attn.q_proj.weight", True),
        "wk": ("self_attn.k_proj.weight", True),
        "wv": ("self_attn.v_proj.weight", True),
        "wo": ("self_attn.o_proj.weight", True),
        "attn_norm": ("input_layernorm.weight", False),
        "mlp_norm": ("post_attention_layernorm.weight", False),
    }
    if cfg.is_moe:
        router = ("block_sparse_moe.gate.weight" if family == "mixtral"
                  else "mlp.gate.weight")
        specs["router"] = (router, True)
        if cfg.num_shared_experts:
            specs.update(
                shared_gate=("mlp.shared_experts.gate_proj.weight", True),
                shared_up=("mlp.shared_experts.up_proj.weight", True),
                shared_down=("mlp.shared_experts.down_proj.weight", True),
            )
    else:
        specs.update(
            w_gate=("mlp.gate_proj.weight", True),
            w_up=("mlp.up_proj.weight", True),
            w_down=("mlp.down_proj.weight", True),
        )
    return specs


def _moe_family(reader: "CheckpointReader", cfg: ModelConfig) -> str:
    """Detect the MoE naming family from the checkpoint's tensor names:
    mixtral (block_sparse_moe.experts.N.w1/w2/w3) vs deepseek/qwen-moe
    (mlp.experts.N.gate_proj/up_proj/down_proj + optional shared_experts)."""
    if "model.layers.0.block_sparse_moe.gate.weight" in reader:
        if cfg.num_shared_experts:
            raise ValueError(
                "config declares shared experts but checkpoint uses "
                "mixtral-style names, which have none")
        return "mixtral"
    if "model.layers.0.mlp.gate.weight" in reader:
        return "deepseek"
    raise ValueError(
        "MoE config but no recognized MoE router tensor in checkpoint "
        "(looked for block_sparse_moe.gate / mlp.gate)")


def _expert_specs(family: str) -> dict[str, str]:
    """Routed-expert weights: our name → HF suffix pattern.

    Mixtral convention: w1=gate, w3=up, w2=down."""
    if family == "mixtral":
        return {
            "w_gate": "block_sparse_moe.experts.{e}.w1.weight",
            "w_up": "block_sparse_moe.experts.{e}.w3.weight",
            "w_down": "block_sparse_moe.experts.{e}.w2.weight",
        }
    return {
        "w_gate": "mlp.experts.{e}.gate_proj.weight",
        "w_up": "mlp.experts.{e}.up_proj.weight",
        "w_down": "mlp.experts.{e}.down_proj.weight",
    }


def iter_param_leaves(
    cfg: ModelConfig, reader: CheckpointReader, dtype: np.dtype
) -> Iterator[tuple[tuple[str, ...], np.ndarray]]:
    """Yield ((pytree path), stacked ndarray) for every model parameter.

    Layer params are stacked into [L, ...] host arrays filled one layer at a
    time from the mmap'd files, so peak host memory is one full parameter,
    not one full checkpoint.
    """
    L = cfg.num_layers
    family = _moe_family(reader, cfg) if cfg.is_moe else "llama"

    def grab(name: str, transpose: bool) -> np.ndarray:
        if name not in reader:
            raise KeyError(
                f"checkpoint is missing tensor {name!r} (family={family}); "
                f"config/checkpoint mismatch?")
        t = reader.get(name)
        if transpose:
            t = t.T
        return np.ascontiguousarray(t, dtype=dtype)

    yield ("embed",), grab("model.embed_tokens.weight", False)
    yield ("final_norm",), grab("model.norm.weight", False)
    if not cfg.tie_word_embeddings:
        yield ("lm_head",), grab("lm_head.weight", True)

    for our, (suffix, transpose) in _layer_specs(cfg, family).items():
        first = grab(f"model.layers.0.{suffix}", transpose)
        out = np.empty((L, *first.shape), dtype=dtype)
        out[0] = first
        for i in range(1, L):
            out[i] = grab(f"model.layers.{i}.{suffix}", transpose)
        yield ("layers", our), out

    if cfg.is_moe:
        E = cfg.num_experts
        for our, pattern in _expert_specs(family).items():
            first = grab(f"model.layers.0.{pattern.format(e=0)}", True)
            out = np.empty((L, E, *first.shape), dtype=dtype)
            for i in range(L):
                for e in range(E):
                    out[i, e] = grab(
                        f"model.layers.{i}.{pattern.format(e=e)}", True
                    )
            yield ("layers", our), out


def load_params(
    cfg: ModelConfig, model_dir: str | Path, mesh=None
) -> dict[str, Any]:
    """Load an HF llama-family checkpoint into the engine's params pytree.

    With a mesh, each parameter is placed with its logical-axis sharding as
    soon as it is assembled (parallel/mesh.py rules); without one, params
    land on the default device.
    """
    import jax
    import jax.numpy as jnp

    from dynamo_tpu.models.llama import param_logical_axes
    from dynamo_tpu.parallel.mesh import param_sharding_rules

    reader = CheckpointReader(model_dir)
    axes = param_logical_axes(cfg)
    dtype = _np_dtype(cfg.dtype)
    params: dict[str, Any] = {}
    n_bytes = 0
    for path, arr in iter_param_leaves(cfg, reader, dtype):
        leaf_axes = axes
        node = params
        for key in path[:-1]:
            leaf_axes = leaf_axes[key]
            node = node.setdefault(key, {})
        leaf_axes = leaf_axes[path[-1]]
        if mesh is not None:
            from dynamo_tpu.parallel.mesh import global_put

            placed = global_put(arr, param_sharding_rules(mesh, leaf_axes))
        else:
            placed = jnp.asarray(arr)
        node[path[-1]] = placed
        n_bytes += arr.nbytes
    log.info("loaded %s: %.2f GiB of weights from %s",
             cfg.name, n_bytes / 2**30, model_dir)
    return params
