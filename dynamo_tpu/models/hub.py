"""Model acquisition: resolve a model *name* to a local checkpoint path.

Fills the reference's hub-download role (reference: lib/llm/src/hub.rs —
`from_hf` snapshot download into the HF cache; probe order in
lib/llm/src/local_model.rs:45 LocalModelBuilder: local path → GGUF file →
hub repo id). TPU-relevant framing: weights land in the shared HF cache
directory once per host; the loader then mmaps safetensors from there and
shards straight onto the device mesh, so the download never transits
device memory.

Resolution order for ``resolve_model_path(model)``:

1. An existing local path (directory or ``.gguf`` file) → returned as-is.
2. A built-in preset name (``MODEL_PRESETS``) → returned as-is (random
   init or test fixtures; no weights on disk).
3. Anything shaped like an HF repo id (``org/name``) → snapshot download
   via ``huggingface_hub`` (honoring ``HF_HUB_OFFLINE`` / an offline
   environment with a clear error), returning the local snapshot dir.

Only weight/config/tokenizer artifacts are fetched — ``*.bin`` torch
duplicates of safetensors checkpoints are excluded, halving the pull for
dual-format repos.
"""

from __future__ import annotations

import os
from pathlib import Path

from dynamo_tpu.utils.logging import get_logger

log = get_logger("hub")

# What a serving snapshot needs: weights, configs, tokenizer assets.
ALLOW_PATTERNS = [
    "*.safetensors",
    "*.safetensors.index.json",
    "*.json",
    "*.model",          # sentencepiece
    "tokenizer*",
    "*.gguf",
]


def looks_like_repo_id(model: str) -> bool:
    """``org/name`` shape, not an existing filesystem path."""
    if os.path.exists(model):
        return False
    parts = model.split("/")
    return (
        len(parts) == 2
        and all(p and not p.startswith((".", "~")) for p in parts)
        and not model.endswith(".gguf")
    )


def resolve_model_path(model: str, revision: str | None = None) -> str:
    """Resolve ``model`` to a local path, downloading from the HF hub when
    it names a repo id. Raises ValueError with a actionable message when
    the download cannot proceed (offline env, missing repo, gated)."""
    from dynamo_tpu.models.config import MODEL_PRESETS

    if model in MODEL_PRESETS or os.path.exists(model):
        return model
    if not looks_like_repo_id(model):
        return model  # let the engine's weight probe report the bad path

    try:
        from huggingface_hub import snapshot_download
        from huggingface_hub.errors import (
            HfHubHTTPError,
            LocalEntryNotFoundError,
            RepositoryNotFoundError,
        )
    except ImportError as exc:  # pragma: no cover - hub lib is baked in
        raise ValueError(
            f"{model!r} looks like a HF hub repo id but huggingface_hub is "
            "not installed; pass a local checkpoint path instead") from exc

    offline = os.environ.get("HF_HUB_OFFLINE", "").lower() in ("1", "true", "yes")
    try:
        path = snapshot_download(
            model, revision=revision, allow_patterns=ALLOW_PATTERNS,
            local_files_only=offline,
        )
    except LocalEntryNotFoundError as exc:
        raise ValueError(
            f"model {model!r} is not in the local HF cache and the "
            "environment is offline (HF_HUB_OFFLINE / no egress); "
            "pre-download it or pass a local checkpoint path") from exc
    except RepositoryNotFoundError as exc:
        raise ValueError(
            f"HF hub repo {model!r} does not exist (or is gated and no "
            "token is configured)") from exc
    except HfHubHTTPError as exc:
        raise ValueError(f"HF hub download of {model!r} failed: {exc}") from exc
    except OSError as exc:  # DNS failure etc. in a zero-egress environment
        raise ValueError(
            f"cannot reach the HF hub to download {model!r} "
            f"(offline environment?): {exc}") from exc
    log.info("resolved hub model %s → %s", model, path)

    # GGUF-only repos resolve to the single .gguf file (the loader's
    # entry format probe keys off the suffix, reference gguf.rs role).
    snap = Path(path)
    if not any(snap.glob("*.safetensors")):
        ggufs = sorted(snap.glob("*.gguf"))
        if len(ggufs) == 1:
            return str(ggufs[0])
    return path
