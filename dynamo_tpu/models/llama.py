"""Llama-family transformer in pure JAX over a paged KV cache.

This is the engine's model math — the part the reference delegates to
vLLM/SGLang/TRT-LLM (SURVEY.md §7: first-party JAX engine). Design points:

- **One forward for prefill and decode.** A step processes ``T`` query
  tokens per sequence (T=chunk for prefill, T=1 for decode) against a paged
  KV cache addressed by per-request block tables. Static shapes per
  (batch-bucket, T-bucket) so XLA compiles once per bucket.
- **Layers are scanned** (``lax.scan`` over stacked layer params) so 80-layer
  models trace/compile in constant time, with the per-layer KV cache slices
  threaded through the scan.
- **Paged attention via gather** in the portable path: context KV is gathered
  from cache blocks by block table then attended densely with position
  masking (XLA fuses this well); a Pallas kernel (ops/) replaces it on TPU.
- **Block 0 is the trash block**: padding tokens scatter their KV there, so
  no dynamic control flow is needed for ragged batches.

Sharding: logical axes annotated per param (parallel/mesh.py rules) — heads
and MLP intermediate on the "model" mesh axis, experts on "expert".
"""

from __future__ import annotations

from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax

from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.obs.profiler import phase as _perf_phase
from dynamo_tpu.parallel.mesh import shard_map_compat
from dynamo_tpu.utils.logging import get_logger

log = get_logger("models.llama")

Params = dict[str, Any]


def _dtype(cfg: ModelConfig):
    return jnp.dtype(cfg.dtype)


# ---------------------------------------------------------------------------
# Parameter init (shapes + logical sharding axes)
# ---------------------------------------------------------------------------

def param_logical_axes(cfg: ModelConfig) -> Params:
    """Logical axis names per parameter leaf (for mesh sharding rules)."""
    layer = {
        "wq": ("layers", None, "heads"),
        "wk": ("layers", None, "kv_heads"),
        "wv": ("layers", None, "kv_heads"),
        "wo": ("layers", "heads", None),
        "attn_norm": ("layers", None),
        "mlp_norm": ("layers", None),
    }
    if cfg.is_moe:
        layer.update(
            router=("layers", None, "expert"),
            w_gate=("layers", "expert", None, "moe_mlp"),
            w_up=("layers", "expert", None, "moe_mlp"),
            w_down=("layers", "expert", "moe_mlp", None),
        )
        if cfg.num_shared_experts:
            layer.update(
                shared_gate=("layers", None, "mlp"),
                shared_up=("layers", None, "mlp"),
                shared_down=("layers", "mlp", None),
            )
    else:
        layer.update(
            w_gate=("layers", None, "mlp"),
            w_up=("layers", None, "mlp"),
            w_down=("layers", "mlp", None),
        )
    axes: Params = {"embed": ("vocab", None), "final_norm": (None,), "layers": layer}
    if not cfg.tie_word_embeddings:
        axes["lm_head"] = (None, "vocab")
    return axes


def init_params(cfg: ModelConfig, key: jax.Array) -> Params:
    """Random-init params (tests/tiny models; real weights come from loaders)."""
    dt = _dtype(cfg)
    k = iter(jax.random.split(key, 24))
    h, L = cfg.hidden_size, cfg.num_layers

    def dense(key, shape, fan_in):
        return (jax.random.normal(key, shape, jnp.float32) * (fan_in**-0.5)).astype(dt)

    layer: Params = {
        "wq": dense(next(k), (L, h, cfg.q_size), h),
        "wk": dense(next(k), (L, h, cfg.kv_size), h),
        "wv": dense(next(k), (L, h, cfg.kv_size), h),
        "wo": dense(next(k), (L, cfg.q_size, h), cfg.q_size),
        "attn_norm": jnp.ones((L, h), dt),
        "mlp_norm": jnp.ones((L, h), dt),
    }
    if cfg.is_moe:
        E, m = cfg.num_experts, cfg.moe_intermediate_size
        layer.update(
            router=dense(next(k), (L, h, E), h),
            w_gate=dense(next(k), (L, E, h, m), h),
            w_up=dense(next(k), (L, E, h, m), h),
            w_down=dense(next(k), (L, E, m, h), m),
        )
        if cfg.num_shared_experts:
            sm = cfg.moe_intermediate_size * cfg.num_shared_experts
            layer.update(
                shared_gate=dense(next(k), (L, h, sm), h),
                shared_up=dense(next(k), (L, h, sm), h),
                shared_down=dense(next(k), (L, sm, h), sm),
            )
    else:
        i = cfg.intermediate_size
        layer.update(
            w_gate=dense(next(k), (L, h, i), h),
            w_up=dense(next(k), (L, h, i), h),
            w_down=dense(next(k), (L, i, h), i),
        )
    params: Params = {
        "embed": dense(next(k), (cfg.vocab_size, h), h),
        "final_norm": jnp.ones((h,), dt),
        "layers": layer,
    }
    if not cfg.tie_word_embeddings:
        params["lm_head"] = dense(next(k), (h, cfg.vocab_size), h)
    return params


# ---------------------------------------------------------------------------
# Building blocks
# ---------------------------------------------------------------------------

def rms_norm(x: jax.Array, w: jax.Array, eps: float) -> jax.Array:
    x32 = x.astype(jnp.float32)
    scale = jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + eps)
    return (x32 * scale).astype(x.dtype) * w


def rope(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """Rotary embedding, half-rotate (HF llama) convention.

    x: [B, T, H, D]; positions: [B, T].
    """
    d = x.shape[-1]
    inv_freq = 1.0 / (theta ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d))
    angles = positions[..., None].astype(jnp.float32) * inv_freq  # [B,T,D/2]
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


#: floor for quantization scales — avoids div-by-zero on all-zero updates
#: (e.g. trash-block padding writes) while keeping real scales untouched.
_KV_SCALE_EPS = 1e-8


def _scatter_kv(cache, new: jax.Array, slot_idx: jax.Array):
    """Write new KV [B,T,KH,D] into paged cache [NB,BS,KH,D] at flat slots.

    slot_idx: [B,T] flat slot index (block*block_size + offset); padding
    tokens point at the trash block (block 0).

    Quantized caches ({"q": int8 [NB,BS,KH,D], "s": f32 [NB,KH]}) quantize
    at scatter time, symmetric per-block-per-head (engine/cache.py).
    """
    if isinstance(cache, dict):
        return _scatter_kv_quant(cache, new, slot_idx)
    nb, bs, kh, d = cache.shape
    flat = cache.reshape(nb * bs, kh, d)
    idx = slot_idx.reshape(-1)
    vals = new.reshape(-1, kh, d)
    flat = flat.at[idx].set(vals, mode="drop")
    return flat.reshape(nb, bs, kh, d)


def _scatter_kv_quant(cache: dict, new: jax.Array, slot_idx: jax.Array) -> dict:
    """Int8/int4 scatter: abs-max over the block update sets/merges the
    block's per-head scale, existing rows of touched blocks are rescaled to
    the new scale, then the new rows are quantized and written.

    A write at block offset 0 marks the block as freshly (re)tenanted and
    resets its scale — otherwise a recycled block would inherit the previous
    tenant's (possibly much larger) scale forever. Mid-block writes merge via
    max so already-committed rows never lose range. Rows past the write
    frontier hold stale garbage but every reader masks by kv_len.

    A uint8 payload means packed int4 (engine/cache.py): values quantize to
    ±7 and pack two nibbles per byte along head_dim; the scale lifecycle
    (reset / max-merge / requant of committed rows) is identical — requant
    unpacks, rescales, and repacks the touched blocks.
    """
    from dynamo_tpu.ops.paged_attention import pack_int4, unpack_int4

    q, s = cache["q"], cache["s"]
    int4 = q.dtype == jnp.uint8
    qmax = 7.0 if int4 else 127.0
    nb, bs, kh, _dp = q.shape
    d = new.shape[-1]
    idx = slot_idx.reshape(-1)                                   # [N]
    vals = new.reshape(-1, kh, d).astype(jnp.float32)            # [N,KH,D]
    blk = jnp.clip(idx // bs, 0, nb - 1)
    off = idx % bs

    row_amax = jnp.max(jnp.abs(vals), axis=-1)                   # [N,KH]
    upd_amax = jnp.zeros((nb, kh), jnp.float32).at[blk].max(row_amax)
    resets = jnp.zeros((nb,), jnp.int32).at[blk].max(
        (off == 0).astype(jnp.int32)) > 0                        # fresh tenant
    s_cand = upd_amax / qmax
    s_new = jnp.where(resets[:, None], s_cand, jnp.maximum(s, s_cand))
    s_new = jnp.maximum(s_new, jnp.where(upd_amax > 0, _KV_SCALE_EPS, s_new))

    # Rescale the already-written rows of every touched block. Gathering per
    # token row (duplicates write identical values) keeps shapes static; cost
    # is bounded by (tokens-in-update × block_size), not by NB.
    ratio = jnp.where(s_new > 0, s / jnp.maximum(s_new, _KV_SCALE_EPS), 0.0)
    old = q[blk]                                                 # [N,BS,KH,Dp]
    old = (unpack_int4(old) if int4 else old).astype(jnp.float32)  # [N,BS,KH,D]
    requant = jnp.clip(jnp.round(old * ratio[blk][:, None, :, None]),
                       -qmax, qmax).astype(jnp.int32)
    requant = pack_int4(requant) if int4 else requant.astype(jnp.int8)
    q = q.at[blk].set(requant, mode="drop")

    # Quantize and write the new rows (overwrites the rescaled slots).
    s_rows = jnp.maximum(s_new[blk], _KV_SCALE_EPS)              # [N,KH]
    q_rows = jnp.clip(jnp.round(vals / s_rows[:, :, None]), -qmax, qmax)
    q_rows = (pack_int4(q_rows.astype(jnp.int32)) if int4
              else q_rows.astype(jnp.int8))
    flat = q.reshape(nb * bs, kh, -1)
    flat = flat.at[idx].set(q_rows, mode="drop")
    return {"q": flat.reshape(q.shape), "s": s_new}


def _gather_kv(cache, block_tables: jax.Array) -> jax.Array:
    """Gather context KV: cache [NB,BS,KH,D], block_tables [B,NBLK] →
    [B, NBLK*BS, KH, D] laid out in position order. Quantized caches are
    dequantized on gather (dense fallback path); packed-int4 payloads
    (uint8) unpack their nibbles first."""
    if isinstance(cache, dict):
        from dynamo_tpu.ops.paged_attention import unpack_int4

        g = cache["q"][block_tables]                      # [B,NBLK,BS,KH,Dp]
        if g.dtype == jnp.uint8:
            g = unpack_int4(g)
        g = g.astype(jnp.float32)
        g = g * cache["s"][block_tables][:, :, None, :, None]
        b, nblk, bs, kh, d = g.shape
        return g.reshape(b, nblk * bs, kh, d)
    g = cache[block_tables]  # [B, NBLK, BS, KH, D]
    b, nblk, bs, kh, d = g.shape
    return g.reshape(b, nblk * bs, kh, d)


def _cache_block_size(cache) -> int:
    """block_size from a per-layer-stacked cache (plain array or {"q","s"})."""
    return (cache["q"] if isinstance(cache, dict) else cache).shape[2]


def paged_attention(
    q: jax.Array,           # [B, T, H, D]
    ctx_k: jax.Array,       # [B, S, KH, D]
    ctx_v: jax.Array,       # [B, S, KH, D]
    q_positions: jax.Array,  # [B, T]
    kv_lens: jax.Array,      # [B] total valid context length
) -> jax.Array:
    """Dense attention over gathered paged context with causal position mask.

    Portable path (CPU + TPU); the Pallas paged-attention kernel
    (ops/paged_attention.py) is numerically equivalent.
    """
    b, t, h, d = q.shape
    s = ctx_k.shape[1]
    kh = ctx_k.shape[2]
    rep = h // kh
    qf = q.astype(jnp.float32) * (d**-0.5)
    qf = qf.reshape(b, t, kh, rep, d)
    scores = jnp.einsum("btkrd,bskd->btkrs", qf, ctx_k.astype(jnp.float32))
    ctx_idx = jnp.arange(s)[None, None, :]                      # [1,1,S]
    visible = (ctx_idx <= q_positions[:, :, None]) & (ctx_idx < kv_lens[:, None, None])
    scores = jnp.where(visible[:, :, None, None, :], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("btkrs,bskd->btkrd", probs, ctx_v.astype(jnp.float32))
    return out.reshape(b, t, h, d).astype(q.dtype)


def mm(x: jax.Array, w) -> jax.Array:
    """Dense matmul that understands weight-only int8 leaves
    ({"q": int8, "so": per-out-channel scale} — models/quant.py). The
    scale factors out of the contraction exactly; XLA fuses the int8→bf16
    widening into the dot so weights stream from HBM at 1 byte/elem."""
    if isinstance(w, dict):
        return (x @ w["q"].astype(x.dtype)) * w["so"].astype(x.dtype)
    return x @ w


def embed_lookup(embed, token_ids: jax.Array, dt) -> jax.Array:
    """Embedding gather over a plain or row-quantized ({"q","sr"}) table."""
    if isinstance(embed, dict):
        return (embed["q"][token_ids].astype(dt)
                * embed["sr"][token_ids][..., None].astype(dt))
    return embed[token_ids].astype(dt)


def swiglu(x: jax.Array, w_gate, w_up, w_down) -> jax.Array:
    return mm(jax.nn.silu(mm(x, w_gate)) * mm(x, w_up), w_down)


def moe_mlp(x: jax.Array, lp: Params, cfg: ModelConfig) -> jax.Array:
    """MoE FFN, dense-dispatch formulation (every expert computed, combined by
    top-k router weights). Exact for any E; the EP-sharded ragged-dispatch
    version lives in models/moe.py and is numerically equivalent.

    x: [B, T, H]
    """
    b, t, h = x.shape
    xt = x.reshape(-1, h)                                     # [N, H]
    logits = (xt.astype(jnp.float32)) @ lp["router"].astype(jnp.float32)  # [N, E]
    k = cfg.num_experts_per_tok
    topv, topi = lax.top_k(logits, k)
    weights = jax.nn.softmax(topv, axis=-1)                   # [N, k]
    e = cfg.num_experts
    gate_mask = jnp.zeros((xt.shape[0], e), jnp.float32)
    gate_mask = gate_mask.at[jnp.arange(xt.shape[0])[:, None], topi].add(weights)  # [N, E]
    # all-experts compute: [N,E,m]
    up = jnp.einsum("nh,ehm->nem", xt, lp["w_up"])
    gate = jnp.einsum("nh,ehm->nem", xt, lp["w_gate"])
    act = jax.nn.silu(gate) * up
    per_expert = jnp.einsum("nem,emh->neh", act, lp["w_down"])
    out = jnp.einsum("neh,ne->nh", per_expert.astype(jnp.float32), gate_mask).astype(x.dtype)
    if cfg.num_shared_experts:
        out = out + swiglu(xt, lp["shared_gate"], lp["shared_up"], lp["shared_down"])
    return out.reshape(b, t, h)


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------

def forward(
    params: Params,
    cfg: ModelConfig,
    token_ids: jax.Array,    # [B, T] int32
    q_start: jax.Array,      # [B] position of first query token
    q_len: jax.Array,        # [B] number of valid query tokens (≤ T)
    block_tables: jax.Array,  # [B, NBLK] int32 block ids into the cache
    cache_k: jax.Array,      # [L, NB, BS, KH, D]
    cache_v: jax.Array,
    attn_impl: str = "dense",
    moe_impl: str = "dense",
    mesh=None,
    sp_prefill: bool = False,
    return_all_hidden: bool = False,
    embed_override: jax.Array | None = None,  # [B, T, H] multimodal embeds
    embed_mask: jax.Array | None = None,      # [B, T] True → use override
    pp_microbatches: int = 0,                 # pp>1: schedule depth (0 = auto)
    attn_num_splits: int = 0,                 # split-K: 0 auto, 1 off, N forced
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """One engine step. Returns (last_hidden [B,H], cache_k, cache_v) —
    or (hidden [B,T,H], ...) with ``return_all_hidden`` (the speculative
    verify step needs logits at every chunk position).

    Query token t of sequence b sits at position q_start[b]+t; its KV is
    written into the cache slot named by the block table; attention sees all
    cache positions ≤ its own. Works unchanged for prefill chunks (T>1) and
    decode (T=1).
    """
    b, t = token_ids.shape
    bs = _cache_block_size(cache_k)
    tp = mesh.shape.get("model", 1) if mesh is not None else 1
    dp = mesh.shape.get("data", 1) if mesh is not None else 1
    sp = mesh.shape.get("seq", 1) if mesh is not None else 1
    if mesh is not None and mesh.shape.get("pipe", 1) > 1:
        # Pipeline-parallel path: layer blocks sharded over "pipe".
        return forward_pp(params, cfg, token_ids, q_start, q_len, block_tables,
                          cache_k, cache_v, mesh, attn_impl=attn_impl,
                          microbatches=pp_microbatches,
                          attn_num_splits=attn_num_splits)
    if attn_impl in ("pallas", "pallas_interpret") and tp > 1 and (
        cfg.num_kv_heads % tp != 0 or b % dp != 0
    ):
        # Heads/batch don't divide the mesh: fall back to the dense gather
        # path, partitioned by GSPMD. Trace-time decision — tracing happens
        # once per (batch, chunk) bucket, so this logs once per bucket that
        # actually serves the slow path rather than silently degrading.
        reason = (f"num_kv_heads={cfg.num_kv_heads} mod tp={tp}"
                  if cfg.num_kv_heads % tp != 0 else f"batch={b} mod dp={dp}")
        log.warning(
            "paged-attention kernel disabled for bucket (b=%d, t=%d): %s does "
            "not divide; serving the dense gather path", b, t, reason)
        attn_impl = "dense"
    # Sequence-parallel prefill (ring attention over "seq"): exact for a
    # fresh full-prompt chunk — its attention context is the chunk itself.
    # Trace-time divisibility guards; fall back to the dense path otherwise.
    use_ring = (
        sp_prefill and sp > 1 and t > 1 and t % sp == 0
        and cfg.num_kv_heads % tp == 0 and b % dp == 0
    )
    positions = q_start[:, None] + jnp.arange(t)[None, :]          # [B, T]
    valid = jnp.arange(t)[None, :] < q_len[:, None]                # [B, T]
    kv_lens = q_start + q_len                                      # [B]

    # Flat cache slot per query token; padding → trash block 0.
    blk = jnp.take_along_axis(
        block_tables, jnp.clip(positions // bs, 0, block_tables.shape[1] - 1), axis=1
    )                                                              # [B, T]
    slot = jnp.where(valid, blk * bs + positions % bs, 0)

    h = embed_lookup(params["embed"], token_ids, _dtype(cfg))      # [B, T, H]
    if embed_override is not None:
        # Multimodal positions carry encoder outputs instead of token
        # embeddings (their placeholder ids exist only for position/hash
        # bookkeeping — see preprocessor digest-salted placeholders).
        h = jnp.where(embed_mask[..., None], embed_override.astype(h.dtype), h)

    def layer_fn(carry, xs):
        hid = carry
        lp, ck, cv = xs
        x = rms_norm(hid, lp["attn_norm"], cfg.rms_norm_eps)
        q = mm(x, lp["wq"]).reshape(b, t, cfg.num_heads, cfg.head_dim)
        k = mm(x, lp["wk"]).reshape(b, t, cfg.num_kv_heads, cfg.head_dim)
        v = mm(x, lp["wv"]).reshape(b, t, cfg.num_kv_heads, cfg.head_dim)
        q = rope(q, positions, cfg.rope_theta)
        k = rope(k, positions, cfg.rope_theta)
        # Phase hooks (obs/profiler.py): jax.named_scope annotations for
        # XLA profiles, plus wall capture in eager profiling runs. Under
        # jit they execute at trace time only — zero ops in the program.
        with _perf_phase("scatter"):
            ck = _scatter_kv(ck, k, slot)
            cv = _scatter_kv(cv, v, slot)
        if use_ring:
            from dynamo_tpu.ops.ring_attention import ring_attention_prefill

            with _perf_phase("attention"):
                attn = ring_attention_prefill(mesh, q, k, v, kv_lens)
        elif attn_impl in ("pallas", "pallas_interpret"):
            from dynamo_tpu.ops.paged_attention import (
                paged_attention_kernel,
                paged_attention_sharded,
            )

            interp = attn_impl == "pallas_interpret"
            with _perf_phase("attention"):
                if tp > 1:
                    # TP: shard_map the kernel over the head axis; GSPMD's
                    # psum in the wo projection completes the TP contraction.
                    attn = paged_attention_sharded(
                        mesh, q, ck, cv, block_tables, q_start, kv_lens,
                        num_splits=attn_num_splits, interpret=interp,
                    )
                else:
                    attn = paged_attention_kernel(
                        q, ck, cv, block_tables, q_start, kv_lens,
                        num_splits=attn_num_splits, interpret=interp,
                    )
        else:
            with _perf_phase("gather"):
                ctx_k = _gather_kv(ck, block_tables)
                ctx_v = _gather_kv(cv, block_tables)
            with _perf_phase("attention"):
                attn = paged_attention(q, ctx_k, ctx_v, positions, kv_lens)
        attn = mm(attn.reshape(b, t, cfg.q_size), lp["wo"])
        hid = hid + attn
        x = rms_norm(hid, lp["mlp_norm"], cfg.rms_norm_eps)
        if cfg.is_moe:
            if moe_impl == "ep":
                # Dropless ragged dispatch (serving default for ep>1): exact
                # under any routing skew — see models/moe.py.
                from dynamo_tpu.models.moe import moe_mlp_dropless

                mlp_out = moe_mlp_dropless(x, lp, cfg, mesh=mesh)
            elif moe_impl == "ep_capacity":
                from dynamo_tpu.models.moe import moe_mlp_ep

                mlp_out = moe_mlp_ep(x, lp, cfg)
            else:
                mlp_out = moe_mlp(x, lp, cfg)
        else:
            mlp_out = swiglu(x, lp["w_gate"], lp["w_up"], lp["w_down"])
        hid = hid + mlp_out
        return hid, (ck, cv)

    h, (cache_k, cache_v) = lax.scan(layer_fn, h, (params["layers"], cache_k, cache_v))
    h = rms_norm(h, params["final_norm"], cfg.rms_norm_eps)

    if return_all_hidden:
        return h, cache_k, cache_v                                 # [B, T, H]
    # Hidden state at each sequence's last valid query token.
    last_idx = jnp.clip(q_len - 1, 0, t - 1)                       # [B]
    last_h = jnp.take_along_axis(h, last_idx[:, None, None], axis=1)[:, 0]  # [B, H]
    return last_h, cache_k, cache_v


def forward_pp(
    params: Params,
    cfg: ModelConfig,
    token_ids: jax.Array,
    q_start: jax.Array,
    q_len: jax.Array,
    block_tables: jax.Array,
    cache_k: jax.Array,
    cache_v: jax.Array,
    mesh,
    attn_impl: str = "dense",
    microbatches: int = 0,
    attn_num_splits: int = 0,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Pipeline-parallel forward: layer blocks sharded over the "pipe" axis.

    The reference's planner sizes ``pp`` for its engines
    (components/src/dynamo/planner/utils/planner_core.py:110-118); here PP
    is first-party. Each stage holds ``L/pp`` stacked layers and the
    matching slice of the paged KV cache (kv_cache_spec shards the layer
    dim). Inside one ``shard_map`` over "pipe", a GPipe-style microbatch
    schedule runs M + pp - 1 ticks: every tick each stage computes its
    layer block on ONE microbatch and ``ppermute``s the activations to the
    next stage, so in steady state all pp stages work on different
    microbatches simultaneously — efficiency M/(M+pp-1) vs 1/pp for the
    naive select-and-broadcast pipeline (kept as the fallback for shapes
    too small to split).

    Microbatch axis: prefill chunks (T > 1) split along T — sub-chunk c's
    attention context is the cache, which sub-chunks < c of the same stage
    populated at earlier ticks (the tick order IS the causal order).
    Decode (T = 1) splits along B. Bubble ticks write their (garbage) KV
    to trash block 0 — the same masking the engine's padding rows use —
    and contribute nothing to the output.

    The Pallas paged-attention kernel runs INSIDE the stage block
    (pallas_call nests fine under shard_map; this is the same composition
    paged_attention_sharded uses over "model"). tp/ep stay 1 when pp > 1
    (runner-guarded).
    """
    pp = mesh.shape["pipe"]
    if cfg.num_layers % pp != 0:
        raise ValueError(f"num_layers={cfg.num_layers} not divisible by pp={pp}")
    b, t = token_ids.shape
    bs = _cache_block_size(cache_k)
    nblk = block_tables.shape[1]
    from jax.sharding import PartitionSpec as P

    positions = q_start[:, None] + jnp.arange(t)[None, :]
    valid = jnp.arange(t)[None, :] < q_len[:, None]
    blk = jnp.take_along_axis(
        block_tables, jnp.clip(positions // bs, 0, block_tables.shape[1] - 1), axis=1
    )
    slot = jnp.where(valid, blk * bs + positions % bs, 0)
    h0 = embed_lookup(params["embed"], token_ids, _dtype(cfg))

    # Microbatch count: the largest divisor of the split axis ≤ the target
    # (default 2*pp — enough for ~2/3+ steady-state efficiency without
    # blowing up compile time on the tick loop).
    target = microbatches if microbatches > 0 else 2 * pp
    split_t = t > 1
    axis = t if split_t else b
    m = min(target, axis)
    while m > 1 and axis % m:
        m -= 1
    use_kernel = attn_impl in ("pallas", "pallas_interpret")

    if m < 2:
        if use_kernel:
            log.warning(
                "pp>1 bucket (b=%d, t=%d) too small to microbatch: serving "
                "the sequential dense-attention pipeline", b, t)
        return _forward_pp_sequential(
            params, cfg, positions, q_start + q_len, slot, block_tables,
            cache_k, cache_v, mesh, h0, q_len, pp)

    # Per-microbatch statics, uniformly [M, B', T', ...].
    if split_t:
        tm = t // m
        bm = b
        h0_mb = h0.reshape(b, m, tm, -1).swapaxes(0, 1)
        pos_mb = positions.reshape(b, m, tm).swapaxes(0, 1)
        slot_mb = slot.reshape(b, m, tm).swapaxes(0, 1)
        bt_mb = jnp.broadcast_to(block_tables[None], (m, b, nblk))
        qs_mb = q_start[None, :] + (jnp.arange(m) * tm)[:, None]
        # visible context after sub-chunk c = everything ≤ its last valid
        # token; clip keeps rows whose q_len ends mid-earlier-chunk exact.
        kl_mb = q_start[None, :] + jnp.minimum(
            q_len[None, :], (jnp.arange(m)[:, None] + 1) * tm)
    else:
        tm = t
        bm = b // m
        h0_mb = h0.reshape(m, bm, t, -1)
        pos_mb = positions.reshape(m, bm, t)
        slot_mb = slot.reshape(m, bm, t)
        bt_mb = block_tables.reshape(m, bm, nblk)
        qs_mb = q_start.reshape(m, bm)
        kl_mb = (q_start + q_len).reshape(m, bm)

    def pp_fn(lp_stack, ck_loc, cv_loc, h0_mb, pos_mb, slot_mb, bt_mb, qs_mb, kl_mb):
        s = lax.axis_index("pipe")

        def tick(i, carry):
            h_cur, ck, cv, out = carry
            mb = i - s                     # microbatch at this stage now
            mbc = jnp.clip(mb, 0, m - 1)
            live = (mb >= 0) & (mb < m)
            # Bubble ticks compute on stale activations (finite — zeros at
            # worst) and must leave no trace: KV writes go to trash block 0
            # and the output contribution is masked.
            slot_t = jnp.where(live, slot_mb[mbc], 0)
            h_in = jnp.where(s == 0, h0_mb[mbc], h_cur)
            h_out, ck, cv = _pp_stage_block(
                cfg, lp_stack, ck, cv, h_in, pos_mb[mbc], slot_t, bt_mb[mbc],
                kl_mb[mbc], attn_impl=attn_impl, q_start=qs_mb[mbc],
                attn_num_splits=attn_num_splits)
            out = out.at[mbc].add(jnp.where((s == pp - 1) & live, h_out, 0))
            h_nxt = lax.ppermute(
                h_out, "pipe", [(j, (j + 1) % pp) for j in range(pp)])
            return (h_nxt, ck, cv, out)

        init = (jnp.zeros_like(h0_mb[0]), ck_loc, cv_loc, jnp.zeros_like(h0_mb))
        _, ck_loc, cv_loc, out = lax.fori_loop(0, m + pp - 1, tick, init)
        # Only the last stage accumulated into `out`; the psum replicates it.
        return lax.psum(out, "pipe"), ck_loc, cv_loc

    fn = shard_map_compat(
        pp_fn, mesh=mesh,
        in_specs=(P("pipe"), P("pipe"), P("pipe"), P(), P(), P(), P(), P(), P()),
        out_specs=(P(), P("pipe"), P("pipe")),
        check_vma=False,
    )
    out, cache_k, cache_v = fn(params["layers"], cache_k, cache_v,
                               h0_mb, pos_mb, slot_mb, bt_mb, qs_mb, kl_mb)
    h = out.swapaxes(0, 1).reshape(b, t, -1) if split_t else out.reshape(b, t, -1)
    h = rms_norm(h, params["final_norm"], cfg.rms_norm_eps)
    last_idx = jnp.clip(q_len - 1, 0, t - 1)
    last_h = jnp.take_along_axis(h, last_idx[:, None, None], axis=1)[:, 0]
    return last_h, cache_k, cache_v


def _pp_stage_block(cfg, lp_stack, ck_loc, cv_loc, h, pos, slot, bt, kv_lens,
                    attn_impl="dense", q_start=None, attn_num_splits=0):
    """One pipeline stage's layer block — the shared layer math of BOTH pp
    schedules (microbatched and sequential fallback): same per-layer flow
    as forward's layer_fn, attention over the stage's local cache slice.
    ``q_start`` is only needed by the Pallas kernel path."""
    b_, t_ = pos.shape

    def layer_fn(carry, xs):
        hid = carry
        lp, ck, cv = xs
        x = rms_norm(hid, lp["attn_norm"], cfg.rms_norm_eps)
        q = mm(x, lp["wq"]).reshape(b_, t_, cfg.num_heads, cfg.head_dim)
        k = mm(x, lp["wk"]).reshape(b_, t_, cfg.num_kv_heads, cfg.head_dim)
        v = mm(x, lp["wv"]).reshape(b_, t_, cfg.num_kv_heads, cfg.head_dim)
        q = rope(q, pos, cfg.rope_theta)
        k = rope(k, pos, cfg.rope_theta)
        ck = _scatter_kv(ck, k, slot)
        cv = _scatter_kv(cv, v, slot)
        if attn_impl in ("pallas", "pallas_interpret"):
            from dynamo_tpu.ops.paged_attention import paged_attention_kernel

            attn = paged_attention_kernel(
                q, ck, cv, bt, q_start, kv_lens,
                num_splits=attn_num_splits,
                interpret=(attn_impl == "pallas_interpret"))
        else:
            ctx_k = _gather_kv(ck, bt)
            ctx_v = _gather_kv(cv, bt)
            attn = paged_attention(q, ctx_k, ctx_v, pos, kv_lens)
        hid = hid + mm(attn.reshape(b_, t_, cfg.q_size), lp["wo"])
        x = rms_norm(hid, lp["mlp_norm"], cfg.rms_norm_eps)
        if cfg.is_moe:
            mlp_out = moe_mlp(x, lp, cfg)
        else:
            mlp_out = swiglu(x, lp["w_gate"], lp["w_up"], lp["w_down"])
        hid = hid + mlp_out
        return hid, (ck, cv)

    h, (ck_loc, cv_loc) = lax.scan(layer_fn, h, (lp_stack, ck_loc, cv_loc))
    return h, ck_loc, cv_loc


def _forward_pp_sequential(params, cfg, positions, kv_lens, slot, block_tables,
                           cache_k, cache_v, mesh, h0, q_len, pp):
    """Fallback pipeline for shapes too small to microbatch (e.g. a lone
    decode row): pp select-and-broadcast rounds — every stage computes the
    full batch each round, round i keeps stage i's result. Efficiency 1/pp;
    correctness identical. Dense attention only (the warning at the call
    site covers the kernel case)."""
    b, t = positions.shape
    from jax.sharding import PartitionSpec as P

    def pp_fn(lp_stack, ck_local, cv_local, h):
        s = lax.axis_index("pipe")
        for i in range(pp):
            h_out, ck_new, cv_new = _pp_stage_block(
                cfg, lp_stack, ck_local, cv_local, h, positions, slot,
                block_tables, kv_lens)
            keep = s == i
            # tree_map: quantized caches are {"q","s"} pytrees.
            ck_local = jax.tree.map(lambda a, b: jnp.where(keep, a, b),
                                    ck_new, ck_local)
            cv_local = jax.tree.map(lambda a, b: jnp.where(keep, a, b),
                                    cv_new, cv_local)
            h = lax.psum(jnp.where(keep, h_out, jnp.zeros_like(h_out)), "pipe")
        return h, ck_local, cv_local

    fn = shard_map_compat(
        pp_fn, mesh=mesh,
        in_specs=(P("pipe"), P("pipe"), P("pipe"), P()),
        out_specs=(P(), P("pipe"), P("pipe")),
        check_vma=False,
    )
    h, cache_k, cache_v = fn(params["layers"], cache_k, cache_v, h0)
    h = rms_norm(h, params["final_norm"], cfg.rms_norm_eps)
    last_idx = jnp.clip(q_len - 1, 0, t - 1)
    last_h = jnp.take_along_axis(h, last_idx[:, None, None], axis=1)[:, 0]
    return last_h, cache_k, cache_v


def logits_from_hidden(params: Params, cfg: ModelConfig, hidden: jax.Array) -> jax.Array:
    """Project hidden [B,H] → logits [B,V] (tied or separate lm head).
    Row-quantized embeddings put the scale on the vocab axis, so it
    applies per logit column after the contraction."""
    with _perf_phase("logits"):
        if cfg.tie_word_embeddings:
            e = params["embed"]
            if isinstance(e, dict):
                return (hidden @ e["q"].astype(hidden.dtype).T) * e["sr"].astype(hidden.dtype)
            return hidden @ e.T
        return mm(hidden, params["lm_head"])
