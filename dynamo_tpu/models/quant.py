"""Weight-only int8 quantization: per-channel scales, bf16 compute.

Fills the role of the reference's quantized serving path (reference: the
baseline model is Llama-3.3-70B-Instruct-FP8,
recipes/llama-3-70b/vllm/agg/deploy.yaml:36-47, served through vLLM's
quantized kernels) — redesigned for TPU: batched decode is HBM-bandwidth
bound (roofline tok/s = batch * BW / param_bytes), so storing weights as
int8 halves the bytes read per step and directly doubles the decode
roofline. Compute stays bf16 on the MXU: the dequant is a cast fused by
XLA into the consuming matmul (weights stream from HBM as int8, widen in
registers), never materialized.

Scheme: symmetric per-output-channel scales. For a matrix W[in, out],
``scale[o] = max_i |W[i,o]| / 127`` and ``q = round(W/scale)``; the
matmul applies the scale AFTER the contraction — ``(x @ q) * scale`` —
which is exact algebra because the scale is constant along the
contracted axis. The embedding quantizes per vocab row, which serves
both the gather (row dequant) and the tied lm_head (scale per logit
column). A quantized leaf is the pytree ``{"q": int8, "so"|"sr": float32}``;
``llama.mm`` consumes either representation, so every forward variant
(TP, PP stages, fused windows) works unchanged. The scheme rides in the
key name ("so" out-channel / "sr" row) — static structure, jit-safe.

Quantization happens AFTER mesh placement: the elementwise quantize jit
preserves the source sharding, so TP/EP layouts carry over for free.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.utils.logging import get_logger

log = get_logger("quant")

# Matrices consumed through llama.mm (contraction along the second-to-last
# axis, scale on the last). MoE expert tensors ride einsum/ragged paths and
# stay bf16 for now.
_MM_KEYS = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down",
            "shared_gate", "shared_up", "shared_down")


def is_quantized(leaf) -> bool:
    # "so" = per-output-channel scale (mm matrices); "sr" = per-row scale
    # (embedding). The scheme lives in the KEY name — static pytree
    # structure, so jitted step fns take quantized params unchanged.
    return isinstance(leaf, dict) and "q" in leaf and ("so" in leaf or "sr" in leaf)


@partial(jax.jit, donate_argnums=0)
def _quant_mm(w):
    """[..., in, out] → q int8 + per-out-channel scale [..., out]."""
    amax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=-2)
    scale = jnp.maximum(amax / 127.0, 1e-12)
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / scale[..., None, :]),
                 -127, 127).astype(jnp.int8)
    return {"q": q, "so": scale}


@partial(jax.jit, donate_argnums=0)
def _quant_rows(w):
    """[rows, h] → q int8 + per-row scale [rows] (embedding / lm vocab)."""
    amax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=-1)
    scale = jnp.maximum(amax / 127.0, 1e-12)
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / scale[..., None]),
                 -127, 127).astype(jnp.int8)
    return {"q": q, "sr": scale}


def quantize_params_int8(params: dict, cfg: ModelConfig,
                         quantize_embed: bool = True) -> dict:
    """Quantize the big matrices of a loaded params pytree in place of
    their bf16 leaves. Norms stay bf16 (tiny, precision-sensitive); MoE
    expert stacks stay bf16 (einsum/ragged paths). Idempotent: an
    already-quantized tree passes through."""
    out = dict(params)
    layers = dict(params["layers"])
    skipped = []
    for key in _MM_KEYS:
        if key not in layers or is_quantized(layers[key]):
            continue
        if cfg.is_moe and key in ("w_gate", "w_up", "w_down"):
            skipped.append(key)
            continue
        layers[key] = _quant_mm(layers[key])
    out["layers"] = layers
    if quantize_embed and not is_quantized(params["embed"]):
        out["embed"] = _quant_rows(params["embed"])
        if "lm_head" in params and not is_quantized(params["lm_head"]):
            # lm_head is [h, vocab]: per-vocab-column scale == per-row of
            # the transpose — same _quant_mm geometry.
            out["lm_head"] = _quant_mm(params["lm_head"])
    if skipped:
        log.warning("int8 quantization skipped MoE expert tensors %s "
                    "(einsum/ragged dispatch paths are bf16-only for now)",
                    skipped)
    return out


def dequantize_params(params: dict) -> dict:
    """Inverse (testing): expand every quantized leaf back to floats."""
    def deq(leaf):
        if not is_quantized(leaf):
            return leaf
        q = leaf["q"].astype(jnp.float32)
        if "sr" in leaf:
            return q * leaf["sr"][..., None]
        return q * leaf["so"][..., None, :]

    return jax.tree.map(deq, params, is_leaf=is_quantized)


def param_bytes(params: dict) -> int:
    """Actual HBM bytes of a params pytree (int8 leaves count as 1B)."""
    return sum(x.size * x.dtype.itemsize for x in jax.tree.leaves(params))
