"""Expert-parallel MoE dispatch: dropless ragged groups + capacity variant.

The reference only passes wide-EP flags through to SGLang/vLLM
(SURVEY.md §2.7: TEP16/DEP16 recipes, e.g. recipes/deepseek-r1/sglang-wideep);
the expert math itself is ours.

Two formulations:

- :func:`moe_mlp_dropless` (the serving default, ``moe_impl="ep"``) — EXACT
  under any load: (token, choice) rows are sorted by expert id so each
  expert's tokens form one contiguous ragged group feeding one MXU matmul
  (``lax.ragged_dot`` — static shapes, no capacity, nothing dropped).
  EP sharding is an explicit ``shard_map`` over the "expert" axis with the
  batch staying on "data": each device computes the rows of ITS experts
  (non-local rows route through an appended all-zero "void" expert, so
  shapes stay static) and partial outputs ``psum`` over the axis. A
  serving engine cannot ship an output-changing dispatch — vLLM-class
  engines are dropless for the same reason.

- :func:`moe_mlp_ep` (``moe_impl="ep_capacity"``) — the classic
  Switch/GShard capacity-bounded dispatch/combine einsum formulation, kept
  for experimentation: with enough capacity it equals the dense reference;
  under pressure it drops over-capacity choices.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.parallel.mesh import shard_map_compat

Params = dict


def _router_topk(xt: jax.Array, lp: Params, cfg: ModelConfig):
    """Top-k routing shared by both formulations: returns ([N,k] expert ids,
    [N,k] softmax weights) — identical math to the dense reference
    (models.llama.moe_mlp), so dispatch equivalence is purely about which
    chosen pairs get computed."""
    logits = xt.astype(jnp.float32) @ lp["router"].astype(jnp.float32)   # [N, E]
    topv, topi = lax.top_k(logits, cfg.num_experts_per_tok)
    return topi, jax.nn.softmax(topv, axis=-1)


def _dropless_rows(xt, topi, weights, w_gate, w_up, w_down, e_lo, e_local):
    """Compute this device's expert rows. xt [N,H]; topi/weights [N,k];
    w_* [E_local(+0), H|M, M|H] local expert slabs. Returns [N, H] partial
    output (zero contribution for rows owned by other devices)."""
    n, h = xt.shape
    k = topi.shape[1]
    flat_e = topi.reshape(-1)                         # [Nk] token-major
    flat_t = jnp.repeat(jnp.arange(n), k)             # [Nk]
    local_e = flat_e - e_lo
    is_local = (local_e >= 0) & (local_e < e_local)
    # Sort rows by local expert; foreign rows collect in a trailing "void"
    # group whose weights are zero, keeping every shape static.
    key = jnp.where(is_local, local_e, e_local)
    perm = jnp.argsort(key, stable=True)
    xs = xt[flat_t[perm]]                             # [Nk, H]
    group_sizes = jnp.zeros((e_local + 1,), jnp.int32).at[key].add(1)

    void = jnp.zeros_like(w_gate[:1])
    wg = jnp.concatenate([w_gate, void], axis=0)
    wu = jnp.concatenate([w_up, void], axis=0)
    wd = jnp.concatenate([w_down, jnp.zeros_like(w_down[:1])], axis=0)

    gate = lax.ragged_dot(xs, wg, group_sizes)        # [Nk, M]
    up = lax.ragged_dot(xs, wu, group_sizes)
    act = jax.nn.silu(gate) * up
    out = lax.ragged_dot(act, wd, group_sizes)        # [Nk, H]

    contrib = out.astype(jnp.float32) * weights.reshape(-1)[perm][:, None]
    # Stays fp32: under EP sharding this is a PARTIAL sum — the caller must
    # psum across devices in fp32 and cast once, like the dense reference's
    # single fp32 accumulation (bf16 partials would compound per expert).
    return jnp.zeros((n, h), jnp.float32).at[flat_t[perm]].add(contrib)


def moe_mlp_dropless(x: jax.Array, lp: Params, cfg: ModelConfig,
                     mesh=None) -> jax.Array:
    """Dropless MoE FFN. x: [B, T, H] → [B, T, H]; exact vs the dense
    reference under ANY routing skew (tests/test_moe.py pressure tests)."""
    b, t, h = x.shape
    e = cfg.num_experts
    ep = mesh.shape.get("expert", 1) if mesh is not None else 1

    shared = (
        (lp["shared_gate"], lp["shared_up"], lp["shared_down"])
        if cfg.num_shared_experts else None
    )
    if ep <= 1 or e % ep != 0:
        xt = x.reshape(-1, h)
        topi, weights = _router_topk(xt, lp, cfg)
        y = _dropless_rows(xt, topi, weights, lp["w_gate"], lp["w_up"],
                           lp["w_down"], 0, e)
        if shared is not None:
            from dynamo_tpu.models.llama import swiglu

            y = y + swiglu(xt, *shared).astype(jnp.float32)
        return y.astype(x.dtype).reshape(x.shape)

    e_local = e // ep

    def shard_fn(x3, router, wg, wu, wd, *shared_w):
        # Each device owns (its expert slab) x (its slice of the expert
        # intermediate dim, on TEP meshes where "model" also shards M).
        # gate/up slice M locally (silu is columnwise-exact); w_down
        # contracts the local M slice, so y is a partial sum over BOTH
        # axes — one fp32 psum completes expert combine and TEP contraction.
        e_lo = lax.axis_index("expert") * e_local
        xt = x3.reshape(-1, h)
        topi, weights = _router_topk(xt, {"router": router}, cfg)
        y = _dropless_rows(xt, topi, weights, wg, wu, wd, e_lo, e_local)
        if shared_w:
            from dynamo_tpu.models.llama import swiglu

            # Shared-expert slabs are "model"-sharded the same way; their
            # partial rides the same psum, and the expert-axis replication
            # is cancelled by pre-dividing.
            sh = swiglu(xt, *shared_w).astype(jnp.float32)
            y = y + sh / ep
        y = lax.psum(y, ("expert", "model"))
        return y.astype(x3.dtype).reshape(x3.shape)

    # Batch rides the "data" axis when it divides; odd buckets (e.g. the
    # B=1 prefill bucket on a dp>1 mesh) fall back to replicated batch.
    batch_spec = P("data") if b % mesh.shape.get("data", 1) == 0 else P()
    args = [x, lp["router"], lp["w_gate"], lp["w_up"], lp["w_down"]]
    # Weight specs mirror PARAM_RULES (parallel/mesh.py): experts on
    # "expert", the per-expert intermediate on "model" (TEP) — declaring
    # them this way means NO resharding of the slabs at the shard_map
    # boundary. The router needs full columns for top_k, so it alone
    # gathers (tiny: [H, E]).
    in_specs = [batch_spec, P(),
                P("expert", None, "model"), P("expert", None, "model"),
                P("expert", "model", None)]
    if shared is not None:
        args.extend(shared)
        in_specs.extend([P(None, "model"), P(None, "model"), P("model", None)])
    fn = shard_map_compat(
        shard_fn, mesh=mesh,
        in_specs=tuple(in_specs), out_specs=batch_spec,
        check_vma=False,
    )
    return fn(*args)


def expert_capacity(num_tokens: int, num_experts: int, top_k: int,
                    capacity_factor: float) -> int:
    """Per-expert token slots, padded to a lane-friendly multiple of 8."""
    cap = int(num_tokens * top_k / num_experts * capacity_factor) + 1
    return max(-(-cap // 8) * 8, 8)


def moe_mlp_ep(x: jax.Array, lp: Params, cfg: ModelConfig,
               capacity_factor: float = 2.0) -> jax.Array:
    """Capacity-based EP MoE FFN. x: [B, T, H] → [B, T, H].

    The dispatch/combine tensors route each token's top-k expert choices to
    per-expert buffers of C slots; choice order is priority order (a token's
    1st choice wins slots over another token's 2nd choice at equal index by
    flattened position).
    """
    b, t, h = x.shape
    n = b * t
    e, k = cfg.num_experts, cfg.num_experts_per_tok
    xt = x.reshape(n, h)
    topi, weights = _router_topk(xt, lp, cfg)                            # [N, k]

    cap = expert_capacity(n, e, k, capacity_factor)
    # Position of each (choice, token) within its expert's buffer. Flatten
    # choice-major so every token's 1st choice outranks all 2nd choices.
    oh = jax.nn.one_hot(topi.T.reshape(k * n), e, dtype=jnp.int32)       # [kN, E]
    pos = jnp.cumsum(oh, axis=0) * oh - 1                                # [kN, E]
    pos_in_e = jnp.max(pos, axis=1)                                      # [kN]
    keep = (pos_in_e >= 0) & (pos_in_e < cap)
    pos_in_e = jnp.where(keep, pos_in_e, 0)

    # Back to [N, k] layout.
    keep = keep.reshape(k, n).T
    pos_nk = pos_in_e.reshape(k, n).T                                    # [N, k]

    # dispatch[n, e, c] = 1 where token n's choice lands in slot c of expert e
    slot_oh = jax.nn.one_hot(pos_nk, cap, dtype=jnp.float32)             # [N, k, C]
    exp_oh = jax.nn.one_hot(topi, e, dtype=jnp.float32)                  # [N, k, E]
    keep_f = keep.astype(jnp.float32)[..., None]
    dispatch = jnp.einsum("nke,nkc->nec", exp_oh, slot_oh * keep_f)      # [N, E, C]
    combine = jnp.einsum("nke,nkc->nec", exp_oh * (weights * keep)[..., None],
                         slot_oh)                                        # [N, E, C]

    # Expert buffers [E, C, H]: sharded on "expert" with the weights; GSPMD
    # turns the N↔(E,C) einsums into token all-to-alls over ICI.
    expert_in = jnp.einsum("nec,nh->ech", dispatch, xt.astype(jnp.float32))
    expert_in = expert_in.astype(x.dtype)
    gate = jnp.einsum("ech,ehm->ecm", expert_in, lp["w_gate"])
    up = jnp.einsum("ech,ehm->ecm", expert_in, lp["w_up"])
    act = jax.nn.silu(gate) * up
    out_e = jnp.einsum("ecm,emh->ech", act, lp["w_down"])                # [E, C, H]
    y = jnp.einsum("nec,ech->nh", combine, out_e.astype(jnp.float32)).astype(x.dtype)

    if cfg.num_shared_experts:
        from dynamo_tpu.models.llama import swiglu

        y = y + swiglu(xt, lp["shared_gate"], lp["shared_up"], lp["shared_down"])
    return y.reshape(b, t, h)
