"""Expert-parallel MoE dispatch (GSPMD capacity-based all-to-all).

The reference only passes wide-EP flags through to SGLang/vLLM
(SURVEY.md §2.7: TEP16/DEP16 recipes, e.g. recipes/deepseek-r1/sglang-wideep);
the expert math itself is ours. This is the TPU-idiomatic formulation:
tokens are dispatched to experts through a capacity-bounded one-hot dispatch
tensor, and the three einsums below — dispatch, expert FFN, combine — are
written so that with ``w_gate/w_up/w_down`` sharded on the "expert" mesh
axis, GSPMD inserts the token all-to-alls automatically (the scaling-book
recipe: annotate shardings, let XLA place collectives on ICI).

Equivalence: with enough capacity (no dropped tokens) the result equals the
dense-dispatch ``models.llama.moe_mlp``; under pressure, choices over
capacity are dropped (standard Switch/GShard behavior — their router weight
simply doesn't contribute, no renormalization).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from dynamo_tpu.models.config import ModelConfig

Params = dict


def expert_capacity(num_tokens: int, num_experts: int, top_k: int,
                    capacity_factor: float) -> int:
    """Per-expert token slots, padded to a lane-friendly multiple of 8."""
    cap = int(num_tokens * top_k / num_experts * capacity_factor) + 1
    return max(-(-cap // 8) * 8, 8)


def moe_mlp_ep(x: jax.Array, lp: Params, cfg: ModelConfig,
               capacity_factor: float = 2.0) -> jax.Array:
    """Capacity-based EP MoE FFN. x: [B, T, H] → [B, T, H].

    The dispatch/combine tensors route each token's top-k expert choices to
    per-expert buffers of C slots; choice order is priority order (a token's
    1st choice wins slots over another token's 2nd choice at equal index by
    flattened position).
    """
    b, t, h = x.shape
    n = b * t
    e, k = cfg.num_experts, cfg.num_experts_per_tok
    xt = x.reshape(n, h)
    logits = xt.astype(jnp.float32) @ lp["router"].astype(jnp.float32)   # [N, E]
    topv, topi = lax.top_k(logits, k)                                    # [N, k]
    weights = jax.nn.softmax(topv, axis=-1)                              # [N, k]

    cap = expert_capacity(n, e, k, capacity_factor)
    # Position of each (choice, token) within its expert's buffer. Flatten
    # choice-major so every token's 1st choice outranks all 2nd choices.
    oh = jax.nn.one_hot(topi.T.reshape(k * n), e, dtype=jnp.int32)       # [kN, E]
    pos = jnp.cumsum(oh, axis=0) * oh - 1                                # [kN, E]
    pos_in_e = jnp.max(pos, axis=1)                                      # [kN]
    keep = (pos_in_e >= 0) & (pos_in_e < cap)
    pos_in_e = jnp.where(keep, pos_in_e, 0)

    # Back to [N, k] layout.
    keep = keep.reshape(k, n).T
    pos_nk = pos_in_e.reshape(k, n).T                                    # [N, k]

    # dispatch[n, e, c] = 1 where token n's choice lands in slot c of expert e
    slot_oh = jax.nn.one_hot(pos_nk, cap, dtype=jnp.float32)             # [N, k, C]
    exp_oh = jax.nn.one_hot(topi, e, dtype=jnp.float32)                  # [N, k, E]
    keep_f = keep.astype(jnp.float32)[..., None]
    dispatch = jnp.einsum("nke,nkc->nec", exp_oh, slot_oh * keep_f)      # [N, E, C]
    combine = jnp.einsum("nke,nkc->nec", exp_oh * (weights * keep)[..., None],
                         slot_oh)                                        # [N, E, C]

    # Expert buffers [E, C, H]: sharded on "expert" with the weights; GSPMD
    # turns the N↔(E,C) einsums into token all-to-alls over ICI.
    expert_in = jnp.einsum("nec,nh->ech", dispatch, xt.astype(jnp.float32))
    expert_in = expert_in.astype(x.dtype)
    gate = jnp.einsum("ech,ehm->ecm", expert_in, lp["w_gate"])
    up = jnp.einsum("ech,ehm->ecm", expert_in, lp["w_up"])
    act = jax.nn.silu(gate) * up
    out_e = jnp.einsum("ecm,emh->ech", act, lp["w_down"])                # [E, C, H]
    y = jnp.einsum("nec,ech->nh", combine, out_e.astype(jnp.float32)).astype(x.dtype)

    if cfg.num_shared_experts:
        from dynamo_tpu.models.llama import swiglu

        y = y + swiglu(xt, lp["shared_gate"], lp["shared_up"], lp["shared_down"])
    return y.reshape(b, t, h)
