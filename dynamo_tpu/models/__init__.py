from dynamo_tpu.models.config import MODEL_PRESETS, ModelConfig

__all__ = ["ModelConfig", "MODEL_PRESETS"]
