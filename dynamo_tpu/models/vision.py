"""Vision encoder: images → a fixed number of LM-space embedding tokens.

Fills the multimodal-encode role of the reference's encode workers
(reference: components/src/dynamo/sglang multimodal processor/encode
workers; trtllm/encode_helper.py) — the model itself is TPU-first: a
small ViT expressed as plain jitted JAX (patchify → linear → pre-norm
transformer blocks → learned query pooling to ``num_image_tokens``
LM-hidden-size vectors), MXU-friendly batched matmuls throughout, no
dynamic shapes (images are resized to a fixed grid on the host).

Like ``tiny-llama``, weights are seed-deterministic random unless a
checkpoint is provided — the wiring (encode worker → data-plane embedding
transfer → prefill injection) is the framework capability under test;
swapping in real CLIP/SigLIP weights is a loader exercise.
"""

from __future__ import annotations

import io
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np


@dataclass(frozen=True)
class VisionConfig:
    image_size: int = 64           # input resized to image_size x image_size
    patch_size: int = 16
    hidden_size: int = 128         # ViT width
    num_layers: int = 2
    num_heads: int = 4
    num_image_tokens: int = 8      # pooled output tokens
    lm_hidden_size: int = 64       # target LM hidden (tiny-llama default)
    seed: int = 7

    @property
    def num_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @property
    def patch_dim(self) -> int:
        return self.patch_size * self.patch_size * 3


def init_vision_params(cfg: VisionConfig) -> dict:
    n_keys = 6 * cfg.num_layers + 5  # 6 denses/layer + 4 top-level + slack
    k = iter(jax.random.split(jax.random.key(cfg.seed), n_keys))
    h = cfg.hidden_size

    def dense(shape, fan_in):
        return jax.random.normal(next(k), shape, jnp.float32) * (fan_in ** -0.5)

    layers = []
    for _ in range(cfg.num_layers):
        layers.append({
            "wq": dense((h, h), h), "wk": dense((h, h), h),
            "wv": dense((h, h), h), "wo": dense((h, h), h),
            "w1": dense((h, 4 * h), h), "w2": dense((4 * h, h), 4 * h),
            "ln1": jnp.ones((h,)), "ln2": jnp.ones((h,)),
        })
    return {
        "patch_proj": dense((cfg.patch_dim, h), cfg.patch_dim),
        "pos": dense((cfg.num_patches, h), h),
        "queries": dense((cfg.num_image_tokens, h), h),
        "out_proj": dense((h, cfg.lm_hidden_size), h),
        "final_ln": jnp.ones((h,)),
        "layers": layers,
    }


def _ln(x, g, eps=1e-5):
    mu = x.mean(-1, keepdims=True)
    var = ((x - mu) ** 2).mean(-1, keepdims=True)
    return (x - mu) * jax.lax.rsqrt(var + eps) * g


def _attn(x, q_in, wq, wk, wv, wo, num_heads):
    """Cross(or self)-attention: queries q_in attend over x."""
    B, N, H = x.shape
    M = q_in.shape[1]
    d = H // num_heads
    q = (q_in @ wq).reshape(B, M, num_heads, d).transpose(0, 2, 1, 3)
    kk = (x @ wk).reshape(B, N, num_heads, d).transpose(0, 2, 1, 3)
    v = (x @ wv).reshape(B, N, num_heads, d).transpose(0, 2, 1, 3)
    a = jax.nn.softmax((q @ kk.transpose(0, 1, 3, 2)) * (d ** -0.5), axis=-1)
    o = (a @ v).transpose(0, 2, 1, 3).reshape(B, M, H)
    return o @ wo


def encode_patches(params: dict, cfg: VisionConfig,
                   patches: jax.Array) -> jax.Array:
    """[B, num_patches, patch_dim] float32 → [B, num_image_tokens, lm_H]."""
    x = patches @ params["patch_proj"] + params["pos"][None]
    for lp in params["layers"]:
        xn = _ln(x, lp["ln1"])
        x = x + _attn(xn, xn, lp["wq"], lp["wk"], lp["wv"], lp["wo"],
                      cfg.num_heads)
        xn = _ln(x, lp["ln2"])
        x = x + jax.nn.gelu(xn @ lp["w1"]) @ lp["w2"]
    x = _ln(x, params["final_ln"])
    # learned-query pooling to a fixed token count
    q = jnp.broadcast_to(params["queries"][None],
                         (x.shape[0], cfg.num_image_tokens, cfg.hidden_size))
    lp0 = params["layers"][0]
    pooled = _attn(x, q, lp0["wq"], lp0["wk"], lp0["wv"], lp0["wo"],
                   cfg.num_heads)
    return pooled @ params["out_proj"]


class VisionEncoder:
    """Host-facing encoder: decodes/preps images, runs the jitted model."""

    def __init__(self, cfg: VisionConfig | None = None):
        self.cfg = cfg or VisionConfig()
        self.params = init_vision_params(self.cfg)
        self._fn = jax.jit(lambda p, x: encode_patches(p, self.cfg, x))

    def _to_patches(self, img: "np.ndarray") -> np.ndarray:
        c = self.cfg
        P, G = c.patch_size, c.image_size // c.patch_size
        x = img.astype(np.float32) / 255.0
        x = x.reshape(G, P, G, P, 3).transpose(0, 2, 1, 3, 4)
        return x.reshape(c.num_patches, c.patch_dim)

    def decode_image(self, data: bytes) -> np.ndarray:
        """PNG/JPEG bytes → fixed-size RGB array (host-side resize keeps
        the jitted model's shapes static)."""
        from PIL import Image

        img = Image.open(io.BytesIO(data)).convert("RGB")
        img = img.resize((self.cfg.image_size, self.cfg.image_size))
        return np.asarray(img)

    def encode(self, images: list[bytes]) -> np.ndarray:
        """Image bytes → [N, num_image_tokens, lm_hidden] float32."""
        patches = np.stack([self._to_patches(self.decode_image(b))
                            for b in images])
        return np.asarray(self._fn(self.params, patches), np.float32)
