"""Token block sequences and content hashing.

Fills the role of the reference's ``lib/tokens`` crate
(reference: lib/tokens/src/lib.rs:16-60): fixed-size token blocks with
xxh3-based *block hashes* (local content) and chained *sequence hashes*
(prefix identity), shared by the KV router and the KV block manager so a
block of tokens has one global identity everywhere.

Hash scheme (kept simple and documented so fixtures are reproducible):
  block_hash(block)    = xxh3_64(le_u32_bytes(tokens in block))
  seq_hash(block_0)    = block_hash(block_0)
  seq_hash(block_i)    = xxh3_64(le_u64(seq_hash(block_{i-1})) || le_u64(block_hash(block_i)))

Implementation is pure Python over the xxhash C extension; hashing whole
blocks via ``struct.pack`` keeps the per-block cost a single C call.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field
from typing import Iterable, Sequence

import xxhash

Token = int
BlockHash = int
SequenceHash = int

__all__ = [
    "Token",
    "BlockHash",
    "SequenceHash",
    "compute_block_hash",
    "compute_seq_hashes",
    "compute_block_hashes_for_tokens",
    "TokenBlock",
    "TokenBlockSequence",
]


def compute_block_hash(tokens: Sequence[int]) -> BlockHash:
    """Hash the raw token contents of one block (no chaining)."""
    return xxhash.xxh3_64_intdigest(struct.pack(f"<{len(tokens)}I", *tokens))


def _chain(parent: SequenceHash, block_hash: BlockHash) -> SequenceHash:
    return xxhash.xxh3_64_intdigest(struct.pack("<QQ", parent, block_hash))


def compute_seq_hashes(block_hashes: Sequence[BlockHash]) -> list[SequenceHash]:
    """Chain block hashes into prefix-identifying sequence hashes."""
    out: list[SequenceHash] = []
    parent: SequenceHash | None = None
    for bh in block_hashes:
        parent = bh if parent is None else _chain(parent, bh)
        out.append(parent)
    return out


def compute_block_hashes_for_tokens(tokens: Sequence[int], block_size: int) -> list[SequenceHash]:
    """Sequence hashes for every *complete* block of ``tokens``.

    This is the router's request-time hash path
    (reference: lib/llm/src/kv_router/indexer.rs:125 compute_block_hash_for_seq).
    Long prompts take the batched native path (native/tokens.cc: one C call
    packs + hashes + chains every block); short ones stay in Python —
    identical values either way (parity-fuzzed, tests/test_native_tokens.py).
    """
    n_full = len(tokens) // block_size
    if n_full >= 64:  # ~1k tokens: below this marshalling eats the win
        out = _native_seq_hashes(tokens, block_size, n_full)
        if out is not None:
            return out
    hashes = [compute_block_hash(tokens[i * block_size : (i + 1) * block_size]) for i in range(n_full)]
    return compute_seq_hashes(hashes)


def _native_seq_hashes(tokens: Sequence[int], block_size: int,
                       n_full: int) -> "list[SequenceHash] | None":
    from dynamo_tpu.native import load_library

    lib = load_library()
    if lib is None:
        return None
    import array
    import ctypes

    n = n_full * block_size
    # array('I') packs the list at C speed; from_buffer is zero-copy
    # (building a ctypes array element-wise would cost more than the hash)
    buf = array.array("I", tokens[:n] if len(tokens) != n else tokens)
    arr = (ctypes.c_uint32 * n).from_buffer(buf)
    out = (ctypes.c_uint64 * n_full)()
    wrote = lib.dyn_token_seq_hashes(arr, n, block_size, out, n_full)
    if wrote != n_full:  # defensive; cannot happen with max_out == n_full
        return None
    return list(out)


@dataclass(frozen=True)
class TokenBlock:
    tokens: tuple[int, ...]
    block_hash: BlockHash
    sequence_hash: SequenceHash
    position: int  # block index within the sequence


@dataclass
class TokenBlockSequence:
    """A token sequence chunked into fixed-size blocks with incremental hashing.

    Reference: lib/tokens/src/lib.rs (TokenBlockSequence). Supports appending
    tokens one at a time (decode) or in bulk (prefill); complete blocks are
    frozen with their hashes, the partial tail is kept mutable.
    """

    block_size: int
    blocks: list[TokenBlock] = field(default_factory=list)
    partial: list[int] = field(default_factory=list)

    @classmethod
    def from_tokens(cls, tokens: Iterable[int], block_size: int) -> "TokenBlockSequence":
        seq = cls(block_size=block_size)
        seq.extend(tokens)
        return seq

    def __len__(self) -> int:
        return len(self.blocks) * self.block_size + len(self.partial)

    @property
    def tokens(self) -> list[int]:
        out: list[int] = []
        for b in self.blocks:
            out.extend(b.tokens)
        out.extend(self.partial)
        return out

    def append(self, token: int) -> TokenBlock | None:
        """Append one token; returns the newly-completed block, if any."""
        self.partial.append(token)
        if len(self.partial) == self.block_size:
            return self._seal()
        return None

    def extend(self, tokens: Iterable[int]) -> list[TokenBlock]:
        sealed = []
        for t in tokens:
            blk = self.append(t)
            if blk is not None:
                sealed.append(blk)
        return sealed

    def _seal(self) -> TokenBlock:
        bh = compute_block_hash(self.partial)
        parent = self.blocks[-1].sequence_hash if self.blocks else None
        sh = bh if parent is None else _chain(parent, bh)
        blk = TokenBlock(
            tokens=tuple(self.partial), block_hash=bh, sequence_hash=sh, position=len(self.blocks)
        )
        self.blocks.append(blk)
        self.partial.clear()
        return blk

    def sequence_hashes(self) -> list[SequenceHash]:
        return [b.sequence_hash for b in self.blocks]

    def truncate_blocks(self, n_blocks: int) -> None:
        """Drop blocks beyond ``n_blocks`` and any partial tail."""
        del self.blocks[n_blocks:]
        self.partial.clear()
