"""Single-binary launcher: ``python -m dynamo_tpu.launch.run in=<mode> out=<engine>``.

Fills the role of the reference's dynamo-run CLI
(reference: launch/dynamo-run/src/main.rs `in=http|text|batch out=engine`):
one process, no external infra (the StaticFull pipeline,
reference: lib/llm/src/entrypoint.rs:58): frontend → preprocessor → engine
→ detokenizer, all in-process.

Examples:
    python -m dynamo_tpu.launch.run in=http out=jax --model tiny-llama --port 8080
    python -m dynamo_tpu.launch.run in=text out=jax --model tiny-llama
    python -m dynamo_tpu.launch.run in=batch out=jax --model tiny-llama --input-jsonl prompts.jsonl
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys

from dynamo_tpu.engine.engine import AsyncJaxEngine, EngineCore
from dynamo_tpu.frontend.model_manager import ModelManager
from dynamo_tpu.frontend.service import HttpService
from dynamo_tpu.preprocessor.preprocessor import ModelDefaults
from dynamo_tpu.protocols.common import PreprocessedRequest, SamplingOptions, StopConditions
from dynamo_tpu.tokenizer import DecodeStream, load_tokenizer
from dynamo_tpu.utils.config import EngineConfig
from dynamo_tpu.utils.logging import configure_logging, get_logger

log = get_logger("launch")


def parse_args(argv: list[str] | None = None) -> argparse.Namespace:
    argv = list(sys.argv[1:] if argv is None else argv)
    in_mode, out_mode = "text", "jax"
    rest = []
    for a in argv:
        if a.startswith("in="):
            in_mode = a[3:]
        elif a.startswith("out="):
            out_mode = a[4:]
        else:
            rest.append(a)
    p = argparse.ArgumentParser("dynamo-run")
    p.add_argument("--model", default="tiny-llama")
    p.add_argument("--tokenizer", default=None)
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=8080)
    p.add_argument("--max-batch-size", type=int, default=64)
    p.add_argument("--max-model-len", type=int, default=8192)
    p.add_argument("--block-size", type=int, default=16)
    p.add_argument("--num-blocks", type=int, default=0)
    p.add_argument("--tp", type=int, default=1)
    p.add_argument("--pp", type=int, default=1,
                   help="pipeline stages (layer blocks sharded over 'pipe')")
    p.add_argument("--dp", type=int, default=1,
                   help="data-parallel replicas within the engine ('data' axis)")
    p.add_argument("--ep", type=int, default=1,
                   help="expert-parallel shards ('expert' axis; MoE models)")
    p.add_argument("--sp", type=int, default=1,
                   help="sequence-parallel shards ('seq' axis; ring attention)")
    p.add_argument("--max-tokens", type=int, default=256, help="default max output tokens")
    p.add_argument("--input-jsonl", default=None)
    p.add_argument("--allow-random-weights", action="store_true",
                   help="serve RANDOM weights when the model path has no "
                        "loadable safetensors (tests/benches only)")
    p.add_argument("--spec-ngram", type=int, default=0,
                   help="n-gram speculative decoding (greedy-exact; 0 = off)")
    p.add_argument("--spec-k", type=int, default=4,
                   help="max proposed tokens per verify step")
    p.add_argument("--quantization", choices=["none", "int8"], default="none",
                   help="weight-only quantization (int8)")
    p.add_argument("--kv-dtype", choices=["bfloat16", "int8", "int4"],
                   default="bfloat16",
                   help="paged KV cache storage dtype (int8: in-kernel "
                        "dequant, ~2x KV capacity; int4: packed nibbles, "
                        "~4x capacity, even head_dim only)")
    p.add_argument("--decode-window", type=int, default=1,
                   help="decode steps fused per device dispatch (stop checks "
                        "lag by up to window-1 tokens; output is unchanged)")
    p.add_argument("--prefill-chunk", type=int, default=512,
                   help="prefill chunk tokens per step; 0 = SLO-driven auto "
                        "sizing (largest per-QoS chunk keeping predicted "
                        "decode ITL inside --itl-slo-ms)")
    p.add_argument("--itl-slo-ms", type=float, default=50.0,
                   help="decode ITL SLO budget for --prefill-chunk 0 auto "
                        "sizing (interactive 1x, standard 2x, batch 4x)")
    p.add_argument("--no-unified-step", action="store_true",
                   help="dispatch decode and prefill chunks as the legacy "
                        "two XLA launches instead of one ragged mixed step")
    p.add_argument("--host-kv-blocks", type=int, default=0, help="G2 host KV tier capacity")
    p.add_argument("--session-ttl", type=float, default=0.0,
                   help="session-sticky KV retention: seconds a finished "
                        "session's committed blocks stay pinned so the next "
                        "turn prefills only the suffix (0 = off)")
    p.add_argument("--no-session-tiers", action="store_true",
                   help="skip staging expired session KV down the KVBM tier "
                        "ladder before unpinning")
    p.add_argument("--ring-prefill-threshold", type=int, default=0,
                   help="sp>1 only: min prompt tokens for ring prefill "
                        "(0 = cost-model break-even, -1 = never)")
    p.add_argument("--disk-kv-path", default=None, help="G3 disk KV tier directory")
    p.add_argument("--remote-kv-addr", default=None,
                   help="G4 remote block store host:port")
    p.add_argument("--tool-call-parser", default=None,
                   help="tool-call parser name (hermes, mistral, llama3_json, ...)")
    p.add_argument("--reasoning-parser", default=None,
                   help="reasoning parser name (basic, deepseek_r1, ...)")
    p.add_argument("--mm-image-tokens", type=int, default=0,
                   help="enable multimodal chat: run an in-process vision "
                        "encoder producing this many embedding tokens per "
                        "image (0 = multimodal off)")
    ns = p.parse_args(rest)
    ns.in_mode, ns.out_mode = in_mode, out_mode
    return ns


def build_local_engine(ns: argparse.Namespace) -> tuple[AsyncJaxEngine, EngineConfig]:
    # Hub repo ids resolve to a local snapshot; the SERVED model name
    # (ns.model, used for registration) keeps the user-given id.
    from dynamo_tpu.models.hub import resolve_model_path

    resolved = resolve_model_path(ns.model)
    if ns.tokenizer is None and resolved != ns.model:
        ns.tokenizer = resolved
    cfg = EngineConfig(
        model=resolved,
        max_batch_size=ns.max_batch_size,
        max_model_len=ns.max_model_len,
        block_size=ns.block_size,
        num_blocks=ns.num_blocks,
        tp=ns.tp,
        pp=ns.pp,
        dp=ns.dp,
        ep=ns.ep,
        sp=ns.sp,
        decode_window=ns.decode_window,
        prefill_chunk=ns.prefill_chunk,
        itl_slo_ms=ns.itl_slo_ms,
        unified_step=not ns.no_unified_step,
        quantization=ns.quantization,
        kv_dtype=ns.kv_dtype,
        spec_ngram=ns.spec_ngram,
        spec_k=ns.spec_k,
        allow_random_weights=ns.allow_random_weights,
        host_kv_blocks=ns.host_kv_blocks,
        disk_kv_path=ns.disk_kv_path,
        remote_kv_addr=ns.remote_kv_addr,
        session_ttl=ns.session_ttl,
        session_tiers=not ns.no_session_tiers,
        ring_prefill_threshold=ns.ring_prefill_threshold,
    )
    from dynamo_tpu.engine.engine import build_engine

    return build_engine(cfg), cfg


async def run_http(ns: argparse.Namespace) -> None:
    engine, cfg = build_local_engine(ns)
    tok = load_tokenizer(ns.tokenizer or ns.model)
    image_encoder = None
    if ns.mm_image_tokens > 0:
        from dynamo_tpu.models.config import resolve_model_config
        from dynamo_tpu.models.vision import VisionConfig, VisionEncoder

        venc = VisionEncoder(VisionConfig(
            num_image_tokens=ns.mm_image_tokens,
            lm_hidden_size=resolve_model_config(cfg.model).hidden_size))
        loop = asyncio.get_event_loop()

        async def image_encoder(imgs: list[bytes]):
            out = await loop.run_in_executor(None, venc.encode, imgs)
            return [out[i] for i in range(len(imgs))]

    models = ModelManager()
    models.register(
        ns.model, tok, engine.generate,
        defaults=ModelDefaults(max_model_len=cfg.max_model_len, default_max_tokens=ns.max_tokens),
        stats=engine.stats,
        tool_parser=ns.tool_call_parser,
        reasoning_parser=ns.reasoning_parser,
        embed=engine.embed,
        image_encoder=image_encoder,
    )
    svc = HttpService(models)
    # Single-process launch: the engine lives here, so its perf-counter
    # family belongs on this /metrics (workers do the same in
    # components/worker.py).
    from dynamo_tpu.obs.profiler import install_perf_metrics
    install_perf_metrics(svc.metrics)
    # The scheduling ledger (dynamo_sched_*) likewise mirrors onto the
    # single-process /metrics endpoint.
    from dynamo_tpu.obs.sched_ledger import install_sched_metrics
    install_sched_metrics(svc.metrics)
    # The memory ledger (dynamo_mem_*) too — occupancy waterfall, leak
    # audit, TTX forecast (obs/mem_ledger.py).
    from dynamo_tpu.obs.mem_ledger import install_mem_metrics
    install_mem_metrics(svc.metrics)
    if ns.session_ttl > 0:
        from dynamo_tpu.engine.session import install_session_metrics

        # Session retention feeds dynamo_session_* (engine/session.py).
        install_session_metrics(svc.metrics)
    if ns.sp > 1:
        from dynamo_tpu.obs.ring_prefill import install_ring_prefill_metrics

        # Ring-vs-chunked arbitration feeds dynamo_ring_prefill_*.
        install_ring_prefill_metrics(svc.metrics)
    if cfg.warmup_mode != "off":
        from dynamo_tpu.obs.compile_ledger import install_compile_metrics

        # Compile ledger feeds dynamo_xla_compile_* (obs/compile_ledger.py).
        install_compile_metrics(svc.metrics)
    await svc.start(ns.host, ns.port)
    log.info("serving %s on http://%s:%d/v1", ns.model, ns.host, svc.port)
    try:
        await asyncio.Event().wait()
    finally:
        await svc.stop()
        await engine.shutdown()


async def run_text(ns: argparse.Namespace) -> None:
    engine, cfg = build_local_engine(ns)
    tok = load_tokenizer(ns.tokenizer or ns.model)
    print(f"dynamo_tpu REPL — model={ns.model} (ctrl-d to exit)")
    loop = asyncio.get_running_loop()
    while True:
        try:
            line = await loop.run_in_executor(None, lambda: input("> "))
        except (EOFError, KeyboardInterrupt):
            break
        req = PreprocessedRequest(
            token_ids=tok.encode(tok.apply_chat_template([{"role": "user", "content": line}]), add_bos=True),
            stop_conditions=StopConditions(max_tokens=ns.max_tokens),
            sampling_options=SamplingOptions(temperature=0.7),
            eos_token_ids=[tok.eos_id],
        )
        stream = DecodeStream(tok)
        async for out in engine.generate(req):
            for t in out.token_ids:
                sys.stdout.write(stream.step(t))
                sys.stdout.flush()
        sys.stdout.write(stream.flush() + "\n")
    await engine.shutdown()


async def run_batch(ns: argparse.Namespace) -> None:
    """Batch mode: JSONL of {"prompt": ...} → JSONL of completions."""
    engine, cfg = build_local_engine(ns)
    tok = load_tokenizer(ns.tokenizer or ns.model)

    async def one(line: str) -> dict:
        obj = json.loads(line)
        req = PreprocessedRequest(
            token_ids=tok.encode(obj["prompt"], add_bos=True),
            stop_conditions=StopConditions(max_tokens=obj.get("max_tokens", ns.max_tokens)),
            sampling_options=SamplingOptions(temperature=obj.get("temperature", 0.0)),
            eos_token_ids=[tok.eos_id],
        )
        toks: list[int] = []
        async for out in engine.generate(req):
            toks.extend(out.token_ids)
        return {"prompt": obj["prompt"], "text": tok.decode(toks), "tokens": len(toks)}

    src = open(ns.input_jsonl) if ns.input_jsonl else sys.stdin
    lines = [ln for ln in src.read().splitlines() if ln.strip()]
    results = await asyncio.gather(*(one(ln) for ln in lines))
    for r in results:
        print(json.dumps(r))
    await engine.shutdown()


def main() -> None:
    configure_logging()
    ns = parse_args()
    if ns.out_mode not in ("jax",):
        raise SystemExit(f"unknown out={ns.out_mode} (supported: jax)")
    if ns.in_mode == "http":
        asyncio.run(run_http(ns))
    elif ns.in_mode == "text":
        asyncio.run(run_text(ns))
    elif ns.in_mode == "batch":
        asyncio.run(run_batch(ns))
    else:
        raise SystemExit(f"unknown in={ns.in_mode} (supported: http, text, batch)")


if __name__ == "__main__":
    main()
