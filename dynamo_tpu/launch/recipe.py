"""Recipe launcher: declarative serving topologies → running processes.

Fills the role of the reference's deployment recipes + K8s operator
surface (reference: recipes/*/deploy.yaml `DynamoGraphDeployment` CRDs,
deploy/cloud/operator) in a TPU-native shape: a `TpuServeDeployment`
YAML names the model, the frontend(s), and worker pools with their mesh
geometry (tp/pp/dp/ep/sp, multi-host node counts) — everything the
operator would template into pods maps 1:1 onto this framework's
component CLIs (`dynamo_tpu.components.*`).

Two consumers:

- ``plan``: print the exact process commands a deployment implies (what
  a K8s operator would put in pod specs — also the contract tests pin).
- ``up``: run the whole topology locally (one host): coordinator →
  kv-store → workers → frontends, readiness-gated, torn down on SIGINT.
  `--engine mocker` overrides every worker's engine for chip-free runs.

    python -m dynamo_tpu.launch.recipe plan recipes/llama-3-70b/disagg-v5e-64.yaml
    python -m dynamo_tpu.launch.recipe up recipes/llama-3-8b/agg.yaml --engine mocker
"""

from __future__ import annotations

import argparse
import signal
import subprocess
import sys
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any

import yaml

from dynamo_tpu.utils.logging import configure_logging, get_logger

log = get_logger("recipe")

KIND = "TpuServeDeployment"


@dataclass
class Process:
    """One planned process: a component module + argv."""

    name: str
    module: str
    args: list[str]
    replicas: int = 1
    ready_line: str | None = None
    # Processes sharing a group are spawned TOGETHER before any readiness
    # wait — multi-host ranks block in jax.distributed.initialize until
    # every rank exists, so gating rank 0 alone would deadlock.
    group: str | None = None

    def argv(self) -> list[str]:
        return [sys.executable, "-m", self.module, *self.args]


@dataclass
class Plan:
    name: str
    coordinator_url: str
    processes: list[Process] = field(default_factory=list)


def _engine_args(engine: dict[str, Any]) -> list[str]:
    flags = {
        "blockSize": "--block-size", "numBlocks": "--num-blocks",
        "maxBatchSize": "--max-batch-size", "maxModelLen": "--max-model-len",
        "decodeWindow": "--decode-window", "hostKvBlocks": "--host-kv-blocks",
        "diskKvPath": "--disk-kv-path", "remoteKvAddr": "--remote-kv-addr",
    }
    # Boolean switches: present-and-truthy emits the bare flag.
    switches = {"globalPrefixCache": "--global-prefix-cache"}
    out: list[str] = []
    for key, flag in flags.items():
        if key in engine:
            out += [flag, str(engine[key])]
    for key, flag in switches.items():
        if engine.get(key):
            out.append(flag)
    return out


def _mesh_args(mesh: dict[str, Any]) -> list[str]:
    out: list[str] = []
    for axis in ("tp", "pp", "dp", "ep", "sp"):
        if axis in mesh:
            out += [f"--{axis}", str(mesh[axis])]
    return out


def load_spec(path: str | Path) -> dict:
    with open(path) as f:
        doc = yaml.safe_load(f)
    if not isinstance(doc, dict) or doc.get("kind") != KIND:
        raise ValueError(f"{path}: expected kind {KIND}")
    if "spec" not in doc or "metadata" not in doc:
        raise ValueError(f"{path}: missing spec/metadata")
    return doc


def build_plan(doc: dict, engine_override: str | None = None,
               coordinator_port: int = 4222) -> Plan:
    """Pure mapping: deployment spec → process list (the operator's job)."""
    spec = doc["spec"]
    name = doc["metadata"]["name"]
    coord = spec.get("coordinator", {})
    url = coord.get("external") or f"tcp://127.0.0.1:{coord.get('port', coordinator_port)}"
    plan = Plan(name=name, coordinator_url=url)

    if not coord.get("external"):
        plan.processes.append(Process(
            name="coordinator", module="dynamo_tpu.transports.coordinator",
            args=["--host", "0.0.0.0", "--port", str(coord.get("port", coordinator_port))],
            ready_line="COORDINATOR_READY"))

    if "kvStore" in spec:
        ks = spec["kvStore"]
        plan.processes.append(Process(
            name="kv-store", module="dynamo_tpu.components.kv_store",
            args=["--coordinator", url,
                  "--capacity-gib", str(ks.get("capacityGib", 4)),
                  "--port", str(ks.get("port", 0))],
            ready_line="KV_STORE_READY"))

    if "encoder" in spec:
        enc = spec["encoder"] or {}
        plan.processes.append(Process(
            name="encoder", module="dynamo_tpu.components.encode",
            args=["--coordinator", url,
                  "--image-tokens", str(enc.get("imageTokens", 8)),
                  "--lm-hidden", str(enc.get("lmHidden", 64)),
                  "--image-size", str(enc.get("imageSize", 64))],
            replicas=int(enc.get("replicas", 1)),
            ready_line="ENCODE_READY"))

    model = spec["model"]
    for w in spec.get("workers", []):
        args = ["--coordinator", url, "--model", model,
                "--engine", engine_override or w.get("engine_kind", "jax")]
        if w.get("servedModelName") or spec.get("servedModelName"):
            args += ["--served-model-name",
                     w.get("servedModelName") or spec["servedModelName"]]
        parsers = w.get("parsers") or spec.get("parsers") or {}
        if parsers.get("toolCall"):
            args += ["--tool-call-parser", parsers["toolCall"]]
        if parsers.get("reasoning"):
            args += ["--reasoning-parser", parsers["reasoning"]]
        role = w.get("role", "none")
        if role in ("prefill", "decode"):
            args += ["--disagg", role]
            if role == "prefill":
                args += ["--component", "prefill"]
        args += _mesh_args(w.get("mesh", {}))
        args += _engine_args(w.get("engine", {}))
        nodes = int(w.get("nodes", 1))
        if engine_override and engine_override != "jax":
            # Chip-free override (mocker): a simulator doesn't shard — one
            # process stands in for the whole multi-host engine.
            nodes = 1
        if nodes > 1:
            # Multi-host: one process per (replica, rank); rank 0 leads
            # (parallel/multihost.py resolves the leader through the
            # coordination service). Each replica rendezvouses in its own
            # group — two replicas of one component must not share a
            # leader key.
            for rep in range(int(w.get("replicas", 1))):
                group = f"{name}.{w['name']}.r{rep}"
                for rank in range(nodes):
                    plan.processes.append(Process(
                        name=f"{w['name']}-r{rep}-rank{rank}",
                        module="dynamo_tpu.components.worker",
                        args=args + ["--num-nodes", str(nodes),
                                     "--node-rank", str(rank),
                                     "--multihost-group", group],
                        replicas=1, group=group,
                        ready_line="WORKER_READY" if rank == 0 else None))
        else:
            plan.processes.append(Process(
                name=w["name"], module="dynamo_tpu.components.worker",
                args=args, replicas=int(w.get("replicas", 1)),
                ready_line="WORKER_READY"))

    fe = spec.get("frontend", {})
    fe_args = ["--coordinator", url,
               "--port", str(fe.get("port", 8080)),
               "--router-mode", fe.get("routerMode", "kv")]
    if "encoder" in spec:
        fe_args += ["--encoder-endpoint", "dyn://dynamo.encoder.encode"]
    if "grpcPort" in fe:
        fe_args += ["--grpc-port", str(fe["grpcPort"])]
    if "migrationLimit" in fe:
        fe_args += ["--migration-limit", str(fe["migrationLimit"])]
    qos = fe.get("qos", {})
    if qos.get("enabled") is False:
        fe_args += ["--no-qos"]
    for key, flag in (("defaultPriority", "--qos-default-priority"),
                      ("rateLimitRps", "--qos-rate-limit-rps"),
                      ("rateBurst", "--qos-rate-burst"),
                      ("degradeQueueDepth", "--qos-degrade-queue-depth"),
                      ("shedQueueDepth", "--qos-shed-queue-depth"),
                      ("maxQueueDepth", "--qos-max-queue-depth"),
                      ("clampMaxTokens", "--qos-clamp-max-tokens"),
                      ("defaultDeadlineMs", "--qos-default-deadline-ms")):
        if key in qos:
            fe_args += [flag, str(qos[key])]
    plan.processes.append(Process(
        name="frontend", module="dynamo_tpu.components.frontend",
        args=fe_args, replicas=int(fe.get("replicas", 1)),
        ready_line="FRONTEND_READY"))

    agg_port = None
    if spec.get("aggregator", {}).get("enabled"):
        ag = spec["aggregator"]
        agg_port = int(ag.get("port", 9090))
        ag_args = ["--coordinator", url, "--port", str(agg_port)]
        for key, flag in (("scrapeInterval", "--scrape-interval"),
                          ("scrapeTimeout", "--scrape-timeout"),
                          ("stalenessTtl", "--staleness-ttl"),
                          ("sloSpec", "--slo-spec")):
            if key in ag:
                ag_args += [flag, str(ag[key])]
        plan.processes.append(Process(
            name="aggregator", module="dynamo_tpu.components.aggregator",
            args=ag_args, ready_line="AGGREGATOR_READY"))

    if spec.get("planner", {}).get("enabled"):
        pl = spec["planner"]
        pl_args = ["--coordinator", url]
        if agg_port is not None:
            # Close the SLA loop: the planner consumes the aggregator's
            # fleet-wide rollup instead of a single frontend.
            pl_args += ["--fleet-url", f"http://127.0.0.1:{agg_port}"]
        for key, flag in (("ttftSla", "--ttft-sla"), ("itlSla", "--itl-sla"),
                          ("minReplicas", "--min-replicas"),
                          ("maxReplicas", "--max-replicas"),
                          ("chipBudget", "--chip-budget"),
                          ("adjustmentInterval", "--adjustment-interval"),
                          ("mode", "--mode")):
            if key in pl:
                pl_args += [flag, str(pl[key])]
        plan.processes.append(Process(
            name="planner", module="dynamo_tpu.components.planner",
            args=pl_args))
    return plan


def format_plan(plan: Plan) -> str:
    lines = [f"deployment {plan.name} (coordinator {plan.coordinator_url}):"]
    for p in plan.processes:
        rep = f" x{p.replicas}" if p.replicas > 1 else ""
        lines.append(f"  [{p.name}{rep}] " + " ".join(p.argv()))
    return "\n".join(lines)


class _Child:
    """A spawned process with a drain thread: the pipe is read for the
    process's whole life (a full 64KB pipe would block the child mid-serve)
    and the ready line is detected without blocking the launcher."""

    def __init__(self, spec: Process, idx: int):
        import threading

        self.spec = spec
        self.proc = subprocess.Popen(
            spec.argv(), stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True)
        self.name = spec.name if spec.replicas == 1 else f"{spec.name}[{idx}]"
        self.ready = threading.Event()
        self._thread = threading.Thread(target=self._drain, daemon=True)
        self._thread.start()

    def _drain(self) -> None:
        for line in self.proc.stdout:  # type: ignore[union-attr]
            sys.stdout.write(f"{self.name}: {line}")
            sys.stdout.flush()
            if self.spec.ready_line and self.spec.ready_line in line:
                self.ready.set()

    def wait_ready(self, deadline: float) -> None:
        while not self.ready.wait(timeout=0.25):
            if self.proc.poll() is not None:
                raise RuntimeError(f"{self.name} exited rc={self.proc.returncode} "
                                   "before ready")
            if time.monotonic() > deadline:
                raise TimeoutError(f"{self.name} not ready in time")


def run_local(plan: Plan, timeout: float = 600.0) -> None:
    """Launch every process on this host. Processes are readiness-gated in
    plan order, except that a ``group`` (multi-host rank set) is spawned in
    full before its readiness wait — rank 0 cannot become ready until every
    follower has joined the jax.distributed rendezvous."""
    children: list[_Child] = []

    def stop_all() -> None:
        for c in reversed(children):
            if c.proc.poll() is None:
                c.proc.terminate()
        for c in reversed(children):
            try:
                c.proc.wait(10)
            except subprocess.TimeoutExpired:
                c.proc.kill()

    def spawn(p: Process) -> list[_Child]:
        out = []
        for r in range(p.replicas):
            c = _Child(p, r)
            children.append(c)
            out.append(c)
            log.info("started %s pid=%d", c.name, c.proc.pid)
        return out

    try:
        i = 0
        procs = plan.processes
        while i < len(procs):
            group = procs[i].group
            batch: list[_Child] = []
            if group is None:
                batch += spawn(procs[i])
                i += 1
            else:  # spawn the whole rank group before any wait
                while i < len(procs) and procs[i].group == group:
                    batch += spawn(procs[i])
                    i += 1
            deadline = time.monotonic() + timeout
            for c in batch:
                if c.spec.ready_line:
                    c.wait_ready(deadline)
        print(f"RECIPE_UP {plan.name} processes={len(children)}", flush=True)
        # Block BEFORE waiting: bare sigwait races the default SIGTERM
        # action (process death without the finally → leaked children).
        signal.pthread_sigmask(signal.SIG_BLOCK,
                               {signal.SIGINT, signal.SIGTERM})
        signal.sigwait({signal.SIGINT, signal.SIGTERM})
    finally:
        stop_all()


def main(argv: list[str] | None = None) -> None:
    ap = argparse.ArgumentParser("dynamo-recipe", description=__doc__)
    ap.add_argument("cmd", choices=["plan", "up"])
    ap.add_argument("recipe")
    ap.add_argument("--engine", default=None,
                    help="override every worker's engine (e.g. mocker)")
    ap.add_argument("--start-timeout", type=float, default=600.0)
    ns = ap.parse_args(argv)
    configure_logging()
    plan = build_plan(load_spec(ns.recipe), engine_override=ns.engine)
    if ns.cmd == "plan":
        print(format_plan(plan))
        return
    run_local(plan, timeout=ns.start_timeout)


if __name__ == "__main__":
    main()
