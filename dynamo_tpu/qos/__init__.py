"""Request-level quality-of-service: admission control, priority-weighted
fair queueing, deadline propagation, and load shedding.

The gateway sits in the frontend's request path; the WDRR queue slots in
front of the engine scheduler; deadline annotations ride the existing
PreprocessedRequest wire format so every hop (frontend → router → worker
→ engine) can cancel expired work.
"""

from dynamo_tpu.qos.admission import AdmissionController, Decision, EngineLoad, aggregate_stats
from dynamo_tpu.qos.config import DEFAULT_WEIGHTS, PRIORITY_CLASSES, QosConfig, class_rank
from dynamo_tpu.qos.deadline import (
    DEADLINE_KEY,
    NO_SPEC_KEY,
    PRIORITY_KEY,
    deadline_of,
    expired,
    priority_of,
    remaining_s,
)
from dynamo_tpu.qos.gateway import QosGateway
from dynamo_tpu.qos.token_bucket import ClientRateLimiter, TokenBucket
from dynamo_tpu.qos.wdrr import WdrrQueue

__all__ = [
    "AdmissionController",
    "ClientRateLimiter",
    "DEADLINE_KEY",
    "DEFAULT_WEIGHTS",
    "Decision",
    "EngineLoad",
    "NO_SPEC_KEY",
    "PRIORITY_CLASSES",
    "PRIORITY_KEY",
    "QosConfig",
    "QosGateway",
    "TokenBucket",
    "WdrrQueue",
    "aggregate_stats",
    "class_rank",
    "deadline_of",
    "expired",
    "priority_of",
    "remaining_s",
]
