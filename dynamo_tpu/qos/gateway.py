"""The QoS gateway: one object the frontend consults per request.

Combines per-client rate limiting, capacity-predicate admission,
deadline bookkeeping, and graceful degradation, and exports every
decision through the metrics registry so shedding is observable.
"""

from __future__ import annotations

import logging
import time
from typing import Any, Callable, Mapping

from dynamo_tpu.qos.admission import AdmissionController, Decision, aggregate_stats
from dynamo_tpu.qos.config import QosConfig
from dynamo_tpu.qos.deadline import DEADLINE_KEY, NO_SPEC_KEY, PRIORITY_KEY, expired
from dynamo_tpu.qos.token_bucket import ClientRateLimiter
from dynamo_tpu.utils.metrics import MetricsRegistry

log = logging.getLogger(__name__)


class QosGateway:
    def __init__(self, cfg: QosConfig | None = None,
                 registry: MetricsRegistry | None = None,
                 now_fn: Callable[[], float] = time.time,
                 mono_fn: Callable[[], float] = time.monotonic):
        self.cfg = cfg or QosConfig()
        self._now = now_fn
        self.limiter = ClientRateLimiter(
            self.cfg.rate_limit_rps, self.cfg.rate_burst,
            self.cfg.max_tracked_clients, mono_fn)
        self.admission = AdmissionController(self.cfg)
        reg = registry if registry is not None else MetricsRegistry()
        self.registry = reg
        self.m_admitted = reg.counter("qos_admitted_total", "Requests admitted by the QoS gateway")
        self.m_rejected = reg.counter("qos_rejected_total", "Requests rejected by the QoS gateway")
        self.m_degraded = reg.counter("qos_degraded_total", "Degradation actions applied under pressure")
        self.m_deadline_cancelled = reg.counter(
            "qos_deadline_cancelled_total", "Requests cancelled because their deadline expired")
        self.g_pressure = reg.gauge("qos_pressure_level", "Current pressure level (0=ok..4=full)")
        self.g_queue_depth = reg.gauge("qos_queue_depth", "Per-worker average waiting queue depth")
        self.g_kv_usage = reg.gauge("qos_kv_usage", "Max KV-cache block usage across workers")
        reg.func_gauge("qos_tracked_clients", lambda: float(len(self.limiter)),
                       "Clients with live rate-limit buckets")

    def admit(self, client_id: str, priority: str,
              stats: Mapping[str, Any] | None,
              deadline_ts: float | None = None) -> Decision:
        """Full admission pipeline: deadline → rate limit → capacity."""
        if not self.cfg.enabled:
            return Decision(True)
        now = self._now()
        if expired(deadline_ts, now):
            self.m_deadline_cancelled.inc(stage="admission")
            self.m_rejected.inc(priority=priority, reason="deadline")
            return Decision(False, 504, "deadline")
        allowed, retry_after = self.limiter.check(client_id)
        if not allowed:
            self.m_rejected.inc(priority=priority, reason="rate_limit")
            return Decision(False, 429, "rate_limit", max(retry_after, 0.1))
        load = aggregate_stats(stats)
        decision = self.admission.evaluate(priority, load)
        self.g_pressure.set(float(decision.pressure))
        self.g_queue_depth.set(load.queue_depth)
        self.g_kv_usage.set(load.kv_usage)
        if decision.admitted:
            self.m_admitted.inc(priority=priority)
        else:
            self.m_rejected.inc(priority=priority, reason=decision.reason)
            log.debug("qos: shed %s request (reason=%s pressure=%s)",
                      priority, decision.reason, decision.pressure_name)
        return decision

    def annotate(self, pre: Any, priority: str,
                 deadline_ts: float | None, decision: Decision) -> None:
        """Stamp QoS annotations onto a PreprocessedRequest and apply
        degradation actions when the admission decision asked for them."""
        ann = getattr(pre, "annotations", None)
        if ann is None:
            ann = {}
            try:
                pre.annotations = ann
            except AttributeError:
                return
        ann[PRIORITY_KEY] = priority
        if deadline_ts is not None:
            ann[DEADLINE_KEY] = deadline_ts
        if decision.degrade:
            stop = getattr(pre, "stop_conditions", None)
            max_tok = getattr(stop, "max_tokens", None) if stop is not None else None
            if max_tok is None or max_tok > self.cfg.clamp_max_tokens:
                if stop is not None:
                    stop.max_tokens = self.cfg.clamp_max_tokens
                    self.m_degraded.inc(action="clamp_max_tokens")
            if not ann.get(NO_SPEC_KEY):
                ann[NO_SPEC_KEY] = True
                self.m_degraded.inc(action="disable_spec")

    def note_deadline_cancel(self, stage: str) -> None:
        self.m_deadline_cancelled.inc(stage=stage)
