"""QoS configuration: priority classes, pressure thresholds, degradation knobs."""

from __future__ import annotations

from dataclasses import dataclass, field

# Highest first. Unknown classes are treated as "standard" for shedding
# decisions but still get their own WDRR lane (weight 1).
PRIORITY_CLASSES: tuple[str, ...] = ("interactive", "standard", "batch")

DEFAULT_WEIGHTS: dict[str, int] = {"interactive": 8, "standard": 4, "batch": 1}


def class_rank(priority: str) -> int:
    """0 = most important. Unknown classes rank with 'standard'."""
    try:
        return PRIORITY_CLASSES.index(priority)
    except ValueError:
        return PRIORITY_CLASSES.index("standard")


@dataclass
class QosConfig:
    enabled: bool = True
    default_priority: str = "standard"
    weights: dict[str, int] = field(default_factory=lambda: dict(DEFAULT_WEIGHTS))

    # Per-client token bucket; rate 0 disables rate limiting.
    rate_limit_rps: float = 0.0
    rate_burst: float = 10.0
    max_tracked_clients: int = 10_000

    # Pressure thresholds, evaluated against aggregated engine stats.
    # Queue depths are per-worker averages so the knobs don't need
    # retuning when the fleet scales.
    degrade_queue_depth: int = 16   # soft: clamp max_tokens, disable spec
    degrade_kv_usage: float = 0.85
    shed_queue_depth: int = 32      # shed "batch" class with 429
    shed_kv_usage: float = 0.95
    max_queue_depth: int = 64       # only "interactive" admitted (429 others)
    min_kv_headroom: float = 0.02
    full_queue_depth: int = 128     # 503 everything

    # Graceful degradation.
    clamp_max_tokens: int = 256

    # Deadlines.
    default_deadline_ms: float | None = None

    # Hint returned in Retry-After when we cannot estimate drain time.
    retry_after_s: float = 1.0
