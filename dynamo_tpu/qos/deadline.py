"""Deadline and priority annotations on the request wire format.

Deadlines are absolute wall-clock epoch seconds carried in
`PreprocessedRequest.annotations`, so they survive to_dict/from_dict
across frontend → router → worker → engine hops without re-deriving.
Clients express deadlines as a relative budget (`x-deadline-ms` header
or `deadline_ms` body field); the frontend converts on arrival.
"""

from __future__ import annotations

import time
from typing import Any, Mapping

PRIORITY_KEY = "qos.priority"
DEADLINE_KEY = "qos.deadline_ts"
NO_SPEC_KEY = "qos.no_spec"

PRIORITY_HEADER = "x-priority"
DEADLINE_HEADER = "x-deadline-ms"
CLIENT_HEADER = "x-client-id"


def priority_from(headers: Mapping[str, str] | None = None,
                  body: Mapping[str, Any] | None = None,
                  default: str = "standard") -> str:
    p = None
    if headers is not None:
        p = headers.get(PRIORITY_HEADER)
    if p is None and body is not None:
        p = body.get("priority")
    if p is None:
        return default
    p = str(p).strip().lower()
    return p if p else default


def deadline_from(headers: Mapping[str, str] | None = None,
                  body: Mapping[str, Any] | None = None,
                  default_ms: float | None = None,
                  now: float | None = None) -> float | None:
    """Resolve a relative ms budget into an absolute epoch-seconds deadline."""
    ms: Any = None
    if headers is not None:
        ms = headers.get(DEADLINE_HEADER)
    if ms is None and body is not None:
        ms = body.get("deadline_ms")
    if ms is None:
        ms = default_ms
    if ms is None:
        return None
    try:
        ms = float(ms)
    except (TypeError, ValueError):
        return None
    return (time.time() if now is None else now) + ms / 1000.0


def deadline_of(annotations: Mapping[str, Any] | None) -> float | None:
    if not annotations:
        return None
    ts = annotations.get(DEADLINE_KEY)
    if ts is None:
        return None
    try:
        return float(ts)
    except (TypeError, ValueError):
        return None


def priority_of(annotations: Mapping[str, Any] | None,
                default: str = "standard") -> str:
    if not annotations:
        return default
    p = annotations.get(PRIORITY_KEY)
    return str(p) if p else default


def remaining_s(deadline_ts: float | None, now: float | None = None) -> float | None:
    if deadline_ts is None:
        return None
    return deadline_ts - (time.time() if now is None else now)


def expired(deadline_ts: float | None, now: float | None = None) -> bool:
    if deadline_ts is None:
        return False
    return (time.time() if now is None else now) >= deadline_ts
