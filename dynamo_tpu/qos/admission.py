"""Capacity-aware admission: turn live engine stats into admit/shed decisions.

Stats arrive in two shapes depending on deployment: a flat engine dict
(`{"num_waiting": ..., "kv_usage": ...}`) when the frontend wraps an
engine directly, or the watcher shape (`{"workers": {wid: stats}}`)
when fed by the KV-metrics watcher. `aggregate_stats` normalizes both.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Mapping

from dynamo_tpu.qos.config import QosConfig, class_rank

# Pressure levels, low to high.
OK, DEGRADE, SHED, OVERLOAD, FULL = 0, 1, 2, 3, 4

_LEVEL_NAMES = {OK: "ok", DEGRADE: "degrade", SHED: "shed",
                OVERLOAD: "overload", FULL: "full"}


@dataclass
class EngineLoad:
    queue_depth: float = 0.0      # per-worker average waiting requests
    running: float = 0.0
    kv_usage: float = 0.0         # max across workers, 0..1
    kv_total_blocks: float = 0.0
    workers: int = 0
    known: bool = False           # False → no signal yet, fail open


@dataclass
class Decision:
    admitted: bool
    status: int = 200
    reason: str = ""              # "" | "rate_limit" | "shed" | "overload" | "deadline"
    retry_after_s: float = 0.0
    degrade: bool = False         # clamp max_tokens / disable spec
    pressure: int = OK

    @property
    def pressure_name(self) -> str:
        return _LEVEL_NAMES.get(self.pressure, "ok")


def _flat_load(stats: Mapping[str, Any]) -> EngineLoad:
    return EngineLoad(
        queue_depth=float(stats.get("num_waiting", 0) or 0),
        running=float(stats.get("num_running", 0) or 0),
        kv_usage=float(stats.get("kv_usage", 0.0) or 0.0),
        kv_total_blocks=float(stats.get("kv_total_blocks", 0) or 0),
        workers=1,
        known=True,
    )


def aggregate_stats(stats: Mapping[str, Any] | None) -> EngineLoad:
    """Normalize either stats shape into a single EngineLoad."""
    if not stats:
        return EngineLoad()
    workers = stats.get("workers")
    if isinstance(workers, Mapping) and workers:
        loads = [_flat_load(w) for w in workers.values() if isinstance(w, Mapping)]
        if not loads:
            return EngineLoad()
        n = len(loads)
        return EngineLoad(
            queue_depth=sum(l.queue_depth for l in loads) / n,
            running=sum(l.running for l in loads),
            kv_usage=max(l.kv_usage for l in loads),
            kv_total_blocks=sum(l.kv_total_blocks for l in loads),
            workers=n,
            known=True,
        )
    if "num_waiting" in stats or "kv_usage" in stats or "num_running" in stats:
        return _flat_load(stats)
    return EngineLoad()


class AdmissionController:
    """Maps (priority class, engine load) to an admit/degrade/shed decision."""

    def __init__(self, cfg: QosConfig):
        self.cfg = cfg

    def pressure(self, load: EngineLoad) -> int:
        if not load.known:
            return OK
        c = self.cfg
        headroom = 1.0 - load.kv_usage
        if load.queue_depth >= c.full_queue_depth:
            return FULL
        if load.queue_depth >= c.max_queue_depth or headroom < c.min_kv_headroom:
            return OVERLOAD
        if load.queue_depth >= c.shed_queue_depth or load.kv_usage >= c.shed_kv_usage:
            return SHED
        if load.queue_depth >= c.degrade_queue_depth or load.kv_usage >= c.degrade_kv_usage:
            return DEGRADE
        return OK

    def _retry_after(self, load: EngineLoad) -> float:
        # Crude drain estimate: half the queue at ~1 req/s/worker, floored
        # at the configured hint. Good enough to spread retries out.
        base = self.cfg.retry_after_s
        if load.workers > 0 and load.queue_depth > 0:
            return max(base, round(load.queue_depth / (2.0 * load.workers), 1))
        return base

    def evaluate(self, priority: str, load: EngineLoad) -> Decision:
        level = self.pressure(load)
        rank = class_rank(priority)
        if level >= FULL:
            return Decision(False, 503, "overload", self._retry_after(load), pressure=level)
        if level >= OVERLOAD and rank > class_rank("interactive"):
            return Decision(False, 429, "overload", self._retry_after(load), pressure=level)
        if level >= SHED and rank >= class_rank("batch"):
            return Decision(False, 429, "shed", self._retry_after(load), pressure=level)
        return Decision(True, 200, "", 0.0, degrade=level >= DEGRADE, pressure=level)
