"""Weighted deficit-round-robin queue, deque-compatible.

Drop-in replacement for the scheduler's `waiting: deque[Seq]`: supports
the exact surface the scheduler uses — `append`, `appendleft` (preempt
resume), `q[0]` peek, `popleft`, `remove`, `in`, `len`, truthiness,
iteration — while serving classes by weighted DRR underneath.

Peek semantics: `q[0]` commits the DRR decision and caches the item so
the scheduler's peek-then-popleft admission pattern stays consistent
(the same item is peeked and popped even if enqueues happen between).
Preempted items pushed back via `appendleft` bypass DRR entirely: they
already held resources and must re-admit first to avoid losing work.
"""

from __future__ import annotations

from collections import deque
from typing import Any, Callable, Iterator

from dynamo_tpu.qos.config import DEFAULT_WEIGHTS, PRIORITY_CLASSES


class WdrrQueue:
    def __init__(self, key_fn: Callable[[Any], str] | None = None,
                 weights: dict[str, int] | None = None):
        self._key = key_fn or (lambda item: getattr(item, "qos_priority", "standard"))
        self._weights = {c: max(1, int(w)) for c, w in (weights or DEFAULT_WEIGHTS).items()}
        self._lanes: dict[str, deque] = {}
        self._order: list[str] = [c for c in PRIORITY_CLASSES if c in self._weights]
        for c in self._weights:
            if c not in self._order:
                self._order.append(c)
        for c in self._order:
            self._lanes[c] = deque()
        self._deficit: dict[str, float] = {c: 0.0 for c in self._order}
        self._idx = 0
        self._fresh = True  # rotation pointer just arrived at _order[_idx]
        self._resume: deque = deque()  # preempted items, served before all lanes
        self._peeked: Any = None
        self._has_peeked = False

    # -- enqueue ----------------------------------------------------------

    def _lane(self, cls: str) -> deque:
        lane = self._lanes.get(cls)
        if lane is None:
            lane = self._lanes[cls] = deque()
            self._order.append(cls)
            self._weights.setdefault(cls, 1)
            self._deficit[cls] = 0.0
        return lane

    def append(self, item: Any) -> None:
        self._lane(str(self._key(item))).append(item)

    def appendleft(self, item: Any) -> None:
        # Preserve deque semantics: item goes ahead of whatever q[0]
        # currently is, including an already-committed peek.
        if self._has_peeked:
            self._resume.appendleft(self._peeked)
            self._peeked, self._has_peeked = None, False
        self._resume.appendleft(item)

    # -- serve ------------------------------------------------------------

    def _advance(self) -> None:
        self._idx = (self._idx + 1) % len(self._order)
        self._fresh = True

    def _next(self) -> Any:
        """Commit the next item to serve (removes it from its lane)."""
        if self._has_peeked:
            return self._peeked
        if self._resume:
            self._peeked, self._has_peeked = self._resume.popleft(), True
            return self._peeked
        # Weight >= 1 guarantees a fresh visit to a non-empty lane serves,
        # so 2 passes over the rotation always suffice.
        for _ in range(2 * len(self._order)):
            cls = self._order[self._idx]
            lane = self._lanes[cls]
            if not lane:
                self._deficit[cls] = 0.0
                self._advance()
                continue
            if self._fresh:
                self._deficit[cls] += self._weights.get(cls, 1)
                self._fresh = False
            if self._deficit[cls] >= 1.0:
                self._deficit[cls] -= 1.0
                item = lane.popleft()
                if not lane:
                    self._deficit[cls] = 0.0
                    self._advance()
                self._peeked, self._has_peeked = item, True
                return item
            self._advance()
        raise IndexError("pop from empty WdrrQueue")

    def __getitem__(self, i: int) -> Any:
        if i != 0:
            raise IndexError("WdrrQueue only supports peeking index 0")
        if not self:
            raise IndexError("peek from empty WdrrQueue")
        return self._next()

    def popleft(self) -> Any:
        if not self:
            raise IndexError("pop from empty WdrrQueue")
        item = self._next()
        self._peeked, self._has_peeked = None, False
        return item

    # -- bookkeeping ------------------------------------------------------

    def remove(self, item: Any) -> None:
        if self._has_peeked and self._peeked is item:
            self._peeked, self._has_peeked = None, False
            return
        try:
            self._resume.remove(item)
            return
        except ValueError:
            pass
        for lane in self._lanes.values():
            try:
                lane.remove(item)
                return
            except ValueError:
                continue
        raise ValueError("WdrrQueue.remove(x): x not in queue")

    def __contains__(self, item: Any) -> bool:
        if self._has_peeked and self._peeked is item:
            return True
        if item in self._resume:
            return True
        return any(item in lane for lane in self._lanes.values())

    def __len__(self) -> int:
        n = (1 if self._has_peeked else 0) + len(self._resume)
        return n + sum(len(lane) for lane in self._lanes.values())

    def __bool__(self) -> bool:
        return self._has_peeked or bool(self._resume) or any(self._lanes.values())

    def __iter__(self) -> Iterator[Any]:
        if self._has_peeked:
            yield self._peeked
        yield from self._resume
        for cls in self._order:
            yield from self._lanes[cls]

    def depths(self) -> dict[str, int]:
        """Per-class queue depth (peeked/resume items counted in their class)."""
        out = {c: len(lane) for c, lane in self._lanes.items()}
        for item in list(self._resume) + ([self._peeked] if self._has_peeked else []):
            cls = str(self._key(item))
            out[cls] = out.get(cls, 0) + 1
        return out
