"""Token-bucket rate limiting with a bounded per-client registry."""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Callable


class TokenBucket:
    """Classic token bucket. `now_fn` is injectable for deterministic tests."""

    def __init__(self, rate: float, burst: float,
                 now_fn: Callable[[], float] = time.monotonic):
        self.rate = float(rate)
        self.burst = float(burst)
        self._now = now_fn
        self.tokens = float(burst)
        self._t_last = now_fn()

    def _refill(self) -> None:
        t = self._now()
        if t > self._t_last:
            self.tokens = min(self.burst, self.tokens + (t - self._t_last) * self.rate)
        self._t_last = t

    def try_acquire(self, n: float = 1.0) -> bool:
        self._refill()
        if self.tokens >= n:
            self.tokens -= n
            return True
        return False

    def retry_after(self, n: float = 1.0) -> float:
        """Seconds until `n` tokens will be available (0 if already)."""
        self._refill()
        deficit = n - self.tokens
        if deficit <= 0:
            return 0.0
        if self.rate <= 0:
            return 60.0
        return deficit / self.rate


class ClientRateLimiter:
    """Per-client token buckets with an LRU cap on tracked clients.

    rate <= 0 disables limiting entirely (check() always admits).
    """

    def __init__(self, rate: float, burst: float, max_clients: int = 10_000,
                 now_fn: Callable[[], float] = time.monotonic):
        self.rate = float(rate)
        self.burst = float(burst)
        self.max_clients = max_clients
        self._now = now_fn
        self._buckets: OrderedDict[str, TokenBucket] = OrderedDict()
        self._lock = threading.Lock()

    def __len__(self) -> int:
        return len(self._buckets)

    def check(self, client_id: str, n: float = 1.0) -> tuple[bool, float]:
        """Returns (allowed, retry_after_seconds)."""
        if self.rate <= 0:
            return True, 0.0
        with self._lock:
            bucket = self._buckets.get(client_id)
            if bucket is None:
                bucket = TokenBucket(self.rate, self.burst, self._now)
                self._buckets[client_id] = bucket
                while len(self._buckets) > self.max_clients:
                    self._buckets.popitem(last=False)
            else:
                self._buckets.move_to_end(client_id)
            if bucket.try_acquire(n):
                return True, 0.0
            return False, bucket.retry_after(n)
