"""Drain-aware worker retirement: the protocol between planner and worker.

Fills the role of the reference planner's graceful scale-down (reference:
components/src/dynamo/planner/ KubernetesConnector — a K8s Deployment
patch triggers preStop drain hooks; here the connector and the worker
speak directly). Retirement is a first-class protocol, not a SIGKILL:

1. **Request** — the planner (or an operator) writes a
   :class:`DrainRequest` under ``planner/drain/{namespace}/{instance}``,
   carrying the human-readable reason and a deadline; sending SIGTERM to
   the worker starts the same protocol with default knobs.
2. **Membership out** — the worker flips readiness NotReady, deletes its
   model card + endpoint instance keys, and stops admitting new streams.
   Its lease (and data-plane connections) stay live so in-flight streams
   finish. Because every registration is lease-bound, a drain that dies
   half-way can never leave stale membership: the lease revoke/expiry
   removes whatever the explicit deregistration didn't.
3. **Run down** — in-flight streams run to completion under the bounded
   deadline; past the batch grace, batch-class streams are early-stopped
   (QoS: interactive work gets the whole window, batch work yields it).
4. **Evacuate** — session-retained KV and its resumable session records
   are pushed to the shared remote block store (kvbm/remote.py), so the
   session's next turn lands on a surviving worker as pull-to-warm
   instead of a full recompute.
5. **Exit** — only then are publishers stopped and the lease dropped.

A second SIGTERM/SIGINT aborts the drain (skip waiting + evacuation,
bounded fast teardown) so an operator always has a fast exit.

The ``dynamo_drain_*`` family below is cross-checked by
tools/lint_metrics.py DRAIN_METRICS.
"""

from __future__ import annotations

import asyncio
import inspect
import json
import time
from dataclasses import dataclass, field
from typing import Awaitable, Callable

from dynamo_tpu.utils.logging import get_logger
from dynamo_tpu.utils.metrics import MetricsRegistry

log = get_logger("runtime.drain")

DRAIN_PREFIX = "planner/drain"


def drain_key(namespace: str, instance_id: int) -> str:
    """Coordinator key the planner writes to request a drain."""
    return f"{DRAIN_PREFIX}/{namespace}/{instance_id:016x}"


def drain_status_key(namespace: str, instance_id: int) -> str:
    """Where the draining worker reports progress (not lease-bound, so the
    planner can read the terminal state after the worker exits)."""
    return drain_key(namespace, instance_id) + "/status"


@dataclass
class DrainRequest:
    """The planner→worker handshake payload."""

    reason: str = ""
    deadline_s: float | None = None     # None = the worker's default
    ts: float = 0.0

    def to_bytes(self) -> bytes:
        return json.dumps({"reason": self.reason, "deadline_s": self.deadline_s,
                           "ts": self.ts}).encode()

    @classmethod
    def from_bytes(cls, raw: bytes) -> "DrainRequest":
        d = json.loads(raw)
        return cls(reason=str(d.get("reason", "")),
                   deadline_s=d.get("deadline_s"),
                   ts=float(d.get("ts", 0.0)))


class DrainMetrics:
    """The dynamo_drain_* family (names cross-checked by
    tools/lint_metrics.py DRAIN_METRICS)."""

    def __init__(self, registry: MetricsRegistry | None = None):
        self.bind(registry or MetricsRegistry())

    def bind(self, registry: MetricsRegistry) -> None:
        self.registry = registry
        self.duration = registry.histogram(
            "drain_duration_seconds",
            "Wall-clock seconds a worker drain took, request to exit-ready",
            buckets=(0.1, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0))
        self.streams_completed = registry.counter(
            "drain_streams_completed",
            "In-flight streams that ran to completion during a drain")
        self.streams_aborted = registry.counter(
            "drain_streams_aborted",
            "In-flight streams early-stopped during a drain (batch-class "
            "grace or deadline/abort)")
        self.evacuated_blocks = registry.counter(
            "drain_evacuated_blocks",
            "Session-retained KV blocks pushed to the remote store by drains")
        self.evacuated_bytes = registry.counter(
            "drain_evacuated_bytes",
            "Bytes of session-retained KV pushed to the remote store by drains")
        self.evacuated_sessions = registry.counter(
            "drain_evacuated_sessions",
            "Retained sessions whose resumable record reached the remote store")
        self.active = registry.gauge(
            "drain_active",
            "1 while this worker is draining, else 0")
        self.aborted = registry.counter(
            "drain_aborted",
            "Drains aborted early (operator second signal) before the "
            "run-down and evacuation phases completed")


_metrics: DrainMetrics | None = None


def get_drain_metrics() -> DrainMetrics:
    global _metrics
    if _metrics is None:
        _metrics = DrainMetrics()
    return _metrics


def install_drain_metrics(registry: MetricsRegistry) -> DrainMetrics:
    """Re-home the singleton into a runtime registry (worker /metrics)."""
    m = get_drain_metrics()
    m.bind(registry)
    return m


@dataclass
class DrainReport:
    """What a drain did — logged, published on the status key, and carried
    in the WORKER_DRAINED stdout line the harness asserts on."""

    state: str = "done"                # done | aborted
    reason: str = ""
    duration_s: float = 0.0
    streams_completed: int = 0
    streams_aborted: int = 0
    evacuated_sessions: int = 0
    evacuated_blocks: int = 0
    evacuated_bytes: int = 0

    def to_dict(self) -> dict:
        return {
            "state": self.state, "reason": self.reason,
            "duration_s": round(self.duration_s, 3),
            "streams_completed": self.streams_completed,
            "streams_aborted": self.streams_aborted,
            "evacuated_sessions": self.evacuated_sessions,
            "evacuated_blocks": self.evacuated_blocks,
            "evacuated_bytes": self.evacuated_bytes,
        }


async def _maybe_await(fn: Callable, *args):
    out = fn(*args)
    if inspect.isawaitable(out):
        return await out
    return out


@dataclass
class WorkerDrainer:
    """Orchestrates one drain. Transport-free: every side effect arrives
    as a callback, so the protocol is unit-testable without a fleet.

    ``deregister`` must leave the lease and data plane ALIVE — only
    membership (readiness, model card, instance keys) goes; ``abort_batch``
    early-stops batch-class streams, ``abort_all`` everything still
    running; both return how many streams they stopped. ``evacuate``
    pushes session KV out and returns
    ``{"sessions": n, "blocks": n, "bytes": n}``.
    """

    inflight: Callable[[], int]
    deregister: Callable[[], Awaitable[None] | None]
    evacuate: Callable[[], "Awaitable[dict] | dict | None"] | None = None
    abort_batch: Callable[[], "Awaitable[int] | int"] | None = None
    abort_all: Callable[[], "Awaitable[int] | int"] | None = None
    abort_event: asyncio.Event | None = None
    deadline_s: float = 30.0
    batch_grace_s: float | None = None  # None = half the deadline
    poll_s: float = 0.05
    _state: str = field(default="idle", init=False)

    @property
    def state(self) -> str:
        return self._state

    async def drain(self, reason: str = "",
                    deadline_s: float | None = None) -> DrainReport:
        m = get_drain_metrics()
        m.active.set(1.0)
        self._state = "draining"
        deadline_total = deadline_s if deadline_s else self.deadline_s
        t0 = time.monotonic()
        deadline = t0 + deadline_total
        grace = self.batch_grace_s
        batch_at = t0 + (grace if grace is not None and grace >= 0
                         else deadline_total / 2)
        rep = DrainReport(reason=reason)
        start_inflight = self.inflight()
        log.info("drain start: reason=%r inflight=%d deadline=%.1fs",
                 reason, start_inflight, deadline_total)
        try:
            await _maybe_await(self.deregister)
        except Exception:
            # Unreachable coordinator mid-partition: membership keys are
            # lease-bound, so exit (lease revoke/expiry) still removes them
            # atomically — keep draining locally rather than half-stopping.
            log.warning("drain deregistration failed (coordinator "
                        "unreachable?); lease expiry will clean up", exc_info=True)

        batch_stopped = False
        while self.inflight() > 0:
            now = time.monotonic()
            if self.abort_event is not None and self.abort_event.is_set():
                rep.state = "aborted"
                break
            if now >= deadline:
                break
            if not batch_stopped and now >= batch_at and self.abort_batch:
                batch_stopped = True
                n = int(await _maybe_await(self.abort_batch) or 0)
                if n:
                    rep.streams_aborted += n
                    log.info("drain batch grace expired: early-stopped %d "
                             "batch-class stream(s)", n)
            await asyncio.sleep(self.poll_s)

        if self.inflight() > 0:
            # Deadline overrun (or operator abort): force-stop what's left.
            # The drain still counts as "done" on overrun — it ran the full
            # protocol, bounded; only the second-signal path is "aborted".
            if rep.state != "aborted":
                log.warning("drain deadline (%.1fs) hit with %d stream(s) "
                            "still in flight; force-stopping",
                            deadline_total, self.inflight())
            if self.abort_all is not None:
                rep.streams_aborted += int(
                    await _maybe_await(self.abort_all) or 0)
        rep.streams_completed = max(
            start_inflight - rep.streams_aborted - self.inflight(), 0)

        if rep.state != "aborted" and self.evacuate is not None:
            # Evacuation gets whatever deadline budget is left, floor 2s —
            # a drain that spent its whole window on streams still gets a
            # bounded chance to save the sessions.
            budget = max(deadline - time.monotonic(), 2.0)
            try:
                evac = await asyncio.wait_for(
                    _maybe_await(self.evacuate), timeout=budget)
                if evac:
                    rep.evacuated_sessions = int(evac.get("sessions", 0))
                    rep.evacuated_blocks = int(evac.get("blocks", 0))
                    rep.evacuated_bytes = int(evac.get("bytes", 0))
            except asyncio.TimeoutError:
                log.warning("session evacuation exceeded its %.1fs budget; "
                            "remaining sessions will recompute", budget)
            except Exception:
                log.warning("session evacuation failed; affected sessions "
                            "will recompute", exc_info=True)

        rep.duration_s = time.monotonic() - t0
        m.duration.observe(rep.duration_s)
        m.streams_completed.inc(rep.streams_completed)
        m.streams_aborted.inc(rep.streams_aborted)
        m.evacuated_sessions.inc(rep.evacuated_sessions)
        m.evacuated_blocks.inc(rep.evacuated_blocks)
        m.evacuated_bytes.inc(rep.evacuated_bytes)
        if rep.state == "aborted":
            m.aborted.inc()
        m.active.set(0.0)
        self._state = rep.state
        log.info("drain %s in %.2fs: %d completed, %d aborted, "
                 "%d session(s) / %d block(s) evacuated",
                 rep.state, rep.duration_s, rep.streams_completed,
                 rep.streams_aborted, rep.evacuated_sessions,
                 rep.evacuated_blocks)
        return rep
