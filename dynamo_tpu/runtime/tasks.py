"""Structured task management: tracker hierarchy, policies, compute pool.

Fills the role of the reference's task-tracker subsystem
(reference: lib/runtime/src/utils/tasks/tracker.rs:407,785,890,966 —
scheduling policies via semaphore, error policies incl. retry/cancel-on-
error, continuations, hierarchical child trackers;
``CriticalTaskExecutionHandle`` utils/tasks/critical.rs) and of the
compute pool (reference: lib/runtime/src/compute/pool.rs:76-240 — rayon
offload of blocking compute from async context).

Python/TPU framing: asyncio is the runtime's only event loop, so the
tracker manages ``asyncio.Task``s; the compute pool is a thread pool —
the GIL is irrelevant for its real workload (blocking device transfers,
``np.asarray`` materialization, tokenizer encode on big prompts — all
release the GIL).

- :class:`TaskTracker` — spawn with bounded concurrency (scheduling
  policy), per-task retry policies with exponential backoff (error
  policy), cancel-all teardown, hierarchical children (cancelling a
  parent cancels its subtree), task counters for observability.
- :class:`RetryPolicy` — which exceptions retry, how many attempts,
  backoff shape.
- :func:`TaskTracker.spawn_critical` — a failure beyond retries invokes
  the ``on_fatal`` callback (process shutdown hook), the
  CriticalTaskExecutionHandle contract.
- :class:`ComputePool` — ``await pool.run(fn, *args)`` executes blocking
  work off-loop; bounded queue so unbounded blocking work can't pile up.
"""

from __future__ import annotations

import asyncio
import concurrent.futures
from dataclasses import dataclass, field
from typing import Any, Awaitable, Callable

from dynamo_tpu.utils.logging import get_logger

log = get_logger("tasks")


@dataclass(frozen=True)
class RetryPolicy:
    """Error policy: retry matching failures with exponential backoff
    (reference: tracker.rs error policies)."""

    max_attempts: int = 3
    backoff_base_s: float = 0.1
    backoff_cap_s: float = 5.0
    retry_on: tuple[type[BaseException], ...] = (Exception,)

    def delay(self, attempt: int) -> float:
        return min(self.backoff_base_s * (2 ** (attempt - 1)), self.backoff_cap_s)


@dataclass
class TaskCounts:
    spawned: int = 0
    succeeded: int = 0
    failed: int = 0
    cancelled: int = 0
    retries: int = 0

    def to_dict(self) -> dict:
        return self.__dict__.copy()


class TaskTracker:
    """Hierarchical structured task manager.

    Every coroutine spawned through a tracker is owned by it: closing the
    tracker cancels the whole subtree and awaits it, so background work
    can never outlive the component that started it (the tokio
    JoinSet/tracker discipline the reference enforces, done the asyncio
    way)."""

    def __init__(self, name: str = "root", max_concurrency: int | None = None,
                 parent: "TaskTracker | None" = None):
        self.name = name
        self._sem = (asyncio.Semaphore(max_concurrency)
                     if max_concurrency else None)
        self._tasks: set[asyncio.Task] = set()
        self._children: list[TaskTracker] = []
        self._parent = parent
        self._closed = False
        self.counts = TaskCounts()

    # -- hierarchy ---------------------------------------------------------
    def child(self, name: str, max_concurrency: int | None = None) -> "TaskTracker":
        c = TaskTracker(f"{self.name}/{name}", max_concurrency, parent=self)
        self._children.append(c)
        return c

    # -- spawning ----------------------------------------------------------
    def spawn(self, fn: Callable[..., Awaitable[Any]], *args: Any,
              name: str | None = None, retry: RetryPolicy | None = None,
              ) -> asyncio.Task:
        """Run ``fn(*args)`` under this tracker's scheduling policy.

        The returned task resolves to the coroutine's result; with a
        retry policy, matching failures re-run ``fn`` (fresh coroutine)
        up to ``max_attempts`` with backoff."""
        if self._closed:
            raise RuntimeError(f"tracker {self.name} is closed")
        self.counts.spawned += 1
        task = asyncio.create_task(
            self._run(fn, args, retry), name=name or fn.__qualname__)
        self._tasks.add(task)
        task.add_done_callback(self._on_done)
        return task

    def spawn_critical(self, fn: Callable[..., Awaitable[Any]], *args: Any,
                       on_fatal: Callable[[BaseException], None],
                       name: str | None = None,
                       retry: RetryPolicy | None = None) -> asyncio.Task:
        """A task whose unrecovered failure must not pass silently:
        ``on_fatal(exc)`` runs when it fails beyond any retries
        (reference: CriticalTaskExecutionHandle)."""
        async def critical() -> Any:
            try:
                return await self._run(fn, args, retry)
            except asyncio.CancelledError:
                raise
            except BaseException as exc:  # noqa: BLE001 - handed to on_fatal
                log.error("critical task %s failed: %s", name or fn.__qualname__, exc)
                on_fatal(exc)
                raise

        if self._closed:
            raise RuntimeError(f"tracker {self.name} is closed")
        self.counts.spawned += 1
        task = asyncio.create_task(critical(), name=name or fn.__qualname__)
        self._tasks.add(task)
        task.add_done_callback(self._on_done)
        return task

    async def _attempt(self, fn, args) -> Any:
        """One execution under the scheduling policy (semaphore slot held
        only while the coroutine runs — backoff sleeps never hold one)."""
        if self._sem is not None:
            async with self._sem:
                return await fn(*args)
        return await fn(*args)

    async def _run(self, fn, args, retry: RetryPolicy | None) -> Any:
        attempt = 0
        while True:
            attempt += 1
            try:
                return await self._attempt(fn, args)
            except asyncio.CancelledError:
                raise
            except (retry.retry_on if retry else ()) as exc:
                if attempt >= retry.max_attempts:
                    raise
                self.counts.retries += 1
                log.warning("task %s retry %d/%d after %s: %s", self.name,
                            attempt, retry.max_attempts, type(exc).__name__, exc)
                await asyncio.sleep(retry.delay(attempt))

    def _on_done(self, task: asyncio.Task) -> None:
        self._tasks.discard(task)
        if task.cancelled():
            self.counts.cancelled += 1
        elif task.exception() is not None:
            self.counts.failed += 1
        else:
            self.counts.succeeded += 1

    # -- lifecycle ---------------------------------------------------------
    @property
    def active(self) -> int:
        return len(self._tasks)

    async def join(self) -> None:
        """Wait for all current tasks (and children's) to finish."""
        for c in self._children:
            await c.join()
        while self._tasks:
            await asyncio.gather(*list(self._tasks), return_exceptions=True)

    async def close(self, timeout: float | None = None) -> None:
        """Cancel the subtree and await teardown, bounded by ``timeout``
        (None = wait forever). A task that survives cancellation past the
        deadline (e.g. wedged in a blocking executor call) is abandoned
        with a log line rather than blocking shutdown. Idempotent."""
        self._closed = True
        deadline = (asyncio.get_running_loop().time() + timeout
                    if timeout is not None else None)
        for c in self._children:
            left = (None if deadline is None
                    else max(deadline - asyncio.get_running_loop().time(), 0.0))
            await c.close(left)
        for t in list(self._tasks):
            t.cancel()
        if self._tasks:
            left = (None if deadline is None
                    else max(deadline - asyncio.get_running_loop().time(), 0.01))
            done, pending = await asyncio.wait(list(self._tasks), timeout=left)
            if pending:
                log.warning("tracker %s: abandoning %d task(s) that ignored "
                            "cancellation", self.name, len(pending))
                self._tasks.clear()

    def snapshot(self) -> dict:
        out = {"name": self.name, "active": self.active, **self.counts.to_dict()}
        if self._children:
            out["children"] = [c.snapshot() for c in self._children]
        return out


class ComputePool:
    """Blocking compute off the event loop (reference: compute/pool.rs
    ``execute_sync``). ``max_pending`` bounds admission so a stalled
    consumer can't queue unbounded blocking work."""

    def __init__(self, max_workers: int = 4, max_pending: int = 256,
                 name: str = "compute"):
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=max_workers, thread_name_prefix=name)
        self._admission = asyncio.Semaphore(max_pending)

    async def run(self, fn: Callable[..., Any], *args: Any) -> Any:
        async with self._admission:
            return await asyncio.get_running_loop().run_in_executor(
                self._pool, fn, *args)

    def shutdown(self) -> None:
        self._pool.shutdown(wait=False, cancel_futures=True)
