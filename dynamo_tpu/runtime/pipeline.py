"""Typed operator pipeline: the reusable request/stream DAG.

Fills the role of the reference's pipeline graph
(reference: lib/runtime/src/pipeline.rs:8-60 — Source/Sink/Operator with
``link()`` chaining; node impls pipeline/nodes.rs:1-339). The reference
models forward edges (request transforms) and backward edges (response
transforms) as separate graph links; in Python both directions collapse
into ONE natural shape: an operator is an async generator that receives
the request and a ``next`` callable, transforms the request on the way
in (forward edge), delegates, and transforms/filters/retries the yielded
stream on the way out (backward edge). Cancellation propagates the
async-generator way — closing the outer stream closes every inner one —
so no separate Context plumbing is needed for teardown.

Used by the frontend's routed model pipelines
(components/frontend.py: migration → decode → router) and available to
any component that composes streaming stages.

    # stream direction runs sink→left: Migration (innermost, next to the
    # sink) retries over raw wire dicts; MapOutput decodes for the consumer
    pipe = link(MapOutput(LLMEngineOutput.from_dict),
                Migration(migration_limit=3), sink=router_sink)
    async for item in pipe.generate(req): ...
"""

from __future__ import annotations

import abc
from typing import Any, AsyncIterator, Callable

# A sink-shaped callable: request -> async iterator of items.
NextFn = Callable[[Any], AsyncIterator[Any]]


class Sink(abc.ABC):
    """Terminal stage: turns a request into a stream (the reference's
    ServiceBackend / SegmentSink role)."""

    @abc.abstractmethod
    def generate(self, req: Any) -> AsyncIterator[Any]:
        ...


class Operator(abc.ABC):
    """Mid-pipeline stage. ``generate`` MUST delegate to ``next`` (exactly
    once per attempt — retrying operators may call it again) and may
    transform the request before and each item after."""

    @abc.abstractmethod
    def generate(self, req: Any, next: NextFn) -> AsyncIterator[Any]:
        ...


class FnSink(Sink):
    """Adapt a bare ``req -> async iterator`` callable to the Sink type."""

    def __init__(self, fn: NextFn):
        self._fn = fn

    def generate(self, req: Any) -> AsyncIterator[Any]:
        return self._fn(req)


class Pipeline(Sink):
    """Operators folded onto a sink; itself a Sink, so pipelines nest."""

    def __init__(self, operators: list[Operator], sink: Sink):
        self.operators = list(operators)
        self.sink = sink
        nxt: NextFn = sink.generate
        for op in reversed(self.operators):
            # bind loop variables by default-arg capture
            def nxt(req: Any, _op: Operator = op, _next: NextFn = nxt
                    ) -> AsyncIterator[Any]:
                return _op.generate(req, _next)
        self._entry = nxt

    def generate(self, req: Any) -> AsyncIterator[Any]:
        return self._entry(req)


def link(*stages: Any, sink: Any = None) -> Pipeline:
    """Compose stages left-to-right onto a sink (the reference's ``link()``
    chaining, pipeline.rs:31-42). ``stages`` are Operators; ``sink`` (or
    the last positional stage) is a Sink or a bare request→stream
    callable."""
    stages_l = list(stages)
    if sink is None:
        if not stages_l:
            raise ValueError("link() needs at least a sink")
        sink = stages_l.pop()
    if not isinstance(sink, Sink):
        sink = FnSink(sink)
    for s in stages_l:
        if not isinstance(s, Operator):
            raise TypeError(f"mid-pipeline stage {s!r} is not an Operator")
    return Pipeline(stages_l, sink)


# ---------------------------------------------------------------------------
# General-purpose operators
# ---------------------------------------------------------------------------

class MapRequest(Operator):
    """Forward-edge transform (the reference's forward link)."""

    def __init__(self, fn: Callable[[Any], Any]):
        self._fn = fn

    async def generate(self, req: Any, next: NextFn) -> AsyncIterator[Any]:
        async for item in next(self._fn(req)):
            yield item


class MapOutput(Operator):
    """Backward-edge transform applied to every streamed item."""

    def __init__(self, fn: Callable[[Any], Any]):
        self._fn = fn

    async def generate(self, req: Any, next: NextFn) -> AsyncIterator[Any]:
        async for item in next(req):
            yield self._fn(item)
