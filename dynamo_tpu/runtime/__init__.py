from dynamo_tpu.runtime.runtime import DistributedRuntime, Endpoint, Component, Namespace
from dynamo_tpu.runtime.client import EndpointClient, PushRouter, RouterMode

__all__ = [
    "DistributedRuntime",
    "Namespace",
    "Component",
    "Endpoint",
    "EndpointClient",
    "PushRouter",
    "RouterMode",
]
