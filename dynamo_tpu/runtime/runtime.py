"""DistributedRuntime: process node handle + component model + endpoint serving.

Fills the role of the reference's runtime core
(reference: lib/runtime/src/lib.rs DistributedRuntime, component.rs
Namespace→Component→Endpoint, component/endpoint.rs serve_endpoint,
ingress/push_endpoint.rs):

- One coordinator connection, one primary lease (liveness: lease drop ⇒
  instances vanish ⇒ clients re-route), one data-plane TCP server per
  process serving all endpoints.
- ``serve_endpoint(handler)`` registers the instance in the coordinator KV
  and dispatches incoming CALL frames to the handler — an async generator
  ``handler(request: dict, ctx) -> yields response dicts`` streamed back as
  DATA/END/ERR frames. Cancellation arrives as a CANCEL frame and cancels
  the handler task (graceful drain on shutdown).

Unlike the reference there is no broker hop: callers dial the instance's
advertised address directly (the reference's NATS-push + TCP-callback pair
collapses into one duplex connection — fewer hops, same semantics).
"""

from __future__ import annotations

import asyncio
import os
import random
import socket
import time
from dataclasses import dataclass, field
from typing import Any, AsyncIterator, Callable

from dynamo_tpu.transports.client import CoordinatorClient, Lease
from dynamo_tpu.transports.wire import Frame, MsgpackConnection
from dynamo_tpu.runtime.protocols import EndpointId, Instance, MetricsTarget
from dynamo_tpu.utils.config import RuntimeConfig
from dynamo_tpu.utils.logging import get_logger
from dynamo_tpu.utils.metrics import MetricsRegistry

log = get_logger("runtime")

# handler(request, context) -> async iterator of response payloads
Handler = Callable[[dict, "RequestContext"], AsyncIterator[Any]]


@dataclass
class RequestContext:
    """Per-request context (reference: pipeline/context.rs Context)."""

    request_id: str
    endpoint: str
    cancelled: asyncio.Event = field(default_factory=asyncio.Event)
    trace_headers: dict[str, str] = field(default_factory=dict)
    # QoS: absolute wall-clock deadline (epoch seconds). An expired deadline
    # reads as cancellation so every per-output `is_cancelled()` check in
    # worker/router handlers doubles as mid-stream deadline enforcement.
    deadline_ts: float | None = None

    def is_expired(self) -> bool:
        return self.deadline_ts is not None and time.time() >= self.deadline_ts

    def is_cancelled(self) -> bool:
        return self.cancelled.is_set() or self.is_expired()


@dataclass
class _Served:
    endpoint: EndpointId
    handler: Handler
    instance: Instance


class DistributedRuntime:
    """Node handle: coordinator client + lease + data-plane server."""

    def __init__(self, config: RuntimeConfig | None = None):
        self.config = config or RuntimeConfig.from_settings()
        self.client: CoordinatorClient | None = None
        self.primary_lease: Lease | None = None
        self.instance_id: int = (int(time.time() * 1000) << 16) | (os.getpid() & 0xFFFF)
        self.metrics = MetricsRegistry()
        self._served: dict[str, _Served] = {}   # "ns.comp.ep" -> served
        self._server: asyncio.Server | None = None
        self._advertise_host = "127.0.0.1"
        self.data_port: int = 0
        self._inflight = self.metrics.gauge("runtime_inflight_requests", "in-flight handler streams")
        # Structured ownership of every background coroutine this node runs
        # (reference: utils/tasks/tracker.rs): handler streams live in a
        # bounded child; components hang their own children off `tasks`.
        from dynamo_tpu.runtime.tasks import TaskTracker

        self.tasks = TaskTracker(name=f"rt{os.getpid()}")
        self._streams = self.tasks.child(
            "streams", max_concurrency=self.config.max_handler_streams)
        self._draining = False
        self._reconnect_hooks: list = []
        self._metrics_targets: dict[str, MetricsTarget] = {}
        # Per-process system status server (reference:
        # system_status_server.rs), env-gated DYN_SYSTEM_ENABLED/PORT.
        self.status_server = None

    # ------------------------------------------------------------------
    @classmethod
    async def create(cls, config: RuntimeConfig | None = None) -> "DistributedRuntime":
        rt = cls(config)
        rt.client = await CoordinatorClient.connect(
            rt.config.coordinator_url, auto_reconnect=True)
        rt.client.on_reconnected.append(rt._restore_registrations)
        rt.primary_lease = await rt.client.lease_grant(ttl=rt.config.lease_ttl_s)
        # Lease death WITHOUT a connection outage (keepalive starvation /
        # expiry storm) also means every lease-bound key is gone — recover
        # through the same re-declaration path as a reconnect.
        rt.primary_lease.on_lost = rt._restore_registrations
        # Coordinator lease ids are server-unique — mixing one in makes
        # instance ids collision-free even for runtimes created in the same
        # millisecond in the same process.
        rt.instance_id = (int(time.time() * 1000) << 20) | (rt.primary_lease.id & 0xFFFFF)
        rt._server = await asyncio.start_server(rt._on_conn, "0.0.0.0", 0)
        rt.data_port = rt._server.sockets[0].getsockname()[1]
        rt._advertise_host = os.environ.get("DYN_ADVERTISE_HOST", "127.0.0.1")
        if rt.config.system_enabled:
            from dynamo_tpu.runtime.status import SystemStatusServer

            rt.status_server = SystemStatusServer(rt.metrics, rt.config.system_port)
            await rt.status_server.start()
        return rt

    @property
    def inflight_streams(self) -> int:
        """Handler streams currently running (drain run-down watches this)."""
        return self._streams.active

    async def deregister(self, timeout: float = 3.0) -> None:
        """Membership out, lease and data plane STAY ALIVE: readiness goes
        NotReady, new streams are refused (clients re-route via Migration),
        and the endpoint instance + metrics-target keys are deleted so
        routers stop picking this worker — while in-flight streams keep
        their open connections. The drain protocol's step 2
        (runtime/drain.py); ``shutdown()`` later revokes the lease, which
        also sweeps these keys if the coordinator was unreachable here."""
        self._draining = True
        if self.status_server is not None:
            self.status_server.ready = False
        if self.client is None:
            return
        keys = [s.endpoint.instance_key(self.instance_id)
                for s in self._served.values()]
        keys += list(self._metrics_targets)
        for key in keys:
            try:
                # Bounded per-key: a partitioned coordinator must not eat
                # the drain window — lease expiry deletes these anyway.
                await asyncio.wait_for(self.client.delete(key), timeout)
            except Exception:
                log.warning("deregister: could not delete %s "
                            "(lease expiry will)", key)

    async def shutdown(self) -> None:
        """Graceful: deregister instances, drain in-flight, drop lease."""
        self._draining = True
        if self.status_server is not None:
            # NotReady (503) during the drain window — but keep SERVING
            # probes until the drain completes, else a kubelet reads
            # connection-refused as dead and SIGKILLs mid-drain.
            self.status_server.ready = False
        if self.client:
            for served in self._served.values():
                try:
                    await asyncio.wait_for(self.client.delete(
                        served.endpoint.instance_key(self.instance_id)), 3.0)
                except Exception:
                    # Partitioned coordinator: the lease sweep below (or its
                    # TTL expiry server-side) removes the key regardless.
                    log.warning("shutdown: instance deregistration skipped "
                                "(coordinator unreachable)")
        deadline = time.monotonic() + self.config.drain_timeout_s
        while self._streams.active and time.monotonic() < deadline:
            await asyncio.sleep(0.05)
        # Bounded teardown: drain_timeout_s caps the WHOLE shutdown — a
        # handler wedged past cancellation is abandoned, not waited on.
        await self.tasks.close(
            timeout=max(deadline - time.monotonic(), 1.0))
        if self.status_server is not None:
            await self.status_server.stop()
        if self.primary_lease and self.client:
            try:
                # Partition-safe: an unreachable coordinator must not wedge
                # process exit — the lease TTL expires server-side instead.
                await asyncio.wait_for(
                    self.primary_lease.revoke(self.client), 3.0)
            except Exception:
                log.warning("lease revoke skipped (coordinator unreachable);"
                            " TTL expiry will reclaim it")
        if self._server:
            self._server.close()
        if self.client:
            await self.client.close()

    async def _restore_registrations(self) -> None:
        """After a coordinator reconnect (possibly a RESTARTED coordinator
        with empty state): leases are gone — grant a fresh primary lease and
        re-put every served instance under it (same instance_id: identity is
        stable across outages), then run component-level hooks (model cards
        etc.). The reference gets this durability from etcd itself; our
        built-in coordinator gets it from clients re-declaring their state."""
        assert self.client is not None
        if self.primary_lease is not None:
            # Client-side-only blip (coordinator survived, lease TTL not yet
            # expired): the lease AND every key bound to it are intact —
            # reuse it (revoking would broadcast deletes and churn every
            # frontend's pipelines for nothing). Just restart the keepalive,
            # whose loop died with the old connection. Gated on epoch
            # continuity: a RESTARTED coordinator re-mints lease ids from 1,
            # so a bare keepalive probe could "renew" a DIFFERENT client's
            # lease and skip re-declaration entirely.
            try:
                alive = (not self.client.epoch_changed
                         and (await self.client._request(
                             {"op": "lease_keepalive",
                              "lease_id": self.primary_lease.id})).get("alive"))
            except Exception:
                alive = False
            if alive:
                if self.primary_lease._task:
                    self.primary_lease._task.cancel()
                self.primary_lease._task = asyncio.create_task(
                    self.client._keepalive_loop(self.primary_lease))
                log.info("coordinator blip: primary lease %d survived; "
                         "registrations intact", self.primary_lease.id)
                return
            # Lease is gone (expired, or the coordinator restarted): stop
            # the orphaned keepalive and re-declare everything fresh. When
            # on_lost delivered us FROM that keepalive task, cancelling it
            # would abort this very restore mid-flight (new lease granted but
            # never re-put, never kept alive) — the loop returns on its own
            # after the callback, so only cancel a foreign task.
            if (self.primary_lease._task is not None
                    and self.primary_lease._task is not asyncio.current_task()):
                self.primary_lease._task.cancel()
        self.primary_lease = await self.client.lease_grant(
            ttl=self.config.lease_ttl_s)
        self.primary_lease.on_lost = self._restore_registrations
        import dataclasses as _dc

        for served in self._served.values():
            served.instance = _dc.replace(
                served.instance, lease_id=self.primary_lease.id)
            await self.client.put(
                served.endpoint.instance_key(self.instance_id),
                served.instance.to_bytes(),
                lease_id=self.primary_lease.id)
        for target in self._metrics_targets.values():
            await self.client.put(target.key, target.to_bytes(),
                                  lease_id=self.primary_lease.id)
        log.info("re-registered %d endpoint(s) after coordinator reconnect",
                 len(self._served))
        for hook in list(self._reconnect_hooks):
            try:
                await hook()
            except Exception:
                log.exception("reconnect hook failed")

    def on_reconnect(self, hook) -> None:
        """Register an async callback run after coordinator reconnection +
        instance re-registration (components re-put model cards here)."""
        self._reconnect_hooks.append(hook)

    async def advertise_metrics(self, role: str, url: str | None = None) -> "MetricsTarget | None":
        """Publish this process's /metrics URL under METRICS_PREFIX, bound
        to the primary lease, so the fleet aggregator discovers it without
        static target lists. ``url=None`` advertises the status server (a
        no-op when DYN_SYSTEM_ENABLED is off — nothing to scrape)."""
        assert self.client and self.primary_lease
        if url is None:
            if self.status_server is None:
                return None
            url = f"http://{self._advertise_host}:{self.status_server.port}"
        target = MetricsTarget(role=role, instance_id=self.instance_id,
                               url=url, namespace=self.config.namespace)
        self._metrics_targets[target.key] = target
        await self.client.put(target.key, target.to_bytes(),
                              lease_id=self.primary_lease.id)
        log.info("advertised %s metrics target %s", role, url)
        return target

    @property
    def advertise_address(self) -> str:
        """The 'host:port' other processes dial to reach this node's data
        plane (what Instance.address is built from)."""
        return f"{self._advertise_host}:{self.data_port}"

    def namespace(self, name: str) -> "Namespace":
        return Namespace(self, name)

    # ------------------------------------------------------------------
    async def _register(self, endpoint: EndpointId, handler: Handler) -> Instance:
        assert self.client and self.primary_lease
        inst = Instance(
            endpoint=endpoint,
            instance_id=self.instance_id,
            address=f"{self._advertise_host}:{self.data_port}",
            lease_id=self.primary_lease.id,
        )
        key = str(endpoint)[len("dyn://"):]
        self._served[key] = _Served(endpoint=endpoint, handler=handler, instance=inst)
        await self.client.put(
            endpoint.instance_key(self.instance_id), inst.to_bytes(),
            lease_id=self.primary_lease.id)
        log.info("serving %s instance=%x at %s", endpoint, self.instance_id, inst.address)
        return inst

    # ------------------------------------------------------------------
    async def _on_conn(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter) -> None:
        conn = MsgpackConnection(reader, writer)
        streams: dict[int, asyncio.Task] = {}
        try:
            while True:
                msg = await conn.recv()
                if msg is None:
                    break
                t = msg.get("t")
                if t == Frame.PING:
                    await conn.send({"t": Frame.PONG})
                elif t == Frame.CALL:
                    sid = msg["stream_id"]
                    try:
                        task = self._streams.spawn(
                            self._run_stream, conn, sid, msg,
                            name=f"stream-{msg.get('endpoint', '?')}-{sid}")
                    except RuntimeError:
                        # Tracker already closed (shutdown race): refuse THIS
                        # stream, keep the multiplexed connection alive for
                        # its in-flight siblings.
                        await conn.send({"t": Frame.ERR, "stream_id": sid,
                                         "error": "shutting down"})
                        continue
                    streams[sid] = task
                    task.add_done_callback(
                        lambda t_, sid=sid: streams.pop(sid, None))
                elif t == Frame.CANCEL:
                    task = streams.get(msg.get("stream_id"))
                    if task:
                        task.cancel()
        finally:
            for task in streams.values():
                task.cancel()
            conn.close()

    async def _run_stream(self, conn: MsgpackConnection, sid: int, msg: dict) -> None:
        target = msg.get("endpoint", "")
        served = self._served.get(target)
        if served is None or self._draining:
            await conn.send({"t": Frame.ERR, "stream_id": sid,
                             "error": f"no such endpoint {target!r}"})
            return
        ctx = RequestContext(
            request_id=msg.get("request_id", ""),
            endpoint=target,
            trace_headers=msg.get("headers") or {},
        )
        try:
            # Deadline propagation for generic endpoints: LLM requests carry
            # it in payload annotations (the worker handler re-stamps ctx),
            # anything else can use this wire header.
            hdr = ctx.trace_headers.get("x-deadline-ts")
            if hdr is not None:
                ctx.deadline_ts = float(hdr)
        except (TypeError, ValueError):
            pass
        self._inflight.inc(endpoint=target)
        try:
            async for item in served.handler(msg.get("payload"), ctx):
                await conn.send({"t": Frame.DATA, "stream_id": sid, "payload": item})
            await conn.send({"t": Frame.END, "stream_id": sid})
        except asyncio.CancelledError:
            ctx.cancelled.set()
            try:
                await conn.send({"t": Frame.END, "stream_id": sid, "cancelled": True})
            except Exception:
                pass
        except Exception as exc:
            log.exception("handler error endpoint=%s", target)
            try:
                await conn.send({"t": Frame.ERR, "stream_id": sid, "error": str(exc)})
            except Exception:
                pass
        finally:
            self._inflight.inc(-1, endpoint=target)


@dataclass
class Namespace:
    runtime: DistributedRuntime
    name: str

    def component(self, name: str) -> "Component":
        return Component(self.runtime, self.name, name)


@dataclass
class Component:
    runtime: DistributedRuntime
    namespace: str
    name: str

    def endpoint(self, name: str) -> "Endpoint":
        return Endpoint(self.runtime, EndpointId(self.namespace, self.name, name))


@dataclass
class Endpoint:
    runtime: DistributedRuntime
    id: EndpointId

    async def serve(self, handler: Handler) -> Instance:
        """Register and serve this endpoint (reference: serve_endpoint)."""
        return await self.runtime._register(self.id, handler)

    async def client(self) -> "EndpointClient":
        from dynamo_tpu.runtime.client import EndpointClient

        return await EndpointClient.create(self.runtime, self.id)
