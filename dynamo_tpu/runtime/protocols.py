"""Endpoint addressing: ``dyn://namespace.component.endpoint``.

Reference: lib/runtime/src/protocols.rs (EndpointId parse) and the etcd path
layout in component.rs (INSTANCE_ROOT_PATH).
"""

from __future__ import annotations

import json
from dataclasses import dataclass

INSTANCE_PREFIX = "dyn/instances"
MODEL_PREFIX = "dyn/models"
METRICS_PREFIX = "dyn/metrics"


@dataclass(frozen=True)
class EndpointId:
    namespace: str
    component: str
    endpoint: str

    @classmethod
    def parse(cls, s: str) -> "EndpointId":
        s = s.removeprefix("dyn://")
        parts = s.split(".")
        if len(parts) != 3 or not all(parts):
            raise ValueError(f"bad endpoint id {s!r}; want ns.component.endpoint")
        return cls(*parts)

    def __str__(self) -> str:
        return f"dyn://{self.namespace}.{self.component}.{self.endpoint}"

    @property
    def instance_prefix(self) -> str:
        return f"{INSTANCE_PREFIX}/{self.namespace}/{self.component}/{self.endpoint}/"

    def instance_key(self, instance_id: int) -> str:
        return f"{self.instance_prefix}{instance_id:016x}"


@dataclass(frozen=True)
class MetricsTarget:
    """A scrapeable /metrics endpoint, registered under METRICS_PREFIX and
    bound to its owner's primary lease: lease death deletes the key, so the
    fleet aggregator's discovery view tracks process liveness with no
    static target lists (reference: the Prometheus service-discovery role
    etcd registration plays for the reference metrics aggregator)."""

    role: str           # "frontend" | "worker" | "router" | ...
    instance_id: int
    url: str            # http base URL; <url>/metrics serves the exposition
    namespace: str = ""

    @property
    def key(self) -> str:
        return f"{METRICS_PREFIX}/{self.namespace}/{self.role}/{self.instance_id:016x}"

    @property
    def instance(self) -> str:
        """Stable per-target label value for the fleet exposition."""
        return self.url.split("//", 1)[-1].rstrip("/")

    def to_bytes(self) -> bytes:
        return json.dumps({
            "role": self.role,
            "instance_id": self.instance_id,
            "url": self.url,
            "namespace": self.namespace,
        }).encode()

    @classmethod
    def from_bytes(cls, data: bytes) -> "MetricsTarget":
        d = json.loads(data)
        return cls(role=d["role"], instance_id=d["instance_id"],
                   url=d["url"], namespace=d.get("namespace", ""))


@dataclass(frozen=True)
class Instance:
    """A live endpoint instance (reference: component.rs Instance)."""

    endpoint: EndpointId
    instance_id: int
    address: str        # host:port of the worker's data-plane server
    lease_id: int = 0

    def to_bytes(self) -> bytes:
        return json.dumps({
            "namespace": self.endpoint.namespace,
            "component": self.endpoint.component,
            "endpoint": self.endpoint.endpoint,
            "instance_id": self.instance_id,
            "address": self.address,
            "lease_id": self.lease_id,
        }).encode()

    @classmethod
    def from_bytes(cls, data: bytes) -> "Instance":
        d = json.loads(data)
        return cls(
            endpoint=EndpointId(d["namespace"], d["component"], d["endpoint"]),
            instance_id=d["instance_id"],
            address=d["address"],
            lease_id=d.get("lease_id", 0),
        )
