"""Endpoint client: discovery-watching instance source + push router.

Fills the role of the reference's Client/InstanceSource + PushRouter
(reference: lib/runtime/src/component/client.rs InstanceSource;
pipeline/network/egress/push_router.rs Random/RoundRobin/Direct/KV modes
with busy-threshold): a prefix watch keeps the live instance set current
(lease expiry ⇒ DELETE event ⇒ instance drops out), and ``generate`` opens
a response stream over a pooled duplex connection straight to the chosen
worker.
"""

from __future__ import annotations

import asyncio
import itertools
import random
import uuid
from dataclasses import dataclass, field
from enum import Enum
from typing import Any, AsyncIterator

from dynamo_tpu.runtime.protocols import EndpointId, Instance
from dynamo_tpu.runtime.runtime import DistributedRuntime
from dynamo_tpu.transports.wire import Frame, MsgpackConnection
from dynamo_tpu.utils.logging import get_logger

log = get_logger("runtime.client")


class RouterMode(str, Enum):
    RANDOM = "random"
    ROUND_ROBIN = "round_robin"
    DIRECT = "direct"
    KV = "kv"


class NoInstancesError(RuntimeError):
    pass


class StreamError(RuntimeError):
    pass


class _WorkerConnection:
    """Multiplexed duplex connection to one worker address."""

    def __init__(self, conn: MsgpackConnection):
        self.conn = conn
        self._ids = itertools.count(1)
        self._streams: dict[int, asyncio.Queue] = {}
        self._reader = asyncio.create_task(self._read_loop())
        self.alive = True

    async def _read_loop(self) -> None:
        while True:
            msg = await self.conn.recv()
            if msg is None:
                break
            q = self._streams.get(msg.get("stream_id"))
            if q is not None:
                q.put_nowait(msg)
        self.alive = False
        for q in self._streams.values():
            q.put_nowait({"t": Frame.ERR, "error": "connection lost"})

    async def call(self, endpoint: str, payload: Any, request_id: str,
                   headers: dict | None = None) -> AsyncIterator[Any]:
        sid = next(self._ids)
        q: asyncio.Queue = asyncio.Queue()
        self._streams[sid] = q
        await self.conn.send({
            "t": Frame.CALL, "stream_id": sid, "endpoint": endpoint,
            "request_id": request_id, "payload": payload, "headers": headers or {},
        })
        try:
            while True:
                msg = await q.get()
                t = msg.get("t")
                if t == Frame.DATA:
                    yield msg.get("payload")
                elif t == Frame.END:
                    return
                elif t == Frame.ERR:
                    raise StreamError(msg.get("error", "stream error"))
        finally:
            self._streams.pop(sid, None)
            if self.alive:
                try:
                    await self.conn.send({"t": Frame.CANCEL, "stream_id": sid})
                except Exception:
                    pass

    def close(self) -> None:
        self._reader.cancel()
        self.conn.close()


class EndpointClient:
    """Watches instances of one endpoint and routes requests to them."""

    def __init__(self, runtime: DistributedRuntime, endpoint: EndpointId):
        self.runtime = runtime
        self.endpoint = endpoint
        self.instances: dict[int, Instance] = {}
        self._conns: dict[str, _WorkerConnection] = {}
        self._watch_task: asyncio.Task | None = None
        self._rr = itertools.count()
        self._ready = asyncio.Event()

    @classmethod
    async def create(cls, runtime: DistributedRuntime, endpoint: EndpointId) -> "EndpointClient":
        self = cls(runtime, endpoint)
        assert runtime.client is not None
        watch = await runtime.client.watch_prefix(endpoint.instance_prefix)
        self._watch_task = asyncio.create_task(self._watch_loop(watch))
        return self

    async def _watch_loop(self, watch) -> None:
        async for ev in watch:
            if ev.op == "put" and ev.value:
                inst = Instance.from_bytes(ev.value)
                self.instances[inst.instance_id] = inst
                self._ready.set()
            elif ev.op == "delete":
                iid = int(ev.key.rsplit("/", 1)[-1], 16)
                inst = self.instances.pop(iid, None)
                if inst is not None:
                    log.info("instance %x of %s vanished", iid, self.endpoint)
            if not self.instances:
                self._ready.clear()

    async def wait_for_instances(self, timeout: float = 10.0) -> None:
        await asyncio.wait_for(self._ready.wait(), timeout)

    def instance_ids(self) -> list[int]:
        return sorted(self.instances)

    # ------------------------------------------------------------------
    async def _connect(self, inst: Instance) -> _WorkerConnection:
        wc = self._conns.get(inst.address)
        if wc is not None and wc.alive:
            return wc
        host, _, port = inst.address.rpartition(":")
        wc = _WorkerConnection(await MsgpackConnection.connect(host, int(port)))
        self._conns[inst.address] = wc
        return wc

    async def generate_direct(self, payload: Any, instance_id: int,
                              request_id: str | None = None) -> AsyncIterator[Any]:
        inst = self.instances.get(instance_id)
        if inst is None:
            raise NoInstancesError(f"instance {instance_id:x} not found for {self.endpoint}")
        wc = await self._connect(inst)
        target = f"{self.endpoint.namespace}.{self.endpoint.component}.{self.endpoint.endpoint}"
        async for item in wc.call(target, payload, request_id or uuid.uuid4().hex):
            yield item

    async def close(self) -> None:
        if self._watch_task:
            self._watch_task.cancel()
        for wc in self._conns.values():
            wc.close()


@dataclass
class PushRouter:
    """Instance selection policies over an EndpointClient
    (reference: push_router.rs RouterMode + busy-threshold fallback)."""

    client: EndpointClient
    mode: RouterMode = RouterMode.ROUND_ROBIN
    # KV mode is provided by dynamo_tpu.router.KvPushRouter (subclass wiring)

    def _pick(self) -> int:
        ids = self.client.instance_ids()
        if not ids:
            raise NoInstancesError(f"no instances for {self.client.endpoint}")
        if self.mode is RouterMode.RANDOM:
            return random.choice(ids)
        return ids[next(self.client._rr) % len(ids)]

    async def generate(self, payload: Any, request_id: str | None = None,
                       instance_id: int | None = None) -> AsyncIterator[Any]:
        iid = instance_id if instance_id is not None else self._pick()
        async for item in self.client.generate_direct(payload, iid, request_id):
            yield item
