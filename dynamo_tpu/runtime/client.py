"""Endpoint client: discovery-watching instance source + push router.

Fills the role of the reference's Client/InstanceSource + PushRouter
(reference: lib/runtime/src/component/client.rs InstanceSource;
pipeline/network/egress/push_router.rs Random/RoundRobin/Direct/KV modes
with busy-threshold): a prefix watch keeps the live instance set current
(lease expiry ⇒ DELETE event ⇒ instance drops out), and ``generate`` opens
a response stream over a pooled duplex connection straight to the chosen
worker.
"""

from __future__ import annotations

import asyncio
import itertools
import random
import uuid
from dataclasses import dataclass, field
from enum import Enum
from typing import Any, AsyncIterator

from dynamo_tpu import chaos
from dynamo_tpu.runtime.protocols import EndpointId, Instance
from dynamo_tpu.runtime.runtime import DistributedRuntime
from dynamo_tpu.transports.wire import Frame, MsgpackConnection
from dynamo_tpu.utils.logging import get_logger

log = get_logger("runtime.client")


class RouterMode(str, Enum):
    RANDOM = "random"
    ROUND_ROBIN = "round_robin"
    DIRECT = "direct"
    KV = "kv"


class NoInstancesError(RuntimeError):
    pass


class StreamError(RuntimeError):
    """A response stream broke (worker ERR frame or lost connection).

    Carries the ``instance_id`` that was serving the stream (when known) so
    recovery layers can act on the FAILING worker — Migration quarantines
    it before re-dispatch instead of racing the lease-expiry watch and
    re-picking the same dead instance."""

    def __init__(self, message: str = "stream error",
                 instance_id: int | None = None):
        super().__init__(message)
        self.instance_id = instance_id


class _WorkerConnection:
    """Multiplexed duplex connection to one worker address."""

    def __init__(self, conn: MsgpackConnection):
        self.conn = conn
        self._ids = itertools.count(1)
        self._streams: dict[int, asyncio.Queue] = {}
        self._reader = asyncio.create_task(self._read_loop())
        self.alive = True
        self._closing = False

    async def _read_loop(self) -> None:
        try:
            while True:
                msg = await self.conn.recv()
                if msg is None:
                    break
                q = self._streams.get(msg.get("stream_id"))
                if q is not None:
                    q.put_nowait(msg)
        except Exception as exc:  # corrupt frame, unpack error, socket error
            log.warning("worker connection reader failed: %s", exc)
        finally:
            # Always mark dead + poison in-flight streams so no caller blocks
            # forever and _connect() dials a fresh connection next time.
            self.alive = False
            self.conn.close()
            for q in self._streams.values():
                q.put_nowait({"t": Frame.ERR, "error": "connection lost"})

    async def call(self, endpoint: str, payload: Any, request_id: str,
                   headers: dict | None = None) -> AsyncIterator[Any]:
        await chaos.ainject("runtime.client.call", endpoint=endpoint)
        if self._closing:
            raise StreamError("connection closing")
        sid = next(self._ids)
        q: asyncio.Queue = asyncio.Queue()
        self._streams[sid] = q
        await self.conn.send({
            "t": Frame.CALL, "stream_id": sid, "endpoint": endpoint,
            "request_id": request_id, "payload": payload, "headers": headers or {},
        })
        try:
            while True:
                msg = await q.get()
                t = msg.get("t")
                if t == Frame.DATA:
                    yield msg.get("payload")
                elif t == Frame.END:
                    return
                elif t == Frame.ERR:
                    raise StreamError(msg.get("error", "stream error"))
        finally:
            self._streams.pop(sid, None)
            if self.alive:
                try:
                    await self.conn.send({"t": Frame.CANCEL, "stream_id": sid})
                except Exception:
                    pass
            if self._closing and not self._streams:
                self.close()

    def close(self) -> None:
        self._reader.cancel()
        self.conn.close()

    def close_when_idle(self) -> None:
        """Refuse new streams and close once in-flight ones end. A model
        being unregistered (its last worker deregistered to drain) must not
        cut responses already streaming — the draining worker keeps its
        lease and data plane alive precisely so they can finish."""
        self._closing = True
        if not self._streams:
            self.close()


class EndpointClient:
    """Watches instances of one endpoint and routes requests to them."""

    def __init__(self, runtime: DistributedRuntime, endpoint: EndpointId):
        self.runtime = runtime
        self.endpoint = endpoint
        self.instances: dict[int, Instance] = {}
        self._conns: dict[str, _WorkerConnection] = {}
        self._watch_task: asyncio.Task | None = None
        # instance_id -> monotonic time until which it is skipped (connect
        # failures quarantine an instance until its lease expires or it
        # re-registers — avoids burning retries on a dead address)
        self._quarantine: dict[int, float] = {}

    @classmethod
    async def create(cls, runtime: DistributedRuntime, endpoint: EndpointId) -> "EndpointClient":
        self = cls(runtime, endpoint)
        assert runtime.client is not None
        watch = await runtime.client.watch_prefix(endpoint.instance_prefix)
        self._watch_task = asyncio.create_task(self._watch_loop(watch))
        return self

    async def _watch_loop(self, watch) -> None:
        async for ev in watch:
            if ev.op == "reset":
                # coordinator reconnect: the replay that follows is the
                # complete truth — instances that died during the outage
                # would otherwise linger forever
                if self.instances:
                    log.info("instance set for %s reset on reconnect (%d dropped)",
                             self.endpoint, len(self.instances))
                self.instances.clear()
            elif ev.op == "put" and ev.value:
                inst = Instance.from_bytes(ev.value)
                self.instances[inst.instance_id] = inst
                self._quarantine.pop(inst.instance_id, None)
            elif ev.op == "delete":
                iid = int(ev.key.rsplit("/", 1)[-1], 16)
                inst = self.instances.pop(iid, None)
                if inst is not None:
                    log.info("instance %x of %s vanished", iid, self.endpoint)
        log.warning("instance watch for %s ended (coordinator lost)", self.endpoint)

    async def wait_for_instances(self, timeout: float = 10.0) -> None:
        """Wait until at least one non-quarantined instance is known."""
        deadline = asyncio.get_running_loop().time() + timeout
        while not self.instance_ids():
            if asyncio.get_running_loop().time() >= deadline:
                raise asyncio.TimeoutError(f"no live instances for {self.endpoint}")
            await asyncio.sleep(0.05)

    def instance_ids(self) -> list[int]:
        now = asyncio.get_event_loop().time()
        return sorted(i for i in self.instances if self._quarantine.get(i, 0.0) <= now)

    def known_instance_ids(self) -> list[int]:
        """All registered instances, including quarantined ones. Use for
        liveness decisions (a quarantined instance is still discovered —
        only a lease expiry actually removes it)."""
        return sorted(self.instances)

    def quarantine(self, instance_id: int, duration_s: float = 10.0) -> None:
        """Skip ``instance_id`` in routing for ``duration_s`` (or until it
        re-registers, whichever comes first). Called on connect failures and
        by Migration when a stream dies on a specific worker — routing away
        immediately instead of racing the lease-expiry watch."""
        self._quarantine[instance_id] = (
            asyncio.get_event_loop().time() + duration_s)
        log.info("instance %x quarantined for %.1fs", instance_id, duration_s)

    # ------------------------------------------------------------------
    async def _connect(self, inst: Instance) -> _WorkerConnection:
        await chaos.ainject("runtime.client.connect", address=inst.address)
        wc = self._conns.get(inst.address)
        if wc is not None and wc.alive:
            return wc
        host, _, port = inst.address.rpartition(":")
        wc = _WorkerConnection(await MsgpackConnection.connect(host, int(port)))
        self._conns[inst.address] = wc
        return wc

    async def generate_direct(self, payload: Any, instance_id: int,
                              request_id: str | None = None) -> AsyncIterator[Any]:
        inst = self.instances.get(instance_id)
        if inst is None:
            raise NoInstancesError(f"instance {instance_id:x} not found for {self.endpoint}")
        try:
            wc = await self._connect(inst)
        except OSError:
            self.quarantine(instance_id)
            log.info("instance %x unreachable", instance_id)
            raise
        target = f"{self.endpoint.namespace}.{self.endpoint.component}.{self.endpoint.endpoint}"
        try:
            async for item in wc.call(target, payload, request_id or uuid.uuid4().hex):
                yield item
        except StreamError as exc:
            # Stamp the failing worker so recovery (Migration) can act on
            # it; wire-level ERR frames can't know their own instance.
            if exc.instance_id is None:
                exc.instance_id = instance_id
            raise

    async def close(self, graceful: bool = True) -> None:
        """Stop watching and release connections. Graceful (default) lets
        each connection's in-flight streams run to completion before it
        closes; ``graceful=False`` cuts them immediately (poisoning their
        queues with a connection-lost ERR)."""
        if self._watch_task:
            self._watch_task.cancel()
        for wc in self._conns.values():
            if graceful:
                wc.close_when_idle()
            else:
                wc.close()


@dataclass
class PushRouter:
    """Instance selection policies over an EndpointClient
    (reference: push_router.rs RouterMode + busy-threshold fallback).
    KV mode lives in dynamo_tpu.router.KvPushRouter."""

    client: EndpointClient
    mode: RouterMode = RouterMode.ROUND_ROBIN
    _rr: "itertools.count" = field(default_factory=itertools.count)

    def _pick(self) -> int:
        ids = self.client.instance_ids()
        if not ids:
            raise NoInstancesError(f"no instances for {self.client.endpoint}")
        if self.mode is RouterMode.RANDOM:
            return random.choice(ids)
        return ids[next(self._rr) % len(ids)]

    def pick(self) -> int:
        """Resolve the instance this policy would dispatch to NOW. Callers
        that need the id *before* streaming (so recovery layers can
        attribute a silent truncation to the serving worker) pick here and
        pass it to generate() explicitly."""
        return self._pick()

    async def generate(self, payload: Any, request_id: str | None = None,
                       instance_id: int | None = None) -> AsyncIterator[Any]:
        if instance_id is None:
            if self.mode is RouterMode.DIRECT:
                raise ValueError("RouterMode.DIRECT requires an explicit instance_id")
            instance_id = self._pick()
        async for item in self.client.generate_direct(payload, instance_id, request_id):
            yield item
