"""Leader-worker barrier: multi-process rendezvous over the coordinator.

Fills the role of the reference's etcd leader-worker barrier
(reference: lib/runtime/src/utils/leader_worker_barrier.rs:14-50 — the
leader posts data under a barrier id and waits for N workers to check in;
workers post themselves and wait for the leader's ``complete`` key).

Used wherever N processes must meet before proceeding (multi-host engine
bring-up, KVBM leader/worker handshakes). Keys live under
``barrier/{id}/...`` and are lease-bound when a lease id is given, so a
crashed participant's state evaporates with its lease.

Re-run safety: each leader run stamps a fresh generation token into the
data key, check-ins carry the token of the data they saw, and the leader
counts only current-generation check-ins — so stale check-ins can never
satisfy a new leader early, and a leader restart mid-rendezvous makes
workers re-check-in against the new generation. The leader also deletes
leftover data/complete keys from a finished prior run before starting.
One window remains open by construction: a worker that registers its
watch while a COMPLETED prior run's keys still exist (leader of the new
run not yet started) sees a self-consistent stale data+complete pair and
returns the old payload — bind keys to leases (``lease_id``) so a dead
run's keys evaporate, or use a fresh ``barrier_id`` per rendezvous, to
close it. Waits are watch-driven (the coordinator replays current state
into a new watch, then pushes events), not polled.
"""

from __future__ import annotations

import asyncio
import json
import uuid
from typing import Any

from dynamo_tpu.utils.logging import get_logger

log = get_logger("barrier")

ROOT = "barrier"


class BarrierTimeout(TimeoutError):
    pass


async def leader_barrier(client, barrier_id: str, num_workers: int,
                         data: Any = None, timeout: float = 120.0,
                         lease_id: int = 0) -> list[str]:
    """Leader side: publish ``data``, wait for ``num_workers`` check-ins,
    then post the completion marker. Returns the worker names seen."""
    gen = uuid.uuid4().hex
    # Clear a finished prior run's markers so late-registering workers
    # can't be released by them once this run's data key lands.
    await client.delete(f"{ROOT}/{barrier_id}/complete")
    await client.put(f"{ROOT}/{barrier_id}/data",
                     json.dumps({"gen": gen, "payload": data}).encode(),
                     lease_id)
    prefix = f"{ROOT}/{barrier_id}/workers/"
    watch = await client.watch_prefix(prefix)
    seen: set[str] = set()

    async def wait_for_workers() -> None:
        if len(seen) >= num_workers:  # trivially complete (num_workers == 0)
            return
        async for ev in watch:
            if ev.op == "put" and ev.value == gen.encode():
                seen.add(ev.key[len(prefix):])
                if len(seen) >= num_workers:
                    return
        raise ConnectionError("coordinator watch ended during barrier")

    try:
        await asyncio.wait_for(wait_for_workers(), timeout)
    except asyncio.TimeoutError:
        raise BarrierTimeout(
            f"barrier {barrier_id!r}: {len(seen)}/{num_workers} workers "
            f"within {timeout}s ({sorted(seen)})") from None
    finally:
        await watch.cancel()
    await client.put(f"{ROOT}/{barrier_id}/complete", gen.encode(), lease_id)
    return sorted(seen)


async def worker_barrier(client, barrier_id: str, worker_name: str,
                         timeout: float = 120.0, lease_id: int = 0) -> Any:
    """Worker side: wait for the leader's data, check in against its
    generation, wait for the matching completion marker, and return the
    leader's published payload. A leader restart mid-wait (new generation
    appearing on the data key) triggers a re-check-in, so the rendezvous
    survives the race instead of deadlocking until timeout."""
    prefix = f"{ROOT}/{barrier_id}/"
    watch = await client.watch_prefix(prefix)
    payload: Any = None
    gen: str | None = None
    complete: str | None = None

    async def participate() -> None:
        nonlocal payload, gen, complete
        async for ev in watch:
            if ev.op == "delete":
                if ev.key == f"{prefix}complete":
                    complete = None  # a new leader run is resetting
                continue
            if ev.value is None:
                continue
            if ev.key == f"{prefix}data":
                blob = json.loads(ev.value.decode())
                payload, new_gen = blob["payload"], blob["gen"]
                if new_gen != gen:
                    gen = new_gen
                    await client.put(f"{prefix}workers/{worker_name}",
                                     gen.encode(), lease_id)
            elif ev.key == f"{prefix}complete":
                complete = ev.value.decode()
            if gen is not None and complete == gen:
                return
        # watch ended: the coordinator connection died mid-rendezvous —
        # fail loudly (mirrors the leader side) instead of returning a
        # half-formed payload as success.
        raise ConnectionError("coordinator watch ended during barrier")

    try:
        await asyncio.wait_for(participate(), timeout)
    except asyncio.TimeoutError:
        stage = "leader data" if gen is None else "completion marker"
        raise BarrierTimeout(
            f"barrier {barrier_id!r}: no {stage} within {timeout}s") from None
    finally:
        await watch.cancel()
    return payload
