"""Leader-worker barrier: multi-process rendezvous over the coordinator.

Fills the role of the reference's etcd leader-worker barrier
(reference: lib/runtime/src/utils/leader_worker_barrier.rs:14-50 — the
leader posts data under a barrier id and waits for N workers to check in;
workers post themselves and wait for the leader's ``complete`` key).

Used wherever N processes must meet before proceeding (multi-host engine
bring-up, KVBM leader/worker handshakes). Keys live under
``barrier/{id}/...`` and are lease-bound when a lease id is given, so a
crashed participant's state evaporates with its lease.
"""

from __future__ import annotations

import asyncio
import json
import time
from typing import Any

from dynamo_tpu.utils.logging import get_logger

log = get_logger("barrier")

ROOT = "barrier"


class BarrierTimeout(TimeoutError):
    pass


async def leader_barrier(client, barrier_id: str, num_workers: int,
                         data: Any = None, timeout: float = 120.0,
                         lease_id: int = 0) -> list[str]:
    """Leader side: publish ``data``, wait for ``num_workers`` check-ins,
    then post the completion marker. Returns the worker names seen."""
    await client.put(f"{ROOT}/{barrier_id}/data",
                     json.dumps(data).encode(), lease_id)
    prefix = f"{ROOT}/{barrier_id}/workers/"
    deadline = time.monotonic() + timeout
    while True:
        got = await client.get_prefix(prefix)
        if len(got) >= num_workers:
            await client.put(f"{ROOT}/{barrier_id}/complete", b"1", lease_id)
            return [k[len(prefix):] for k in got]
        if time.monotonic() > deadline:
            raise BarrierTimeout(
                f"barrier {barrier_id!r}: {len(got)}/{num_workers} workers "
                f"within {timeout}s ({sorted(k[len(prefix):] for k in got)})")
        await asyncio.sleep(0.1)


async def worker_barrier(client, barrier_id: str, worker_name: str,
                         timeout: float = 120.0, lease_id: int = 0) -> Any:
    """Worker side: check in, wait for the leader's completion marker, and
    return the leader's published data."""
    await client.put(f"{ROOT}/{barrier_id}/workers/{worker_name}",
                     b"1", lease_id)
    deadline = time.monotonic() + timeout
    while True:
        if await client.get(f"{ROOT}/{barrier_id}/complete"):
            blob = await client.get(f"{ROOT}/{barrier_id}/data")
            return json.loads(blob.decode()) if blob else None
        if time.monotonic() > deadline:
            raise BarrierTimeout(
                f"barrier {barrier_id!r}: leader did not complete within "
                f"{timeout}s")
        await asyncio.sleep(0.1)
