"""Health-check canaries: idle-endpoint payload replay flipping Ready/NotReady.

Fills the role of the reference's endpoint health checks
(reference: lib/runtime/src/health_check.rs:20-36 — ``HealthCheckConfig``
with a canary payload plumbed through serve_endpoint; replayed after an
idle period so a wedged worker is discovered BEFORE a real request times
out on it).

Mechanics here: :class:`EndpointHealthMonitor` wraps an endpoint handler.
Real traffic both proves liveness (every completed request marks the
endpoint Ready) and suppresses canaries (no replay while busy). Once the
endpoint has been idle past ``idle_interval_s``, the canary payload is
driven through the SAME handler the router reaches; a hang/timeout or
exception flips the endpoint NotReady. The state is exported through the
worker's load-metrics stats (``ready``), which the KV router consumes —
a NotReady worker stops receiving traffic without being killed, and
recovers the moment a canary succeeds again.
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass, field
from typing import Any, AsyncIterator, Callable

from dynamo_tpu.utils.logging import get_logger
from dynamo_tpu.utils.metrics import MetricsRegistry

log = get_logger("health")


class HealthMetrics:
    """dynamo_health_canary_{total,failures} (cross-checked by
    tools/lint_metrics.py RECOVERY_METRICS). Singleton + install idiom of
    disagg/metrics.py: workers re-home it into their runtime registry."""

    def __init__(self, registry: MetricsRegistry | None = None):
        self.bind(registry or MetricsRegistry())

    def bind(self, registry: MetricsRegistry) -> None:
        self.registry = registry
        self.canary_total = registry.counter(
            "health_canary_total",
            "Health-check canary payloads replayed through idle endpoints")
        self.canary_failures = registry.counter(
            "health_canary_failures",
            "Canary replays that failed (endpoint flipped NotReady)")


_metrics: HealthMetrics | None = None


def get_health_metrics() -> HealthMetrics:
    global _metrics
    if _metrics is None:
        _metrics = HealthMetrics()
    return _metrics


def install_health_metrics(registry: MetricsRegistry) -> HealthMetrics:
    m = get_health_metrics()
    m.bind(registry)
    return m


@dataclass
class HealthCheckConfig:
    """Canary settings (reference: health_check.rs HealthCheckConfig)."""

    payload: dict = field(default_factory=dict)
    idle_interval_s: float = 5.0    # replay after this much idle time
    timeout_s: float = 10.0         # canary must finish within this
    # With requests IN FLIGHT, no-progress must exceed this (not the idle
    # interval) before a canary fires: a legitimately long first-token wait
    # (cold compile, long-context prefill) is not a wedge, and a canary
    # queued behind it would time out and flip a healthy worker NotReady.
    busy_grace_s: float = 30.0
    request_id_prefix: str = "health-canary"


class _CanaryContext:
    """Minimal RequestContext stand-in for canary calls. Mirrors the QoS
    surface handlers touch (worker handlers stamp ``deadline_ts`` and poll
    ``is_expired``) so a canary replay can't AttributeError a healthy
    worker into NotReady."""

    deadline_ts: float | None = None

    def is_expired(self) -> bool:
        return self.deadline_ts is not None and time.time() >= self.deadline_ts

    def is_cancelled(self) -> bool:
        return self.is_expired()


class EndpointHealthMonitor:
    """Wraps a handler; tracks activity; replays a canary when idle."""

    def __init__(self, handler: Callable[[Any, Any], AsyncIterator],
                 config: HealthCheckConfig):
        self._handler = handler
        self.config = config
        self.ready = True
        self._last_activity = time.monotonic()
        self._inflight = 0
        self._task: asyncio.Task | None = None
        self._seq = 0

    # -- the wrapped handler served on the endpoint ------------------------
    async def handler(self, payload: Any, ctx: Any) -> AsyncIterator:
        self._inflight += 1
        self._last_activity = time.monotonic()
        try:
            async for item in self._handler(payload, ctx):
                self._last_activity = time.monotonic()
                yield item
            # A completed real request is the strongest health signal.
            self.ready = True
        finally:
            self._inflight -= 1
            self._last_activity = time.monotonic()

    # -- canary loop -------------------------------------------------------
    def start(self) -> None:
        self._task = asyncio.ensure_future(self._loop())

    async def stop(self) -> None:
        if self._task:
            self._task.cancel()
            try:
                await self._task
            except (asyncio.CancelledError, Exception):
                pass

    async def _loop(self) -> None:
        while True:
            await asyncio.sleep(max(self.config.idle_interval_s / 4, 0.05))
            # Idle means "no PROGRESS", not "no requests": a stream yielding
            # tokens keeps _last_activity fresh and suppresses canaries, but
            # in-flight requests that stopped progressing (engine wedged
            # mid-stream — the common production failure) must NOT suppress
            # them, or the wedge goes undetected until a client times out.
            idle = time.monotonic() - self._last_activity
            threshold = (self.config.busy_grace_s if self._inflight > 0
                         else self.config.idle_interval_s)
            if idle < threshold:
                continue
            await self._run_canary()

    async def _run_canary(self) -> None:
        self._seq += 1
        rid = f"{self.config.request_id_prefix}-{self._seq}"
        payload = dict(self.config.payload)
        payload.setdefault("request_id", rid)

        async def drive() -> None:
            async for _ in self._handler(payload, _CanaryContext()):
                pass

        get_health_metrics().canary_total.inc()
        try:
            await asyncio.wait_for(drive(), self.config.timeout_s)
        except Exception as exc:
            get_health_metrics().canary_failures.inc()
            if self.ready:
                log.warning("canary %s failed (%s: %s): endpoint NotReady",
                            rid, type(exc).__name__, exc)
            self.ready = False
            return
        finally:
            # Success or failure, the canary counts as activity: the next
            # replay waits a full idle interval (a fast-FAILING handler must
            # not trigger a canary storm against an unhealthy engine).
            self._last_activity = time.monotonic()
        if not self.ready:
            log.info("canary %s succeeded: endpoint Ready again", rid)
        self.ready = True


def default_canary_payload(max_tokens: int = 1) -> dict:
    """A minimal generate-shaped payload every engine handler accepts
    (reference pattern: the vllm worker's health-check payload,
    components/src/dynamo/vllm/health_check.py)."""
    return {
        "token_ids": [1, 2, 3],
        "stop_conditions": {"max_tokens": max_tokens, "ignore_eos": True},
        "sampling_options": {"temperature": 0.0},
    }
