"""Per-process system status server: /health, /live, /metrics.

Fills the role of the reference's system status server
(reference: lib/runtime/src/system_status_server.rs:1-811 + system_health.rs
— an env-gated (DYN_SYSTEM_ENABLED / DYN_SYSTEM_PORT) HTTP endpoint every
process can expose, independent of any model-serving frontend, giving
k8s probes and Prometheus a uniform per-process surface).

Workers previously published metrics only over the coordinator; with this,
every DistributedRuntime process can also be scraped/probed directly.
Status providers (e.g. the worker's engine stats fn) plug in at runtime.
"""

from __future__ import annotations

import time
from typing import Callable

from aiohttp import web

from dynamo_tpu.utils.logging import get_logger
from dynamo_tpu.utils.metrics import MetricsRegistry

log = get_logger("status")


class SystemStatusServer:
    def __init__(self, metrics: MetricsRegistry, port: int = 0):
        self.metrics = metrics
        self.port = port
        self._providers: dict[str, Callable[[], dict]] = {}
        self._t0 = time.monotonic()
        self._runner: web.AppRunner | None = None
        # Readiness: a static flag AND an optional dynamic probe (e.g. the
        # worker's health-canary state); /health is 503 when either is off.
        self.ready = True
        self._ready_fn: Callable[[], bool] | None = None

    def set_ready_fn(self, fn: Callable[[], bool]) -> None:
        self._ready_fn = fn

    def _is_ready(self) -> bool:
        try:
            dynamic = self._ready_fn() if self._ready_fn is not None else True
        except Exception:
            dynamic = False
        return self.ready and dynamic

    def add_provider(self, name: str, fn: Callable[[], dict]) -> None:
        """Register a status section (e.g. the engine's stats fn)."""
        self._providers[name] = fn

    async def start(self, host: str = "0.0.0.0") -> int:
        app = web.Application()
        app.router.add_get("/health", self._health)
        app.router.add_get("/live", self._live)
        app.router.add_get("/metrics", self._metrics)
        app.router.add_get("/debug/sched", self._debug_sched)
        app.router.add_get("/debug/mem", self._debug_mem)
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, host, self.port)
        await site.start()
        self.port = site._server.sockets[0].getsockname()[1]  # type: ignore[union-attr]
        log.info("system status server on port %d", self.port)
        return self.port

    async def stop(self) -> None:
        if self._runner:
            await self._runner.cleanup()

    async def _health(self, request: web.Request) -> web.Response:
        ready = self._is_ready()
        body = {
            "status": "ready" if ready else "notready",
            "uptime_s": round(time.monotonic() - self._t0, 1),
        }
        for name, fn in self._providers.items():
            try:
                body[name] = fn()
            except Exception as exc:  # noqa: BLE001 - a broken provider
                body[name] = {"error": str(exc)}  # must not break the probe
        return web.json_response(body, status=200 if ready else 503)

    async def _live(self, request: web.Request) -> web.Response:
        return web.json_response({"status": "live"})

    async def _debug_sched(self, request: web.Request) -> web.Response:
        """Worker-local scheduling ledger (obs/sched_ledger.py): the
        recent-step ring, goodput trend, and top HOL culprits of THIS
        process's engine — span-level victim detail lives in the worker's
        own FlightRecorder, so merge it in."""
        from dynamo_tpu.obs.sched_ledger import get_sched_ledger
        from dynamo_tpu.obs.tracer import get_tracer

        return web.json_response(get_sched_ledger().debug_info(
            recorder=get_tracer().recorder))

    async def _debug_mem(self, request: web.Request) -> web.Response:
        """Worker-local memory ledger (obs/mem_ledger.py): the tier
        occupancy waterfall, top pin owners, churn trend, consumption
        rates, TTX forecast, and the last pin-leak audit report."""
        from dynamo_tpu.obs.mem_ledger import get_mem_ledger

        return web.json_response(get_mem_ledger().debug_info())

    async def _metrics(self, request: web.Request) -> web.Response:
        text = self.metrics.expose()
        # Status-provider numeric leaves export as gauges too, so engine
        # stats (kv_usage, num_running, ...) are scrapeable without the
        # coordinator in the path.
        lines = [text] if text else []
        for name, fn in self._providers.items():
            try:
                stats = fn()
            except Exception:
                continue
            for k, v in stats.items():
                if isinstance(v, bool):
                    v = int(v)
                if isinstance(v, (int, float)):
                    lines.append(f"dynamo_{name}_{k} {v}")
        return web.Response(text="\n".join(lines) + "\n",
                            content_type="text/plain")
