"""dynamo_tpu — a TPU-native distributed LLM inference serving framework.

A ground-up reimplementation of the capabilities of NVIDIA Dynamo
(reference: vickiegpt/dynamo, see SURVEY.md) designed for TPU hardware:

- A first-party JAX/XLA inference engine (``dynamo_tpu.engine``) with paged
  KV cache, continuous batching, Pallas paged attention, and GSPMD sharding
  over a ``jax.sharding.Mesh`` — filling the role the reference delegates to
  vLLM/SGLang/TRT-LLM.
- A distributed runtime (``dynamo_tpu.runtime``) with the reference's
  Namespace→Component→Endpoint model, discovery with leases, a push request
  plane and a direct-TCP response plane (reference: lib/runtime/src/).
- KV-cache-aware routing over a global radix index fed by worker block
  events (``dynamo_tpu.router``; reference: lib/llm/src/kv_router.rs).
- Disaggregated prefill/decode with KV block handoff over ICI/DCN
  (``dynamo_tpu.disagg``; reference NIXL path: lib/llm/src/block_manager/).
- A tiered KV block manager (``dynamo_tpu.kvbm``).
- An OpenAI-compatible HTTP frontend (``dynamo_tpu.frontend``).
- An SLA planner (``dynamo_tpu.planner``) and a mocker engine
  (``dynamo_tpu.mocker``) for accelerator-free testing.
"""

__version__ = "0.1.0"
