from dynamo_tpu.backend.detokenizer import DetokenizerBackend

__all__ = ["DetokenizerBackend"]
