"""Streaming detokenizer backend with stop-sequence jail.

Fills the role of the reference's ``Backend`` operator
(reference: lib/llm/src/backend.rs:4-60): sits between the engine's token
stream and the OpenAI response path, incrementally detokenizes, and
implements the *hidden stop sequence jail* — when the tail of the generated
text could be the start of a stop string, output is withheld ("jailed")
until the ambiguity resolves, so a stop sequence never leaks to the client
and partial matches stream correctly.
"""

from __future__ import annotations

import time
from dataclasses import dataclass

from dynamo_tpu.protocols.common import BackendOutput, FinishReason, LLMEngineOutput
from dynamo_tpu.tokenizer import BaseTokenizer, DecodeStream


from dynamo_tpu.utils.text import longest_partial_suffix as _longest_partial_suffix


@dataclass
class _StreamState:
    decode: DecodeStream
    jailed: str = ""       # emitted-by-decoder but withheld text
    finished: bool = False


class DetokenizerBackend:
    """Per-request streaming state machine. Feed ``LLMEngineOutput`` deltas,
    receive ``BackendOutput`` text deltas with stop handling applied."""

    def __init__(self, tokenizer: BaseTokenizer, stops: list[str] | None = None):
        self.tokenizer = tokenizer
        self.stops = [s for s in (stops or []) if s]
        self._st = _StreamState(decode=DecodeStream(tokenizer))
        # Cumulative wall time spent detokenizing; the frontend folds it
        # into one aggregate frontend.detokenize span at stream end
        # (obs/tracer.py — a per-delta span would be pure overhead).
        self.elapsed_s = 0.0

    def step(self, out: LLMEngineOutput) -> BackendOutput:
        t0 = time.perf_counter()
        try:
            return self._step(out)
        finally:
            self.elapsed_s += time.perf_counter() - t0

    def _step(self, out: LLMEngineOutput) -> BackendOutput:
        st = self._st
        if st.finished:
            return BackendOutput(finish_reason=out.finish_reason)
        new_text = "".join(st.decode.step(t) for t in out.token_ids)
        buf = st.jailed + new_text

        # 1. full stop-string match → truncate there, finish
        if self.stops:
            hit_at = None
            for stop in self.stops:
                idx = buf.find(stop)
                if idx != -1 and (hit_at is None or idx < hit_at):
                    hit_at = idx
            if hit_at is not None:
                st.finished = True
                st.jailed = ""
                return BackendOutput(
                    text=buf[:hit_at],
                    token_ids=list(out.token_ids),
                    finish_reason=FinishReason.STOP,
                    cum_log_probs=out.cum_log_probs,
                    log_probs=out.log_probs,
                )

        # 2. stream end → flush the jail (no stop hit)
        if out.finish_reason is not None:
            tail = st.decode.flush()
            st.finished = True
            st.jailed = ""
            return BackendOutput(
                text=buf + tail,
                token_ids=list(out.token_ids),
                finish_reason=out.finish_reason,
                cum_log_probs=out.cum_log_probs,
                log_probs=out.log_probs,
            )

        # 3. jail any suffix that could grow into a stop string
        k = _longest_partial_suffix(buf, self.stops) if self.stops else 0
        st.jailed = buf[len(buf) - k :] if k else ""
        emit = buf[: len(buf) - k] if k else buf
        return BackendOutput(text=emit, token_ids=list(out.token_ids),
                             cum_log_probs=out.cum_log_probs,
                             log_probs=out.log_probs)

    @property
    def hit_stop(self) -> bool:
        return self._st.finished
