"""Disaggregated prefill/decode serving.

Fills the role of the reference's disaggregation stack — separate prefill
and decode workers with a KV handoff (reference: disagg flow
components/src/dynamo/vllm/handlers.py:188-247 decode-first pattern;
NIXL transfer docs/architecture/disagg_serving.md) — redesigned for TPU:

- The prefill worker computes the prompt's KV, **pins** the blocks, and
  returns ``kv_transfer_params`` (its data-plane address + the block hash
  chain + a transfer id) instead of NIXL RDMA metadata.
- The decode worker dials that address directly over the runtime's framed
  TCP data plane (DCN path; intra-slice transfers ride ICI inside the
  engine's own sharding), pulls the raw block bytes, and injects them as
  matchable prefix-cache entries — its scheduler then admits the request
  with the whole prompt (minus the tail) already resident.
- Decode-first and conditional: short prompts skip the remote hop, and any
  prefill failure falls back to local prefill (availability over latency,
  same stance as the reference's conditional disaggregation).
"""

from dynamo_tpu.disagg.handlers import DisaggDecodeHandler
from dynamo_tpu.disagg.receiver import pull_and_import
from dynamo_tpu.disagg.source import KvTransferSource

__all__ = ["DisaggDecodeHandler", "KvTransferSource", "pull_and_import"]
