"""Disaggregated prefill/decode serving.

Fills the role of the reference's disaggregation stack — separate prefill
and decode workers with a KV handoff (reference: disagg flow
components/src/dynamo/vllm/handlers.py:188-247 decode-first pattern;
NIXL transfer docs/architecture/disagg_serving.md) — redesigned for TPU:

- The prefill worker computes the prompt's KV, **pins + stages** each
  rank's cache shard to host memory (one replayed ``kv_stage`` op on
  multi-host engines), and returns ``kv_transfer_params`` (the block hash
  chain + a transfer id + every rank's shard-server endpoint with its
  (layer, head) box) instead of NIXL RDMA metadata.
- Every decode rank dials the prefill shards whose boxes intersect its
  own and pulls exactly those slices (DCN path; intra-slice transfers
  ride ICI inside the engine's own sharding) — rank-to-rank, resharding
  across differing prefill/decode topologies — then injects them as
  matchable prefix-cache entries in SPMD lockstep; the scheduler then
  admits the request with the whole prompt (minus the tail) resident.
- Decode-first and conditional: short prompts skip the remote hop, and any
  prefill failure falls back to local prefill (availability over latency,
  same stance as the reference's conditional disaggregation).
"""

from dynamo_tpu.disagg.handlers import DisaggDecodeHandler
from dynamo_tpu.disagg.receiver import pull_and_import
from dynamo_tpu.disagg.source import KvTransferSource

__all__ = ["DisaggDecodeHandler", "KvTransferSource", "pull_and_import"]
