"""Prometheus family for the streamed KV handoff (dynamo_kv_transfer_*).

The consumer-side overlap ratio is the tentpole's headline number: the
fraction of a streamed transfer's pull window that ran while the remote
prefill was still computing (1.0 = the transfer fully hid behind prefill,
0.0 = today's serialized handoff). Stage/pull byte counters and the
per-wave size histogram feed the wave-sizing guidance in docs/PERF.md.

Registrations are idempotent (MetricsRegistry keys by name), so the
module-level singleton can be re-bound into a runtime's registry via
``install_kv_metrics`` — workers call it so the family shows up on
/metrics; tests and library use fall back to a private registry.
"""

from __future__ import annotations

from dynamo_tpu.utils.metrics import MetricsRegistry

# Wave payloads are block-granular host copies: 64 KiB – 256 MiB spans the
# tiny-test to flagship-recipe range.
_WAVE_BYTES_BUCKETS = (
    65536.0, 262144.0, 1048576.0, 4194304.0, 16777216.0,
    67108864.0, 268435456.0, float("inf"),
)


class KvTransferMetrics:
    """The dynamo_kv_transfer_* family (names cross-checked by
    tools/lint_metrics.py KV_TRANSFER_METRICS)."""

    def __init__(self, registry: MetricsRegistry | None = None):
        self.bind(registry or MetricsRegistry())

    def bind(self, registry: MetricsRegistry) -> None:
        self.registry = registry
        self.overlap_ratio = registry.gauge(
            "kv_transfer_overlap_ratio",
            "Fraction of the last streamed KV pull that overlapped the "
            "remote prefill (1.0 = transfer fully hidden behind compute)")
        self.waves = registry.counter(
            "kv_transfer_waves_total",
            "Streamed KV handoff waves processed, by phase "
            "(stage|pull|import)")
        self.bytes = registry.counter(
            "kv_transfer_bytes_total",
            "Bytes moved by the streamed KV handoff, by phase "
            "(stage|pull|import)")
        self.wave_bytes = registry.histogram(
            "kv_transfer_wave_bytes",
            "Per-wave payload size of the streamed KV handoff (this rank's "
            "shard slice)", buckets=_WAVE_BYTES_BUCKETS)

    def record_wave(self, phase: str, nbytes: int) -> None:
        self.waves.inc(1, phase=phase)
        self.bytes.inc(nbytes, phase=phase)
        self.wave_bytes.observe(nbytes)


_metrics: KvTransferMetrics | None = None


def get_kv_metrics() -> KvTransferMetrics:
    global _metrics
    if _metrics is None:
        _metrics = KvTransferMetrics()
    return _metrics


def install_kv_metrics(registry: MetricsRegistry) -> KvTransferMetrics:
    """Re-home the singleton's metrics into ``registry`` (the worker's
    runtime registry) so the family is exposed on /metrics."""
    m = get_kv_metrics()
    m.bind(registry)
    return m
