"""Sharded KV handoff: per-rank staging, shard servers, box-sliced pulls.

Fills the role of the reference's multi-node disaggregated KV transfer
(reference: recipes/llama-3-70b/vllm/disagg-multi-node/deploy.yaml:36-71 —
prefill and decode engines spanning hosts with NIXL moving KV between
GPU pools; lib/llm/src/block_manager/distributed/leader.rs:126 +
worker.rs:143 coordinate per-GPU transfers over a ZMQ control channel).

The TPU redesign needs no control channel: a multi-host engine already
replays ONE deterministic op stream on every rank (parallel/multihost.py),
so staging and import run as replayed exec ops in SPMD lockstep. What this
module adds is the *data* path between two engines whose meshes may differ
(the flagship recipe hands tp16-prefill KV to tp32-decode):

- ``StagingStore``  — host-memory staging of each rank's LOCAL cache shard
  of the pinned blocks, keyed by transfer id. Staged at register time (one
  replayed ``kv_stage`` op), so serving a pull never touches device state.
- ``ShardServer``   — a per-rank daemon thread serving box-sliced reads of
  staged shards over the framed sync-socket protocol multihost.py already
  uses. Every prefill rank (leader AND followers) runs one.
- ``fetch_box``     — the decode-rank side: dial every prefill shard whose
  (layer, head) box intersects mine, pull exactly the intersecting slices,
  and assemble my local per-block contribution. Rank-to-rank, no central
  hop — the same locality NIXL's GPU↔GPU transfers have, ridden over
  DCN-facing TCP instead.

Boxes are global (layer_start, layer_end, head_start, head_end) extents;
the shard geometry comes from ``kvbm.distributed.local_box``. A
single-host engine is the 1-shard degenerate case of the same protocol.
"""

from __future__ import annotations

import socket
import threading
from dataclasses import dataclass, field

import numpy as np

from dynamo_tpu.parallel.multihost import recv_frame, send_frame
from dynamo_tpu.utils.logging import get_logger

log = get_logger("disagg.sharded")

Box = tuple[int, int, int, int]  # (layer_start, layer_end, head_start, head_end)


def box_intersection(a: Box, b: Box) -> Box | None:
    ls, le = max(a[0], b[0]), min(a[1], b[1])
    hs, he = max(a[2], b[2]), min(a[3], b[3])
    if ls >= le or hs >= he:
        return None
    return (ls, le, hs, he)


@dataclass
class Staged:
    """One rank's staged shard of a transfer: data[n, 2, L_loc, bs, H_loc, hd]
    covering ``box`` of the global (layer, head) space, for ``hashes`` (with
    ``parents`` the chain links import needs)."""

    ready: threading.Event = field(default_factory=threading.Event)
    hashes: list[int] = field(default_factory=list)
    parents: list[int | None] = field(default_factory=list)
    data: np.ndarray | None = None
    box: Box = (0, 0, 0, 0)
    dtype: str = "bfloat16"


class StagingStore:
    """Thread-safe xfer_id → Staged. Entries may be created by an early
    pull (placeholder, unset event) or by the stage op (fills + sets)."""

    def __init__(self) -> None:
        self._entries: dict[str, Staged] = {}
        self._lock = threading.Lock()

    def get_or_create(self, xfer_id: str) -> Staged:
        with self._lock:
            entry = self._entries.get(xfer_id)
            if entry is None:
                entry = self._entries[xfer_id] = Staged()
            return entry

    def fill(self, xfer_id: str, hashes: list[int], parents: list[int | None],
             data: np.ndarray, box: Box) -> None:
        entry = self.get_or_create(xfer_id)
        with self._lock:  # publish all fields atomically (see snapshot)
            entry.hashes, entry.parents = hashes, parents
            entry.dtype = str(data.dtype)
            entry.data, entry.box = data, box
        entry.ready.set()

    def snapshot(self, xfer_id: str):
        """Consistent read of a staged entry's fields (or None if not
        staged). Serve threads that wake from a TIMED-OUT ready.wait() can
        race a concurrent fill(); reading under the same lock fill()
        publishes under means they see all-or-nothing, never fresh data
        paired with a stale dtype/box."""
        entry = self.get_or_create(xfer_id)
        with self._lock:
            if entry.data is None:
                return None
            return (entry.hashes, entry.parents, entry.data, entry.box,
                    entry.dtype)

    def drop(self, xfer_id: str) -> None:
        with self._lock:
            entry = self._entries.pop(xfer_id, None)
        if entry is not None:
            entry.data = None
            entry.ready.set()  # unblock any waiter; it will see data=None

    def drop_if_empty(self, xfer_id: str) -> None:
        """Remove a never-filled placeholder (created by a pull that raced
        ahead of — or outlived — the stage op) so late/retried pulls can't
        grow the store unboundedly."""
        with self._lock:
            entry = self._entries.get(xfer_id)
            if entry is not None and entry.data is None:
                del self._entries[xfer_id]


class ShardServer:
    """Serve box-sliced reads of staged shards. One per prefill rank.

    Protocol (framed msgpack, multihost.py codec):
      request  {"xfer_id", "ls", "le", "hs", "he"}
      reply    {"hashes", "parents", "box": [ls, le, hs, he], "dtype"}
               then one {"i": idx, "d": bytes} frame per block (the
               requested slice, C-contiguous), then {"end": true}
      release  {"xfer_id", "release": true} → {"ok": true} — the decode
               side's done-ack, honored only by the LEADER's server (the
               shards[0] convention): ``on_release`` forwards it to the
               KvTransferSource, which broadcasts the replayed unpin.
      error    {"error": msg}
    """

    def __init__(self, store: StagingStore, host: str = "0.0.0.0",
                 stage_timeout: float = 60.0, on_release=None):
        self.store = store
        self.stage_timeout = stage_timeout
        self.on_release = on_release
        self._server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._server.bind((host, 0))
        self.port = self._server.getsockname()[1]
        self._server.listen(32)
        self._stop = False
        self._thread = threading.Thread(
            target=self._accept_loop, name="kv-shard-server", daemon=True)
        self._thread.start()

    def close(self) -> None:
        self._stop = True
        try:
            self._server.close()
        except OSError:
            pass

    def _accept_loop(self) -> None:
        while not self._stop:
            try:
                conn, _ = self._server.accept()
            except OSError:
                return
            threading.Thread(target=self._serve_one, args=(conn,),
                             daemon=True).start()

    def _serve_one(self, conn: socket.socket) -> None:
        try:
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            req = recv_frame(conn)
            if req is None:
                return
            if req.get("release"):
                if self.on_release is not None:
                    self.on_release(req["xfer_id"])
                send_frame(conn, {"ok": True})
                return
            entry = self.store.get_or_create(req["xfer_id"])
            entry.ready.wait(self.stage_timeout)
            snap = self.store.snapshot(req["xfer_id"])
            if snap is not None:
                hashes, parents, data, box, dtype = snap
            else:
                data = None
            if data is None:
                self.store.drop_if_empty(req["xfer_id"])
                send_frame(conn, {"error": f"transfer {req['xfer_id']} not "
                                           "staged (expired or never registered)"})
                return
            want = (req["ls"], req["le"], req["hs"], req["he"])
            inter = box_intersection(want, box)
            if inter is None:
                send_frame(conn, {"error": f"no overlap: want {want}, "
                                           f"have {box}"})
                return
            ls, le, hs, he = inter
            sl = data[:, :, ls - box[0]:le - box[0], :, hs - box[2]:he - box[2], :]
            send_frame(conn, {"hashes": hashes, "parents": parents,
                              "box": list(inter), "dtype": dtype})
            for i in range(sl.shape[0]):
                send_frame(conn, {"i": i,
                                  "d": np.ascontiguousarray(sl[i]).tobytes()})
            send_frame(conn, {"end": True})
        except Exception as exc:  # noqa: BLE001 — a handler thread must not
            # die silently; best-effort error frame, then close.
            log.warning("shard serve failed: %s", exc)
            try:
                send_frame(conn, {"error": f"shard serve failed: {exc}"})
            except OSError:
                pass
        finally:
            try:
                conn.close()
            except OSError:
                pass


def send_release(addr: str, xfer_id: str, timeout: float = 10.0) -> None:
    """Tell the transfer's owner (the leader shard server, shards[0]) the
    pull is done — it unpins/unstages on every prefill rank."""
    host, _, port = addr.rpartition(":")
    with socket.create_connection((host, int(port)), timeout=timeout) as conn:
        conn.settimeout(timeout)
        send_frame(conn, {"xfer_id": xfer_id, "release": True})
        recv_frame(conn)


def fetch_slice(addr: str, xfer_id: str, box: Box,
                timeout: float = 30.0) -> tuple[list[int], list[int | None],
                                                np.ndarray, Box]:
    """Pull the slice of ``box`` one shard server holds. Synchronous —
    called from the engine-core thread inside the replayed import op."""
    host, _, port = addr.rpartition(":")
    with socket.create_connection((host, int(port)), timeout=timeout) as conn:
        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        conn.settimeout(timeout)
        send_frame(conn, {"xfer_id": xfer_id, "ls": box[0], "le": box[1],
                          "hs": box[2], "he": box[3]})
        meta = recv_frame(conn)
        if meta is None or "error" in meta:
            raise RuntimeError(f"shard pull {addr} failed: "
                               f"{(meta or {}).get('error', 'connection closed')}")
        got: Box = tuple(meta["box"])  # type: ignore[assignment]
        n = len(meta["hashes"])
        out = None  # [n, flat] — reshaped by assemble_local (bs/hd caller-known)
        count = 0
        while True:
            frame = recv_frame(conn)
            if frame is None or frame.get("end"):
                break
            arr = np.frombuffer(frame["d"], dtype=np.dtype(meta["dtype"]))
            if out is None:
                out = np.empty((n, arr.size), dtype=arr.dtype)
            out[frame["i"]] = arr
            count += 1
        if out is None or count != n:
            raise RuntimeError(f"shard pull {addr}: got {count}/{n} blocks")
        return meta["hashes"], meta["parents"], out, got


def assemble_local(my_box: Box, pieces: list[tuple[np.ndarray, Box]],
                   n: int, bs: int, hd: int, dtype) -> np.ndarray | None:
    """Place fetched slices into this rank's [n, 2, myL, bs, myH, hd] block
    array. Returns None (fetch incomplete) unless the pieces tile my box
    exactly."""
    ls, le, hs, he = my_box
    out = np.empty((n, 2, le - ls, bs, he - hs, hd), dtype=dtype)
    covered = np.zeros((le - ls, he - hs), dtype=bool)
    for flat, box in pieces:
        bl, bL, bh, bH = box[0], box[1], box[2], box[3]
        block = flat.reshape(n, 2, bL - bl, bs, bH - bh, hd)
        out[:, :, bl - ls:bL - ls, :, bh - hs:bH - hs, :] = block
        covered[bl - ls:bL - ls, bh - hs:bH - hs] = True
    if not covered.all():
        return None
    return out
