"""Sharded KV handoff: per-rank staging, shard servers, box-sliced pulls.

Fills the role of the reference's multi-node disaggregated KV transfer
(reference: recipes/llama-3-70b/vllm/disagg-multi-node/deploy.yaml:36-71 —
prefill and decode engines spanning hosts with NIXL moving KV between
GPU pools; lib/llm/src/block_manager/distributed/leader.rs:126 +
worker.rs:143 coordinate per-GPU transfers over a ZMQ control channel).

The TPU redesign needs no control channel: a multi-host engine already
replays ONE deterministic op stream on every rank (parallel/multihost.py),
so staging and import run as replayed exec ops in SPMD lockstep. What this
module adds is the *data* path between two engines whose meshes may differ
(the flagship recipe hands tp16-prefill KV to tp32-decode):

- ``StagingStore``  — host-memory staging of each rank's LOCAL cache shard
  of the pinned blocks, keyed by transfer id. Entries may be filled in one
  shot (the legacy ``kv_stage`` op) or grow wave-by-wave while the prefill
  is still running (``begin``/``append``/``finalize`` driven by the
  per-chunk ``kv_stage_wave`` ops); ``wait_for`` gives serve threads a
  consistent snapshot of whatever prefix is staged so far.
- ``ShardServer``   — a per-rank daemon thread serving box-sliced reads of
  staged shards over the framed sync-socket protocol multihost.py already
  uses. Every prefill rank (leader AND followers) runs one. A connection
  may issue many requests (one per wave); a mid-stream client disconnect
  closes only that connection, never the staged transfer.
- ``ShardClient``   — the decode-rank side: a persistent per-shard
  connection with bounded reconnect/retry, pulling exactly the
  intersecting slices of the waves that are ready. Rank-to-rank, no
  central hop — the same locality NIXL's GPU↔GPU transfers have, ridden
  over DCN-facing TCP instead.

Boxes are global (layer_start, layer_end, head_start, head_end) extents;
the shard geometry comes from ``kvbm.distributed.local_box``. A
single-host engine is the 1-shard degenerate case of the same protocol.
"""

from __future__ import annotations

import socket
import threading
import time
from dataclasses import dataclass, field

import numpy as np

from dynamo_tpu import chaos
from dynamo_tpu.parallel.multihost import recv_frame, send_frame
from dynamo_tpu.utils.logging import get_logger

log = get_logger("disagg.sharded")

Box = tuple[int, int, int, int]  # (layer_start, layer_end, head_start, head_end)


def box_intersection(a: Box, b: Box) -> Box | None:
    ls, le = max(a[0], b[0]), min(a[1], b[1])
    hs, he = max(a[2], b[2]), min(a[3], b[3])
    if ls >= le or hs >= he:
        return None
    return (ls, le, hs, he)


@dataclass
class Staged:
    """One rank's staged shard of a transfer: data[n, 2, L_loc, bs, H_loc, hd]
    covering ``box`` of the global (layer, head) space, for ``hashes`` (with
    ``parents`` the chain links import needs).

    A streamed transfer declares the full expected chain up front (``begin``)
    and grows ``n_ready`` as waves land; only rows below ``n_ready`` are
    published (append never touches them again), so serve threads may read
    them without copying. ``ready`` stays the legacy completion event: set
    once the transfer is complete (or dropped)."""

    ready: threading.Event = field(default_factory=threading.Event)
    hashes: list[int] = field(default_factory=list)
    parents: list[int | None] = field(default_factory=list)
    data: np.ndarray | None = None
    box: Box = (0, 0, 0, 0)
    dtype: str = "bfloat16"
    n_ready: int = 0
    complete: bool = False
    dropped: bool = False


class StagingStore:
    """Thread-safe xfer_id → Staged. Entries may be created by an early
    pull (placeholder), by the one-shot stage op (``fill``), or by a
    streamed transfer (``begin`` + per-wave ``append`` + ``finalize``)."""

    def __init__(self) -> None:
        self._entries: dict[str, Staged] = {}
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)

    def get_or_create(self, xfer_id: str) -> Staged:
        with self._lock:
            entry = self._entries.get(xfer_id)
            if entry is None:
                entry = self._entries[xfer_id] = Staged()
            return entry

    # -- streamed path -------------------------------------------------
    def begin(self, xfer_id: str, hashes: list[int],
              parents: list[int | None], box: Box, dtype: str) -> None:
        """Declare the full expected chain of a streamed transfer. The data
        array is allocated lazily on the first append (its per-block shape
        isn't known until a wave is extracted)."""
        entry = self.get_or_create(xfer_id)
        with self._cond:
            if entry.dropped:
                return
            entry.hashes, entry.parents = list(hashes), list(parents)
            entry.box, entry.dtype = box, dtype
            entry.n_ready, entry.complete = 0, False
            self._cond.notify_all()

    def append(self, xfer_id: str, start: int, wave: np.ndarray) -> bool:
        """Publish one wave of rows [start, start+len(wave)). Waves must be
        contiguous with what's already staged (start ≤ n_ready); a gap means
        the caller lost a wave and the stream is broken — refused."""
        entry = self.get_or_create(xfer_id)
        with self._cond:
            if entry.dropped or entry.complete:
                return False
            if start > entry.n_ready:
                log.warning("staging %s: wave gap (start %d > ready %d)",
                            xfer_id, start, entry.n_ready)
                return False
            stop = start + wave.shape[0]
            if stop > len(entry.hashes):
                return False
            if entry.data is None:
                entry.data = np.empty((len(entry.hashes), *wave.shape[1:]),
                                      dtype=wave.dtype)
                entry.dtype = str(wave.dtype)
            entry.data[start:stop] = wave
            entry.n_ready = max(entry.n_ready, stop)
            self._cond.notify_all()
            return True

    def finalize(self, xfer_id: str, covered: int) -> None:
        """Close a streamed transfer at ``covered`` blocks (the mesh-wide
        voted minimum — may trim waves a minority of ranks staged)."""
        entry = self.get_or_create(xfer_id)
        with self._cond:
            if not entry.dropped:
                entry.n_ready = min(entry.n_ready, covered)
                entry.complete = True
            self._cond.notify_all()
        entry.ready.set()

    def wait_for(self, xfer_id: str, want: int | None,
                 timeout: float) -> tuple | None:
        """Block until ``want`` blocks are staged (or the transfer is
        complete/dropped), then return a consistent snapshot
        ``(hashes[:m], parents[:m], data view [:m], box, dtype)`` of the
        published prefix. ``want=None`` waits for completion (the legacy
        whole-transfer pull). Returns None on timeout/drop/empty."""
        entry = self.get_or_create(xfer_id)
        deadline = time.monotonic() + timeout
        with self._cond:
            while True:
                if entry.dropped:
                    return None
                if entry.complete or (want is not None and entry.n_ready >= want):
                    break
                left = deadline - time.monotonic()
                if left <= 0:
                    return None
                self._cond.wait(left)
            m = entry.n_ready if want is None else min(want, entry.n_ready)
            if entry.data is None or m == 0:
                return None
            return (entry.hashes[:m], entry.parents[:m], entry.data[:m],
                    entry.box, entry.dtype)

    # -- one-shot path -------------------------------------------------
    def fill(self, xfer_id: str, hashes: list[int], parents: list[int | None],
             data: np.ndarray, box: Box) -> None:
        entry = self.get_or_create(xfer_id)
        with self._cond:  # publish all fields atomically (see snapshot)
            entry.hashes, entry.parents = hashes, parents
            entry.dtype = str(data.dtype)
            entry.data, entry.box = data, box
            entry.n_ready, entry.complete = len(hashes), True
            self._cond.notify_all()
        entry.ready.set()

    def snapshot(self, xfer_id: str):
        """Consistent read of a staged entry's published prefix (or None if
        nothing is staged). Reading under the same lock fill()/append()
        publish under means readers see all-or-nothing, never fresh data
        paired with a stale dtype/box."""
        entry = self.get_or_create(xfer_id)
        with self._lock:
            if entry.data is None or entry.n_ready == 0:
                return None
            m = entry.n_ready
            return (entry.hashes[:m], entry.parents[:m], entry.data[:m],
                    entry.box, entry.dtype)

    def drop(self, xfer_id: str) -> None:
        with self._cond:
            entry = self._entries.pop(xfer_id, None)
            if entry is not None:
                entry.dropped = True
                entry.data = None
                self._cond.notify_all()
        if entry is not None:
            entry.ready.set()  # unblock any waiter; it will see data=None

    def drop_if_empty(self, xfer_id: str) -> None:
        """Remove a never-filled placeholder (created by a pull that raced
        ahead of — or outlived — the stage op) so late/retried pulls can't
        grow the store unboundedly."""
        with self._lock:
            entry = self._entries.get(xfer_id)
            if entry is not None and entry.data is None:
                del self._entries[xfer_id]


class ShardServer:
    """Serve box-sliced reads of staged shards. One per prefill rank.

    Protocol (framed msgpack, multihost.py codec); a connection may carry
    MANY requests back-to-back (the streamed consumer reuses one socket per
    shard across waves):
      request  {"xfer_id", "ls", "le", "hs", "he"[, "start", "stop"]}
               — no "stop": wait for the complete transfer (legacy pull);
               with "stop": wait until blocks [start, stop) are staged and
               serve exactly that window of the chain (a wave pull racing
               the staging of later waves).
      reply    {"hashes", "parents", "box": [ls, le, hs, he], "dtype",
               "start": s} then one {"i": idx, "d": bytes} frame per block
               (idx relative to "start"; the requested slice,
               C-contiguous), then {"end": true}
      release  {"xfer_id", "release": true} → {"ok": true} — the decode
               side's done-ack, honored only by the LEADER's server (the
               shards[0] convention): ``on_release`` forwards it to the
               KvTransferSource, which broadcasts the replayed unpin.
      error    {"error": msg}

    A client disconnect (clean EOF or reset) mid-conversation closes only
    that connection; the staged transfer stays, so the consumer can
    reconnect and retry the same window."""

    def __init__(self, store: StagingStore, host: str = "0.0.0.0",
                 stage_timeout: float = 60.0, on_release=None):
        self.store = store
        self.stage_timeout = stage_timeout
        self.on_release = on_release
        self._server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._server.bind((host, 0))
        self.port = self._server.getsockname()[1]
        self._server.listen(32)
        self._stop = False
        self._thread = threading.Thread(
            target=self._accept_loop, name="kv-shard-server", daemon=True)
        self._thread.start()

    def close(self) -> None:
        self._stop = True
        try:
            self._server.close()
        except OSError:
            pass

    def _accept_loop(self) -> None:
        while not self._stop:
            try:
                conn, _ = self._server.accept()
            except OSError:
                return
            threading.Thread(target=self._serve_one, args=(conn,),
                             daemon=True).start()

    def _serve_one(self, conn: socket.socket) -> None:
        try:
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            while True:
                req = recv_frame(conn)
                if req is None:  # client done with this connection
                    return
                if req.get("release"):
                    if self.on_release is not None:
                        self.on_release(req["xfer_id"])
                    send_frame(conn, {"ok": True})
                    continue
                if not self._serve_pull(conn, req):
                    continue  # application error sent; connection reusable
        except (BrokenPipeError, ConnectionResetError, ConnectionAbortedError):
            # Mid-stream client disconnect: only this connection dies; the
            # staged transfer is untouched and a reconnect can re-pull.
            log.debug("shard client disconnected mid-stream")
        except Exception as exc:  # noqa: BLE001 — a handler thread must not
            # die silently; best-effort error frame, then close.
            log.warning("shard serve failed: %s", exc)
            try:
                send_frame(conn, {"error": f"shard serve failed: {exc}"})
            except OSError:
                pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _serve_pull(self, conn: socket.socket, req: dict) -> bool:
        """Answer one pull request; False means an error frame was sent and
        the connection stays usable for the next request."""
        xid = req["xfer_id"]
        start = int(req.get("start", 0))
        stop = req.get("stop")  # None → wait for the complete transfer
        snap = self.store.wait_for(xid, stop, self.stage_timeout)
        if snap is None:
            self.store.drop_if_empty(xid)
            send_frame(conn, {"error": f"transfer {xid} not staged "
                                       "(expired, trimmed, or never registered)"})
            return False
        hashes, parents, data, box, dtype = snap
        m = len(hashes)
        if start >= m:
            send_frame(conn, {"error": f"window [{start}:{stop}) beyond "
                                       f"staged prefix {m}"})
            return False
        end = m if stop is None else min(int(stop), m)
        want = (req["ls"], req["le"], req["hs"], req["he"])
        inter = box_intersection(want, box)
        if inter is None:
            send_frame(conn, {"error": f"no overlap: want {want}, have {box}"})
            return False
        ls, le, hs, he = inter
        sl = data[start:end, :,
                  ls - box[0]:le - box[0], :, hs - box[2]:he - box[2], :]
        send_frame(conn, {"hashes": hashes[start:end],
                          "parents": parents[start:end],
                          "box": list(inter), "dtype": dtype, "start": start})
        for i in range(sl.shape[0]):
            send_frame(conn, {"i": i,
                              "d": np.ascontiguousarray(sl[i]).tobytes()})
        send_frame(conn, {"end": True})
        return True


def send_release(addr: str, xfer_id: str, timeout: float = 10.0) -> None:
    """Tell the transfer's owner (the leader shard server, shards[0]) the
    pull is done — it unpins/unstages on every prefill rank."""
    host, _, port = addr.rpartition(":")
    with socket.create_connection((host, int(port)), timeout=timeout) as conn:
        conn.settimeout(timeout)
        send_frame(conn, {"xfer_id": xfer_id, "release": True})
        recv_frame(conn)


class ShardClient:
    """Persistent connection to one shard server with bounded
    reconnect/retry. Socket-level failures (reset, timeout, truncated
    stream) reconnect with exponential backoff; application error frames
    (no such transfer, no box overlap) raise immediately — retrying can't
    fix them. NOT thread-safe: the streamed consumer chains its wave
    fetches on one thread per transfer."""

    def __init__(self, addr: str, timeout: float = 30.0, retries: int = 3,
                 backoff: float = 0.05):
        self.addr = addr
        self.timeout = timeout
        self.retries = max(1, retries)
        self.backoff = backoff
        self._conn: socket.socket | None = None

    def _connect(self) -> socket.socket:
        host, _, port = self.addr.rpartition(":")
        conn = socket.create_connection((host, int(port)), timeout=self.timeout)
        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        conn.settimeout(self.timeout)
        return conn

    def close(self) -> None:
        if self._conn is not None:
            try:
                self._conn.close()
            except OSError:
                pass
            self._conn = None

    def fetch(self, xfer_id: str, box: Box, start: int | None = None,
              stop: int | None = None) -> tuple[list[int], list[int | None],
                                                np.ndarray, Box]:
        """Pull blocks [start, stop) of the slice of ``box`` this shard
        holds (the whole staged transfer when stop is None). Synchronous."""
        last: Exception | None = None
        for attempt in range(self.retries):
            if attempt:
                time.sleep(self.backoff * (2 ** (attempt - 1)))
            try:
                if self._conn is None:
                    self._conn = self._connect()
                return self._fetch_once(self._conn, xfer_id, box, start, stop)
            except (OSError, EOFError) as exc:
                last = exc
                self.close()
        raise RuntimeError(f"shard pull {self.addr} failed after "
                           f"{self.retries} attempt(s): {last}")

    def _fetch_once(self, conn: socket.socket, xfer_id: str, box: Box,
                    start: int | None, stop: int | None):
        # Chaos: injected disconnect/error lands inside fetch()'s retry loop
        # (ChaosInjectedError is a ConnectionError, i.e. retryable OSError)
        # — the mid-wave shard-death scenario without killing a real server.
        chaos.inject("disagg.pull", addr=self.addr, xfer_id=xfer_id)
        req = {"xfer_id": xfer_id, "ls": box[0], "le": box[1],
               "hs": box[2], "he": box[3]}
        if start is not None:
            req["start"] = int(start)
        if stop is not None:
            req["stop"] = int(stop)
        send_frame(conn, req)
        meta = recv_frame(conn)
        if meta is None:
            raise EOFError("connection closed before reply")  # retryable
        if "error" in meta:
            raise RuntimeError(f"shard pull {self.addr} failed: {meta['error']}")
        got: Box = tuple(meta["box"])  # type: ignore[assignment]
        n = len(meta["hashes"])
        out = None  # [n, flat] — reshaped by assemble_local (bs/hd caller-known)
        count = 0
        while True:
            frame = recv_frame(conn)
            if frame is None:
                raise EOFError(f"truncated stream: got {count}/{n} blocks")
            if frame.get("end"):
                break
            arr = np.frombuffer(frame["d"], dtype=np.dtype(meta["dtype"]))
            if out is None:
                out = np.empty((n, arr.size), dtype=arr.dtype)
            out[frame["i"]] = arr
            count += 1
        if count != n or (out is None and n):
            raise EOFError(f"shard pull {self.addr}: got {count}/{n} blocks")
        if out is None:
            out = np.empty((0, 0), dtype=np.dtype(meta["dtype"]))
        return meta["hashes"], meta["parents"], out, got


def fetch_slice(addr: str, xfer_id: str, box: Box, timeout: float = 30.0,
                start: int | None = None, stop: int | None = None,
                ) -> tuple[list[int], list[int | None], np.ndarray, Box]:
    """One-shot pull of the slice of ``box`` one shard server holds —
    a throwaway ShardClient (callers that pull many waves should hold a
    ShardClient and reuse its connection)."""
    client = ShardClient(addr, timeout=timeout, retries=2)
    try:
        return client.fetch(xfer_id, box, start, stop)
    finally:
        client.close()


def assemble_local(my_box: Box, pieces: list[tuple[np.ndarray, Box]],
                   n: int, bs: int, hd: int, dtype) -> np.ndarray | None:
    """Place fetched slices into this rank's [n, 2, myL, bs, myH, hd] block
    array. Returns None (fetch incomplete) unless the pieces tile my box
    exactly."""
    ls, le, hs, he = my_box
    out = np.empty((n, 2, le - ls, bs, he - hs, hd), dtype=dtype)
    covered = np.zeros((le - ls, he - hs), dtype=bool)
    for flat, box in pieces:
        bl, bL, bh, bH = box[0], box[1], box[2], box[3]
        block = flat.reshape(n, 2, bL - bl, bs, bH - bh, hd)
        out[:, :, bl - ls:bL - ls, :, bh - hs:bH - hs, :] = block
        covered[bl - ls:bL - ls, bh - hs:bH - hs] = True
    if not covered.all():
        return None
    return out
