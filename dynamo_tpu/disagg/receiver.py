"""Decode-side KV transfer receiver: pull shard slices, inject, release.

Reference: the decode worker passing ``kv_transfer_params`` into its local
engine so vLLM pulls blocks via NIXL (components/src/dynamo/vllm/
handlers.py:236-241). Here the pull is the replayed ``kv_import`` core op:
EVERY rank of the decode engine (one, for single-host) fetches exactly the
box slices it owns from the prefill shard servers listed in the params —
rank-to-rank transfers that also handle prefill-tp ≠ decode-tp resharding
— then injects them into its cache shard in SPMD lockstep
(engine.import_remote, disagg/sharded.py). Data never transits the
broker/coordinator, same stance as the reference's direct transfers.

``StreamedKvConsumer`` is the pipelined form (DistServe/Mooncake-style
chunk streaming): availability events from the prefill side trigger
per-wave prefetches whose network fetch overlaps both the remote prefill
still computing AND the device injection of the previous wave. Mixed
``kv_dtype`` conversion stays where it always was — the wave boundary
(stage dequantizes, inject requantizes).
"""

from __future__ import annotations

import asyncio
import time

from dynamo_tpu import chaos
from dynamo_tpu.engine.engine import AsyncJaxEngine
from dynamo_tpu.utils.logging import get_logger

log = get_logger("disagg")


async def _send_release_ack(params: dict) -> None:
    """Done-ack to the owner (shards[0] = the prefill leader): unpins and
    unstages on every prefill rank. Fire-and-forget — TTL expiry covers a
    lost ack."""
    from dynamo_tpu.disagg.sharded import send_release

    try:
        await asyncio.get_running_loop().run_in_executor(
            None, send_release, params["shards"][0]["addr"], params["xfer_id"])
    except Exception as exc:  # noqa: BLE001
        log.warning("kv release ack failed (TTL will reclaim): %s", exc)


async def pull_and_import(engine: AsyncJaxEngine, params: dict) -> int:
    """Pull the transfer described by ``params`` into ``engine``'s prefix
    cache and ack completion to the transfer's owner. Returns blocks
    injected.

    params: {"xfer_id", "block_hashes": [...],
             "shards": [{"addr": "host:port", "box": [ls, le, hs, he]}]}

    Raises on a failed pull (import_remote's voted -1) so the caller's
    conditional-disagg fallback fires; a 0 return is a SUCCESSFUL pull
    whose blocks were all already device-resident.
    """
    # Chaos: an error here surfaces exactly like a voted-down pull — the
    # caller's conditional-disagg fallback (local prefill) must fire.
    await chaos.ainject("disagg.import", xfer_id=params["xfer_id"])
    # Two replayed ops: the prefetch starts the network fetch on a
    # background thread on every rank (engine steps keep running while
    # bytes move); the import joins it, votes, and injects.
    await engine.run_op("kv_prefetch", {"params": params})
    n = await engine.run_op("kv_import", {"params": params})
    if n < 0:
        raise RuntimeError(
            f"kv pull {params['xfer_id']} failed (voted down across ranks)")
    log.info("pulled %s KV blocks from %d shard(s)", n, len(params["shards"]))
    await _send_release_ack(params)
    return n


class StreamedKvConsumer:
    """Pipelined consumer of a streamed KV handoff.

    Built from the prefill side's announce event (xfer_id + shard list +
    full expected hash chain). Each availability event ``advance(ready)``
    issues a ``kv_prefetch_wave`` for the new window immediately (its
    network fetch starts on a background thread on every rank) and then
    imports the PREVIOUS window — so at steady state the fetch of wave w
    overlaps the device injection of wave w-1 and whatever prefill chunks
    are still computing remotely. ``finish(final_params)`` drains the
    pipeline, records the overlap ratio, and acks the release.
    """

    def __init__(self, engine: AsyncJaxEngine, announce: dict):
        self.engine = engine
        self.xfer_id = announce["xfer_id"]
        self.params = {"xfer_id": self.xfer_id,
                       "shards": announce["shards"],
                       "block_hashes": list(announce["block_hashes"])}
        self.expected = len(self.params["block_hashes"])
        self.issued = 0              # blocks with a prefetch in flight/done
        self.injected = 0
        self.pending: list[tuple[int, int]] = []  # prefetched, not imported
        self.failed = False
        self.waves = 0
        self.tail_waves = 0          # waves first seen after prefill ended
        self.t_first: float | None = None
        self.t_prefill_done: float | None = None

    async def advance(self, ready: int, tail: bool = False) -> None:
        """A wave availability event: blocks [0, ready) are pullable."""
        ready = min(int(ready), self.expected)
        if self.failed or ready <= self.issued:
            return
        if self.t_first is None:
            self.t_first = time.monotonic()
        start, stop = self.issued, ready
        await self.engine.run_op(
            "kv_prefetch_wave",
            {"params": self.params, "start": start, "stop": stop,
             "tail": tail})
        self.pending.append((start, stop))
        self.issued = stop
        self.waves += 1
        if tail:
            self.tail_waves += 1
        # Keep exactly one wave in the network stage: import everything
        # older — its bytes are already host-side, so this is the device-
        # injection half of the pipeline.
        while len(self.pending) > 1:
            await self._import_next(final=False)

    async def _import_next(self, final: bool) -> None:
        start, stop = self.pending.pop(0)
        n = await self.engine.run_op(
            "kv_import_wave",
            {"params": self.params, "start": start, "stop": stop,
             "final": final})
        if n < 0:
            self.failed = True
            raise RuntimeError(
                f"kv wave pull {self.xfer_id}[{start}:{stop}) failed "
                "(voted down across ranks)")
        self.injected += n

    async def finish(self, final_params: dict | None) -> int:
        """Prefill is done: pull any not-yet-issued tail (the voted final
        covered count can exceed the last announced wave), drain pending
        imports, record metrics, ack release. Returns blocks injected."""
        self.t_prefill_done = time.monotonic()
        covered = (len(final_params.get("block_hashes", []))
                   if final_params else self.issued)
        if covered > self.issued:
            await self.advance(covered, tail=True)
        while self.pending:
            await self._import_next(final=len(self.pending) == 1)
        self._record_overlap()
        log.info("streamed pull %s: %d blocks over %d wave(s), %d after "
                 "prefill end", self.xfer_id, self.injected, self.waves,
                 self.tail_waves)
        await _send_release_ack(self.params)
        return self.injected

    async def abort(self) -> None:
        """Tear down mid-stream: close pull state on every rank and ask the
        prefill side to release shipped and unshipped waves alike."""
        self.failed = True
        try:
            await self.engine.run_op("kv_pull_abort",
                                     {"xfer_id": self.xfer_id})
        except Exception as exc:  # noqa: BLE001 — best-effort teardown
            log.warning("kv pull abort failed: %s", exc)
        await _send_release_ack(self.params)

    def overlap_ratio(self) -> float:
        """Fraction of the pull window [first prefetch, last import] that
        ran while the remote prefill was still computing. 1.0 when every
        wave was issued before prefill ended and nothing remained to drain
        afterwards; 0.0 for the legacy serialized handoff shape."""
        if self.t_first is None or self.t_prefill_done is None:
            return 0.0
        t_end = time.monotonic()
        total = t_end - self.t_first
        if total <= 0:
            return 1.0
        overlapped = min(self.t_prefill_done, t_end) - self.t_first
        return max(0.0, min(1.0, overlapped / total))

    def _record_overlap(self) -> None:
        from dynamo_tpu.disagg.metrics import get_kv_metrics

        if self.waves:
            get_kv_metrics().overlap_ratio.set(self.overlap_ratio())
