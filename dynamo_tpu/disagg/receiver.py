"""Decode-side KV transfer receiver: dial, pull, inject.

Reference: the decode worker passing ``kv_transfer_params`` into its local
engine so vLLM pulls blocks via NIXL (components/src/dynamo/vllm/
handlers.py:236-241). Here the pull is explicit: a direct framed-TCP call
to the prefill instance's data plane (the caller address came inside the
params — data never transits the broker/coordinator, same stance as the
reference's direct TCP response plane).
"""

from __future__ import annotations

import uuid

import jax.numpy as jnp
import numpy as np

from dynamo_tpu.engine.engine import AsyncJaxEngine
from dynamo_tpu.kvbm.pools import block_shape
from dynamo_tpu.transports.wire import Frame, MsgpackConnection
from dynamo_tpu.utils.logging import get_logger

log = get_logger("disagg")


async def pull_and_import(engine: AsyncJaxEngine, params: dict) -> int:
    """Pull the blocks described by ``params`` from the prefill worker and
    inject them into ``engine``'s prefix cache. Returns blocks injected.

    params: {"addr": "host:port", "endpoint": "ns.comp.kv_pull",
             "xfer_id": ..., "block_hashes": [...]}
    """
    spec = engine.core.runner.spec
    shape = block_shape(spec)
    dtype = jnp.dtype(spec.dtype)
    host, _, port = params["addr"].rpartition(":")
    conn = await MsgpackConnection.connect(host, int(port))
    plan: list[tuple[int, int | None, np.ndarray]] = []
    try:
        await conn.send({
            "t": Frame.CALL, "stream_id": 1, "endpoint": params["endpoint"],
            "request_id": uuid.uuid4().hex,
            "payload": {"xfer_id": params["xfer_id"],
                        "hashes": params["block_hashes"], "release": True},
        })
        while True:
            msg = await conn.recv()
            if msg is None or msg.get("t") == Frame.END:
                break
            if msg.get("t") == Frame.ERR:
                raise RuntimeError(f"kv pull failed: {msg.get('error')}")
            if msg.get("t") != Frame.DATA:
                continue
            item = msg["payload"]
            data = np.frombuffer(item["d"], dtype=dtype).reshape(shape)
            plan.append((item["h"], item.get("p"), data))
    finally:
        conn.close()
    if not plan:
        return 0
    n = await engine.run_in_core(lambda core: core.import_blocks(plan))
    log.info("pulled %d KV blocks from %s (injected %d)",
             len(plan), params["addr"], n)
    return n
