"""Decode-side KV transfer receiver: pull shard slices, inject, release.

Reference: the decode worker passing ``kv_transfer_params`` into its local
engine so vLLM pulls blocks via NIXL (components/src/dynamo/vllm/
handlers.py:236-241). Here the pull is the replayed ``kv_import`` core op:
EVERY rank of the decode engine (one, for single-host) fetches exactly the
box slices it owns from the prefill shard servers listed in the params —
rank-to-rank transfers that also handle prefill-tp ≠ decode-tp resharding
— then injects them into its cache shard in SPMD lockstep
(engine.import_remote, disagg/sharded.py). Data never transits the
broker/coordinator, same stance as the reference's direct transfers.
"""

from __future__ import annotations

import asyncio

from dynamo_tpu.engine.engine import AsyncJaxEngine
from dynamo_tpu.utils.logging import get_logger

log = get_logger("disagg")


async def pull_and_import(engine: AsyncJaxEngine, params: dict) -> int:
    """Pull the transfer described by ``params`` into ``engine``'s prefix
    cache and ack completion to the transfer's owner. Returns blocks
    injected.

    params: {"xfer_id", "block_hashes": [...],
             "shards": [{"addr": "host:port", "box": [ls, le, hs, he]}]}

    Raises on a failed pull (import_remote's voted -1) so the caller's
    conditional-disagg fallback fires; a 0 return is a SUCCESSFUL pull
    whose blocks were all already device-resident.
    """
    # Two replayed ops: the prefetch starts the network fetch on a
    # background thread on every rank (engine steps keep running while
    # bytes move); the import joins it, votes, and injects.
    await engine.run_op("kv_prefetch", {"params": params})
    n = await engine.run_op("kv_import", {"params": params})
    if n < 0:
        raise RuntimeError(
            f"kv pull {params['xfer_id']} failed (voted down across ranks)")
    log.info("pulled %s KV blocks from %d shard(s)", n, len(params["shards"]))
    # Done-ack to the owner (shards[0] = the prefill leader): unpins and
    # unstages on every prefill rank. Fire-and-forget — TTL expiry covers a
    # lost ack.
    from dynamo_tpu.disagg.sharded import send_release

    try:
        await asyncio.get_running_loop().run_in_executor(
            None, send_release, params["shards"][0]["addr"], params["xfer_id"])
    except Exception as exc:  # noqa: BLE001
        log.warning("kv release ack failed (TTL will reclaim): %s", exc)
    return n
