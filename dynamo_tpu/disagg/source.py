"""Prefill-side KV transfer source: stage, advertise, expire.

Reference: the KVBM-distributed leader/worker + NIXL metadata handshake
(lib/llm/src/block_manager/distributed/leader.rs, storage/nixl.rs).
Here the "RDMA registration" becomes one replayed ``kv_stage`` op: every
rank of the prefill engine pins the blocks in its pool and copies ITS
cache shard to host staging (engine.stage_export), where the per-rank
shard servers (disagg/sharded.py) serve box-sliced pulls. The transfer
params advertise the full shard list, so a decode engine of ANY topology
(single-host or multi-host, different tp) can assemble its own boxes.
Unpulled transfers expire after a TTL so an aborted decode can't leak
pinned device blocks (the release is a replayed op too).
"""

from __future__ import annotations

import asyncio
import time
import uuid
from dataclasses import dataclass

from dynamo_tpu.engine.engine import AsyncJaxEngine
from dynamo_tpu.utils.logging import get_logger

log = get_logger("disagg")


@dataclass
class _Transfer:
    seq_hashes: list[int]     # covered chain prefix (staged + pinned)
    deadline: float


class KvTransferSource:
    def __init__(self, engine: AsyncJaxEngine, ttl_s: float = 60.0,
                 advertise_host: str = "127.0.0.1",
                 extra_shards: list[dict] | None = None):
        """``extra_shards``: follower shard endpoints
        ``[{"addr": "host:port", "box": [ls, le, hs, he]}, ...]`` from the
        op channel's ready acks (multi-host prefill). The leader's own
        shard server is always started here and listed FIRST — shards[0]
        is where the decode side sends the release ack."""
        self.engine = engine
        self.ttl_s = ttl_s
        self.advertise_host = advertise_host
        self.extra_shards = extra_shards or []
        self.shards: list[dict] | None = None
        self._transfers: dict[str, _Transfer] = {}
        self._gc_task: asyncio.Task | None = None

    def start(self) -> None:
        if self._gc_task is None:
            self._gc_task = asyncio.create_task(self._gc_loop())

    async def stop(self) -> None:
        if self._gc_task is not None:
            self._gc_task.cancel()
            self._gc_task = None
        for xid in list(self._transfers):
            await self._release(xid)
        server = getattr(self.engine.core, "_shard_server", None)
        if server is not None:
            server.close()
            self.engine.core._shard_server = None
            self.shards = None

    # ------------------------------------------------------------------
    def _ensure_shards(self) -> list[dict]:
        if self.shards is None:
            core = self.engine.core
            loop = asyncio.get_running_loop()

            def on_release(xid: str) -> None:  # shard-server thread → loop
                loop.call_soon_threadsafe(
                    lambda: loop.create_task(self.release(xid)))

            addr = core.start_shard_server(self.advertise_host,
                                           on_release=on_release)
            self.shards = [{"addr": addr, "box": list(core.my_box())},
                           *self.extra_shards]
        return self.shards

    async def register(self, seq_hashes: list[int]) -> dict | None:
        """Stage the device-resident prefix of ``seq_hashes`` on every rank;
        returns the kv_transfer_params (id + covered hashes + shard
        endpoints) or None if nothing is resident (e.g. prompt shorter than
        one block)."""
        if not seq_hashes:
            return None
        shards = self._ensure_shards()
        xid = uuid.uuid4().hex
        covered_n = await self.engine.run_op(
            "kv_stage", {"xfer_id": xid, "hashes": seq_hashes})
        if not covered_n:
            return None
        covered = seq_hashes[:covered_n]
        self._transfers[xid] = _Transfer(
            seq_hashes=covered, deadline=time.monotonic() + self.ttl_s)
        return {"xfer_id": xid, "block_hashes": covered, "shards": shards}

    async def release(self, xfer_id: str) -> None:
        """Decode-side ack: the pull completed (or was abandoned) — unpin
        and drop staging on every rank."""
        await self._release(xfer_id)

    async def _release(self, xid: str) -> None:
        if self._transfers.pop(xid, None) is not None:
            await self.engine.run_op("kv_release", {"xfer_id": xid})

    async def _gc_loop(self) -> None:
        while True:
            await asyncio.sleep(self.ttl_s / 4)
            now = time.monotonic()
            for xid, xfer in list(self._transfers.items()):
                if xfer.deadline <= now:
                    log.warning("kv transfer %s expired unpulled; releasing", xid)
                    await self._release(xid)
