"""Prefill-side KV transfer source: stage, advertise, expire.

Reference: the KVBM-distributed leader/worker + NIXL metadata handshake
(lib/llm/src/block_manager/distributed/leader.rs, storage/nixl.rs).
Here the "RDMA registration" becomes one replayed ``kv_stage`` op: every
rank of the prefill engine pins the blocks in its pool and copies ITS
cache shard to host staging (engine.stage_export), where the per-rank
shard servers (disagg/sharded.py) serve box-sliced pulls. The transfer
params advertise the full shard list, so a decode engine of ANY topology
(single-host or multi-host, different tp) can assemble its own boxes.

Streamed transfers (register_streaming) flip the order: the transfer is
registered ONCE up front with the full expected hash chain, then the
engine's step loop stages each committed prefill chunk as a wave
(kv_stage_wave ops) while later chunks are still computing; wave
completions are announced to the PrefillHandler so the decode side can
pull blocks that exist before the prompt is done.

Transfers expire after a TTL measured from their last progress (stream
registration, wave landing, or stream end), so an aborted decode — or a
prefill that dies mid-stream — can't leak pinned device blocks (the
release is a replayed op too, covering shipped and not-yet-staged waves
alike).
"""

from __future__ import annotations

import asyncio
import time
import uuid
from dataclasses import dataclass

from dynamo_tpu import chaos
from dynamo_tpu.engine.engine import AsyncJaxEngine
from dynamo_tpu.utils.logging import get_logger

log = get_logger("disagg")


@dataclass
class _Transfer:
    seq_hashes: list[int]     # covered chain prefix (staged + pinned)
    deadline: float


class KvTransferSource:
    def __init__(self, engine: AsyncJaxEngine, ttl_s: float = 60.0,
                 advertise_host: str = "127.0.0.1",
                 extra_shards: list[dict] | None = None):
        """``extra_shards``: follower shard endpoints
        ``[{"addr": "host:port", "box": [ls, le, hs, he]}, ...]`` from the
        op channel's ready acks (multi-host prefill). The leader's own
        shard server is always started here and listed FIRST — shards[0]
        is where the decode side sends the release ack."""
        self.engine = engine
        self.ttl_s = ttl_s
        self.advertise_host = advertise_host
        self.extra_shards = extra_shards or []
        self.shards: list[dict] | None = None
        self._transfers: dict[str, _Transfer] = {}
        self._gc_task: asyncio.Task | None = None
        self._wave_queues: dict[str, asyncio.Queue] = {}
        self._listener_loop: asyncio.AbstractEventLoop | None = None

    def start(self) -> None:
        if self._gc_task is None:
            self._gc_task = asyncio.create_task(self._gc_loop())

    async def stop(self) -> None:
        if self._gc_task is not None:
            self._gc_task.cancel()
            self._gc_task = None
        for xid in list(self._transfers):
            await self._release(xid)
        server = getattr(self.engine.core, "_shard_server", None)
        if server is not None:
            server.close()
            self.engine.core._shard_server = None
            self.shards = None

    # ------------------------------------------------------------------
    def _ensure_shards(self) -> list[dict]:
        if self.shards is None:
            core = self.engine.core
            loop = asyncio.get_running_loop()

            def on_release(xid: str) -> None:  # shard-server thread → loop
                loop.call_soon_threadsafe(
                    lambda: loop.create_task(self.release(xid)))

            addr = core.start_shard_server(self.advertise_host,
                                           on_release=on_release)
            self.shards = [{"addr": addr, "box": list(core.my_box())},
                           *self.extra_shards]
        return self.shards

    async def register(self, seq_hashes: list[int]) -> dict | None:
        """Stage the device-resident prefix of ``seq_hashes`` on every rank;
        returns the kv_transfer_params (id + covered hashes + shard
        endpoints) or None if nothing is resident (e.g. prompt shorter than
        one block)."""
        if not seq_hashes:
            return None
        await chaos.ainject("disagg.stage", blocks=len(seq_hashes))
        shards = self._ensure_shards()
        xid = uuid.uuid4().hex
        covered_n = await self.engine.run_op(
            "kv_stage", {"xfer_id": xid, "hashes": seq_hashes})
        if not covered_n:
            return None
        covered = seq_hashes[:covered_n]
        self._transfers[xid] = _Transfer(
            seq_hashes=covered, deadline=time.monotonic() + self.ttl_s)
        return {"xfer_id": xid, "block_hashes": covered, "shards": shards}

    # -- streamed registration -----------------------------------------
    def _ensure_stream_listener(self) -> None:
        """Hook the engine-core wave detector (AsyncJaxEngine._run) to this
        source: wave completions are marshaled from the engine thread onto
        the event loop and fanned out to the per-transfer queues the
        PrefillHandler consumes."""
        loop = asyncio.get_running_loop()
        if self._listener_loop is loop:
            return
        self._listener_loop = loop

        def on_wave(xid: str, staged: int) -> None:  # engine-core thread
            q = self._wave_queues.get(xid)
            xfer = self._transfers.get(xid)
            if xfer is not None:
                # A live stream is making progress — a slow prefill must
                # not expire its own transfer mid-stream (TTL measures
                # time since last progress; see _gc_loop).
                xfer.deadline = time.monotonic() + self.ttl_s
            if q is not None:
                loop.call_soon_threadsafe(q.put_nowait, ("wave", staged))

        self.engine.core._stream_listener = on_wave

    async def register_streaming(self, request_id: str, seq_hashes: list[int],
                                 events: asyncio.Queue) -> dict | None:
        """Open a streamed transfer for ``request_id``'s full expected hash
        chain BEFORE prefill runs. Waves land via the engine's per-chunk
        stage hook and are announced as ``("wave", staged_count)`` items on
        ``events``. Returns the announce params (id + chain + shard
        endpoints) or None for an empty chain."""
        if not seq_hashes:
            return None
        await chaos.ainject("disagg.stage", blocks=len(seq_hashes))
        shards = self._ensure_shards()
        self._ensure_stream_listener()
        xid = uuid.uuid4().hex
        self._wave_queues[xid] = events
        await self.engine.run_op(
            "kv_stream_begin",
            {"xfer_id": xid, "request_id": request_id,
             "hashes": list(seq_hashes)})
        self._transfers[xid] = _Transfer(
            seq_hashes=list(seq_hashes),
            deadline=time.monotonic() + self.ttl_s)
        return {"xfer_id": xid, "block_hashes": list(seq_hashes),
                "shards": shards}

    async def finish_streaming(self, xid: str) -> int:
        """Prefill finished: vote + trim the stream on every rank. Returns
        the covered (pullable) block count; 0 releases the transfer
        entirely (nothing for the decode side to pull)."""
        self._wave_queues.pop(xid, None)
        covered = await self.engine.run_op("kv_stream_end", {"xfer_id": xid})
        covered = int(covered or 0)
        xfer = self._transfers.get(xid)
        if covered and xfer is not None:
            xfer.seq_hashes = xfer.seq_hashes[:covered]
            xfer.deadline = time.monotonic() + self.ttl_s
        else:
            await self._release(xid)
        return covered

    async def abort_streaming(self, xid: str) -> None:
        """Mid-stream abort (cancelled request, errored prefill): release
        pins for shipped AND not-yet-staged waves on every rank."""
        self._wave_queues.pop(xid, None)
        await self._release(xid)

    async def release(self, xfer_id: str) -> None:
        """Decode-side ack: the pull completed (or was abandoned) — unpin
        and drop staging on every rank."""
        await self._release(xfer_id)

    async def _release(self, xid: str) -> None:
        self._wave_queues.pop(xid, None)
        if self._transfers.pop(xid, None) is not None:
            await self.engine.run_op("kv_release", {"xfer_id": xid})

    async def _gc_loop(self) -> None:
        while True:
            await asyncio.sleep(self.ttl_s / 4)
            now = time.monotonic()
            for xid, xfer in list(self._transfers.items()):
                if xfer.deadline <= now:
                    log.warning("kv transfer %s expired unpulled; releasing", xid)
                    await self._release(xid)
