"""Prefill-side KV transfer source: pin, serve, expire.

Reference: the KVBM-distributed leader/worker + NIXL metadata handshake
(lib/llm/src/block_manager/distributed/leader.rs, storage/nixl.rs).
Here the "RDMA registration" becomes: pin the blocks in the prefill
engine's pool (incref — survives scheduler churn), hand out a transfer id,
and stream the raw block bytes over the runtime data plane when the decode
side calls the ``kv_pull`` endpoint. Unpulled transfers expire after a TTL
so an aborted decode can't leak device blocks.
"""

from __future__ import annotations

import asyncio
import time
import uuid
from dataclasses import dataclass

from dynamo_tpu.engine.engine import AsyncJaxEngine
from dynamo_tpu.utils.logging import get_logger

log = get_logger("disagg")

KV_PULL_ENDPOINT = "kv_pull"


@dataclass
class _Transfer:
    block_ids: list[int]      # pinned device blocks (refcounted)
    seq_hashes: list[int]     # chain covered by the pin, same length
    deadline: float


class KvTransferSource:
    def __init__(self, engine: AsyncJaxEngine, ttl_s: float = 60.0):
        self.engine = engine
        self.ttl_s = ttl_s
        self._transfers: dict[str, _Transfer] = {}
        self._gc_task: asyncio.Task | None = None

    def start(self) -> None:
        if self._gc_task is None:
            self._gc_task = asyncio.create_task(self._gc_loop())

    async def stop(self) -> None:
        if self._gc_task is not None:
            self._gc_task.cancel()
            self._gc_task = None
        for xid in list(self._transfers):
            await self._release(xid)

    # ------------------------------------------------------------------
    async def register(self, seq_hashes: list[int]) -> dict | None:
        """Pin the device-resident prefix of ``seq_hashes``; returns the
        kv_transfer_params fragment (id + covered hashes) or None if nothing
        is resident (e.g. prompt shorter than one block)."""
        if not seq_hashes:
            return None
        block_ids = await self.engine.run_in_core(
            lambda core: core.pin_blocks(seq_hashes))
        if not block_ids:
            return None
        xid = uuid.uuid4().hex
        covered = seq_hashes[: len(block_ids)]
        self._transfers[xid] = _Transfer(
            block_ids=block_ids, seq_hashes=covered,
            deadline=time.monotonic() + self.ttl_s)
        return {"xfer_id": xid, "block_hashes": covered}

    async def _release(self, xid: str) -> None:
        xfer = self._transfers.pop(xid, None)
        if xfer is not None:
            await self.engine.run_in_core(
                lambda core: core.unpin_blocks(xfer.block_ids))

    async def _gc_loop(self) -> None:
        while True:
            await asyncio.sleep(self.ttl_s / 4)
            now = time.monotonic()
            for xid, xfer in list(self._transfers.items()):
                if xfer.deadline <= now:
                    log.warning("kv transfer %s expired unpulled; releasing", xid)
                    await self._release(xid)

    # ------------------------------------------------------------------
    async def kv_pull_handler(self, payload: dict, ctx):
        """Data-plane handler: stream the pinned blocks' raw bytes.

        One DATA frame per block keeps frames small and lets the decode
        side overlap receive with inject."""
        xid = payload.get("xfer_id", "")
        xfer = self._transfers.get(xid)
        if xfer is None:
            raise KeyError(f"unknown or expired kv transfer {xid!r}")
        plan = await self.engine.run_in_core(
            lambda core: core.export_blocks(xfer.seq_hashes))
        try:
            for h, parent, data in plan:
                yield {"h": h, "p": parent, "d": data.tobytes()}
        finally:
            if payload.get("release", True):
                await self._release(xid)
