"""Disaggregated request handlers: decode-first flow + prefill wrapper.

Reference: components/src/dynamo/vllm/handlers.py — the decode worker
decides per request whether remote prefill is worthwhile (``can_prefill``),
calls the prefill pool, then generates locally with the handed-off KV; the
prefill worker generates exactly one token and returns transfer metadata.
The TRT-LLM PREFILL_FIRST strategy routes through prefill first — here we
implement the decode-first (vLLM) pattern.
"""

from __future__ import annotations

import copy
from typing import Any, AsyncIterator, Callable

from dynamo_tpu.disagg.receiver import pull_and_import
from dynamo_tpu.disagg.source import KvTransferSource
from dynamo_tpu.engine.engine import AsyncJaxEngine
from dynamo_tpu.protocols.common import PreprocessedRequest, StopConditions
from dynamo_tpu.tokens import compute_block_hashes_for_tokens
from dynamo_tpu.utils.logging import get_logger

log = get_logger("disagg")


class PrefillHandler:
    """Wraps an engine as a prefill-only worker: compute prompt KV, discard
    the sampled token, pin + advertise the blocks for pulling."""

    def __init__(self, engine: AsyncJaxEngine, source: KvTransferSource,
                 block_size: int):
        self.engine = engine
        self.source = source
        self.block_size = block_size

    async def generate(self, payload: dict, ctx) -> AsyncIterator[dict]:
        req = PreprocessedRequest.from_dict(payload)
        # Prefill-only: one step past the prompt, sampling result discarded
        # (the decode side samples its own first token from the handed-off KV).
        req.stop_conditions = StopConditions(max_tokens=1, ignore_eos=True)
        async for out in self.engine.generate(req):
            if ctx.is_cancelled():
                return
            if out.finish_reason is not None and out.error:
                yield out.to_dict()
                return
        # The decode scheduler can match at most (prompt_len-1)//block_size
        # blocks (it must recompute ≥1 token for last-position state), so a
        # final exactly-full block would be transferred but never matched —
        # don't ship it.
        cap = (len(req.token_ids) - 1) // self.block_size
        hashes = compute_block_hashes_for_tokens(req.token_ids, self.block_size)[:cap]
        params = await self.source.register(hashes)
        result: dict[str, Any] = {"token_ids": [], "finish_reason": "stop"}
        if params is not None:
            result["kv_transfer_params"] = params
        yield result


class DisaggDecodeHandler:
    """Decode worker handler with conditional remote prefill.

    ``prefill_call(payload, request_id)`` is any async-iterator factory that
    reaches the prefill pool (a PushRouter/KvPushRouter generate) — injected
    so the handler is transport-agnostic and unit-testable.
    """

    def __init__(
        self,
        engine: AsyncJaxEngine,
        prefill_call: Callable[[dict, str], AsyncIterator[dict]],
        block_size: int,
        min_prefill_blocks: int = 2,
    ):
        self.engine = engine
        self.prefill_call = prefill_call
        self.block_size = block_size
        self.min_prefill_blocks = min_prefill_blocks
        self.remote_prefills = 0
        self.local_fallbacks = 0

    def _can_disagg(self, req: PreprocessedRequest) -> bool:
        return len(req.token_ids) // self.block_size >= self.min_prefill_blocks

    async def _remote_prefill(self, req: PreprocessedRequest) -> None:
        pre = copy.deepcopy(req)
        pre.request_id = f"{req.request_id}-prefill"
        pre.annotations["disagg"] = "prefill"
        params = None
        async for out in self.prefill_call(pre.to_dict(), pre.request_id):
            if isinstance(out, dict) and out.get("kv_transfer_params"):
                params = out["kv_transfer_params"]
        if params is None:
            raise RuntimeError("prefill worker returned no kv_transfer_params")
        await pull_and_import(self.engine, params)

    async def generate(self, payload: dict, ctx) -> AsyncIterator[dict]:
        req = PreprocessedRequest.from_dict(payload)
        if self._can_disagg(req):
            try:
                await self._remote_prefill(req)
                self.remote_prefills += 1
            except Exception as exc:
                # Conditional disagg: fall back to local prefill rather than
                # failing the request (reference: can_prefill gating).
                self.local_fallbacks += 1
                log.warning("remote prefill failed (%s); prefilling locally", exc)
        async for out in self.engine.generate(req):
            if ctx.is_cancelled():
                return
            yield out.to_dict()
