"""Disaggregated request handlers: decode-first flow + prefill wrapper.

Reference: components/src/dynamo/vllm/handlers.py — the decode worker
decides per request whether remote prefill is worthwhile (``can_prefill``),
calls the prefill pool, then generates locally with the handed-off KV; the
prefill worker generates exactly one token and returns transfer metadata.
The TRT-LLM PREFILL_FIRST strategy routes through prefill first — here we
implement the decode-first (vLLM) pattern.

The streamed handoff (default) turns the prefill response into an event
stream: one announce event up front (transfer id + shard endpoints + full
expected hash chain), one availability event per staged wave while the
prefill is still computing, then the final message with the voted
``kv_transfer_params``. A decode worker that understands the events pulls
waves as they land (StreamedKvConsumer); one that doesn't can ignore them
and use the final params exactly as before — single-wave transfers are
byte-identical to the legacy staged pull either way.
"""

from __future__ import annotations

import asyncio
import copy
from typing import Any, AsyncIterator, Callable

from dynamo_tpu.disagg.receiver import StreamedKvConsumer, pull_and_import
from dynamo_tpu.disagg.source import KvTransferSource
from dynamo_tpu.engine.engine import AsyncJaxEngine
from dynamo_tpu.protocols.common import PreprocessedRequest, StopConditions
from dynamo_tpu.tokens import compute_block_hashes_for_tokens
from dynamo_tpu.utils.logging import get_logger

log = get_logger("disagg")


class PrefillHandler:
    """Wraps an engine as a prefill-only worker: compute prompt KV, discard
    the sampled token, pin + advertise the blocks for pulling — streaming
    wave availability to the caller while the prefill is still running."""

    def __init__(self, engine: AsyncJaxEngine, source: KvTransferSource,
                 block_size: int, stream: bool = True):
        self.engine = engine
        self.source = source
        self.block_size = block_size
        self.stream = stream

    async def generate(self, payload: dict, ctx) -> AsyncIterator[dict]:
        req = PreprocessedRequest.from_dict(payload)
        # Prefill-only: one step past the prompt, sampling result discarded
        # (the decode side samples its own first token from the handed-off KV).
        req.stop_conditions = StopConditions(max_tokens=1, ignore_eos=True)
        # The decode scheduler can match at most (prompt_len-1)//block_size
        # blocks (it must recompute ≥1 token for last-position state), so a
        # final exactly-full block would be transferred but never matched —
        # don't ship it.
        cap = (len(req.token_ids) - 1) // self.block_size
        hashes = compute_block_hashes_for_tokens(req.token_ids, self.block_size)[:cap]
        if self.stream and hashes:
            async for out in self._generate_streamed(req, hashes, ctx):
                yield out
            return
        async for out in self.engine.generate(req):
            if ctx.is_cancelled():
                return
            if out.finish_reason is not None and out.error:
                yield out.to_dict()
                return
        params = await self.source.register(hashes)
        result: dict[str, Any] = {"token_ids": [], "finish_reason": "stop"}
        if params is not None:
            result["kv_transfer_params"] = params
        yield result

    async def _generate_streamed(self, req: PreprocessedRequest,
                                 hashes: list[int],
                                 ctx) -> AsyncIterator[dict]:
        """Register the transfer up front, run the prefill concurrently, and
        relay wave availability events as they land. The engine pump and
        the wave listener share one queue so a single await drives both."""
        events: asyncio.Queue = asyncio.Queue()
        reg = await self.source.register_streaming(req.request_id, hashes,
                                                   events)
        xid = reg["xfer_id"]

        async def pump() -> None:
            try:
                async for out in self.engine.generate(req):
                    if out.finish_reason is not None and out.error:
                        await events.put(("error", out))
                        return
            finally:
                await events.put(("done", None))

        task = asyncio.create_task(pump())
        announced = 0
        error_out = None
        handed_off = False
        try:
            yield {"kv_transfer_stream": {
                "xfer_id": xid, "shards": reg["shards"],
                "block_hashes": hashes, "ready": 0}}
            while True:
                kind, val = await events.get()
                if ctx.is_cancelled():
                    return
                if kind == "wave":
                    val = min(int(val), len(hashes))
                    if val > announced:
                        announced = val
                        yield {"kv_transfer_stream": {"xfer_id": xid,
                                                      "ready": val}}
                elif kind == "error":
                    error_out = val
                elif kind == "done":
                    break
            if error_out is not None:
                yield error_out.to_dict()
                return
            # The final wave may have been staged without its event being
            # consumed yet — the voted covered count is authoritative.
            covered = await self.source.finish_streaming(xid)
            handed_off = True  # TTL owns the transfer from here
            result: dict[str, Any] = {"token_ids": [], "finish_reason": "stop"}
            if covered:
                result["kv_transfer_params"] = {
                    "xfer_id": xid, "block_hashes": hashes[:covered],
                    "shards": reg["shards"], "streamed": True}
            yield result
        finally:
            task.cancel()
            if not handed_off:
                # Cancelled, errored, or the caller dropped the stream:
                # release pins for shipped and not-yet-staged waves alike.
                asyncio.get_running_loop().create_task(
                    self.source.abort_streaming(xid))


class DisaggDecodeHandler:
    """Decode worker handler with conditional remote prefill.

    ``prefill_call(payload, request_id)`` is any async-iterator factory that
    reaches the prefill pool (a PushRouter/KvPushRouter generate) — injected
    so the handler is transport-agnostic and unit-testable.
    """

    def __init__(
        self,
        engine: AsyncJaxEngine,
        prefill_call: Callable[[dict, str], AsyncIterator[dict]],
        block_size: int,
        min_prefill_blocks: int = 2,
    ):
        self.engine = engine
        self.prefill_call = prefill_call
        self.block_size = block_size
        self.min_prefill_blocks = min_prefill_blocks
        self.remote_prefills = 0
        self.local_fallbacks = 0

    def _can_disagg(self, req: PreprocessedRequest) -> bool:
        return len(req.token_ids) // self.block_size >= self.min_prefill_blocks

    async def _remote_prefill(self, req: PreprocessedRequest) -> None:
        pre = copy.deepcopy(req)
        pre.request_id = f"{req.request_id}-prefill"
        pre.annotations["disagg"] = "prefill"
        consumer: StreamedKvConsumer | None = None
        params = None
        try:
            async for out in self.prefill_call(pre.to_dict(), pre.request_id):
                if not isinstance(out, dict):
                    continue
                ev = out.get("kv_transfer_stream")
                if ev is not None:
                    if consumer is None and ev.get("shards"):
                        consumer = StreamedKvConsumer(self.engine, ev)
                    elif consumer is not None and ev.get("ready"):
                        await consumer.advance(int(ev["ready"]))
                if out.get("kv_transfer_params"):
                    params = out["kv_transfer_params"]
        except Exception:
            if consumer is not None:
                await consumer.abort()
            raise
        if consumer is not None:
            try:
                n = await consumer.finish(params)
            except Exception:
                await consumer.abort()
                raise
            if n == 0 and params is None:
                # The prefill stream ended without handing anything off
                # (e.g. its engine errored before the first wave).
                raise RuntimeError(
                    "prefill worker returned no kv_transfer_params")
            return
        if params is None:
            raise RuntimeError("prefill worker returned no kv_transfer_params")
        await pull_and_import(self.engine, params)

    async def generate(self, payload: dict, ctx) -> AsyncIterator[dict]:
        req = PreprocessedRequest.from_dict(payload)
        if self._can_disagg(req):
            try:
                await self._remote_prefill(req)
                self.remote_prefills += 1
            except Exception as exc:
                # Conditional disagg: fall back to local prefill rather than
                # failing the request (reference: can_prefill gating).
                self.local_fallbacks += 1
                log.warning("remote prefill failed (%s); prefilling locally", exc)
        async for out in self.engine.generate(req):
            if ctx.is_cancelled():
                return
            yield out.to_dict()
