from dynamo_tpu.utils.config import RuntimeConfig
from dynamo_tpu.utils.logging import configure_logging, get_logger

__all__ = ["RuntimeConfig", "configure_logging", "get_logger"]
