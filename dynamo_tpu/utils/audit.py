"""Audit bus: broadcast of full request/response records to pluggable sinks.

Fills the role of the reference's audit subsystem
(reference: lib/llm/src/audit/bus.rs:8-23 — a process-wide broadcast
channel of AuditRecord; handle.rs:13-30 — the per-request handle that
captures the full chat request and final response; sinks subscribe for
logging/compliance).

Here: an asyncio fan-out bus with bounded per-subscriber queues
(slow sinks drop oldest, never block serving), a module-level default bus
mirroring the reference's OnceLock pattern, and a JSONL sink. The HTTP
frontend publishes a record per chat completion when auditing is enabled
(``DYN_AUDIT_JSONL=/path`` or programmatic ``init``).
"""

from __future__ import annotations

import asyncio
import json
import time
from dataclasses import asdict, dataclass, field
from typing import Any, AsyncIterator

from dynamo_tpu.utils.logging import get_logger

log = get_logger("audit")

SCHEMA_VERSION = 1


@dataclass
class AuditRecord:
    """(reference: audit/handle.rs AuditRecord)"""

    request_id: str
    model: str
    requested_streaming: bool = False
    schema_version: int = SCHEMA_VERSION
    timestamp: float = field(default_factory=time.time)
    request: dict[str, Any] | None = None
    response: dict[str, Any] | None = None
    error: str | None = None

    def to_json(self) -> str:
        return json.dumps(asdict(self), default=str)


class AuditBus:
    """Fan-out of records to bounded subscriber queues (drop-oldest)."""

    def __init__(self, capacity: int = 256):
        self.capacity = capacity
        self._subs: list[asyncio.Queue] = []
        self.published = 0
        self.dropped = 0

    def publish(self, rec: AuditRecord) -> None:
        self.published += 1
        for q in self._subs:
            if q.full():
                # Never block the serving path on a slow sink.
                try:
                    q.get_nowait()
                    self.dropped += 1
                except asyncio.QueueEmpty:
                    pass
            q.put_nowait(rec)

    def subscribe(self) -> "AuditSubscription":
        q: asyncio.Queue = asyncio.Queue(self.capacity)
        self._subs.append(q)
        return AuditSubscription(self, q)

    def _unsubscribe(self, q: asyncio.Queue) -> None:
        if q in self._subs:
            self._subs.remove(q)


class AuditSubscription:
    def __init__(self, bus: AuditBus, q: asyncio.Queue):
        self._bus = bus
        self._q = q

    def __aiter__(self) -> AsyncIterator[AuditRecord]:
        return self._iter()

    async def _iter(self) -> AsyncIterator[AuditRecord]:
        while True:
            yield await self._q.get()

    def cancel(self) -> None:
        self._bus._unsubscribe(self._q)


class JsonlAuditSink:
    """Appends every record as one JSON line (the compliance-log sink)."""

    def __init__(self, bus: AuditBus, path: str):
        self.path = path
        self._sub = bus.subscribe()
        self._task: asyncio.Task | None = None

    def start(self) -> None:
        self._task = asyncio.ensure_future(self._loop())

    async def _loop(self) -> None:
        import asyncio as _asyncio

        loop = _asyncio.get_running_loop()
        try:
            with open(self.path, "a") as f:
                async for rec in self._sub:
                    line = rec.to_json() + "\n"
                    # Disk writes off-loop: a slow/full filesystem must not
                    # stall the serving event loop this sink shares.
                    await loop.run_in_executor(
                        None, lambda: (f.write(line), f.flush()))
        except _asyncio.CancelledError:
            raise
        except Exception:
            # A dead compliance sink must be LOUD — records keep dropping
            # into this subscriber's queue while the operator believes
            # auditing is on.
            log.exception("audit JSONL sink died (%s); records are NOT "
                          "being persisted", self.path)

    async def stop(self) -> None:
        self._sub.cancel()
        if self._task:
            self._task.cancel()
            try:
                await self._task
            except (asyncio.CancelledError, Exception):
                pass


# -- module-level default bus (reference: bus.rs OnceLock BUS) --------------
_BUS: AuditBus | None = None
_SINK: JsonlAuditSink | None = None


def init(capacity: int = 256, jsonl_path: str | None = None) -> AuditBus:
    global _BUS, _SINK
    if _BUS is None:
        _BUS = AuditBus(capacity)
    if jsonl_path and _SINK is None:
        _SINK = JsonlAuditSink(_BUS, jsonl_path)
        _SINK.start()
        log.info("audit JSONL sink: %s", jsonl_path)
    return _BUS


def maybe_init_from_env() -> AuditBus | None:
    """Enable auditing when DYN_AUDIT_JSONL names a sink path."""
    import os

    path = os.environ.get("DYN_AUDIT_JSONL")
    if path:
        return init(jsonl_path=path)
    return _BUS


def bus() -> AuditBus | None:
    return _BUS


def publish(rec: AuditRecord) -> None:
    if _BUS is not None:
        _BUS.publish(rec)
