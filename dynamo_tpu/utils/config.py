"""Layered runtime configuration.

Mirrors the reference's figment-layered ``RuntimeConfig``
(reference: lib/runtime/src/config.rs) — values resolve, in order of
precedence: explicit kwargs > ``DYN_*`` environment variables > config file
(TOML-like JSON/YAML) > defaults.
"""

from __future__ import annotations

import dataclasses
import json
import os
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any

ENV_PREFIX = "DYN_"


def _coerce(value: str, typ: type) -> Any:
    if typ is bool:
        return value.lower() in ("1", "true", "yes", "on")
    if typ is int:
        return int(value)
    if typ is float:
        return float(value)
    return value


@dataclass
class RuntimeConfig:
    """Process-level runtime settings (reference: lib/runtime/src/config.rs)."""

    # Worker threads for the compute pool (reference: compute/pool.rs).
    num_worker_threads: int = 0  # 0 = os.cpu_count()
    # Coordination service address (our consolidated etcd/NATS equivalent).
    coordinator_url: str = "tcp://127.0.0.1:6650"
    # Namespace this process operates in.
    namespace: str = "dynamo"
    # System status server (health/metrics) — reference: system_status_server.rs.
    system_enabled: bool = False
    system_port: int = 0  # 0 = ephemeral
    # Logging.
    log_level: str = "info"
    log_jsonl: bool = False
    # Request plane.
    request_timeout_s: float = 600.0
    # Primary lease TTL (liveness). Generous enough that a long GIL-holding
    # XLA trace/compile can't starve the keep-alive loop into lease expiry.
    lease_ttl_s: float = 20.0
    # Graceful shutdown drain deadline.
    drain_timeout_s: float = 30.0
    # Scheduling-policy bound on concurrently-executing handler streams
    # (excess CALLs queue; reference: tracker.rs semaphore policies).
    max_handler_streams: int = 1024

    @classmethod
    def from_settings(cls, path: str | os.PathLike | None = None, **overrides: Any) -> "RuntimeConfig":
        """Build config from defaults < file < DYN_* env < explicit overrides."""
        values: dict[str, Any] = {}
        candidate = path or os.environ.get(ENV_PREFIX + "CONFIG")
        if candidate and Path(candidate).exists():
            text = Path(candidate).read_text()
            try:
                values.update(json.loads(text))
            except json.JSONDecodeError:
                try:
                    import yaml

                    values.update(yaml.safe_load(text) or {})
                except Exception as exc:  # pragma: no cover - malformed config
                    raise ValueError(f"could not parse config file {candidate}") from exc
        fields = {f.name: f for f in dataclasses.fields(cls)}
        for name, f in fields.items():
            env_key = ENV_PREFIX + name.upper()
            if env_key in os.environ:
                values[name] = _coerce(os.environ[env_key], f.type if isinstance(f.type, type) else type(f.default))
        values.update({k: v for k, v in overrides.items() if v is not None})
        values = {k: v for k, v in values.items() if k in fields}
        return cls(**values)


@dataclass
class EngineConfig:
    """JAX engine settings (fills the role of vLLM EngineArgs in the reference;
    reference pass-through: components/src/dynamo/vllm/args.py)."""

    model: str = "tiny-llama"           # model name or local path
    tokenizer: str | None = None          # defaults to model path
    dtype: str = "bfloat16"
    block_size: int = 16                  # KV cache tokens per block
    num_blocks: int = 0                   # 0 = auto-size from HBM budget
    max_batch_size: int = 64
    max_model_len: int = 8192
    max_tokens_per_step: int = 8192       # prefill token budget per step
    # Chunked-prefill bucket. 0 = auto: costmodel.auto_prefill_chunk picks
    # the largest chunk whose predicted mixed-step time keeps decode ITL
    # inside itl_slo_ms (resolved to a concrete cap at engine construction
    # so bucket enumeration and warmup see real shapes).
    prefill_chunk: int = 512
    decode_bucket: tuple[int, ...] = (8, 16, 32, 64)
    # Unified ragged mixed-phase steps: pack the step's decode rows (one
    # live token each) and prefill-chunk rows (up to prefill_chunk live
    # tokens) into ONE ragged XLA program per iteration — per-row live
    # token counts ride the scalar-prefetch path, so padding costs
    # DMA-elided grid steps, not FLOPs. False = legacy two-launch path
    # (decode program, then prefill program) for bisection.
    unified_step: bool = True
    # Decode inter-token-latency SLO budget (milliseconds) that
    # costmodel.auto_prefill_chunk sizes chunks against when
    # prefill_chunk=0. Per-QoS ladder scales it: interactive 1x,
    # standard 2x, batch 4x.
    itl_slo_ms: float = 50.0
    # Mesh axes sizes; 1 = unsharded. (data, pipe, seq, model, expert)
    dp: int = 1
    pp: int = 1
    tp: int = 1
    ep: int = 1
    sp: int = 1
    # pp>1: microbatches interleaved across stage blocks per dispatch
    # (models/llama.forward_pp). 0 = auto (2*pp); shapes that don't divide
    # fall back to the sequential pipeline.
    pp_microbatches: int = 0
    # Weight-only quantization (models/quant.py): "none" | "int8".
    # int8 halves decode's HBM traffic (per-out-channel scales, bf16
    # compute on the MXU) — the roofline-doubling lever for the
    # bandwidth-bound decode metric.
    quantization: str = "none"
    # KV-cache storage dtype (engine/cache.py): "bfloat16" (store at model
    # precision — the default) | "int8" (symmetric per-block-per-kv-head
    # quantization: payload + f32 scale sidecar) | "int4" (same scale
    # pytree, two signed nibbles packed per byte along head_dim — needs an
    # even head_dim). int8 halves the paged cache's bytes_per_block and
    # int4 quarters it, so auto-sizing fits ~2x/~4x the blocks in the same
    # HBM budget and decode's KV reads move 1/2 / 1/4 the bytes; dequant
    # (and int4 nibble unpack) folds into the paged-attention kernel's
    # per-block matmuls.
    kv_dtype: str = "bfloat16"
    enable_prefix_caching: bool = True
    kv_event_publishing: bool = True
    # KVBM tiers (reference: lib/llm/src/block_manager.rs CacheLevel):
    # G2 host arena capacity in blocks (0 = disabled) and optional G3 disk
    # tier (path + byte budget). Device-evicted committed blocks write back
    # to host, host spills to disk, prompts onboard from either.
    host_kv_blocks: int = 0
    disk_kv_path: str | None = None
    disk_kv_bytes: int = 1 << 30
    # G4 remote block store ("host:port" of a RemoteBlockServer); chained
    # after host/disk in the offload cascade.
    remote_kv_addr: str | None = None
    # Fleet-wide prefix cache: publish committed prefix blocks to the G4
    # remote store PROACTIVELY (publish-on-commit, kvbm/offload.py) so a
    # cold worker can import a shared prefix another worker computed
    # instead of recomputing it. Requires remote_kv_addr; the import side
    # (admission-time onboard) is always on when tiers exist.
    global_prefix_cache: bool = False
    # N-gram speculative decoding (engine/spec.py): 0 = off; n>0 proposes
    # continuations of the trailing n-gram, verified k at a time in one
    # forward pass. Greedy-exact; mutually exclusive with decode_window>1.
    spec_ngram: int = 0
    spec_k: int = 4
    seed: int = 0
    # A checkpoint PATH without loadable weights fails engine construction
    # unless this is set — a typo'd path must not silently serve garbage.
    # (Named presets always random-init; they exist for tests/benches.)
    allow_random_weights: bool = False
    # Attention implementation: "auto" (pallas on TPU, dense elsewhere),
    # "dense", "pallas", or "pallas_interpret" (CPU-testable kernel path).
    attn_impl: str = "auto"
    # Split-K flash decode (ops/paged_attention.py): partition each row's
    # context-block walk across this many grid programs, combining partial
    # softmax state afterwards. 0 = auto (cost model picks from context
    # length and core count, decode only), 1 = sequential walk (off),
    # N>1 = forced split count (clamped to the block count).
    attn_num_splits: int = 0
    # Fused decode window: run up to this many decode steps inside ONE
    # compiled dispatch (lax.scan on device, sampled tokens feeding back
    # without touching the host). Amortizes the per-dispatch host round
    # trip — the dominant decode cost when the host is far from the chip.
    # Stop conditions lag by at most window-1 tokens; overrun is discarded
    # at finalize, so emitted streams are bit-identical to window=1.
    decode_window: int = 1
    # Session-sticky KV retention (engine/session.py): when a stream with a
    # session.id annotation finishes, its committed KV blocks stay pinned
    # on device for this many seconds (leader-stamped step clock) so turn
    # N+1 prefills only the new suffix. 0 = retention off.
    session_ttl: float = 0.0
    # On TTL expiry or pool pressure, stage a retained session's blocks
    # down the KVBM tier ladder (host→disk) before unpinning, so a later
    # turn can re-import them even after device eviction. False drops the
    # pins to plain LRU without the write-through.
    session_tiers: bool = True
    # AOT bucket warmup / compile ledger (obs/compile_ledger.py):
    # "off" disables the XLA compile ledger entirely (zero per-dispatch
    # overhead), "lazy" records organic compiles against the enumerated
    # bucket lattice (the default — full observability, no precompiles),
    # "full" precompiles the reachable lattice at startup so no serving
    # request ever pays a cold-bucket trace+compile stall (worker
    # readiness waits for it).
    warmup_mode: str = "lazy"
    # Wall-seconds budget for full-mode warmup; lattice entries past the
    # deadline stay cold and show up as coverage < 1.0. 0 = unbounded.
    warmup_deadline: float = 120.0
    # Context-parallel ring prefill (sp>1 meshes, ops/ring_attention.py):
    # minimum prompt tokens before a fresh prompt prefills as ONE
    # seq-sharded ring chunk instead of the chunked sequential path.
    # 0 = auto (ring-vs-chunked break-even from obs/costmodel.py),
    # N>0 = explicit token threshold, -1 = never (ring path fully off —
    # the engine behaves exactly like an sp=1 chunked engine).
    ring_prefill_threshold: int = 0
    # Crash-consistent stream checkpoints (kvbm/stream_ckpt.py): every
    # this-many committed decode blocks (and once at prefill completion)
    # an in-flight stream's newly committed KV blocks plus a resumable
    # StreamCheckpoint record flush to the shared G4 remote store, so an
    # unplanned worker kill costs at most one interval of recompute. The
    # cadence is QoS-degraded (interactive 1x, standard 2x, batch 4x).
    # 0 = off. Requires remote_kv_addr; single-host engines only (the
    # multi-host drain path still covers planned exits).
    stream_ckpt_blocks: int = 0

    def mesh_shape(self) -> dict[str, int]:
        return {"data": self.dp, "pipe": self.pp, "model": self.tp,
                "expert": self.ep, "seq": self.sp}
