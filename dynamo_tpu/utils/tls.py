"""TLS configuration shared by every ingress (HTTP + gRPC)."""

from __future__ import annotations


def validate_tls_pair(tls_cert: str | None, tls_key: str | None) -> bool:
    """True → serve TLS; False → plaintext. One copy of the pair rule,
    callable before any server setup side effects."""
    if tls_cert or tls_key:
        if not (tls_cert and tls_key):
            raise ValueError(
                "TLS needs both a certificate and a private key "
                "(--tls-cert/--tls-key on the frontend CLI)")
        return True
    return False
