"""Shared text-streaming helpers."""

from __future__ import annotations

from typing import Iterable


def longest_partial_suffix(text: str, markers: Iterable[str]) -> int:
    """Length of the longest suffix of ``text`` that is a proper prefix of
    any marker — the amount of text a streaming stage must withhold because
    it may be the start of a marker still arriving.

    Shared by the detokenizer's stop-string jail, the reasoning parser's
    think-tag buffering, and the tool-call jail.
    """
    best = 0
    for marker in markers:
        upper = min(len(marker) - 1, len(text))
        for k in range(upper, 0, -1):
            if marker.startswith(text[-k:]):
                best = max(best, k)
                break
    return best
