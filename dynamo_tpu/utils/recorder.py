"""Stream recorder: capture coordinator subjects (KV events, load metrics)
or request streams to JSONL for replay and analysis.

Fills the role of the reference's recorders
(reference: lib/llm/src/recorder.rs — request/event recorder;
lib/llm/src/kv_router/recorder.rs:135 — the KV-event recorder used to
capture real routing workloads for offline router evaluation).

CLI: ``python -m dynamo_tpu.utils.recorder --coordinator tcp://... \
      --subject 'kv_events.dynamo.backend' --out events.jsonl``

Replay: :func:`load_router_events` turns a recorded KV-event file back
into RouterEvent objects, so recorded workloads can drive a RadixIndexer
offline (router evaluation / regression analysis).
"""

from __future__ import annotations

import argparse
import asyncio
import json
import signal
import time
from typing import Iterator

import msgpack

from dynamo_tpu.utils.logging import configure_logging, get_logger

log = get_logger("recorder")


class _SharedWriter:
    """One file handle + asyncio lock per output path: recorders for
    multiple subjects appending to the same file cannot interleave lines."""

    def __init__(self, path: str):
        self._f = open(path, "a")
        self._lock = asyncio.Lock()

    async def write_line(self, line: str) -> None:
        loop = asyncio.get_running_loop()
        async with self._lock:
            await loop.run_in_executor(
                None, lambda: (self._f.write(line), self._f.flush()))

    def close(self) -> None:
        self._f.close()


class StreamRecorder:
    """Subscribes to one coordinator pub/sub subject; writes one JSON line
    per message: {"t": ..., "subject": ..., "payload": ...}."""

    def __init__(self, coord, subject: str, path: str,
                 writer: "_SharedWriter | None" = None):
        self.coord = coord
        self.subject = subject
        self.path = path
        self._writer = writer or _SharedWriter(path)
        self._owns_writer = writer is None
        self.count = 0
        self._task: asyncio.Task | None = None

    async def start(self) -> None:
        sub = await self.coord.subscribe(self.subject)
        self._task = asyncio.ensure_future(self._loop(sub))

    async def _loop(self, sub) -> None:
        async for subject, payload in sub:
            try:
                obj = msgpack.unpackb(payload, raw=False)
            except Exception:
                obj = {"_raw_hex": payload.hex()}
            line = json.dumps({
                "t": time.time(), "subject": subject, "payload": obj,
            }, default=str) + "\n"
            # Off-loop + per-file locked: recording must neither stall the
            # event loop nor interleave lines across subjects.
            await self._writer.write_line(line)
            self.count += 1

    async def stop(self) -> None:
        if self._task:
            self._task.cancel()
            try:
                await self._task
            except (asyncio.CancelledError, Exception):
                pass
        if self._owns_writer:
            self._writer.close()


def iter_records(path: str) -> Iterator[dict]:
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                yield json.loads(line)


def load_router_events(path: str) -> list:
    """Recorded kv_events file → RouterEvent list (replayable into a
    RadixIndexer for offline router evaluation)."""
    from dynamo_tpu.router.events import RouterEvent

    out = []
    for rec in iter_records(path):
        payload = rec.get("payload")
        if isinstance(payload, list):
            for d in payload:
                try:
                    out.append(RouterEvent.from_dict(d))
                except Exception:
                    log.warning("skipping malformed event record")
    return out


async def amain(ns: argparse.Namespace) -> None:
    from dynamo_tpu.transports.client import CoordinatorClient

    coord = await CoordinatorClient.connect(ns.coordinator)
    writer = _SharedWriter(ns.out)
    recorders = [StreamRecorder(coord, s, ns.out, writer=writer)
                 for s in ns.subject]
    for r in recorders:
        await r.start()
    log.info("recording %s -> %s", ns.subject, ns.out)
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        loop.add_signal_handler(sig, stop.set)
    await stop.wait()
    for r in recorders:
        await r.stop()
    writer.close()
    await coord.close()
    log.info("recorded %d messages", sum(r.count for r in recorders))


def main() -> None:
    configure_logging()
    p = argparse.ArgumentParser("dynamo-recorder")
    p.add_argument("--coordinator", required=True)
    p.add_argument("--subject", action="append", required=True,
                   help="pub/sub subject (repeatable), e.g. kv_events.dynamo.backend")
    p.add_argument("--out", required=True, help="JSONL output path")
    asyncio.run(amain(p.parse_args()))


if __name__ == "__main__":
    main()
