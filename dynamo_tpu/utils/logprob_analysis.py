"""Logprob analysis: confidence/perplexity statistics over served streams.

Fills the role of the reference's logprob perf tooling
(reference: lib/llm/src/perf/logprobs.rs — 1.6k LoC of logprob
extraction + analysis over recorded response streams). Consumes either
live OpenAI response objects (chat `logprobs.content` /
completions `logprobs.token_logprobs`, as emitted by frontend/delta.py)
or a stream-recorder JSONL (utils/recorder.py), and computes per-sequence
statistics:

- total/mean logprob, perplexity (`exp(-mean lp)`)
- surprisal extremes and low-confidence positions (candidate
  hallucination / derail points — the reference's analysis use case)
- sliding-window perplexity to localize where a generation went bad

Pure numpy + stdlib; no engine dependency, so it runs on recorded
artifacts anywhere.
"""

from __future__ import annotations

import json
import math
from dataclasses import dataclass, field
from typing import Any, Iterable


@dataclass
class TokenLogprob:
    token: str
    logprob: float
    position: int


@dataclass
class SequenceStats:
    """Statistics for one generated sequence."""

    request_id: str = ""
    tokens: list[TokenLogprob] = field(default_factory=list)

    @property
    def num_tokens(self) -> int:
        return len(self.tokens)

    @property
    def total_logprob(self) -> float:
        return sum(t.logprob for t in self.tokens)

    @property
    def mean_logprob(self) -> float:
        return self.total_logprob / len(self.tokens) if self.tokens else 0.0

    @property
    def perplexity(self) -> float:
        return math.exp(-self.mean_logprob) if self.tokens else 1.0

    def min_logprob(self) -> TokenLogprob | None:
        return min(self.tokens, key=lambda t: t.logprob, default=None)

    def low_confidence(self, threshold: float = -4.0) -> list[TokenLogprob]:
        """Tokens sampled with logprob below ``threshold`` (p < ~1.8% at
        the default) — the positions worth human review."""
        return [t for t in self.tokens if t.logprob < threshold]

    def window_perplexity(self, window: int = 16) -> list[float]:
        """Sliding-window perplexity; a spike localizes where the
        generation lost the plot."""
        if len(self.tokens) < window:
            return [self.perplexity] if self.tokens else []
        out = []
        lps = [t.logprob for t in self.tokens]
        acc = sum(lps[:window])
        out.append(math.exp(-acc / window))
        for i in range(window, len(lps)):
            acc += lps[i] - lps[i - window]
            out.append(math.exp(-acc / window))
        return out

    def summary(self) -> dict:
        worst = self.min_logprob()
        return {
            "request_id": self.request_id,
            "num_tokens": self.num_tokens,
            "total_logprob": round(self.total_logprob, 4),
            "mean_logprob": round(self.mean_logprob, 4),
            "perplexity": round(self.perplexity, 4),
            "min_logprob": round(worst.logprob, 4) if worst else None,
            "min_logprob_token": worst.token if worst else None,
            "min_logprob_position": worst.position if worst else None,
            "low_confidence_count": len(self.low_confidence()),
        }


# ---------------------------------------------------------------------------
# Extraction from OpenAI response shapes (what frontend/delta.py emits)
# ---------------------------------------------------------------------------

def from_chat_response(resp: dict, request_id: str = "") -> SequenceStats:
    """Chat response/chunk: choices[0].logprobs.content[*].{token,logprob}.
    Accepts a full response or any chunk carrying logprobs content."""
    stats = SequenceStats(request_id=request_id or resp.get("id", ""))
    _extend_from_chat(stats, resp)
    return stats


def _extend_from_chat(stats: SequenceStats, resp: dict) -> None:
    for choice in resp.get("choices") or []:
        content = (choice.get("logprobs") or {}).get("content") or []
        for entry in content:
            lp = entry.get("logprob")
            if lp is None:
                continue  # unmeasured (mocker/legacy peer) — not certainty
            stats.tokens.append(TokenLogprob(
                token=entry.get("token", ""),
                logprob=float(lp),
                position=len(stats.tokens)))


def from_chat_stream(chunks: Iterable[dict], request_id: str = "") -> SequenceStats:
    """Aggregate chat.completion.chunk events (SSE stream) into one
    sequence's stats."""
    stats = SequenceStats(request_id=request_id)
    for chunk in chunks:
        if not stats.request_id:
            stats.request_id = chunk.get("id", "")
        _extend_from_chat(stats, chunk)
    return stats


def from_completion_response(resp: dict, request_id: str = "") -> SequenceStats:
    """Completions response: choices[0].logprobs.{tokens,token_logprobs}."""
    stats = SequenceStats(request_id=request_id or resp.get("id", ""))
    for choice in resp.get("choices") or []:
        lp = choice.get("logprobs") or {}
        for tok, l in zip(lp.get("tokens") or [], lp.get("token_logprobs") or []):
            if l is None:
                continue  # unmeasured — same skip rule as the chat shape
            stats.tokens.append(TokenLogprob(
                token=tok, logprob=float(l), position=len(stats.tokens)))
    return stats


def from_engine_outputs(outputs: Iterable[Any], request_id: str = "") -> SequenceStats:
    """Directly from LLMEngineOutput/BackendOutput deltas (token_ids +
    log_probs) — the in-process path, no HTTP shape required."""
    stats = SequenceStats(request_id=request_id)
    for out in outputs:
        lps = getattr(out, "log_probs", None) or []
        for lp in lps:
            stats.tokens.append(TokenLogprob(
                token="", logprob=float(lp), position=len(stats.tokens)))
    return stats


# ---------------------------------------------------------------------------
# Recorded artifacts (utils/recorder.py JSONL)
# ---------------------------------------------------------------------------

def analyze_recording(path: str) -> list[dict]:
    """Each JSONL record holding an OpenAI response (chat or completion)
    becomes one summary; records without logprobs are skipped."""
    summaries = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            body = rec.get("payload", rec)
            if isinstance(body, str):
                try:
                    body = json.loads(body)
                except json.JSONDecodeError:
                    continue
            if not isinstance(body, dict):
                continue
            if body.get("object", "").startswith("chat.completion"):
                stats = from_chat_response(body)
            elif body.get("object") == "text_completion":
                stats = from_completion_response(body)
            else:
                continue
            if stats.num_tokens:
                summaries.append(stats.summary())
    return summaries
