"""Minimal Prometheus-compatible metrics registry.

Fills the role of the reference's hierarchical MetricsRegistry
(reference: lib/runtime/src/metrics.rs, name constants in
metrics/prometheus_names.rs): counters/gauges/histograms with labels,
hierarchical auto-labels (namespace/component/endpoint), and text
exposition for a /metrics endpoint. Dependency-free.
"""

from __future__ import annotations

import math
import threading
from collections import defaultdict
from dataclasses import dataclass, field
from typing import Callable, Iterable

DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)


def _fmt_labels(labels: dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
    return "{" + inner + "}"


class Counter:
    def __init__(self, name: str, help_: str, const_labels: dict[str, str]):
        self.name, self.help = name, help_
        self.const = const_labels
        self._values: dict[tuple, float] = defaultdict(float)
        self._lock = threading.Lock()

    def inc(self, value: float = 1.0, **labels: str) -> None:
        with self._lock:
            self._values[tuple(sorted(labels.items()))] += value

    def get(self, **labels: str) -> float:
        return self._values.get(tuple(sorted(labels.items())), 0.0)

    def expose(self) -> Iterable[str]:
        yield f"# HELP {self.name} {self.help}"
        yield f"# TYPE {self.name} counter"
        if not self._values:
            yield f"{self.name}{_fmt_labels(self.const)} 0"
        for key, v in sorted(self._values.items()):
            labels = {**self.const, **dict(key)}
            yield f"{self.name}{_fmt_labels(labels)} {v}"


class Gauge(Counter):
    def set(self, value: float, **labels: str) -> None:
        with self._lock:
            self._values[tuple(sorted(labels.items()))] = value

    def expose(self) -> Iterable[str]:
        yield f"# HELP {self.name} {self.help}"
        yield f"# TYPE {self.name} gauge"
        if not self._values:
            yield f"{self.name}{_fmt_labels(self.const)} 0"
        for key, v in sorted(self._values.items()):
            labels = {**self.const, **dict(key)}
            yield f"{self.name}{_fmt_labels(labels)} {v}"


class FuncGauge:
    """Gauge whose value is computed at scrape time from a callback —
    for live state (queue depths, tracked clients) that would otherwise
    need a set() call on every mutation."""

    def __init__(self, name: str, help_: str, const_labels: dict[str, str],
                 fn: "Callable[[], float]"):
        self.name, self.help = name, help_
        self.const = const_labels
        self.fn = fn

    def get(self) -> float:
        return float(self.fn())

    def expose(self) -> Iterable[str]:
        yield f"# HELP {self.name} {self.help}"
        yield f"# TYPE {self.name} gauge"
        try:
            v = float(self.fn())
        except Exception:
            v = 0.0
        yield f"{self.name}{_fmt_labels(self.const)} {v}"


class Histogram:
    def __init__(self, name: str, help_: str, const_labels: dict[str, str],
                 buckets: tuple[float, ...] = DEFAULT_BUCKETS):
        self.name, self.help = name, help_
        self.const = const_labels
        self.buckets = tuple(buckets) + (math.inf,)
        self._counts: dict[tuple, list[int]] = {}
        self._sum: dict[tuple, float] = defaultdict(float)
        self._n: dict[tuple, int] = defaultdict(int)
        self._lock = threading.Lock()

    def observe(self, value: float, **labels: str) -> None:
        key = tuple(sorted(labels.items()))
        with self._lock:
            counts = self._counts.setdefault(key, [0] * len(self.buckets))
            for i, ub in enumerate(self.buckets):
                if value <= ub:
                    counts[i] += 1
            self._sum[key] += value
            self._n[key] += 1

    def percentile(self, q: float, **labels: str) -> float:
        """Approximate percentile from bucket counts (for planner/tests)."""
        key = tuple(sorted(labels.items()))
        counts = self._counts.get(key)
        if not counts or self._n[key] == 0:
            return 0.0
        target = q * self._n[key]
        for i, c in enumerate(counts):
            if c >= target:
                return self.buckets[i] if self.buckets[i] != math.inf else self.buckets[i - 1]
        return self.buckets[-2]

    def expose(self) -> Iterable[str]:
        yield f"# HELP {self.name} {self.help}"
        yield f"# TYPE {self.name} histogram"
        for key in sorted(self._counts):
            labels = {**self.const, **dict(key)}
            for i, ub in enumerate(self.buckets):
                lb = {**labels, "le": "+Inf" if ub == math.inf else repr(ub)}
                yield f"{self.name}_bucket{_fmt_labels(lb)} {self._counts[key][i]}"
            yield f"{self.name}_sum{_fmt_labels(labels)} {self._sum[key]}"
            yield f"{self.name}_count{_fmt_labels(labels)} {self._n[key]}"


@dataclass
class MetricsRegistry:
    """Hierarchical registry: child registries inherit const labels
    (reference: drt→namespace→component→endpoint hierarchy)."""

    prefix: str = "dynamo"
    const_labels: dict[str, str] = field(default_factory=dict)
    _metrics: dict[str, object] = field(default_factory=dict)
    _children: list["MetricsRegistry"] = field(default_factory=list)

    def child(self, **labels: str) -> "MetricsRegistry":
        c = MetricsRegistry(prefix=self.prefix, const_labels={**self.const_labels, **labels})
        self._children.append(c)
        return c

    def _full(self, name: str) -> str:
        return f"{self.prefix}_{name}"

    def counter(self, name: str, help_: str = "") -> Counter:
        key = "c:" + name
        if key not in self._metrics:
            self._metrics[key] = Counter(self._full(name), help_, self.const_labels)
        return self._metrics[key]  # type: ignore[return-value]

    def gauge(self, name: str, help_: str = "") -> Gauge:
        key = "g:" + name
        if key not in self._metrics:
            self._metrics[key] = Gauge(self._full(name), help_, self.const_labels)
        return self._metrics[key]  # type: ignore[return-value]

    def func_gauge(self, name: str, fn, help_: str = "") -> FuncGauge:
        key = "f:" + name
        if key not in self._metrics:
            self._metrics[key] = FuncGauge(self._full(name), help_, self.const_labels, fn)
        return self._metrics[key]  # type: ignore[return-value]

    def histogram(self, name: str, help_: str = "", buckets: tuple[float, ...] = DEFAULT_BUCKETS) -> Histogram:
        key = "h:" + name
        if key not in self._metrics:
            self._metrics[key] = Histogram(self._full(name), help_, self.const_labels, buckets)
        return self._metrics[key]  # type: ignore[return-value]

    def expose(self) -> str:
        lines: list[str] = []
        for m in self._metrics.values():
            lines.extend(m.expose())  # type: ignore[attr-defined]
        for c in self._children:
            lines.append(c.expose().rstrip("\n"))
        return "\n".join(lines) + "\n"
