"""Minimal Prometheus-compatible metrics registry.

Fills the role of the reference's hierarchical MetricsRegistry
(reference: lib/runtime/src/metrics.rs, name constants in
metrics/prometheus_names.rs): counters/gauges/histograms with labels,
hierarchical auto-labels (namespace/component/endpoint), and text
exposition for a /metrics endpoint. Dependency-free.

Exposition follows the Prometheus text format: one ``# HELP``/``# TYPE``
header per metric family across the whole registry tree (child
registries contribute samples, not duplicate headers), label values
escaped per the spec, and histogram ``le`` bounds rendered via a single
repr-stable formatter.
"""

from __future__ import annotations

import math
import re
import threading
from collections import defaultdict
from dataclasses import dataclass, field
from typing import Callable, Iterable, Mapping

DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)


def _escape_label_value(v: str) -> str:
    """Prometheus exposition escaping: backslash, double-quote, newline."""
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_labels(labels: dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{_escape_label_value(v)}"' for k, v in sorted(labels.items()))
    return "{" + inner + "}"


def _fmt_le(ub: float) -> str:
    """Render a bucket upper bound. Shared by observe (bucket identity)
    and expose so the printed ``le`` always names the float actually
    compared against; repr() of a true float is shortest-round-trip."""
    return "+Inf" if ub == math.inf else repr(float(ub))


class Counter:
    kind = "counter"

    def __init__(self, name: str, help_: str, const_labels: dict[str, str]):
        self.name, self.help = name, help_
        self.const = const_labels
        self._values: dict[tuple, float] = defaultdict(float)
        self._lock = threading.Lock()

    def inc(self, value: float = 1.0, **labels: str) -> None:
        with self._lock:
            self._values[tuple(sorted(labels.items()))] += value

    def get(self, **labels: str) -> float:
        with self._lock:
            return self._values.get(tuple(sorted(labels.items())), 0.0)

    def samples(self) -> Iterable[str]:
        with self._lock:
            values = sorted(self._values.items())
        if not values:
            yield f"{self.name}{_fmt_labels(self.const)} 0"
        for key, v in values:
            labels = {**self.const, **dict(key)}
            yield f"{self.name}{_fmt_labels(labels)} {v}"

    def expose(self) -> Iterable[str]:
        yield f"# HELP {self.name} {self.help}"
        yield f"# TYPE {self.name} {self.kind}"
        yield from self.samples()


class Gauge(Counter):
    kind = "gauge"

    def set(self, value: float, **labels: str) -> None:
        with self._lock:
            self._values[tuple(sorted(labels.items()))] = value


class FuncGauge:
    """Gauge whose value is computed at scrape time from a callback —
    for live state (queue depths, tracked clients) that would otherwise
    need a set() call on every mutation. The callback is allowed to
    raise (e.g. after its owner is torn down while the registry is still
    scraped): both get() and exposition fall back to 0.0."""

    kind = "gauge"

    def __init__(self, name: str, help_: str, const_labels: dict[str, str],
                 fn: "Callable[[], float]"):
        self.name, self.help = name, help_
        self.const = const_labels
        self.fn = fn

    def get(self) -> float:
        try:
            return float(self.fn())
        except Exception:
            return 0.0

    def samples(self) -> Iterable[str]:
        yield f"{self.name}{_fmt_labels(self.const)} {self.get()}"

    def expose(self) -> Iterable[str]:
        yield f"# HELP {self.name} {self.help}"
        yield f"# TYPE {self.name} {self.kind}"
        yield from self.samples()


class Histogram:
    kind = "histogram"

    def __init__(self, name: str, help_: str, const_labels: dict[str, str],
                 buckets: tuple[float, ...] = DEFAULT_BUCKETS):
        self.name, self.help = name, help_
        self.const = const_labels
        # Normalize to true floats so observe's comparisons and expose's
        # repr() agree even when callers pass numpy scalars / ints.
        self.buckets = tuple(float(b) for b in buckets) + (math.inf,)
        self._counts: dict[tuple, list[int]] = {}
        self._sum: dict[tuple, float] = defaultdict(float)
        self._n: dict[tuple, int] = defaultdict(int)
        self._lock = threading.Lock()

    def observe(self, value: float, **labels: str) -> None:
        key = tuple(sorted(labels.items()))
        with self._lock:
            counts = self._counts.setdefault(key, [0] * len(self.buckets))
            for i, ub in enumerate(self.buckets):
                if value <= ub:
                    counts[i] += 1
            self._sum[key] += value
            self._n[key] += 1

    def percentile(self, q: float, **labels: str) -> float:
        """Approximate percentile from bucket counts (for planner/tests)."""
        key = tuple(sorted(labels.items()))
        with self._lock:
            counts = self._counts.get(key)
            if not counts or self._n[key] == 0:
                return 0.0
            counts = list(counts)
            target = q * self._n[key]
        for i, c in enumerate(counts):
            if c >= target:
                return self.buckets[i] if self.buckets[i] != math.inf else self.buckets[i - 1]
        return self.buckets[-2]

    def samples(self) -> Iterable[str]:
        with self._lock:
            snap = {k: (list(c), self._sum[k], self._n[k])
                    for k, c in self._counts.items()}
        for key in sorted(snap):
            counts, total, n = snap[key]
            labels = {**self.const, **dict(key)}
            for i, ub in enumerate(self.buckets):
                lb = {**labels, "le": _fmt_le(ub)}
                yield f"{self.name}_bucket{_fmt_labels(lb)} {counts[i]}"
            yield f"{self.name}_sum{_fmt_labels(labels)} {total}"
            yield f"{self.name}_count{_fmt_labels(labels)} {n}"

    def expose(self) -> Iterable[str]:
        yield f"# HELP {self.name} {self.help}"
        yield f"# TYPE {self.name} {self.kind}"
        yield from self.samples()


# ---------------------------------------------------------------------------
# Parsing — the inverse of expose(). One parser for every scraper in the
# tree (planner, chaos invariants, loadgen, fleet aggregator) so label-value
# escaping has exactly one encoder and one decoder.
# ---------------------------------------------------------------------------

Sample = dict[tuple[str, frozenset], float]

_SAMPLE_NAME = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*")
_LABEL = re.compile(r'\s*([a-zA-Z_][a-zA-Z0-9_]*)\s*=\s*"((?:[^"\\]|\\.)*)"\s*(,|\})')
_UNESCAPE = re.compile(r"\\(.)")


def _unescape_label_value(v: str) -> str:
    """Inverse of _escape_label_value: \\n -> newline, \\" -> ", \\\\ -> \\."""
    return _UNESCAPE.sub(
        lambda m: "\n" if m.group(1) == "n" else m.group(1), v)


def parse_prometheus(text: str) -> Sample:
    """Prometheus exposition text -> {(name, frozenset(label items)): value}.

    Label values are unescaped, so round-trips through expose() are exact
    even for values containing quotes, commas, newlines, or backslashes.
    Comment lines, malformed lines, and non-numeric values are skipped."""
    out: Sample = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_NAME.match(line)
        if not m:
            continue
        name = m.group(0)
        pos = m.end()
        labels: dict[str, str] = {}
        if line[pos:pos + 1] == "{":
            pos += 1
            if line[pos:pos + 1] == "}":  # empty label set: name{} value
                pos += 1
            else:
                while True:
                    lm = _LABEL.match(line, pos)
                    if not lm:
                        pos = -1
                        break
                    labels[lm.group(1)] = _unescape_label_value(lm.group(2))
                    pos = lm.end()
                    if lm.group(3) == "}":
                        break
            if pos < 0:
                continue
        rest = line[pos:].split()
        if not rest:
            continue
        try:
            value = float(rest[0])
        except ValueError:
            continue
        out[(name, frozenset(labels.items()))] = value
    return out


def metric_sum(samples: Mapping[tuple[str, frozenset], float], name: str,
               **where: str) -> float:
    """Sum every sample of ``name`` whose labels include ``where``."""
    want = set(where.items())
    return sum(v for (n, labels), v in samples.items()
               if n == name and want <= set(labels))


def metrics_url(url: str) -> str:
    """Normalize a target URL to its /metrics endpoint (idempotent)."""
    u = url.rstrip("/")
    return u if u.endswith("/metrics") else f"{u}/metrics"


async def fetch_metrics(url: str, timeout_s: float = 10.0) -> Sample:
    """GET <url>[/metrics] and parse it. Raises on HTTP/connect errors so
    callers decide whether a dead target is fatal (planner) or counted and
    tolerated (loadgen, fleet aggregator)."""
    import aiohttp  # deferred: the registry itself stays dependency-free

    async with aiohttp.ClientSession() as s:
        async with s.get(metrics_url(url),
                         timeout=aiohttp.ClientTimeout(total=timeout_s)) as resp:
            resp.raise_for_status()
            return parse_prometheus(await resp.text())


@dataclass
class MetricsRegistry:
    """Hierarchical registry: child registries inherit const labels
    (reference: drt→namespace→component→endpoint hierarchy)."""

    prefix: str = "dynamo"
    const_labels: dict[str, str] = field(default_factory=dict)
    _metrics: dict[str, object] = field(default_factory=dict)
    _children: list["MetricsRegistry"] = field(default_factory=list)

    def child(self, **labels: str) -> "MetricsRegistry":
        c = MetricsRegistry(prefix=self.prefix, const_labels={**self.const_labels, **labels})
        self._children.append(c)
        return c

    def _full(self, name: str) -> str:
        return f"{self.prefix}_{name}"

    def counter(self, name: str, help_: str = "") -> Counter:
        key = "c:" + name
        if key not in self._metrics:
            self._metrics[key] = Counter(self._full(name), help_, self.const_labels)
        return self._metrics[key]  # type: ignore[return-value]

    def gauge(self, name: str, help_: str = "") -> Gauge:
        key = "g:" + name
        if key not in self._metrics:
            self._metrics[key] = Gauge(self._full(name), help_, self.const_labels)
        return self._metrics[key]  # type: ignore[return-value]

    def func_gauge(self, name: str, fn, help_: str = "") -> FuncGauge:
        key = "f:" + name
        if key not in self._metrics:
            self._metrics[key] = FuncGauge(self._full(name), help_, self.const_labels, fn)
        return self._metrics[key]  # type: ignore[return-value]

    def histogram(self, name: str, help_: str = "", buckets: tuple[float, ...] = DEFAULT_BUCKETS) -> Histogram:
        key = "h:" + name
        if key not in self._metrics:
            self._metrics[key] = Histogram(self._full(name), help_, self.const_labels, buckets)
        return self._metrics[key]  # type: ignore[return-value]

    def _walk(self) -> Iterable[object]:
        yield from self._metrics.values()
        for c in self._children:
            yield from c._walk()

    def expose(self) -> str:
        """Merge metric families across the registry tree: each family
        (full metric name) emits ONE # HELP/# TYPE header followed by the
        samples from every registry contributing to it. The first
        registration's kind/help wins; same-name metrics of a different
        kind would be invalid exposition, so their samples are grouped
        under the first header rather than emitting a duplicate TYPE."""
        headers: dict[str, tuple[str, str]] = {}
        by_name: dict[str, list[str]] = {}
        order: list[str] = []
        for m in self._walk():
            name = m.name  # type: ignore[attr-defined]
            if name not in headers:
                headers[name] = (m.kind, m.help)  # type: ignore[attr-defined]
                by_name[name] = []
                order.append(name)
            by_name[name].extend(m.samples())  # type: ignore[attr-defined]
        lines: list[str] = []
        for name in order:
            kind, help_ = headers[name]
            lines.append(f"# HELP {name} {help_}")
            lines.append(f"# TYPE {name} {kind}")
            lines.extend(by_name[name])
        return "\n".join(lines) + "\n"
