"""Structured logging with W3C trace-context propagation.

Mirrors the reference's tracing setup (reference: lib/runtime/src/logging.rs):
JSONL mode for machine consumption, human mode otherwise, and ``traceparent``
parse/create so request traces correlate across processes.
"""

from __future__ import annotations

import json
import logging
import os
import secrets
import sys
import time
from dataclasses import dataclass

_CONFIGURED = False


@dataclass(frozen=True)
class TraceContext:
    """W3C traceparent: 00-<trace_id 32hex>-<span_id 16hex>-<flags 2hex>.

    Reference: lib/runtime/src/logging.rs:156-215 (parse/create traceparent).
    """

    trace_id: str
    span_id: str
    flags: str = "01"

    @classmethod
    def new(cls) -> "TraceContext":
        return cls(trace_id=secrets.token_hex(16), span_id=secrets.token_hex(8))

    @classmethod
    def parse(cls, traceparent: str | None) -> "TraceContext | None":
        if not traceparent:
            return None
        parts = traceparent.strip().split("-")
        if len(parts) != 4 or len(parts[1]) != 32 or len(parts[2]) != 16:
            return None
        return cls(trace_id=parts[1], span_id=parts[2], flags=parts[3])

    def child(self) -> "TraceContext":
        return TraceContext(trace_id=self.trace_id, span_id=secrets.token_hex(8), flags=self.flags)

    def header(self) -> str:
        return f"00-{self.trace_id}-{self.span_id}-{self.flags}"


class JsonlFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        entry = {
            "ts": round(time.time(), 6),
            "level": record.levelname,
            "target": record.name,
            "msg": record.getMessage(),
        }
        for key in ("trace_id", "span_id", "request_id", "component", "endpoint"):
            val = getattr(record, key, None)
            if val is not None:
                entry[key] = val
        if record.exc_info:
            entry["exception"] = self.formatException(record.exc_info)
        return json.dumps(entry, separators=(",", ":"))


def configure_logging(level: str | None = None, jsonl: bool | None = None) -> None:
    global _CONFIGURED
    if _CONFIGURED:
        return
    _CONFIGURED = True
    level = level or os.environ.get("DYN_LOG", "info")
    if jsonl is None:
        jsonl = os.environ.get("DYN_LOGGING_JSONL", "").lower() in ("1", "true")
    handler = logging.StreamHandler(sys.stderr)
    if jsonl:
        handler.setFormatter(JsonlFormatter())
    else:
        handler.setFormatter(
            logging.Formatter("%(asctime)s %(levelname).1s %(name)s: %(message)s", datefmt="%H:%M:%S")
        )
    root = logging.getLogger("dynamo_tpu")
    root.setLevel(getattr(logging, level.upper(), logging.INFO))
    root.addHandler(handler)
    root.propagate = False


def get_logger(name: str) -> logging.Logger:
    configure_logging()
    return logging.getLogger(f"dynamo_tpu.{name}")
