"""Device↔host KV block movement.

The TPU replacement for the reference's CUDA block-copy kernel
(reference: lib/llm/src/kernels/block_copy.cu) and its transfer managers
(reference: lib/llm/src/block_manager/offload.rs): block gather/scatter is
expressed as XLA ops under ``jit`` (fused, MXU-free, HBM-bandwidth bound)
and the host hop is the runtime's DMA via ``device_get``/``device_put``.

Block ids are padded up to power-of-two buckets so the number of distinct
compiled programs stays bounded (same static-shape discipline as the engine
step functions).

Host-side block format: one ``np.ndarray`` of shape
``[2, layers, block_size, kv_heads, head_dim]`` (index 0 = K, 1 = V) —
the unit stored by the host/disk tiers and shipped across DCN for
disaggregated prefill→decode handoff (dynamo_tpu.disagg).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def _pad_pow2(ids: list[int], cap: int = 256) -> list[int]:
    n = max(len(ids), 1)
    if n > cap:  # above the pow2 range, round up to a multiple of cap
        b = -(-n // cap) * cap
    else:
        b = 1
        while b < n:
            b *= 2
    # Duplicate writes/reads of the last id are harmless (same content).
    return ids + [ids[-1]] * (b - len(ids))


def _extract(ck, cv, ids):
    return ck[:, ids], cv[:, ids]


def _inject(ck, cv, ids, dk, dv):
    return ck.at[:, ids].set(dk), cv.at[:, ids].set(dv)


class BlockTransferEngine:
    """Bucketed, jit-compiled block gather (extract) / scatter (inject)."""

    def __init__(self) -> None:
        self._extract = jax.jit(_extract)
        self._inject = jax.jit(_inject, donate_argnums=(0, 1))

    def extract(self, cache_k: jax.Array, cache_v: jax.Array, ids: list[int]) -> list[np.ndarray]:
        """Gather blocks off the device; returns one host block per id."""
        from dynamo_tpu.obs.tracer import get_tracer

        n = len(ids)
        with get_tracer().span("kv.transfer", direction="extract",
                               blocks=n):
            padded = jnp.asarray(_pad_pow2(list(ids)), jnp.int32)
            k, v = self._extract(cache_k, cache_v, padded)
            kv = np.stack([np.asarray(k), np.asarray(v)])  # [2, layers, n_pad, bs, kvh, hd]
            per_block = np.moveaxis(kv, 2, 0)              # [n_pad, 2, layers, bs, kvh, hd]
            return [np.ascontiguousarray(per_block[i]) for i in range(n)]

    def inject(
        self,
        cache_k: jax.Array,
        cache_v: jax.Array,
        ids: list[int],
        blocks: list[np.ndarray],
    ) -> tuple[jax.Array, jax.Array]:
        """Scatter host blocks into the device cache (cache args are donated —
        callers must replace their references with the returned arrays)."""
        from dynamo_tpu.obs.tracer import get_tracer

        assert len(ids) == len(blocks) and ids
        with get_tracer().span("kv.transfer", direction="inject",
                               blocks=len(ids)):
            padded = _pad_pow2(list(ids))
            data = np.stack(blocks + [blocks[-1]] * (len(padded) - len(blocks)))
            dk = np.moveaxis(data[:, 0], 0, 1)  # [layers, n_pad, bs, kvh, hd]
            dv = np.moveaxis(data[:, 1], 0, 1)
            return self._inject(
                cache_k, cache_v, jnp.asarray(padded, jnp.int32),
                jnp.asarray(dk, cache_k.dtype), jnp.asarray(dv, cache_v.dtype),
            )
