"""Device↔host KV block movement.

The TPU replacement for the reference's CUDA block-copy kernel
(reference: lib/llm/src/kernels/block_copy.cu) and its transfer managers
(reference: lib/llm/src/block_manager/offload.rs): block gather/scatter is
expressed as XLA ops under ``jit`` (fused, MXU-free, HBM-bandwidth bound)
and the host hop is the runtime's DMA via ``device_get``/``device_put``.

Block ids are padded up to power-of-two buckets so the number of distinct
compiled programs stays bounded (same static-shape discipline as the engine
step functions).

Host-side block formats:

* float caches: one ``np.ndarray`` of shape
  ``[2, layers, block_size, kv_heads, head_dim]`` (index 0 = K, 1 = V).
* quantized caches (engine/cache.py ``{"q","s"}`` pytrees): one FLAT
  ``uint8`` array of ``spec.bytes_per_block()`` bytes — the payload
  ``[2, L, BS, KH, Dp]`` followed by the float32 scales ``[2, L, KH]``
  (``pack_kv_block``/``unpack_kv_block``). For int8 the payload trailing
  dim Dp equals head_dim (one signed byte per element, half the bf16
  footprint); for int4 it is head_dim/2 (two signed nibbles per byte,
  ops/paged_attention's split-half packing — a quarter the footprint).
  The two packed kinds share the flat layout and are told apart by byte
  LENGTH alone (their payloads differ by exactly 2x for the same logical
  shape), so stored/DCN'd blocks carry no extra header.

``inject`` accepts either format against either cache kind and converts at
the boundary (mixed-precision import: a bf16 snapshot flows into an int8
engine by on-device requantization, an int8 snapshot into a float engine by
host-side dequantization, an int8 snapshot into an int4 engine — or vice
versa — by host dequant + requant). ``extract(dequant=True)`` yields float
blocks from a quantized cache — the sharded disagg staging path needs the
box-sliceable 6-d layout (disagg/sharded.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from dynamo_tpu.ops.paged_attention import INT4_QMAX, pack_int4, unpack_int4

#: divide-guard for quantization scales (matches models/llama._KV_SCALE_EPS)
_EPS = 1e-8


def _np_pack_int4(vals: np.ndarray) -> np.ndarray:
    """Host-side mirror of ops.paged_attention.pack_int4 (same split-half
    nibble convention): int values in [-8, 7], even trailing dim → uint8
    with trailing dim halved."""
    d = vals.shape[-1]
    if d % 2:
        raise ValueError(f"int4 packing needs an even trailing dim, got {d}")
    w = vals.astype(np.int32)
    lo = w[..., : d // 2] & 0xF
    hi = w[..., d // 2:] & 0xF
    return (lo | (hi << 4)).astype(np.uint8)


def _np_unpack_int4(packed: np.ndarray) -> np.ndarray:
    """Host-side mirror of ops.paged_attention.unpack_int4: uint8 nibble
    pairs → int32 values in [-8, 7] with trailing dim doubled."""
    w = packed.astype(np.int32)
    lo = w & 0xF
    hi = (w >> 4) & 0xF
    lo = lo - ((lo & 0x8) << 1)
    hi = hi - ((hi & 0x8) << 1)
    return np.concatenate([lo, hi], axis=-1)


def _pad_pow2(ids: list[int], cap: int = 256) -> list[int]:
    n = max(len(ids), 1)
    if n > cap:  # above the pow2 range, round up to a multiple of cap
        b = -(-n // cap) * cap
    else:
        b = 1
        while b < n:
            b *= 2
    # Duplicate writes/reads of the last id are harmless (same content).
    return ids + [ids[-1]] * (b - len(ids))


def _extract(ck, cv, ids):
    return ck[:, ids], cv[:, ids]


def _inject(ck, cv, ids, dk, dv):
    return ck.at[:, ids].set(dk), cv.at[:, ids].set(dv)


# -- quantized-cache device programs -----------------------------------------

def _extract_q(ck, cv, ids):
    """Gather payload + scales: ([L,n,BS,KH,D] int8, [L,n,KH] f32) × k,v."""
    return (ck["q"][:, ids], ck["s"][:, ids],
            cv["q"][:, ids], cv["s"][:, ids])


def _dequant_slice(c, ids):
    g = c["q"][:, ids]                                # [L, n, BS, KH, Dp]
    if g.dtype == jnp.uint8:  # packed int4: widen nibbles first
        g = unpack_int4(g)
    g = g.astype(jnp.float32)                         # [L, n, BS, KH, D]
    return g * c["s"][:, ids][:, :, None, :, None]


def _extract_deq(ck, cv, ids):
    return _dequant_slice(ck, ids), _dequant_slice(cv, ids)


def _inject_q(ck, cv, ids, kq, ks, vq, vs):
    return ({"q": ck["q"].at[:, ids].set(kq), "s": ck["s"].at[:, ids].set(ks)},
            {"q": cv["q"].at[:, ids].set(vq), "s": cv["s"].at[:, ids].set(vs)})


def _quantize_lnh(x, int4: bool = False):
    """[L, n, BS, KH, D] float → (payload, [L, n, KH] scales): symmetric
    per-(layer, block, kv-head) abs-max, the same scheme
    models/llama._scatter_kv_quant commits at write time. ``int4`` packs
    two signed nibbles per byte (uint8 payload, trailing dim halved)."""
    qmax = INT4_QMAX if int4 else 127.0
    x = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(x), axis=(2, 4))
    s = jnp.maximum(amax / qmax, _EPS)
    q = jnp.clip(jnp.round(x / s[:, :, None, :, None]), -qmax, qmax)
    if int4:
        return pack_int4(q.astype(jnp.int32)), s
    return q.astype(jnp.int8), s


def _inject_quant(ck, cv, ids, dk, dv):
    int4 = ck["q"].dtype == jnp.uint8  # dtype is trace-static under jit
    kq, ks = _quantize_lnh(dk, int4)
    vq, vs = _quantize_lnh(dv, int4)
    return _inject_q(ck, cv, ids, kq, ks, vq, vs)


# -- host-side block (de)packing ---------------------------------------------

def pack_kv_block(kq: np.ndarray, ks: np.ndarray,
                  vq: np.ndarray, vs: np.ndarray) -> np.ndarray:
    """(payload [L,BS,KH,Dp] int8|uint8 + scales [L,KH] f32) × k,v → flat
    uint8. A uint8 payload (packed int4 nibbles) is kept byte-for-byte —
    NOT re-cast to int8 — so the flat block's length encodes its kind."""
    payload = np.stack([kq, vq])
    if payload.dtype != np.uint8:
        payload = payload.astype(np.int8)
    payload = np.ascontiguousarray(payload)
    scales = np.ascontiguousarray(np.stack([ks, vs]).astype(np.float32))
    return np.concatenate([payload.reshape(-1).view(np.uint8),
                           scales.reshape(-1).view(np.uint8)])


def unpack_kv_block(flat: np.ndarray, shape: tuple[int, int, int, int],
                    payload_dtype=np.int8) -> tuple[np.ndarray, np.ndarray]:
    """flat uint8 → (payload [2,L,BS,KH,Dp], scales [2,L,KH] f32). ``shape``
    is the PAYLOAD shape — its trailing dim is head_dim for int8 caches and
    head_dim/2 for packed-int4 (uint8) caches — so the byte split is the
    same expression for both kinds."""
    L, BS, KH, Dp = shape
    split = 2 * L * BS * KH * Dp
    payload = flat[:split].view(payload_dtype).reshape(2, L, BS, KH, Dp)
    scales = flat[split:].view(np.float32).reshape(2, L, KH)
    return payload, scales


def _packed_kind(flat: np.ndarray, shape: tuple[int, int, int, int]) -> str:
    """Which quantization a flat block holds, from its byte length alone.
    ``shape`` is the LOGICAL [L, BS, KH, D] block shape (full head_dim)."""
    L, BS, KH, D = shape
    scales = 2 * L * KH * 4
    if flat.size == 2 * L * BS * KH * D + scales:
        return "int8"
    if flat.size == L * BS * KH * D + scales:
        return "int4"
    raise ValueError(
        f"packed block of {flat.size} bytes matches neither int8 nor int4 "
        f"for logical shape {shape}")


def quantize_block(block: np.ndarray, kv_dtype: str = "int8") -> np.ndarray:
    """Float host block [2, L, BS, KH, D] → packed flat uint8 (int8 bytes
    or int4 nibble pairs per ``kv_dtype``)."""
    qmax = INT4_QMAX if kv_dtype == "int4" else 127.0
    x = np.asarray(block, np.float32)
    amax = np.abs(x).max(axis=(2, 4))                       # [2, L, KH]
    s = np.maximum(amax / qmax, _EPS).astype(np.float32)
    q = np.clip(np.round(x / s[:, :, None, :, None]), -qmax, qmax)
    q = _np_pack_int4(q) if kv_dtype == "int4" else q.astype(np.int8)
    return pack_kv_block(q[0], s[0], q[1], s[1])


def dequantize_block(flat: np.ndarray, shape: tuple[int, int, int, int],
                     dtype) -> np.ndarray:
    """Packed flat uint8 (either kind) → float host block [2, L, BS, KH, D]
    of ``dtype``. ``shape`` is the logical block shape (full head_dim)."""
    L, BS, KH, D = shape
    if _packed_kind(flat, shape) == "int4":
        packed, scales = unpack_kv_block(flat, (L, BS, KH, D // 2), np.uint8)
        payload = _np_unpack_int4(packed)
    else:
        payload, scales = unpack_kv_block(flat, shape)
    out = payload.astype(np.float32) * scales[:, :, None, :, None]
    return np.ascontiguousarray(out.astype(dtype))


def _is_packed(block: np.ndarray) -> bool:
    return block.ndim == 1 and block.dtype == np.uint8


def ensure_block_format(block: np.ndarray, spec) -> np.ndarray:
    """Convert a host block to ``spec``'s native format (mixed-precision
    import boundary): packed uint8 of ``spec.kv_dtype``'s kind for
    quantized specs, float [2, L, BS, KH, D] of ``spec.dtype`` otherwise.
    No-op when it already matches; a packed block of the OTHER quantized
    kind (int8 snapshot into an int4 engine or vice versa) round-trips
    through float on the host."""
    shape = (spec.num_layers, spec.block_size, spec.num_kv_heads,
             spec.head_dim)
    if spec.quantized:
        if not _is_packed(block):
            return quantize_block(block, spec.kv_dtype)
        want = "int4" if getattr(spec, "packed_int4", False) else "int8"
        if _packed_kind(block, shape) == want:
            return block
        return quantize_block(
            dequantize_block(block, shape, np.float32), spec.kv_dtype)
    if _is_packed(block):
        return dequantize_block(block, shape, jnp.dtype(spec.dtype))
    return block


class BlockTransferEngine:
    """Bucketed, jit-compiled block gather (extract) / scatter (inject)."""

    def __init__(self) -> None:
        self._extract = jax.jit(_extract)
        self._inject = jax.jit(_inject, donate_argnums=(0, 1))
        self._extract_q = jax.jit(_extract_q)
        self._extract_deq = jax.jit(_extract_deq)
        self._inject_q = jax.jit(_inject_q, donate_argnums=(0, 1))
        self._inject_quant = jax.jit(_inject_quant, donate_argnums=(0, 1))

    def extract(self, cache_k, cache_v, ids: list[int],
                dequant: bool = False,
                span_attrs: dict | None = None) -> list[np.ndarray]:
        """Gather blocks off the device; returns one host block per id.
        Quantized caches yield packed flat-uint8 blocks unless ``dequant``
        (then: float blocks, for the box-sliced disagg staging path).
        ``span_attrs`` annotate the kv.transfer span (the streamed handoff
        tags each wave's phase/window here, so per-wave extracts stay ONE
        span each — wave sizes repeat, so the pow2 id-padding below reuses
        the same jit buckets across waves)."""
        from dynamo_tpu.obs.tracer import get_tracer

        n = len(ids)
        with get_tracer().span("kv.transfer", direction="extract",
                               blocks=n, **(span_attrs or {})) as sp:
            padded = jnp.asarray(_pad_pow2(list(ids)), jnp.int32)
            if isinstance(cache_k, dict) and not dequant:
                kq, ks, vq, vs = self._extract_q(cache_k, cache_v, padded)
                kq, ks = np.asarray(kq), np.asarray(ks)  # [L,n,BS,KH,D]/[L,n,KH]
                vq, vs = np.asarray(vq), np.asarray(vs)
                out = [pack_kv_block(kq[:, i], ks[:, i], vq[:, i], vs[:, i])
                       for i in range(n)]
                sp.attrs["bytes"] = sum(int(b.nbytes) for b in out)
                return out
            if isinstance(cache_k, dict):
                k, v = self._extract_deq(cache_k, cache_v, padded)
            else:
                k, v = self._extract(cache_k, cache_v, padded)
            kv = np.stack([np.asarray(k), np.asarray(v)])  # [2, layers, n_pad, bs, kvh, hd]
            per_block = np.moveaxis(kv, 2, 0)              # [n_pad, 2, layers, bs, kvh, hd]
            out = [np.ascontiguousarray(per_block[i]) for i in range(n)]
            sp.attrs["bytes"] = sum(int(b.nbytes) for b in out)
            return out

    def inject(
        self,
        cache_k,
        cache_v,
        ids: list[int],
        blocks: list[np.ndarray],
        span_attrs: dict | None = None,
    ):
        """Scatter host blocks into the device cache (cache args are donated —
        callers must replace their references with the returned arrays).
        Accepts packed or float blocks against either cache kind; format
        conversion happens here (mixed-precision import — the wave boundary
        of the streamed handoff included)."""
        from dynamo_tpu.obs.tracer import get_tracer

        assert len(ids) == len(blocks) and ids
        with get_tracer().span("kv.transfer", direction="inject",
                               blocks=len(ids),
                               bytes=sum(int(b.nbytes) for b in blocks),
                               **(span_attrs or {})):
            quant_cache = isinstance(cache_k, dict)
            padded = _pad_pow2(list(ids))
            pad = [blocks[-1]] * (len(padded) - len(blocks))
            packed = _is_packed(blocks[0])
            if quant_cache and packed:
                cq = cache_k["q"]
                int4_cache = cq.dtype == jnp.uint8
                Dp = cq.shape[4]
                logical = (cq.shape[0], cq.shape[2], cq.shape[3],
                           Dp * 2 if int4_cache else Dp)
                want = "int4" if int4_cache else "int8"
                if _packed_kind(blocks[0], logical) == want:
                    pshape = (cq.shape[0], cq.shape[2], cq.shape[3], Dp)
                    pdt = np.uint8 if int4_cache else np.int8
                    ups = [unpack_kv_block(b, pshape, pdt)
                           for b in blocks + pad]
                    payload = np.stack([p for p, _ in ups])  # [n,2,L,BS,KH,Dp]
                    scales = np.stack([s for _, s in ups])   # [n,2,L,KH]
                    return self._inject_q(
                        cache_k, cache_v, jnp.asarray(padded, jnp.int32),
                        jnp.asarray(np.moveaxis(payload[:, 0], 0, 1)),
                        jnp.asarray(np.moveaxis(scales[:, 0], 0, 1)),
                        jnp.asarray(np.moveaxis(payload[:, 1], 0, 1)),
                        jnp.asarray(np.moveaxis(scales[:, 1], 0, 1)),
                    )
                # Cross-kind import (int8 block into an int4 engine or vice
                # versa): dequantize on host, requantize on device below.
                blocks = [dequantize_block(b, logical, np.float32)
                          for b in blocks]
                pad = [blocks[-1]] * len(pad)
                packed = False
            if packed:
                # Quantized snapshot into a float engine: dequantize on host.
                L, BS, KH, D = (cache_k.shape[0], cache_k.shape[2],
                                cache_k.shape[3], cache_k.shape[4])
                blocks = [dequantize_block(b, (L, BS, KH, D), cache_k.dtype)
                          for b in blocks]
                pad = [blocks[-1]] * len(pad)
            data = np.stack(list(blocks) + pad)
            dk = np.moveaxis(data[:, 0], 0, 1)  # [layers, n_pad, bs, kvh, hd]
            dv = np.moveaxis(data[:, 1], 0, 1)
            if quant_cache:
                # Float blocks into an int8 engine: requantize on device.
                return self._inject_quant(
                    cache_k, cache_v, jnp.asarray(padded, jnp.int32),
                    jnp.asarray(dk, jnp.float32), jnp.asarray(dv, jnp.float32))
            return self._inject(
                cache_k, cache_v, jnp.asarray(padded, jnp.int32),
                jnp.asarray(dk, cache_k.dtype), jnp.asarray(dv, cache_v.dtype),
            )
