"""Prometheus family for the fleet-wide prefix cache (dynamo_prefix_cache_*).

One module covers both halves of the loop:

* **outcome** (engine/mocker side): every admission-time onboard against the
  kvbm tiers is a *lookup*; finding at least one block anywhere below the
  device is a *hit*; blocks actually scattered into the device pool count as
  *imported* and convert to *recompute-avoided tokens* at the engine's block
  size. ``import_seconds`` measures the whole onboard (tier fetch + device
  inject), so "predicted vs measured import seconds" in tools/perf_report.py
  compares against the cost model's ``pull_seconds``.
* **decision** (router side): the route-vs-pull arbiter's verdict per
  scheduled request, labelled by action (``route`` | ``pull`` |
  ``recompute``).

Registrations are idempotent (MetricsRegistry keys by name), so the
module-level singleton can be re-bound into a runtime's registry via
``install_prefix_cache_metrics`` — workers and routers call it so the
family shows up on /metrics; tests and library use fall back to a private
registry. Names are cross-checked by tools/lint_metrics.py
PREFIX_CACHE_METRICS.
"""

from __future__ import annotations

from dynamo_tpu.utils.metrics import MetricsRegistry

# Imports span one-RTT tiny-test fetches to multi-hundred-block system
# prompts pulled over the DCN.
_IMPORT_SECONDS_BUCKETS = (
    0.0005, 0.002, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
    float("inf"),
)


class PrefixCacheMetrics:
    """The dynamo_prefix_cache_* family (names cross-checked by
    tools/lint_metrics.py PREFIX_CACHE_METRICS)."""

    def __init__(self, registry: MetricsRegistry | None = None):
        self.bind(registry or MetricsRegistry())

    def bind(self, registry: MetricsRegistry) -> None:
        self.registry = registry
        self.lookups = registry.counter(
            "prefix_cache_lookups",
            "Admission-time prefix onboard attempts against the kvbm tiers")
        self.hits = registry.counter(
            "prefix_cache_hits",
            "Onboard attempts that found at least one prefix block in a "
            "tier below the device pool")
        self.imported_blocks = registry.counter(
            "prefix_cache_imported_blocks",
            "Prefix KV blocks scattered into the device pool instead of "
            "being recomputed")
        self.recompute_avoided_tokens = registry.counter(
            "prefix_cache_recompute_avoided_tokens",
            "Prompt tokens whose prefill was skipped because their KV "
            "blocks were imported from a cache tier")
        self.import_seconds = registry.histogram(
            "prefix_cache_import_seconds",
            "Wall seconds of one prefix onboard (tier fetch + device "
            "inject)", buckets=_IMPORT_SECONDS_BUCKETS)
        self.published_blocks = registry.counter(
            "prefix_cache_published_blocks",
            "Committed prefix blocks pushed to the shared remote tier by "
            "the publish-on-commit path")
        self.route_decisions = registry.counter(
            "prefix_cache_route_decisions",
            "Route-vs-pull arbiter verdicts, by action "
            "(route|pull|recompute)")

    def record_onboard(self, *, found_blocks: int, imported_blocks: int,
                       block_size: int, seconds: float) -> None:
        """One admission-time onboard outcome."""
        self.lookups.inc(1)
        if found_blocks > 0:
            self.hits.inc(1)
        if imported_blocks > 0:
            self.imported_blocks.inc(imported_blocks)
            self.recompute_avoided_tokens.inc(imported_blocks * block_size)
        self.import_seconds.observe(seconds)


_metrics: PrefixCacheMetrics | None = None


def get_prefix_cache_metrics() -> PrefixCacheMetrics:
    global _metrics
    if _metrics is None:
        _metrics = PrefixCacheMetrics()
    return _metrics


def install_prefix_cache_metrics(registry: MetricsRegistry) -> PrefixCacheMetrics:
    """Re-home the singleton's metrics into ``registry`` (the worker's or
    router's runtime registry) so the family is exposed on /metrics."""
    m = get_prefix_cache_metrics()
    m.bind(registry)
    return m
