"""KVBM — tiered KV block manager.

Fills the role of the reference's KV Block Manager
(reference: lib/llm/src/block_manager.rs:63-103, CacheLevel G1-G4):

- **G1 (device)** lives in the engine: the paged ``jax.Array`` cache plus
  the refcounted :class:`~dynamo_tpu.engine.prefix_pool.PrefixPool`.
- **G2 (host)** — :class:`HostBlockPool`: a preallocated pinned-host numpy
  arena keyed by sequence hash with LRU eviction.
- **G3 (disk)** — :class:`DiskBlockPool`: file-per-block local-disk tier
  with a byte budget; persists across engine restarts (the reference's
  "KV survives restart only at G3/G4", SURVEY.md §5).
- **Offload manager** — :class:`OffloadManager`: write-back offload when the
  device pool evicts a committed block, and onboarding of host/disk-cached
  prefixes back onto the device at request admission
  (reference: lib/llm/src/block_manager/offload.rs).

On TPU the device↔host copies ride XLA gather/scatter + DMA
(``jax.device_get``/``device_put``) instead of the reference's CUDA
``block_copy.cu`` kernel — see :mod:`dynamo_tpu.kvbm.transfer`.
"""

from dynamo_tpu.kvbm.offload import OffloadManager
from dynamo_tpu.kvbm.pools import DiskBlockPool, HostBlockPool
from dynamo_tpu.kvbm.transfer import BlockTransferEngine

__all__ = ["BlockTransferEngine", "DiskBlockPool", "HostBlockPool", "OffloadManager"]
