"""Host (G2) and disk (G3) KV block tiers.

Reference: lib/llm/src/block_manager/ — pinned-host and local-disk pools
with layouts + a sequence-hash registry (block/registry.rs:478) and
inactive-pool LRU eviction (pool/managed.rs). Here each tier is a plain
hash→block store:

- key: the block's *sequence hash* (chained prefix identity from
  dynamo_tpu.tokens) — the same global identity the KV router uses, so a
  block cached anywhere is addressable from everywhere.
- value: one host block ``[2, layers, block_size, kv_heads, head_dim]``
  (see dynamo_tpu.kvbm.transfer).

Tiers chain: the host pool spills its LRU victim to an optional overflow
tier (disk) instead of dropping it — the reference's offload cascade
G1→G2→G3 (block_manager/offload.rs priority queues).
"""

from __future__ import annotations

from collections import OrderedDict
from dataclasses import dataclass, field
from pathlib import Path

import jax.numpy as jnp
import numpy as np

from dynamo_tpu.engine.cache import KVCacheSpec
from dynamo_tpu.obs.mem_ledger import get_mem_ledger
from dynamo_tpu.utils.logging import get_logger

log = get_logger("kvbm")


@dataclass
class TierStats:
    lookups: int = 0
    hits: int = 0
    stores: int = 0
    evictions: int = 0

    def to_dict(self) -> dict:
        return {"lookups": self.lookups, "hits": self.hits,
                "stores": self.stores, "evictions": self.evictions}


def block_shape(spec: KVCacheSpec) -> tuple[int, ...]:
    """Host-side shape of one tiered block. Quantized specs store the packed
    flat layout (int8 or nibble-packed int4 payload + f32 scale sidecar —
    see kvbm.transfer), so their tier footprint really is
    ``bytes_per_block()``: ~half bf16 for int8, ~a quarter for int4."""
    if spec.quantized:
        return (spec.bytes_per_block(),)
    return (2, spec.num_layers, spec.block_size, spec.num_kv_heads, spec.head_dim)


def block_dtype(spec: KVCacheSpec) -> np.dtype:
    """Element dtype of the host-side block (uint8 for packed quantized)."""
    if spec.quantized:
        return np.dtype(np.uint8)
    return np.dtype(jnp.dtype(spec.dtype))


class HostBlockPool:
    """Preallocated host-memory arena of KV blocks with LRU eviction.

    One contiguous numpy allocation (the pinned-host analog of the
    reference's G2 pool) — blocks are slots in the arena, never
    realloc'd, so offload traffic causes no host allocator churn.
    """

    name = "host"

    def __init__(
        self,
        spec: KVCacheSpec,
        capacity_blocks: int,
        overflow: "DiskBlockPool | None" = None,
    ):
        self.spec = spec
        self.capacity = capacity_blocks
        self.overflow = overflow
        self._arena = np.zeros((capacity_blocks, *block_shape(spec)), block_dtype(spec))
        self._free: list[int] = list(range(capacity_blocks - 1, -1, -1))
        self._lru: OrderedDict[int, int] = OrderedDict()  # seq_hash -> slot, LRU order
        self.stats = TierStats()
        self._mled = get_mem_ledger()

    def __contains__(self, seq_hash: int) -> bool:
        return seq_hash in self._lru

    def __len__(self) -> int:
        return len(self._lru)

    def occupancy(self) -> tuple[int, int]:
        """(resident blocks, resident bytes) — the mem-ledger tier row."""
        n = len(self._lru)
        return n, n * self.spec.bytes_per_block()

    def put(self, seq_hash: int, block: np.ndarray) -> None:
        if seq_hash in self._lru:
            self._lru.move_to_end(seq_hash)
            return
        from dynamo_tpu.kvbm.transfer import ensure_block_format

        block = ensure_block_format(block, self.spec)
        if not self._free:
            victim_hash, victim_slot = self._lru.popitem(last=False)
            self.stats.evictions += 1
            if self._mled.enabled:
                self._mled.record_churn("host", "lru", 1)
            if self.overflow is not None:
                self.overflow.put(victim_hash, self._arena[victim_slot])
            self._free.append(victim_slot)
        slot = self._free.pop()
        self._arena[slot] = block
        self._lru[seq_hash] = slot
        self.stats.stores += 1

    def get(self, seq_hash: int) -> np.ndarray | None:
        """Return a *copy* of the block (the arena slot may be recycled by a
        later put while the caller still holds the data — e.g. onboarding
        triggers device evictions that write back into this pool)."""
        self.stats.lookups += 1
        slot = self._lru.get(seq_hash)
        if slot is None:
            return None
        self._lru.move_to_end(seq_hash)
        self.stats.hits += 1
        return self._arena[slot].copy()


class DiskBlockPool:
    """Local-disk KV block tier (G3): one file per block, byte-budgeted LRU.

    Files are named ``<seq_hash:016x>.kvb`` and contain the raw block bytes;
    the index is rebuilt from the directory on startup so cached KV survives
    engine restarts (reference: SURVEY.md §5 checkpoint/resume — "KV cache
    survives engine restart only at G3/G4").
    """

    name = "disk"

    def __init__(
        self,
        spec: KVCacheSpec,
        path: str | Path,
        capacity_bytes: int = 1 << 30,
        fingerprint: str = "",
        overflow=None,
    ):
        self.spec = spec
        self.overflow = overflow
        self.path = Path(path)
        self.path.mkdir(parents=True, exist_ok=True)
        self.capacity_bytes = capacity_bytes
        self._block_bytes = int(np.prod(block_shape(spec))) * block_dtype(spec).itemsize
        self._lru: OrderedDict[int, None] = OrderedDict()
        self.stats = TierStats()
        self._mled = get_mem_ledger()
        # Sequence hashes cover token content only — a directory written by a
        # different model (even one with identical KV geometry) must not be
        # served. The MANIFEST records model identity + layout; any mismatch
        # purges the tier.
        manifest = self.path / "MANIFEST"
        want = f"{fingerprint}|{block_shape(spec)}|{spec.dtype}|{spec.kv_dtype}"
        have = manifest.read_text() if manifest.exists() else None
        if have != want:
            if have is not None:
                log.warning("disk KV tier %s manifest mismatch; purging", self.path)
            for f in self.path.glob("*.kvb"):
                f.unlink(missing_ok=True)
            manifest.write_text(want)
        for f in sorted(self.path.glob("*.kvb"), key=lambda p: p.stat().st_mtime):
            if f.stat().st_size == self._block_bytes:
                self._lru[int(f.stem, 16)] = None
            else:  # truncated write from a crashed process
                f.unlink(missing_ok=True)

    def __contains__(self, seq_hash: int) -> bool:
        return seq_hash in self._lru

    def __len__(self) -> int:
        return len(self._lru)

    def occupancy(self) -> tuple[int, int]:
        """(resident blocks, resident bytes) — the mem-ledger tier row."""
        n = len(self._lru)
        return n, n * self._block_bytes

    def _file(self, seq_hash: int) -> Path:
        return self.path / f"{seq_hash:016x}.kvb"

    def put(self, seq_hash: int, block: np.ndarray) -> None:
        if seq_hash in self._lru:
            self._lru.move_to_end(seq_hash)
            return
        from dynamo_tpu.kvbm.transfer import ensure_block_format

        block = ensure_block_format(block, self.spec)
        while (len(self._lru) + 1) * self._block_bytes > self.capacity_bytes and self._lru:
            victim, _ = self._lru.popitem(last=False)
            if self._mled.enabled:
                self._mled.record_churn("disk", "byte_budget", 1)
            if self.overflow is not None:
                # read directly (not self.get — that would touch the LRU)
                try:
                    raw = np.fromfile(self._file(victim), dtype=np.uint8)
                except OSError:
                    raw = np.empty(0, np.uint8)
                if raw.size == self._block_bytes:
                    self.overflow.put(victim, raw.view(
                        block_dtype(self.spec)).reshape(block_shape(self.spec)))
            self._file(victim).unlink(missing_ok=True)
            self.stats.evictions += 1
        np.ascontiguousarray(block).view(np.uint8).tofile(self._file(seq_hash))
        self._lru[seq_hash] = None
        self.stats.stores += 1

    def get(self, seq_hash: int) -> np.ndarray | None:
        self.stats.lookups += 1
        if seq_hash not in self._lru:
            return None
        try:
            raw = np.fromfile(self._file(seq_hash), dtype=np.uint8)
            if raw.size != self._block_bytes:  # truncated/concurrent write
                raise OSError(f"short read: {raw.size} != {self._block_bytes}")
        except OSError:
            self._lru.pop(seq_hash, None)
            self._file(seq_hash).unlink(missing_ok=True)
            return None
        self._lru.move_to_end(seq_hash)
        self.stats.hits += 1
        return raw.view(block_dtype(self.spec)).reshape(block_shape(self.spec))
