"""G4 remote KV block tier: a shared block store service + client tier.

Fills the role of the reference's G4 remote cache level
(reference: lib/llm/src/block_manager.rs:63-75 ``CacheLevel::G4`` and the
NIXL-backed remote storage, block_manager/storage/nixl.rs) — the TPU/DCN
way: blocks move host→host over a framed TCP data plane (the same
``[u32 len][msgpack]`` framing every other plane speaks, transports/wire.py),
not RDMA. A pod-wide store lets ANY engine onboard a prefix that any other
engine computed — cross-engine prefix reuse beyond what per-host G2/G3
tiers can offer, and KV that survives whole-host restarts.

Server: :class:`RemoteBlockServer` — asyncio, byte-budgeted LRU arena,
multi-client, one request/response per frame. Runs embedded or as the
``dynamo_tpu.components.kv_store`` process, and registers itself in the
coordinator under ``kvbm/remote/{instance}`` for discovery.

Client: :class:`RemoteBlockPool` — the standard tier protocol
(``put/get/__contains__/len/stats``), so it chains after host/disk in the
offload cascade. The engine's tier calls are synchronous (they run on the
engine-core thread between device dispatches), so the client speaks
blocking sockets with short timeouts; a dead/unreachable store degrades to
misses rather than stalling the serving loop.

Keys are ``(namespace, seq_hash)``: the namespace (model fingerprint +
block geometry, same recipe as the disk tier's MANIFEST) partitions the
shared store so two models can never exchange blocks.
"""

from __future__ import annotations

import asyncio
import socket
import struct
import threading
import time
from collections import OrderedDict

import jax.numpy as jnp
import msgpack
import numpy as np

from dynamo_tpu import chaos
from dynamo_tpu.engine.cache import KVCacheSpec
from dynamo_tpu.kvbm.pools import TierStats
from dynamo_tpu.utils.logging import get_logger

log = get_logger("kvbm.remote")

REMOTE_PREFIX = "kvbm/remote"


# ---------------------------------------------------------------------------
# Server
# ---------------------------------------------------------------------------

class RemoteBlockServer:
    """Byte-budgeted LRU block store speaking framed msgpack.

    Ops: ``{"op": "put", "ns": str, "h": int, "data": bytes}`` → ``{"ok": True}``
         ``{"op": "get", "ns": str, "h": int}`` → ``{"ok": True, "data": bytes | None}``
         ``{"op": "has", "ns": str, "h": int}`` → ``{"ok": True, "has": bool}``
         ``{"op": "del", "ns": str, "h": int}`` → ``{"ok": True, "deleted": bool}``
         ``{"op": "stats"}`` → ``{"ok": True, ...counters}``
    """

    def __init__(self, capacity_bytes: int = 4 << 30):
        self.capacity_bytes = capacity_bytes
        self._store: OrderedDict[tuple[str, int], bytes] = OrderedDict()
        self._bytes = 0
        self.stats = TierStats()
        self._server: asyncio.AbstractServer | None = None
        self._writers: set[asyncio.StreamWriter] = set()
        self.port: int | None = None

    # -- store ------------------------------------------------------------
    def _put(self, ns: str, h: int, data: bytes) -> None:
        key = (ns, h)
        if key in self._store:
            self._store.move_to_end(key)
            return
        while self._bytes + len(data) > self.capacity_bytes and self._store:
            _, victim = self._store.popitem(last=False)
            self._bytes -= len(victim)
            self.stats.evictions += 1
        self._store[key] = data
        self._bytes += len(data)
        self.stats.stores += 1

    def _get(self, ns: str, h: int) -> bytes | None:
        self.stats.lookups += 1
        data = self._store.get((ns, h))
        if data is not None:
            self._store.move_to_end((ns, h))
            self.stats.hits += 1
        return data

    # -- service ----------------------------------------------------------
    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        self._writers.add(writer)
        try:
            while True:
                try:
                    header = await reader.readexactly(4)
                except (asyncio.IncompleteReadError, ConnectionResetError):
                    return
                (length,) = struct.unpack(">I", header)
                payload = await reader.readexactly(length)
                msg = msgpack.unpackb(payload, raw=False)
                op = msg.get("op")
                if op == "put":
                    self._put(msg["ns"], msg["h"], msg["data"])
                    resp = {"ok": True}
                elif op == "get":
                    resp = {"ok": True, "data": self._get(msg["ns"], msg["h"])}
                elif op == "has":
                    resp = {"ok": True,
                            "has": (msg["ns"], msg["h"]) in self._store}
                elif op == "del":
                    victim = self._store.pop((msg["ns"], msg["h"]), None)
                    if victim is not None:
                        self._bytes -= len(victim)
                    resp = {"ok": True, "deleted": victim is not None}
                elif op == "stats":
                    resp = {"ok": True, "blocks": len(self._store),
                            "bytes": self._bytes, **self.stats.to_dict()}
                else:
                    resp = {"ok": False, "error": f"unknown op {op!r}"}
                out = msgpack.packb(resp, use_bin_type=True)
                writer.write(struct.pack(">I", len(out)) + out)
                await writer.drain()
        except Exception:
            log.exception("kv store client connection failed")
        finally:
            self._writers.discard(writer)
            writer.close()

    async def start(self, host: str = "127.0.0.1", port: int = 0) -> int:
        self._server = await asyncio.start_server(self._handle, host, port)
        self.port = self._server.sockets[0].getsockname()[1]
        log.info("remote KV block store on %s:%d (%.1f GiB)",
                 host, self.port, self.capacity_bytes / (1 << 30))
        return self.port

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            # Drop live client connections too — wait_closed() (3.12+) waits
            # for handlers, and engine clients hold persistent connections.
            for w in list(self._writers):
                w.close()
            await self._server.wait_closed()
            self._server = None


# ---------------------------------------------------------------------------
# Client tier
# ---------------------------------------------------------------------------

def tier_namespace(spec: KVCacheSpec, fingerprint: str = "") -> str:
    """Model identity + LOGICAL block geometry — deliberately *without* the
    storage dtype. Blocks on the wire are self-describing by byte length
    (float [2,L,BS,KH,D] vs the packed int8/int4 flat layouts — see
    kvbm/transfer.py), so engines running the same model at different
    kv_dtypes share one namespace: a bf16 engine can onboard a block an
    int8 engine published, with the conversion at the ``get`` boundary.
    (The disk tier's MANIFEST still pins the full storage layout — that
    directory holds raw native-format bytes for one engine only.)"""
    return (f"{fingerprint}|{spec.num_layers}x{spec.block_size}"
            f"x{spec.num_kv_heads}x{spec.head_dim}")


class RemoteBlockPool:
    """Tier-protocol client for a :class:`RemoteBlockServer`.

    Synchronous (engine-core thread); one persistent connection with
    automatic reconnect-once per call. Failures degrade to misses/drops —
    a remote store outage must never wedge the serving loop. ``len`` and
    ``__contains__`` ask the server (the store is shared; local bookkeeping
    would go stale the moment another engine writes)."""

    name = "remote"
    # Shared across engines/ranks: contains/get results can change under
    # our feet (cross-engine LRU, other ranks' writes) — offload dedup and
    # onboard planning must not assume rank-stable answers
    # (kvbm/offload.py: _on_evict skip + vote_plans).
    shared = True

    # After a failed call, skip the store entirely for this long — an
    # outage must cost ONE connect timeout per window, not one per call
    # (metrics polling alone calls into this tier several times a second).
    BREAKER_SECONDS = 30.0

    def __init__(self, spec: KVCacheSpec, addr: str, fingerprint: str = "",
                 timeout: float = 1.0):
        self.spec = spec
        host, _, port = addr.rpartition(":")
        self._addr = (host or "127.0.0.1", int(port))
        self._ns = tier_namespace(spec, fingerprint)
        self._timeout = timeout
        self._sock: socket.socket | None = None
        self._lock = threading.Lock()
        self._broken_until = 0.0
        self._last_len = 0
        self.stats = TierStats()
        # Byte-length → stored format, for the self-describing wire blocks
        # (cross-dtype namespace sharing, see tier_namespace). Packed kinds
        # first; float payloads at an ambiguous itemsize resolve to the
        # spec's own dtype (listed first).
        L, bs, kh, d = (spec.num_layers, spec.block_size,
                        spec.num_kv_heads, spec.head_dim)
        elems = 2 * L * bs * kh * d
        scales = 2 * L * kh * 4
        self._formats: dict[int, str] = {}
        self._formats[elems + scales] = "int8"
        self._formats[elems // 2 + scales] = "int4"
        for fdt in (str(spec.dtype), "bfloat16", "float32"):
            nbytes = elems * np.dtype(jnp.dtype(fdt)).itemsize
            self._formats.setdefault(nbytes, fdt)

    # -- wire -------------------------------------------------------------
    def _connect(self) -> socket.socket:
        # Chaos: a connect-time fault (delay models DCN congestion; an
        # injected ConnectionError a refused/partitioned store) exercises
        # the degrade-to-recompute path separately from per-op faults.
        chaos.inject("kvbm.remote.connect", addr=self._addr[0])
        s = socket.create_connection(self._addr, timeout=self._timeout)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return s

    def _call(self, msg: dict) -> dict | None:
        """One request/response; reconnects once on a broken connection;
        returns None when the store is unreachable. A failure opens the
        circuit breaker: calls return None instantly until it expires."""
        with self._lock:
            if time.monotonic() < self._broken_until:
                return None
            for attempt in (0, 1):
                try:
                    # Chaos: injected ConnectionError takes the same path as
                    # a dead store — reconnect once, then open the breaker.
                    chaos.inject("kvbm.remote", op=msg.get("op"))
                    if self._sock is None:
                        self._sock = self._connect()
                    payload = msgpack.packb(msg, use_bin_type=True)
                    self._sock.sendall(struct.pack(">I", len(payload)) + payload)
                    header = self._recv_exact(4)
                    (length,) = struct.unpack(">I", header)
                    return msgpack.unpackb(self._recv_exact(length), raw=False)
                except (OSError, ValueError, struct.error):
                    if self._sock is not None:
                        try:
                            self._sock.close()
                        except OSError:
                            pass
                        self._sock = None
                    if attempt == 1:
                        self._broken_until = time.monotonic() + self.BREAKER_SECONDS
                        log.warning(
                            "remote KV store %s:%d unreachable; skipping it "
                            "for %.0fs", *self._addr, self.BREAKER_SECONDS)
                        return None
        return None

    def _recv_exact(self, n: int) -> bytes:
        assert self._sock is not None
        buf = b""
        while len(buf) < n:
            chunk = self._sock.recv(n - len(buf))
            if not chunk:
                raise OSError("connection closed")
            buf += chunk
        return buf

    # -- tier protocol -----------------------------------------------------
    def put(self, seq_hash: int, block: np.ndarray) -> None:
        data = np.ascontiguousarray(block).tobytes()
        resp = self._call({"op": "put", "ns": self._ns, "h": seq_hash,
                           "data": data})
        if resp is None:
            log.debug("remote KV store unreachable; dropping block %x", seq_hash)
            return
        self.stats.stores += 1

    def get(self, seq_hash: int) -> np.ndarray | None:
        self.stats.lookups += 1
        resp = self._call({"op": "get", "ns": self._ns, "h": seq_hash})
        data = resp.get("data") if resp else None
        if data is None:
            return None
        fmt = self._formats.get(len(data))
        if fmt is None:  # unknown geometry/format — treat as a miss
            log.warning("remote block %x has %d bytes, matching no known "
                        "format for %s", seq_hash, len(data), self._ns)
            return None
        self.stats.hits += 1
        if fmt in ("int8", "int4"):
            block = np.frombuffer(data, np.uint8)
        else:
            spec = self.spec
            block = np.frombuffer(data, np.dtype(jnp.dtype(fmt))).reshape(
                2, spec.num_layers, spec.block_size, spec.num_kv_heads,
                spec.head_dim)
        # Convert to this engine's native format here, so downstream
        # consumers (onboard plans, host-tier puts) always see homogeneous
        # blocks regardless of which engine published them.
        from dynamo_tpu.kvbm.transfer import ensure_block_format

        return ensure_block_format(block, spec=self.spec)

    # -- session records ---------------------------------------------------
    # Drain evacuation (runtime/drain.py) parks a retired worker's retained
    # sessions here: the KV blocks go through the normal put() path, and a
    # tiny resumable record — the committed hash chain — rides the SAME
    # generic put/get ops under a derived namespace. A surviving worker
    # that misses a local session claim consults the record; a hit means
    # the next turn onboards the evacuated blocks (pull-to-warm) instead
    # of recomputing. Records never collide with block payloads: the "|s"
    # namespace suffix partitions them, and they bypass get()'s byte-length
    # format table entirely.

    @staticmethod
    def _session_hash(session_id: str) -> int:
        import hashlib

        digest = hashlib.sha256(session_id.encode()).digest()
        return int.from_bytes(digest[:8], "big")

    def put_session(self, session_id: str, seq_hashes: list[int],
                    tokens: int = 0) -> bool:
        rec = msgpack.packb({"hashes": [int(h) for h in seq_hashes],
                             "tokens": int(tokens), "ts": time.time()},
                            use_bin_type=True)
        resp = self._call({"op": "put", "ns": self._ns + "|s",
                           "h": self._session_hash(session_id), "data": rec})
        return bool(resp and resp.get("ok"))

    def get_session(self, session_id: str) -> dict | None:
        """The evacuated record for ``session_id`` — ``{"hashes": [...],
        "tokens": n, "ts": ...}`` — or None (no record / store down)."""
        resp = self._call({"op": "get", "ns": self._ns + "|s",
                           "h": self._session_hash(session_id)})
        data = resp.get("data") if resp else None
        if data is None:
            return None
        try:
            rec = msgpack.unpackb(data, raw=False)
        except Exception:
            log.warning("undecodable session record for %r", session_id)
            return None
        return rec if isinstance(rec, dict) else None

    # -- stream checkpoints -------------------------------------------------
    # Crash recovery (kvbm/stream_ckpt.py): the engine parks an in-flight
    # stream's StreamCheckpoint record here every K committed decode blocks
    # (the blocks themselves ride put() under the normal tier namespace).
    # Records live in a FIXED spec-independent namespace: the frontend's
    # migration operator — which has no KVCacheSpec — must be able to look
    # one up with nothing but the request id. TTL is enforced lazily on
    # get: a crashed worker never deletes its records, so a stale one must
    # read as a miss (and be reaped) rather than resurrect an ancient
    # stream. Clean finishes delete the record eagerly.

    CKPT_NAMESPACE = "stream|ckpt"

    def put_stream_ckpt(self, request_id: str, record: dict) -> bool:
        data = msgpack.packb(record, use_bin_type=True)
        resp = self._call({"op": "put", "ns": self.CKPT_NAMESPACE,
                           "h": self._session_hash(request_id), "data": data})
        return bool(resp and resp.get("ok"))

    def get_stream_ckpt(self, request_id: str,
                        ttl: float | None = None) -> dict | None:
        """The live checkpoint record for ``request_id``, or None (no
        record / expired / store down). Expired records are deleted and
        counted on the dynamo_stream_ckpt_expired counter."""
        from dynamo_tpu.kvbm.stream_ckpt import (
            DEFAULT_CKPT_TTL_S, get_stream_ckpt_metrics, parse_ckpt_record)

        resp = self._call({"op": "get", "ns": self.CKPT_NAMESPACE,
                           "h": self._session_hash(request_id)})
        data = resp.get("data") if resp else None
        if data is None:
            return None
        try:
            rec = parse_ckpt_record(msgpack.unpackb(data, raw=False))
        except Exception:
            rec = None
        if rec is None:
            log.warning("undecodable stream checkpoint for %r", request_id)
            return None
        ttl = DEFAULT_CKPT_TTL_S if ttl is None else ttl
        if ttl > 0 and rec["ts"] and time.time() - rec["ts"] > ttl:
            get_stream_ckpt_metrics().expired.inc(1)
            self.del_stream_ckpt(request_id)
            return None
        return rec

    def del_stream_ckpt(self, request_id: str) -> None:
        self._call({"op": "del", "ns": self.CKPT_NAMESPACE,
                    "h": self._session_hash(request_id)})

    def __contains__(self, seq_hash: int) -> bool:
        resp = self._call({"op": "has", "ns": self._ns, "h": seq_hash})
        return bool(resp and resp.get("has"))

    def __len__(self) -> int:
        # Metrics-path call (OffloadManager.snapshot → stats polling):
        # last-known value on failure, never a stall.
        resp = self._call({"op": "stats"})
        if resp:
            self._last_len = int(resp.get("blocks", 0))
        return self._last_len

    def occupancy(self) -> tuple[int, int]:
        """(resident blocks, resident bytes) server-wide — the mem-ledger
        tier row. Last-known/zero on failure, never a stall (the ledger
        only pulls this at snapshot/debug time, and the circuit breaker
        bounds the cost of a dead store)."""
        resp = self._call({"op": "stats"})
        if resp:
            self._last_len = int(resp.get("blocks", 0))
            return self._last_len, int(resp.get("bytes", 0))
        return self._last_len, 0

    def close(self) -> None:
        with self._lock:
            if self._sock is not None:
                try:
                    self._sock.close()
                except OSError:
                    pass
                self._sock = None


# ---------------------------------------------------------------------------
# Discovery
# ---------------------------------------------------------------------------

async def register_store(client, instance_id: int, addr: str,
                         lease_id: int = 0) -> None:
    """Advertise a running store in the coordinator (lease-bound: a dead
    store disappears and engines fall back to local tiers)."""
    await client.put(f"{REMOTE_PREFIX}/{instance_id:016x}", addr.encode(),
                     lease_id)


async def discover_store(client) -> str | None:
    """First advertised store address, or None."""
    got = await client.get_prefix(REMOTE_PREFIX + "/")
    for _, v in sorted(got.items()):
        return v.decode()
    return None


def ckpt_client(addr: str, timeout: float = 1.0) -> RemoteBlockPool:
    """A record-only client for processes with no KVCacheSpec (the
    frontend's migration operator). Stream-checkpoint records live in the
    fixed spec-independent namespace, so the stand-in geometry here is
    never consulted — only the record ops are valid on this client."""
    spec = KVCacheSpec(num_blocks=1, block_size=1, num_layers=1,
                      num_kv_heads=1, head_dim=2)
    return RemoteBlockPool(spec, addr, timeout=timeout)
