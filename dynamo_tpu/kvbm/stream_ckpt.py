"""Crash-consistent stream checkpoints (dynamo_stream_ckpt_*).

The crash-path twin of the drain protocol (runtime/drain.py): drain
evacuates *sessions* on a planned exit; this module's record format and
metrics family cover *in-flight streams* against an unplanned worker kill.
Every K committed decode blocks (and once at prefill completion) the
engine enqueues the stream's newly committed blocks plus a tiny
``StreamCheckpoint`` record through the OffloadManager's budgeted flush
into the shared G4 store (reference: lib/llm/src/migration.rs and
docs/architecture/request_migration.md treat request migration as a
first-class protocol; here the checkpoint makes it *warm* and
token-identical instead of cold and lossy). On ``StreamError`` the
frontend migration operator looks the record up and resumes the stream as
pull-to-warm, replaying only the post-checkpoint suffix — bitwise for
greedy streams, via the restored sampler PRNG state for sampled ones.

One module holds the three pieces every layer shares:

* the **record** schema (build/parse) — request_id, generated-token
  ledger, committed block-hash chain, sampler PRNG state (key data +
  draw counter so non-greedy resume is bit-identical), stop progress;
* the **annotation keys** the frontend stamps on a resume request so the
  engine/mocker can restore sampler state and continue the ledger;
* the **metrics family** (names cross-checked by tools/lint_metrics.py
  STREAM_CKPT_METRICS).
"""

from __future__ import annotations

import time
from typing import Any

from dynamo_tpu.utils.metrics import MetricsRegistry

# -- resume-request annotations (frontend → engine/mocker) -----------------
# Count of already-generated tokens appended to the resume prompt: the
# mocker continues its deterministic ledger at this offset, the engine
# knows how many trailing prompt tokens are *generated* (penalty rebuild +
# recompute accounting), and both count the request as a ckpt resume.
CKPT_GENERATED_KEY = "stream_ckpt.generated"
# Total sampler draws the stream had consumed before the crash (one draw
# per emitted token at decode_window=1) — the fold/step counter the
# engine advances the restored key by.
CKPT_DRAWS_KEY = "stream_ckpt.draws"
# Captured device PRNG key data (list of uint32 words) at checkpoint time
# plus the draw count at capture — the resume path for *unseeded* streams,
# where the key cannot be re-derived from the request.
CKPT_KEY_DATA_KEY = "stream_ckpt.key"
CKPT_KEY_DRAWS_KEY = "stream_ckpt.key_draws"

# Records a crashed worker never deleted expire out of the shared store:
# lazy TTL, enforced client-side on get (kvbm/remote.py get_stream_ckpt).
DEFAULT_CKPT_TTL_S = 600.0

# Device blocks sitting in the checkpoint flush queue are pinned under the
# mem-ledger owner class "stream_ckpt" (obs/mem_ledger.py) — pin at
# OffloadManager.enqueue_stream_ckpt, unpin at flush or staleness drop.
MEM_OWNER_CLASS = "stream_ckpt"


def build_ckpt_record(request_id: str, generated: list[int],
                      seq_hashes: list[int], *,
                      key_data: list[int] | None = None,
                      draws: int = 0, seed: int | None = None,
                      prompt_tokens: int = 0) -> dict[str, Any]:
    """The msgpack-able StreamCheckpoint payload. ``generated`` is the full
    token ledger so far (stop-condition progress is reconstructed from it
    on resume), ``seq_hashes`` the committed chain covering prompt +
    ledger, ``key_data``/``draws`` the sampler PRNG state at capture."""
    return {
        "rid": request_id,
        "generated": [int(t) for t in generated],
        "hashes": [int(h) for h in seq_hashes],
        "key": [int(w) for w in key_data] if key_data is not None else None,
        "draws": int(draws),
        "seed": int(seed) if seed is not None else None,
        "prompt_tokens": int(prompt_tokens),
        "ts": time.time(),
    }


def parse_ckpt_record(rec: Any) -> dict[str, Any] | None:
    """Validate a decoded record; None for anything malformed (a corrupt
    record must degrade to the reprompt path, never raise mid-recovery)."""
    if not isinstance(rec, dict) or "generated" not in rec:
        return None
    try:
        return {
            "rid": str(rec.get("rid", "")),
            "generated": [int(t) for t in rec["generated"]],
            "hashes": [int(h) for h in rec.get("hashes") or []],
            "key": ([int(w) for w in rec["key"]]
                    if rec.get("key") is not None else None),
            "draws": int(rec.get("draws", 0)),
            "seed": (int(rec["seed"]) if rec.get("seed") is not None
                     else None),
            "prompt_tokens": int(rec.get("prompt_tokens", 0)),
            "ts": float(rec.get("ts", 0.0)),
        }
    except (TypeError, ValueError):
        return None


class StreamCkptMetrics:
    """The dynamo_stream_ckpt_* family (names cross-checked by
    tools/lint_metrics.py STREAM_CKPT_METRICS)."""

    def __init__(self, registry: MetricsRegistry | None = None):
        self.bind(registry or MetricsRegistry())

    def bind(self, registry: MetricsRegistry) -> None:
        self.registry = registry
        self.writes = registry.counter(
            "stream_ckpt_writes",
            "StreamCheckpoint records written to the shared remote store")
        self.bytes = registry.counter(
            "stream_ckpt_bytes",
            "Bytes pushed for stream checkpoints (KV blocks + records)")
        self.resumes = registry.counter(
            "stream_ckpt_resumes",
            "Broken streams resumed warm from a stream checkpoint instead "
            "of the cold reprompt path")
        self.resume_recomputed_tokens = registry.counter(
            "stream_ckpt_resume_recomputed_tokens",
            "Tokens recomputed on checkpoint resume (the post-checkpoint "
            "suffix the crash cost — bounded by one checkpoint interval)")
        self.lag_blocks = registry.gauge(
            "stream_ckpt_lag_blocks",
            "Committed blocks of live streams not yet covered by a "
            "checkpoint (crash exposure, in blocks)")
        self.expired = registry.counter(
            "stream_ckpt_expired",
            "Checkpoint lookups that found only a TTL-expired record")


_metrics: StreamCkptMetrics | None = None


def get_stream_ckpt_metrics() -> StreamCkptMetrics:
    global _metrics
    if _metrics is None:
        _metrics = StreamCkptMetrics()
    return _metrics


def install_stream_ckpt_metrics(registry: MetricsRegistry) -> StreamCkptMetrics:
    """Re-home the singleton into ``registry`` (worker or frontend runtime)
    so the family is exposed on /metrics."""
    m = get_stream_ckpt_metrics()
    m.bind(registry)
    return m
