"""KVBM for multi-host engines: shard-local tiers in SPMD lockstep.

Fills the role of the reference's distributed block manager
(reference: lib/llm/src/block_manager/distributed/ — ``KvbmLeader``
leader.rs:126 decides onboard/offload, ``KvbmWorker`` worker.rs:143
executes transfers on its GPU, a ZMQ control channel zmq.rs:448 keeps
them in step). The TPU redesign needs none of that machinery:

- The multi-host engine already replays one deterministic op stream on
  every rank (parallel/multihost.py) — scheduler state, PrefixPool
  evictions, and onboard decisions are bit-identical everywhere. The
  reference's leader/worker *control* problem is solved by construction.
- What remains is the *data* problem: the KV cache is one global array
  sharded over the mesh (layers→"pipe", kv_heads→"model"), so no process
  can materialize whole blocks. Each rank therefore extracts/injects only
  its ADDRESSABLE shard and keeps its own host/disk tier holding
  shard-slices; the union of all ranks' tiers is the distributed block
  store, with zero cross-host block traffic (each shard stays on the host
  that owns the devices it lives on — the same locality the reference's
  per-GPU workers have).

``ShardedBlockTransferEngine`` is a drop-in for ``BlockTransferEngine``
whose extract returns local-shard blocks and whose inject assembles the
global scatter operand from each rank's local contribution
(``jax.make_array_from_callback``). ``local_block_spec`` gives the
per-rank block geometry + a shard fingerprint (so a disk tier written by
rank k can never be consumed by rank j after a topology change).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from dynamo_tpu.engine.cache import KVCacheSpec, cache_payload
from dynamo_tpu.kvbm.transfer import (
    BlockTransferEngine, _extract, _extract_deq, _extract_q, _inject,
    _inject_q, _inject_quant, _is_packed, _packed_kind, _pad_pow2,
    dequantize_block, pack_kv_block, unpack_kv_block)
from dynamo_tpu.utils.logging import get_logger

log = get_logger("kvbm.distributed")


def local_box(arr: jax.Array) -> tuple[tuple[int, ...], tuple[int, ...]]:
    """(starts, stops) of this process's addressable region of ``arr``.

    With the cache sharding (contiguous axis partitions, replication over
    data/seq axes) every process's shards tile one axis-aligned box;
    replicas overlap harmlessly."""
    shards = arr.addressable_shards
    ndim = arr.ndim
    starts = tuple(
        min((s.index[d].start or 0) for s in shards) for d in range(ndim))
    stops = tuple(
        max((s.index[d].stop if s.index[d].stop is not None else arr.shape[d])
            for s in shards) for d in range(ndim))
    return starts, stops


def assemble_local(arr: jax.Array) -> tuple[np.ndarray, tuple[int, ...]]:
    """Copy this process's shard box to host; returns (data, starts)."""
    starts, stops = local_box(arr)
    out = np.empty([b - a for a, b in zip(starts, stops)], arr.dtype)
    for s in arr.addressable_shards:
        sl = tuple(
            slice((idx.start or 0) - st,
                  (idx.stop if idx.stop is not None else dim) - st)
            for idx, st, dim in zip(s.index, starts, arr.shape))
        out[sl] = np.asarray(s.data)
    return out, starts


class ShardedBlockTransferEngine(BlockTransferEngine):
    """extract/inject on the rank-local shard of a mesh-sharded cache."""

    def __init__(self, mesh) -> None:
        self.mesh = mesh
        # Gather output [layers, n_pad, bs, kvh, hd] keeps the cache's
        # layer/head sharding so no collective materializes full blocks.
        out_spec = NamedSharding(mesh, P("pipe", None, None, "model", None))
        # Gathered scale sidecar [layers, n_pad, kvh] shares the payload's
        # layer/head partitions (parallel/mesh.kv_scale_spec minus blocks).
        scale_spec = NamedSharding(mesh, P("pipe", None, "model"))
        self._extract = jax.jit(_extract,
                                out_shardings=(out_spec, out_spec))
        self._inject = jax.jit(_inject, donate_argnums=(0, 1))
        self._extract_q = jax.jit(
            _extract_q,
            out_shardings=(out_spec, scale_spec, out_spec, scale_spec))
        self._extract_deq = jax.jit(_extract_deq,
                                    out_shardings=(out_spec, out_spec))
        self._inject_q = jax.jit(_inject_q, donate_argnums=(0, 1))
        # Requantization reduces over (block_size, head_dim) only — both
        # unsharded — so the on-device quantize stays shard-local too.
        self._inject_quant = jax.jit(_inject_quant, donate_argnums=(0, 1))
        self._out_spec = out_spec
        self._scale_spec = scale_spec

    def extract(self, cache_k, cache_v, ids, dequant: bool = False) -> list[np.ndarray]:
        n = len(ids)
        padded = jnp.asarray(_pad_pow2(list(ids)), jnp.int32)
        if isinstance(cache_k, dict) and not dequant:
            kq, ks, vq, vs = self._extract_q(cache_k, cache_v, padded)
            kq, _ = assemble_local(kq)   # [L_loc, n_pad, bs, H_loc, hd]
            ks, _ = assemble_local(ks)   # [L_loc, n_pad, H_loc]
            vq, _ = assemble_local(vq)
            vs, _ = assemble_local(vs)
            return [pack_kv_block(kq[:, i], ks[:, i], vq[:, i], vs[:, i])
                    for i in range(n)]
        if isinstance(cache_k, dict):
            k, v = self._extract_deq(cache_k, cache_v, padded)
        else:
            k, v = self._extract(cache_k, cache_v, padded)
        k_local, _ = assemble_local(k)   # [L_loc, n_pad, bs, H_loc, hd]
        v_local, _ = assemble_local(v)
        kv = np.stack([k_local, v_local])          # [2, L_loc, n_pad, ...]
        per_block = np.moveaxis(kv, 2, 0)          # [n_pad, 2, L_loc, bs, H_loc, hd]
        return [np.ascontiguousarray(per_block[i]) for i in range(n)]

    def _make_global(self, local, dtype, gshape, offs, out_spec):
        """Global scatter operand: every rank contributes its box. The local
        data covers exactly this process's (layers, heads) slice."""
        local = np.asarray(local, dtype)

        def cb(index):
            sl = tuple(
                slice((idx.start or 0) - o,
                      (idx.stop if idx.stop is not None else dim) - o)
                for idx, o, dim in zip(index, offs, gshape))
            return np.ascontiguousarray(local[sl])
        return jax.make_array_from_callback(gshape, out_spec, cb)

    def inject(self, cache_k, cache_v, ids, blocks):
        assert len(ids) == len(blocks) and ids
        padded = _pad_pow2(list(ids))
        pad = [blocks[-1]] * (len(padded) - len(blocks))
        quant_cache = isinstance(cache_k, dict)
        payload_ref = cache_payload(cache_k)
        L, BS, KH, D = (payload_ref.shape[0], payload_ref.shape[2],
                        payload_ref.shape[3], payload_ref.shape[4])
        starts, stops = local_box(payload_ref)
        loc_shape = (stops[0] - starts[0], BS, stops[3] - starts[3], D)
        int4_cache = quant_cache and payload_ref.dtype == jnp.uint8
        # loc_shape's trailing dim is the PAYLOAD dim (head_dim/2 when the
        # cache is packed int4); float blocks carry the logical head_dim.
        D_log = D * 2 if int4_cache else D
        loc_logical = loc_shape[:3] + (D_log,)
        packed = _is_packed(blocks[0])
        if quant_cache and packed:
            want = "int4" if int4_cache else "int8"
            if _packed_kind(blocks[0], loc_logical) == want:
                pdt = np.uint8 if int4_cache else np.int8
                ups = [unpack_kv_block(b, loc_shape, pdt)
                       for b in blocks + pad]
                payload = np.stack([p for p, _ in ups])  # [n,2,L_loc,BS,H_loc,Dp]
                scales = np.stack([s for _, s in ups])   # [n,2,L_loc,H_loc]
                p_gshape = (L, len(padded), BS, KH, D)
                p_offs = (starts[0], 0, 0, starts[3], 0)
                s_gshape = (L, len(padded), KH)
                s_offs = (starts[0], 0, starts[3])
                mk_p = lambda x: self._make_global(
                    np.moveaxis(x, 0, 1), pdt, p_gshape, p_offs,
                    self._out_spec)
                mk_s = lambda x: self._make_global(
                    np.moveaxis(x, 0, 1), np.float32, s_gshape, s_offs,
                    self._scale_spec)
                return self._inject_q(
                    cache_k, cache_v, jnp.asarray(padded, jnp.int32),
                    mk_p(payload[:, 0]), mk_s(scales[:, 0]),
                    mk_p(payload[:, 1]), mk_s(scales[:, 1]))
            # Cross-kind import: dequantize the local shard, requantize on
            # device through the float path below.
            blocks = [dequantize_block(b, loc_logical, np.float32)
                      for b in blocks]
            pad = [blocks[-1]] * len(pad)
            packed = False
        if packed:
            # Quantized snapshot into a float engine: dequantize the local shard.
            blocks = [dequantize_block(b, loc_logical, payload_ref.dtype)
                      for b in blocks]
            pad = [blocks[-1]] * len(pad)
        data = np.stack(list(blocks) + pad)
        dk_local = np.ascontiguousarray(np.moveaxis(data[:, 0], 0, 1))
        dv_local = np.ascontiguousarray(np.moveaxis(data[:, 1], 0, 1))
        gshape = (L, len(padded), BS, KH, D_log)
        offs = (starts[0], 0, 0, starts[3], 0)  # sharded axes: layers, heads
        dtype = jnp.float32 if quant_cache else payload_ref.dtype
        dk = self._make_global(dk_local, dtype, gshape, offs, self._out_spec)
        dv = self._make_global(dv_local, dtype, gshape, offs, self._out_spec)
        if quant_cache:
            # Float blocks into an int8 engine: requantize on device.
            return self._inject_quant(
                cache_k, cache_v, jnp.asarray(padded, jnp.int32), dk, dv)
        return self._inject(
            cache_k, cache_v, jnp.asarray(padded, jnp.int32), dk, dv)


def local_block_spec(spec: KVCacheSpec, cache_k) -> tuple[KVCacheSpec, str]:
    """Per-rank tier geometry + shard fingerprint.

    The returned spec's ``num_layers``/``num_kv_heads`` are this rank's
    local extents, so tier arenas size to the shard actually stored; the
    fingerprint pins (starts, extents) so a restarted process only reads a
    disk tier written for the SAME shard of the SAME topology.
    ``kv_dtype`` carries through the replace, so quantized engines get
    quantized (packed) shard tiers."""
    starts, stops = local_box(cache_payload(cache_k))
    local = dataclasses.replace(
        spec,
        num_layers=stops[0] - starts[0],
        num_kv_heads=stops[3] - starts[3],
    )
    fp = f"shard(L{starts[0]}:{stops[0]},H{starts[3]}:{stops[3]})"
    return local, fp
