"""Offload manager: G1↔G2↔G3 block movement policy.

Reference: lib/llm/src/block_manager/offload.rs (offload manager with
priority queues + transfer managers). Two flows:

- **Offload (write-back)**: when the device PrefixPool evicts a committed
  block under allocation pressure, its contents are pulled off the device
  *before the slot is reused* and stored in the first tier; tiers cascade
  their own LRU victims downward (host→disk).
- **Onboard**: at request admission the engine asks for the prompt's block
  hashes; hashes missing from the device pool but present in a tier are
  batch-injected into freshly allocated device blocks and committed as
  matchable (inactive) cache entries, so the scheduler's normal prefix
  match then reuses them — TTFT win without touching scheduler logic
  (reference: connector/scheduler.rs onboarding decisions).
"""

from __future__ import annotations

import time
from collections import OrderedDict
from dataclasses import dataclass
from typing import Callable

import numpy as np

from dynamo_tpu import chaos
from dynamo_tpu.engine.errors import NoFreeBlocks
from dynamo_tpu.engine.prefix_pool import PrefixPool
from dynamo_tpu.kvbm.metrics import get_prefix_cache_metrics
from dynamo_tpu.kvbm.stream_ckpt import get_stream_ckpt_metrics
from dynamo_tpu.obs.mem_ledger import get_mem_ledger
from dynamo_tpu.kvbm.transfer import BlockTransferEngine
from dynamo_tpu.utils.logging import get_logger

log = get_logger("kvbm")

# One onboarding unit: (seq_hash, parent_seq_hash | None, block data).
OnboardPlan = list[tuple[int, "int | None", np.ndarray]]


def plan_onboard(
    pool: PrefixPool,
    seq_hashes: list[int],
    lookup: Callable[[int], "np.ndarray | None"],
) -> OnboardPlan:
    """Walk a hash chain: device-resident blocks are touched (MRU-refreshed
    so the upcoming allocation can't evict the chain head), missing blocks
    are resolved through ``lookup``; the walk stops at the first hash found
    nowhere (a later block without its prefix is unmatchable)."""
    plan: OnboardPlan = []
    parent: int | None = None
    for h in seq_hashes:
        if pool.has_hash(h):
            pool.touch(h)
            parent = h
            continue
        block = lookup(h)
        if block is None:
            break
        plan.append((h, parent, block))
        parent = h
    return plan


def inject_and_commit(runner, pool: PrefixPool, transfer: BlockTransferEngine,
                      plan: OnboardPlan, flush: Callable[[], int] | None = None,
                      span_attrs: dict | None = None) -> int:
    """Allocate device blocks, scatter the plan's data in, and commit them as
    matchable inactive cache entries. Returns blocks injected (0 if the pool
    can't make room). ``runner`` is duck-typed: mutable cache_k/cache_v.

    ``flush`` (the offload manager's write-back flush) runs between the
    allocation and the inject: the allocate may queue evictions of the very
    blocks being recycled, and their contents must be extracted before the
    inject overwrites them."""
    if not plan:
        return 0
    try:
        block_ids = pool.allocate(len(plan))
    except NoFreeBlocks:
        return 0
    if flush is not None:
        flush()
    runner.cache_k, runner.cache_v = transfer.inject(
        runner.cache_k, runner.cache_v, block_ids,
        [data for _, _, data in plan],
        span_attrs=span_attrs,
    )
    for bid, (h, par, _) in zip(block_ids, plan):
        pool.commit(bid, h, par)
    pool.release(block_ids)  # park as matchable inactive blocks
    return len(plan)


@dataclass
class OffloadStats:
    offloaded_blocks: int = 0
    onboarded_blocks: int = 0
    published_blocks: int = 0

    def to_dict(self) -> dict:
        return {"offloaded_blocks": self.offloaded_blocks,
                "onboarded_blocks": self.onboarded_blocks,
                "published_blocks": self.published_blocks}


class OffloadManager:
    """Ties the engine's device cache + PrefixPool to host/disk tiers.

    ``runner`` is duck-typed: needs mutable ``cache_k``/``cache_v`` jax
    arrays (this manager replaces them on inject — the inject program
    donates its inputs, mirroring the engine step functions).
    """

    #: device-extract budget for publish-on-commit per flush — bounds the
    #: extra gather + remote puts a busy step pays; leftovers carry over.
    PUBLISH_PER_FLUSH = 8
    #: remembered published hashes (dedup window) — bounds memory, and a
    #: redundant re-publish past the window is an idempotent put.
    PUBLISH_MEMORY = 1 << 16
    #: device-extract budget for stream-checkpoint blocks per flush — the
    #: crash-recovery path shares the step's transfer bucket, so it gets
    #: the same bounded slice as publish-on-commit.
    CKPT_PER_FLUSH = 8

    def __init__(self, runner, pool: PrefixPool, tiers: list, transfer=None,
                 vote_plans: bool = False, publish_tier=None, ckpt_tier=None):
        assert tiers, "OffloadManager needs at least one tier"
        self.runner = runner
        self.pool = pool
        self.tiers = tiers
        # transfer override: multi-host engines pass the sharded engine
        # (kvbm/distributed.py) so tiers hold rank-local shards.
        self.transfer = transfer or BlockTransferEngine()
        # vote_plans: multi-host engines with a SHARED tier (the G4 remote
        # store) can see rank-divergent hit/miss results — evictions,
        # connection hiccups. Divergent onboard plans mean divergent XLA
        # programs → hung collectives, so each onboard truncates its plan to
        # the mesh-wide minimum length (the walk order is a fixed hash
        # chain, so equal lengths ⇒ identical hash sets). Rank-local tiers
        # (G2 host / G3 disk) are deterministic and need no vote.
        self.vote_plans = vote_plans
        # publish_tier: the global prefix cache's publish-on-commit target
        # (the shared G4 remote store). Committed prefix blocks are pushed
        # there PROACTIVELY — not only on LRU eviction — so other engines
        # can import a hot shared prefix while it is still serving here.
        # Publish decisions depend only on the commit stream and local
        # bounded memory (never on shared-tier lookups), so multi-host
        # ranks queue identical batches — no plan vote needed.
        self.publish_tier = publish_tier
        # ckpt_tier: the shared G4 store stream checkpoints park in
        # (kvbm/stream_ckpt.py). Blocks ride the normal tier namespace (so
        # a survivor's admission onboard finds them); the record is written
        # only AFTER every block it covers has flushed — crash-consistent
        # ordering: a record in the store always points at reachable KV.
        self.ckpt_tier = ckpt_tier
        self.stats = OffloadStats()
        self._pending: list[tuple[int, int]] = []  # (block_id, seq_hash)
        self._publish_pending: list[tuple[int, int]] = []
        self._published: OrderedDict[int, None] = OrderedDict()
        self._ckpt_pending: list[tuple[int, int]] = []
        # (request_id, record, seq_hashes still awaiting flush)
        self._ckpt_records: list[tuple[str, dict, set[int]]] = []
        self._onboarding = False
        # Memory ledger (obs/mem_ledger.py): queued publish/checkpoint
        # blocks are device references held outside the pool's refcounts —
        # tagged per owner class for the occupancy waterfall and audit.
        self._mled = get_mem_ledger()
        pool.evict_hook = self._on_evict
        if publish_tier is not None:
            pool.commit_hook = self._on_commit

    # -- offload -----------------------------------------------------------
    def _on_evict(self, block_id: int, seq_hash: int) -> None:
        """Queue the eviction; the device copy happens in one bucketed
        transfer at flush_pending() (an eviction-per-gather here would
        serialize step() with many tiny device round-trips).

        The already-stored dedup check is skipped for SHARED tiers (the G4
        remote store): another rank/engine may have stored the hash between
        two ranks' checks, which would make each rank's pending list — and
        therefore its extract program shapes — diverge. A redundant put of
        identical content is idempotent; a rank-divergent device program is
        a hang."""
        top = self.tiers[0]
        # A queued publish of this block is now stale: once evicted, the
        # slot can be rewritten before the next flush extracts it, and a
        # later extract would publish NEW content under the OLD hash. The
        # eviction write-back below carries the content to the tier cascade
        # instead.
        if self._publish_pending:
            stale = [h for b, h in self._publish_pending if b == block_id]
            if stale:
                self._publish_pending = [
                    (b, h) for b, h in self._publish_pending if b != block_id]
                if self._mled.enabled:
                    for h in stale:
                        self._mled.unpin("prefix_publish", str(h))
        # Same staleness rule for queued checkpoint blocks: drop the pair
        # AND release any record waiting on its hash — the record still
        # writes (covering what did reach the store); a resume's onboard
        # walk simply stops at the first unreachable hash.
        if self._ckpt_pending:
            dropped = {h for b, h in self._ckpt_pending if b == block_id}
            if dropped:
                self._ckpt_pending = [
                    (b, h) for b, h in self._ckpt_pending if b != block_id]
                for _, _, waiting in self._ckpt_records:
                    waiting -= dropped
                if self._mled.enabled:
                    for h in dropped:
                        self._mled.unpin("stream_ckpt", str(h))
        if not getattr(top, "shared", False) and seq_hash in top:
            return
        self._pending.append((block_id, seq_hash))

    def _on_commit(self, block_id: int, seq_hash: int,
                   parent_hash: "int | None") -> None:
        """PrefixPool commit hook: queue a newly committed block for
        publish-on-commit. Imports are skipped (their content just came FROM
        the tiers), as is anything inside the bounded already-published
        window."""
        if self._onboarding or seq_hash in self._published:
            return
        self._published[seq_hash] = None
        while len(self._published) > self.PUBLISH_MEMORY:
            self._published.popitem(last=False)
        self._publish_pending.append((block_id, seq_hash))
        if self._mled.enabled:
            self._mled.pin("prefix_publish", str(seq_hash), 1)

    def flush_pending(self) -> int:
        """Extract all queued evictions — plus this flush's publish-on-commit
        batch — in one bucketed transfer; evictions store to the top tier,
        published blocks push to the shared publish tier. Must run before the
        evicted slots are rewritten (engine step / onboard inject); callers:
        EngineCore.step, inject_and_commit."""
        publish = self._publish_pending[: self.PUBLISH_PER_FLUSH]
        self._publish_pending = self._publish_pending[self.PUBLISH_PER_FLUSH:]
        ckpt = self._ckpt_pending[: self.CKPT_PER_FLUSH]
        self._ckpt_pending = self._ckpt_pending[self.CKPT_PER_FLUSH:]
        if self._mled.enabled:
            for _, h in publish:
                self._mled.unpin("prefix_publish", str(h))
            for _, h in ckpt:
                self._mled.unpin("stream_ckpt", str(h))
        if not self._pending and not publish and not ckpt:
            self._flush_ckpt_records(frozenset())
            return 0
        # Chaos: an error here propagates into the engine step — the
        # offload cascade failing is engine-fatal, not silently droppable.
        chaos.inject("kvbm.offload", blocks=len(self._pending))
        pending, self._pending = self._pending, []
        blocks = self.transfer.extract(
            self.runner.cache_k, self.runner.cache_v,
            [b for b, _ in pending] + [b for b, _ in publish]
            + [b for b, _ in ckpt]
        )
        top = self.tiers[0]
        for (_, seq_hash), block in zip(pending, blocks):
            top.put(seq_hash, block)
        for (_, seq_hash), block in zip(publish, blocks[len(pending):]):
            # RemoteBlockPool.put degrades to a drop when the store is
            # unreachable — publish is strictly best-effort.
            self.publish_tier.put(seq_hash, block)
        if ckpt:
            sm = get_stream_ckpt_metrics()
            off = len(pending) + len(publish)
            for (_, seq_hash), block in zip(ckpt, blocks[off:]):
                self.ckpt_tier.put(seq_hash, block)
                sm.bytes.inc(int(getattr(block, "nbytes", 0)))
        self._flush_ckpt_records({h for _, h in ckpt})
        if publish:
            self.stats.published_blocks += len(publish)
            get_prefix_cache_metrics().published_blocks.inc(len(publish))
        self.stats.offloaded_blocks += len(pending)
        return len(pending)

    # -- stream checkpoints -------------------------------------------------
    def enqueue_stream_ckpt(self, request_id: str, record: dict,
                            pairs: "list[tuple[int, int]]") -> None:
        """Queue a stream's newly committed ``(block_id, seq_hash)`` pairs
        plus its StreamCheckpoint record. Blocks flush through the normal
        budgeted path; the record is held back until every hash it waits on
        has flushed, then written via ``ckpt_tier.put_stream_ckpt`` — so a
        stored record never references KV the store hasn't seen. The
        enqueue decision is a pure function of the commit stream + config,
        so multi-host ranks queue identically (no plan vote)."""
        if self.ckpt_tier is None:
            return
        queued = {h for _, h in self._ckpt_pending}
        fresh = [(b, h) for b, h in pairs if h not in queued]
        self._ckpt_pending.extend(fresh)
        if self._mled.enabled:
            for _, h in fresh:
                self._mled.pin("stream_ckpt", str(h), 1)
        self._ckpt_records.append(
            (request_id, record, {h for _, h in pairs}))

    def _flush_ckpt_records(self, flushed: "frozenset[int] | set[int]") -> None:
        """Write every record whose block set is fully flushed (including
        records enqueued with no new blocks). Best-effort: a failed put is
        dropped — resume degrades to the previous checkpoint or reprompt."""
        if not self._ckpt_records:
            return
        import msgpack

        sm = get_stream_ckpt_metrics()
        still: list[tuple[str, dict, set[int]]] = []
        for rid, record, waiting in self._ckpt_records:
            waiting -= flushed
            if waiting:
                still.append((rid, record, waiting))
                continue
            if self.ckpt_tier.put_stream_ckpt(rid, record):
                sm.writes.inc(1)
                sm.bytes.inc(len(msgpack.packb(record, use_bin_type=True)))
        self._ckpt_records = still

    def delete_stream_ckpt(self, request_id: str) -> None:
        """Clean-finish reap: drop any queued record and delete the stored
        one — a finished stream must not be resumable."""
        if self.ckpt_tier is None:
            return
        self._ckpt_records = [
            (rid, rec, w) for rid, rec, w in self._ckpt_records
            if rid != request_id]
        self.ckpt_tier.del_stream_ckpt(request_id)

    def stage_blocks(self, pairs: "list[tuple[int, int]]") -> int:
        """Write-through ``(block_id, seq_hash)`` pairs into the tier cascade
        NOW, while their device slots are still intact — the session
        retention demotion path (EngineCore._demote_session): a released
        pin's blocks go LRU-evictable immediately, so queueing them like a
        normal eviction could extract a rewritten slot. Returns blocks
        actually staged (already-present hashes in a non-shared top tier
        are skipped, same dedup rule as _on_evict). On transfer failure the
        queued pairs are rolled back so a later flush can't extract stale
        slots."""
        top = self.tiers[0]
        shared = getattr(top, "shared", False)
        queued = set(self._pending)
        fresh = [(b, h) for b, h in pairs
                 if (shared or h not in top) and (b, h) not in queued]
        if not fresh:
            return 0
        self._pending.extend(fresh)
        try:
            self.flush_pending()
        except Exception:
            self._pending = [p for p in self._pending if p not in set(fresh)]
            raise
        return len(fresh)

    def drain_publish(self) -> int:
        """Flush the whole publish-on-commit queue (budgeted slices until
        empty), plus any queued stream-checkpoint blocks/records. Called
        when the engine goes idle — the final finalize's commits would
        otherwise sit queued until the next step_begin."""
        total = 0
        while (self._publish_pending or self._ckpt_pending
               or self._ckpt_records):
            before = len(self._publish_pending)
            self.flush_pending()
            total += before - len(self._publish_pending)
        return total

    # -- onboard -----------------------------------------------------------
    def _lookup(self, seq_hash: int) -> np.ndarray | None:
        for tier in self.tiers:
            block = tier.get(seq_hash)
            if block is not None:
                return block
        return None

    def onboard(self, seq_hashes: list[int]) -> int:
        """Bring the longest tier-cached prefix of ``seq_hashes`` onto the
        device. Returns the number of blocks injected.

        The allocation inside may evict inactive device blocks → reentrant
        ``_on_evict`` (safe: the evicted blocks are disjoint from the ones
        being loaded, and tier ``get`` returned copies)."""
        t0 = time.perf_counter()
        plan = plan_onboard(self.pool, seq_hashes, self._lookup)
        if self.vote_plans:
            from dynamo_tpu.parallel.multihost import vote_min

            plan = plan[: vote_min(len(plan))]
        self._onboarding = True  # imported commits must not re-publish
        try:
            n = inject_and_commit(self.runner, self.pool, self.transfer, plan,
                                  flush=self.flush_pending)
        finally:
            self._onboarding = False
        self.stats.onboarded_blocks += n
        if seq_hashes:
            get_prefix_cache_metrics().record_onboard(
                found_blocks=len(plan), imported_blocks=n,
                block_size=self.pool.block_size,
                seconds=time.perf_counter() - t0)
        return n

    def queue_live_ids(self) -> dict[str, set[str]]:
        """Mem-ledger audit live sets: owner ids currently held by the
        publish / stream-checkpoint queues (string-keyed sequence hashes,
        matching the pin tags above)."""
        return {
            "prefix_publish": {str(h) for _, h in self._publish_pending},
            "stream_ckpt": {str(h) for _, h in self._ckpt_pending},
        }

    def snapshot(self) -> dict:
        out = self.stats.to_dict()
        for tier in self.tiers:
            out[tier.name] = {"blocks": len(tier), **tier.stats.to_dict()}
        return out
