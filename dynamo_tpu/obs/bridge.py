"""Span→metrics bridge: derives ``dynamo_request_*`` phase-latency
histograms from closed spans, so operators get Prometheus aggregates
(TTFT, ITL, queue wait, prefill, decode/token, KV transfer, e2e)
without running a trace backend.

Registered as a tracer sink; also fed by ``Tracer.ingest`` for spans
closed in other processes (engine phases arrive on the wire attached to
the final ``LLMEngineOutput``), so the frontend's ``/metrics`` covers
the whole pipeline.
"""

from __future__ import annotations

from typing import TYPE_CHECKING

from dynamo_tpu.utils.metrics import MetricsRegistry

if TYPE_CHECKING:  # pragma: no cover
    from dynamo_tpu.obs.tracer import Span

_FAST = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
         0.5, 1.0, 2.5)


class SpanMetricsBridge:
    """Maps span names to histograms; call with each closed span."""

    def __init__(self, registry: MetricsRegistry):
        h = registry.histogram
        self.h_e2e = h("request_e2e_seconds",
                       "End-to-end request latency derived from request spans")
        self.h_ttft = h("request_ttft_seconds",
                        "Time to first token derived from request.ttft spans")
        self.h_itl = h("request_itl_seconds",
                       "Per-request mean inter-token latency derived from request spans",
                       buckets=_FAST)
        self.h_queue = h("request_queue_seconds",
                         "Engine admission queue wait derived from engine.queue spans")
        self.h_prefill = h("request_prefill_seconds",
                           "Prefill phase latency derived from engine.prefill spans")
        self.h_decode = h("request_decode_per_token_seconds",
                          "Engine decode time per token derived from engine.decode spans",
                          buckets=_FAST)
        self.h_kv = h("request_kv_transfer_seconds",
                      "KV block transfer latency derived from kv.transfer spans",
                      buckets=_FAST)

    def __call__(self, span: "Span") -> None:
        name, dur = span.name, span.duration
        labels = {}
        model = span.attrs.get("model")
        if model:
            labels["model"] = str(model)
        if name == "request":
            self.h_e2e.observe(dur, **labels)
            # Mean ITL over the request's decode stretch: cheap span-based
            # ITL without a per-token span (see docs/OBSERVABILITY.md).
            toks = span.attrs.get("output_tokens") or 0
            ttft = span.attrs.get("ttft_s")
            if toks and toks > 1 and ttft is not None and dur > ttft:
                self.h_itl.observe((dur - ttft) / (toks - 1), **labels)
        elif name == "request.ttft":
            self.h_ttft.observe(dur, **labels)
        elif name == "engine.queue":
            self.h_queue.observe(dur, **labels)
        elif name == "engine.prefill":
            self.h_prefill.observe(dur, **labels)
        elif name == "engine.decode":
            toks = span.attrs.get("tokens") or 0
            if toks > 0:
                self.h_decode.observe(dur / toks, **labels)
        elif name == "kv.transfer":
            self.h_kv.observe(dur, **labels)
