"""KV memory & capacity ledger: pin-owner taxonomy, leak audit, TTX forecast.

The scheduling ledger prices *compute* decisions; this module does the
same for the resource those decisions actually contend over — KV blocks.
Three planes, all riding one process-global :class:`MemLedger`:

* **Pin-owner taxonomy** — every device-block pin/unpin is tagged with an
  owner class (``OWNER_CLASSES``: ``stream`` — an admitted request's
  block table, ``session`` — session-sticky retention pins,
  ``prefix_publish`` — commit-queue references awaiting a publish flush,
  ``stream_ckpt`` — checkpoint-queue references awaiting a ckpt flush,
  ``staging`` — disagg export/wave pins held for an in-flight transfer)
  plus the owner id (request/session/xfer id, block hash). The per-class
  totals feed the ``dynamo_mem_device_blocks{owner}`` occupancy waterfall;
  tier occupancy (host/disk/remote blocks+bytes) comes from registered
  pull callbacks, and every eviction/demotion increments
  ``dynamo_mem_churn_blocks_total{tier,cause}``.
* **Leak audit** — :meth:`MemLedger.audit` reconciles tagged pins against
  the live-id sets each engine registers (:meth:`register_live_source`):
  a pin whose owner id no longer exists anywhere is an *orphan*, exported
  as ``dynamo_mem_orphan_pins{owner}`` with the offending ids served at
  ``/debug/mem``. The chaos ``InvariantChecker`` consumes this audit
  instead of its old bespoke kv_usage walk.
* **TTX forecasting** — per-QoS EWMA block consumption rates (admission
  allocations minus releases) divide into the current free-block count:
  ``dynamo_mem_ttx_seconds`` plus a capacity posture (``ok|tight|
  critical``). Every observation also increments
  ``dynamo_mem_headroom_observations_total{state}``, the counter pair
  behind the fleet ``kv_headroom`` SLI (obs/fleet.py) that pages on
  sustained short TTX, and the planner stamps the forecast into every
  ``Decision.reason`` as ``mem[ttx=42s posture=tight]``.

Disabled mode (``DYN_MEM_LEDGER=0``) flips ``MemLedger.enabled``; every
call site gates on that flag BEFORE building any record, so a disabled
ledger adds zero per-step work — the same contract as
``DYN_SCHED_LEDGER``. The mocker mirrors the full ledger device-free.

The ``dynamo_mem_*`` family is lint-checked by tools/lint_metrics.py
MEM_METRICS and installs on workers via ``install_mem_metrics``.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Callable, Iterable, Mapping

from dynamo_tpu.utils.metrics import MetricsRegistry

MEM_ENV = "DYN_MEM_LEDGER"

#: Every pin the ledger accepts carries one of these owner classes.
OWNER_CLASSES = ("stream", "session", "prefix_publish", "stream_ckpt",
                 "staging")

#: Capacity postures, in order of severity (the gauge exports the index).
POSTURES = ("ok", "tight", "critical")

#: TTX below these bounds moves the posture to tight / critical. Tight is
#: also the headroom SLI boundary: an observation with ttx < tight counts
#: as a "short" event against the kv_headroom error budget.
TTX_TIGHT_S = 120.0
TTX_CRITICAL_S = 30.0

#: Forecast ceiling when the net consumption rate is <= 0 (the pool is
#: draining or idle): "never exhausts" clamps here so the gauge stays a
#: finite, plottable number (~11.5 days).
TTX_CAP_S = 1e6


def mem_enabled(default: bool = True) -> bool:
    """The module-level gate: DYN_MEM_LEDGER=0 disables all memory-ledger
    accounting (record paths return before any work)."""
    val = os.environ.get(MEM_ENV, "")
    if val == "":
        return default
    return val not in ("0", "false", "no", "off")


# ---------------------------------------------------------------------------
# Prometheus family
# ---------------------------------------------------------------------------

class MemMetrics:
    """The dynamo_mem_* family (names cross-checked by
    tools/lint_metrics.py MEM_METRICS)."""

    def __init__(self, registry: MetricsRegistry | None = None):
        self.bind(registry or MetricsRegistry())

    def bind(self, registry: MetricsRegistry) -> None:
        self.registry = registry
        self.device_blocks = registry.gauge(
            "mem_device_blocks",
            "Device KV occupancy waterfall: blocks pinned per owner class "
            "(stream|session|prefix_publish|stream_ckpt|staging) plus the "
            "free and cached (inactive, evictable) rows, owner label")
        self.tier_blocks = registry.gauge(
            "mem_tier_blocks",
            "KV blocks resident per offload tier (host|disk|remote), "
            "tier label")
        self.tier_bytes = registry.gauge(
            "mem_tier_bytes",
            "KV bytes resident per offload tier (host|disk|remote), "
            "tier label")
        self.churn = registry.counter(
            "mem_churn_blocks_total",
            "Blocks evicted/demoted per tier, by cause "
            "(allocation_pressure|session_demote|clear|lru|byte_budget)")
        self.orphans = registry.gauge(
            "mem_orphan_pins",
            "Pins whose owner id no longer exists in any live source at "
            "the last audit, by owner class (nonzero = leak)")
        self.audits = registry.counter(
            "mem_audits_total",
            "Pin-leak audits run, by result (clean|orphans)")
        self.ttx = registry.gauge(
            "mem_ttx_seconds",
            "Forecast seconds until the device block pool exhausts at the "
            "current EWMA net consumption rate (capped when draining)")
        self.posture = registry.gauge(
            "mem_capacity_posture",
            "Capacity posture index from the TTX forecast "
            "(0=ok, 1=tight, 2=critical)")
        self.alloc = registry.counter(
            "mem_alloc_blocks_total",
            "Device blocks allocated for admissions and decode growth, "
            "by qos_class")
        self.release = registry.counter(
            "mem_release_blocks_total",
            "Device blocks released by stream finish/preemption, "
            "by qos_class")
        self.headroom = registry.counter(
            "mem_headroom_observations_total",
            "TTX observations by headroom state (ok|short): the counter "
            "pair behind the fleet kv_headroom SLI")


_metrics: MemMetrics | None = None


def get_mem_metrics() -> MemMetrics:
    global _metrics
    if _metrics is None:
        _metrics = MemMetrics()
    return _metrics


def install_mem_metrics(registry: MetricsRegistry) -> MemMetrics:
    """Re-home the singleton's metrics into ``registry`` (the worker's
    runtime registry) so the family is exposed on /metrics. Gauges are
    republished from the live ledger so an install that lands AFTER the
    engine started still exposes current occupancy; counters stay
    monotonic and are not replayed."""
    m = get_mem_metrics()
    m.bind(registry)
    get_mem_ledger().republish()
    return m


# ---------------------------------------------------------------------------
# The ledger
# ---------------------------------------------------------------------------

class MemLedger:
    """Process-global KV memory accounting.

    Thread-safe: the engine-core thread pins/unpins while the asyncio side
    reads snapshots for stats/debug endpoints and the audit may run from
    either. Multiple engines in one process (mocker fleets) share the
    ledger; audits union every registered live source, so cross-engine
    aggregation never manufactures orphans."""

    _CHURN_RING = 256   # recent churn events kept for the /debug trend

    def __init__(self):
        self._lock = threading.Lock()
        self.enabled = mem_enabled()
        self.audit_interval_s = 30.0
        self.ewma_alpha = 0.3
        self.ttx_tight_s = TTX_TIGHT_S
        self.ttx_critical_s = TTX_CRITICAL_S
        # (owner_class, owner_id) -> pinned block count
        self._pins: dict[tuple[str, str], int] = {}
        # device waterfall extras (engine publishes at its record point)
        self._device_free = 0
        self._device_cached = 0
        self._device_total = 0
        # tier occupancy pull callbacks: name -> fn() -> (blocks, bytes)
        self._tiers: dict[str, Callable[[], tuple[int, int]]] = {}
        # audit live-id sources: key -> fn() -> {owner_class: iterable ids}
        self._live_sources: dict[str, Callable[[], Mapping]] = {}
        # churn accounting
        self.churn_totals: dict[tuple[str, str], int] = {}
        self._churn_ring: deque[tuple[float, str, str, int]] = deque(
            maxlen=self._CHURN_RING)
        # TTX state
        self._alloc_acc: dict[str, int] = {}     # qos -> blocks since obs
        self._release_acc: dict[str, int] = {}
        self._alloc_rate: dict[str, float] = {}  # qos -> EWMA blocks/s
        self._release_rate: dict[str, float] = {}
        self.alloc_totals: dict[str, int] = {}
        self.release_totals: dict[str, int] = {}
        self._last_obs_t: float | None = None
        self.ttx_s = TTX_CAP_S
        self.posture = "ok"
        # audit state
        self._last_audit_t: float | None = None
        self.last_audit: dict | None = None

    # -- configuration --------------------------------------------------
    def configure(self, enabled: bool | None = None, *,
                  audit_interval_s: float | None = None,
                  ttx_tight_s: float | None = None,
                  ttx_critical_s: float | None = None) -> None:
        """Engine-startup hook: re-read the env gate (or force a value)
        and optionally override the audit cadence / posture thresholds."""
        with self._lock:
            self.enabled = mem_enabled() if enabled is None else enabled
            if audit_interval_s is not None:
                self.audit_interval_s = audit_interval_s
            if ttx_tight_s is not None:
                self.ttx_tight_s = ttx_tight_s
            if ttx_critical_s is not None:
                self.ttx_critical_s = ttx_critical_s

    def reset(self) -> None:
        """Test hook: drop all pins/rates/sources (metrics counters are
        monotonic and keep their values; gauges are re-zeroed)."""
        with self._lock:
            self._pins.clear()
            self._tiers.clear()
            self._live_sources.clear()
            self.churn_totals.clear()
            self._churn_ring.clear()
            self._alloc_acc.clear()
            self._release_acc.clear()
            self._alloc_rate.clear()
            self._release_rate.clear()
            self.alloc_totals.clear()
            self.release_totals.clear()
            self._last_obs_t = None
            self.ttx_s = TTX_CAP_S
            self.posture = "ok"
            self._last_audit_t = None
            self.last_audit = None
            self._device_free = self._device_cached = self._device_total = 0
        m = get_mem_metrics()
        for owner in OWNER_CLASSES:
            m.device_blocks.set(0.0, owner=owner)
            m.orphans.set(0.0, owner=owner)
        m.device_blocks.set(0.0, owner="free")
        m.device_blocks.set(0.0, owner="cached")
        m.ttx.set(TTX_CAP_S)
        m.posture.set(0.0)

    # -- pin taxonomy ----------------------------------------------------
    def pin(self, owner: str, owner_id: str, blocks: int) -> None:
        """Tag ``blocks`` device blocks as pinned by (owner, owner_id)."""
        if not self.enabled or blocks <= 0:
            return
        key = (owner, str(owner_id))
        with self._lock:
            self._pins[key] = self._pins.get(key, 0) + int(blocks)
            total = self._owner_total(owner)
        get_mem_metrics().device_blocks.set(float(total), owner=owner)

    def unpin(self, owner: str, owner_id: str,
              blocks: int | None = None) -> None:
        """Release ``blocks`` pins of (owner, owner_id); None = all of
        them. Over-release clamps at zero (the pool's own double-free
        check is the hard error path, not the ledger's)."""
        if not self.enabled:
            return
        key = (owner, str(owner_id))
        with self._lock:
            held = self._pins.get(key, 0)
            if held <= 0:
                return
            drop = held if blocks is None else min(int(blocks), held)
            left = held - drop
            if left > 0:
                self._pins[key] = left
            else:
                del self._pins[key]
            total = self._owner_total(owner)
        get_mem_metrics().device_blocks.set(float(total), owner=owner)

    def _owner_total(self, owner: str) -> int:
        # caller holds the lock
        return sum(n for (cls, _), n in self._pins.items() if cls == owner)

    def owner_blocks(self) -> dict[str, int]:
        """Pinned device blocks per owner class (zero rows included)."""
        with self._lock:
            out = {cls: 0 for cls in OWNER_CLASSES}
            for (cls, _), n in self._pins.items():
                out[cls] = out.get(cls, 0) + n
        return out

    def top_owners(self, top: int = 10) -> list[dict]:
        """Largest individual pin holders: [{owner, id, blocks}]."""
        with self._lock:
            items = sorted(self._pins.items(), key=lambda kv: kv[1],
                           reverse=True)[:top]
        return [{"owner": cls, "id": oid, "blocks": n}
                for (cls, oid), n in items]

    # -- occupancy -------------------------------------------------------
    def observe_device(self, free: int, cached: int,
                       total: int | None = None) -> None:
        """Publish the pool-side waterfall rows: ``free`` (free-list) and
        ``cached`` (committed-inactive, evictable) block counts."""
        if not self.enabled:
            return
        with self._lock:
            self._device_free = int(free)
            self._device_cached = int(cached)
            if total is not None:
                self._device_total = int(total)
        m = get_mem_metrics()
        m.device_blocks.set(float(free), owner="free")
        m.device_blocks.set(float(cached), owner="cached")

    def register_tier(self, name: str,
                      fn: Callable[[], tuple[int, int]]) -> None:
        """Register a tier occupancy callback ``fn() -> (blocks, bytes)``.
        Pulled only at snapshot/debug/audit time — a tier whose len() is a
        network call (the remote store) never lands on the step path."""
        with self._lock:
            self._tiers[name] = fn

    def tier_occupancy(self) -> dict[str, dict]:
        """Pull every registered tier; a failing callback reports an
        error row instead of raising (a dead remote store must not take
        /debug/mem down with it)."""
        with self._lock:
            tiers = dict(self._tiers)
        out: dict[str, dict] = {}
        m = get_mem_metrics()
        for name, fn in tiers.items():
            try:
                blocks, nbytes = fn()
            except Exception as exc:  # noqa: BLE001 — degrade, don't raise
                out[name] = {"error": f"{type(exc).__name__}: {exc}"[:120]}
                continue
            out[name] = {"blocks": int(blocks), "bytes": int(nbytes)}
            m.tier_blocks.set(float(blocks), tier=name)
            m.tier_bytes.set(float(nbytes), tier=name)
        return out

    # -- churn -----------------------------------------------------------
    def record_churn(self, tier: str, cause: str, blocks: int = 1,
                     ts: float | None = None) -> None:
        """One eviction/demotion event: ``blocks`` left ``tier`` because
        of ``cause`` (allocation_pressure, session_demote, clear, lru,
        byte_budget)."""
        if not self.enabled or blocks <= 0:
            return
        key = (tier, cause)
        with self._lock:
            self.churn_totals[key] = self.churn_totals.get(key, 0) + blocks
            self._churn_ring.append(
                (ts if ts is not None else time.time(), tier, cause, blocks))
        get_mem_metrics().churn.inc(blocks, tier=tier, cause=cause)

    def churn_trend(self, limit: int = 64) -> list[dict]:
        with self._lock:
            recent = list(self._churn_ring)[-limit:]
        return [{"ts": round(t, 3), "tier": tier, "cause": cause,
                 "blocks": n} for t, tier, cause, n in recent]

    # -- TTX forecasting -------------------------------------------------
    def record_alloc(self, qos: str, blocks: int) -> None:
        """Blocks consumed from the pool (admission or decode growth)."""
        if not self.enabled or blocks <= 0:
            return
        with self._lock:
            self._alloc_acc[qos] = self._alloc_acc.get(qos, 0) + blocks
            self.alloc_totals[qos] = self.alloc_totals.get(qos, 0) + blocks
        get_mem_metrics().alloc.inc(blocks, qos_class=qos)

    def record_release(self, qos: str, blocks: int) -> None:
        """Blocks returned to the pool (finish/preempt release)."""
        if not self.enabled or blocks <= 0:
            return
        with self._lock:
            self._release_acc[qos] = self._release_acc.get(qos, 0) + blocks
            self.release_totals[qos] = (
                self.release_totals.get(qos, 0) + blocks)
        get_mem_metrics().release.inc(blocks, qos_class=qos)

    def observe_free(self, free_blocks: int,
                     now: float | None = None) -> tuple[float, str]:
        """Fold the accumulated alloc/release deltas into the per-QoS EWMA
        rates and refresh the forecast: ``ttx = free / net_rate`` where
        ``net_rate = Σ_qos (alloc_ewma - release_ewma)``, capped at
        TTX_CAP_S when the pool is not consuming. Returns (ttx, posture)
        and counts one kv_headroom observation."""
        if not self.enabled:
            return TTX_CAP_S, "ok"
        t = now if now is not None else time.time()
        with self._lock:
            if self._last_obs_t is None or t <= self._last_obs_t:
                # first observation (or non-advancing clock): baseline only
                self._last_obs_t = t
                self._alloc_acc.clear()
                self._release_acc.clear()
                ttx, posture = self.ttx_s, self.posture
            else:
                dt = t - self._last_obs_t
                self._last_obs_t = t
                a = self.ewma_alpha
                for qos in set(self._alloc_rate) | set(self._alloc_acc):
                    inst = self._alloc_acc.get(qos, 0) / dt
                    prev = self._alloc_rate.get(qos, inst)
                    self._alloc_rate[qos] = a * inst + (1 - a) * prev
                for qos in set(self._release_rate) | set(self._release_acc):
                    inst = self._release_acc.get(qos, 0) / dt
                    prev = self._release_rate.get(qos, inst)
                    self._release_rate[qos] = a * inst + (1 - a) * prev
                self._alloc_acc.clear()
                self._release_acc.clear()
                net = (sum(self._alloc_rate.values())
                       - sum(self._release_rate.values()))
                if net > 1e-9:
                    ttx = min(max(free_blocks, 0) / net, TTX_CAP_S)
                else:
                    ttx = TTX_CAP_S
                if ttx < self.ttx_critical_s:
                    posture = "critical"
                elif ttx < self.ttx_tight_s:
                    posture = "tight"
                else:
                    posture = "ok"
                self.ttx_s, self.posture = ttx, posture
        m = get_mem_metrics()
        m.ttx.set(ttx)
        m.posture.set(float(POSTURES.index(posture)))
        m.headroom.inc(state=("ok" if posture == "ok" else "short"))
        return ttx, posture

    def consumption_rates(self) -> dict[str, dict[str, float]]:
        """Per-QoS EWMA rates: {qos: {alloc_bps, release_bps, net_bps}}."""
        with self._lock:
            out = {}
            for qos in sorted(set(self._alloc_rate) | set(self._release_rate)):
                al = self._alloc_rate.get(qos, 0.0)
                rl = self._release_rate.get(qos, 0.0)
                out[qos] = {"alloc_bps": round(al, 4),
                            "release_bps": round(rl, 4),
                            "net_bps": round(al - rl, 4)}
        return out

    # -- leak audit ------------------------------------------------------
    def register_live_source(self, key: str,
                             fn: Callable[[], Mapping]) -> None:
        """Register an audit source: ``fn() -> {owner_class: iterable of
        live owner ids}``. One source per engine (keyed by engine id) so
        in-process fleets union their live sets instead of clobbering."""
        with self._lock:
            self._live_sources[str(key)] = fn

    def unregister_live_source(self, key: str) -> None:
        with self._lock:
            self._live_sources.pop(str(key), None)

    def audit(self, now: float | None = None) -> dict:
        """Reconcile every tagged pin against the union of live ids: a
        pin whose owner id no live source knows is an orphan. Exports
        ``dynamo_mem_orphan_pins{owner}`` and retains the report for
        /debug/mem. Owner classes with NO registered live source are
        skipped (unauditable is not orphaned)."""
        t = now if now is not None else time.time()
        with self._lock:
            sources = list(self._live_sources.values())
            pins = dict(self._pins)
        live: dict[str, set[str]] = {}
        covered: set[str] = set()
        for fn in sources:
            try:
                got = fn()
            except Exception:  # noqa: BLE001 — a dead source audits empty
                continue
            for cls, ids in got.items():
                covered.add(cls)
                live.setdefault(cls, set()).update(str(i) for i in ids)
        orphans: dict[str, list[dict]] = {}
        counts = {cls: 0 for cls in OWNER_CLASSES}
        for (cls, oid), n in pins.items():
            if cls not in covered:
                continue
            if oid in live.get(cls, ()):
                continue
            orphans.setdefault(cls, []).append({"id": oid, "blocks": n})
            counts[cls] = counts.get(cls, 0) + 1
        for rows in orphans.values():
            rows.sort(key=lambda r: r["blocks"], reverse=True)
        total = sum(counts.values())
        report = {
            "ts": t,
            "orphan_pins": total,
            "orphans": orphans,
            "by_owner": counts,
            "pins_checked": len(pins),
            "classes_covered": sorted(covered),
        }
        with self._lock:
            self._last_audit_t = t
            self.last_audit = report
        m = get_mem_metrics()
        for cls in OWNER_CLASSES:
            m.orphans.set(float(counts.get(cls, 0)), owner=cls)
        m.audits.inc(result=("orphans" if total else "clean"))
        return report

    def maybe_audit(self, now: float | None = None) -> dict | None:
        """Periodic-audit valve: runs :meth:`audit` when the configured
        interval elapsed since the last one. Returns the report or None."""
        if not self.enabled:
            return None
        t = now if now is not None else time.time()
        with self._lock:
            last = self._last_audit_t
            due = last is None or t - last >= self.audit_interval_s
        return self.audit(t) if due else None

    # -- publishing ------------------------------------------------------
    def republish(self) -> None:
        """Push current gauge state into the (possibly re-bound) metrics
        family — install_mem_metrics and test hooks."""
        m = get_mem_metrics()
        owners = self.owner_blocks()
        with self._lock:
            free, cached = self._device_free, self._device_cached
            ttx, posture = self.ttx_s, self.posture
            audit = self.last_audit
        for cls, n in owners.items():
            m.device_blocks.set(float(n), owner=cls)
        m.device_blocks.set(float(free), owner="free")
        m.device_blocks.set(float(cached), owner="cached")
        m.ttx.set(ttx)
        m.posture.set(float(POSTURES.index(posture)))
        if audit:
            for cls in OWNER_CLASSES:
                m.orphans.set(
                    float(audit["by_owner"].get(cls, 0)), owner=cls)
        self.tier_occupancy()

    def snapshot(self) -> dict:
        """Compact dict for stats publishing / bench artifacts."""
        owners = self.owner_blocks()
        with self._lock:
            out = {
                "enabled": self.enabled,
                "device_blocks": {
                    **owners,
                    "free": self._device_free,
                    "cached": self._device_cached,
                },
                "device_total_blocks": self._device_total,
                "churn": {f"{t}/{c}": n
                          for (t, c), n in sorted(self.churn_totals.items())},
                "alloc_blocks": dict(self.alloc_totals),
                "release_blocks": dict(self.release_totals),
                "ttx_seconds": round(self.ttx_s, 3),
                "posture": self.posture,
                "orphan_pins": (self.last_audit or {}).get("orphan_pins", 0),
                "last_audit_ts": (self.last_audit or {}).get("ts"),
            }
        out["tiers"] = self.tier_occupancy()
        return out

    def debug_info(self, limit: int = 64) -> dict:
        """The /debug/mem document: tier waterfall, top pin owners, churn
        trend, consumption rates, and the last audit report."""
        return {
            "enabled": self.enabled,
            "env": MEM_ENV,
            "totals": self.snapshot(),
            "top_owners": self.top_owners(),
            "churn_trend": self.churn_trend(limit),
            "rates": self.consumption_rates(),
            "ttx": {
                "seconds": round(self.ttx_s, 3),
                "posture": self.posture,
                "tight_s": self.ttx_tight_s,
                "critical_s": self.ttx_critical_s,
            },
            "last_audit": self.last_audit,
        }


_ledger: MemLedger | None = None
_ledger_lock = threading.Lock()


def get_mem_ledger() -> MemLedger:
    global _ledger
    with _ledger_lock:
        if _ledger is None:
            _ledger = MemLedger()
        return _ledger


def live_ids_of(*, streams: Iterable[str] = (), sessions: Iterable[str] = (),
                prefix_publish: Iterable[str] = (),
                stream_ckpt: Iterable[str] = (),
                staging: Iterable[str] = ()) -> dict[str, list[str]]:
    """Build one live-source payload with every owner class present —
    engines should report ALL classes they own pins for, even when empty
    (an omitted class is 'unauditable', not 'nothing live')."""
    return {
        "stream": [str(i) for i in streams],
        "session": [str(i) for i in sessions],
        "prefix_publish": [str(i) for i in prefix_publish],
        "stream_ckpt": [str(i) for i in stream_ckpt],
        "staging": [str(i) for i in staging],
    }
