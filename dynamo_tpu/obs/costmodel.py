"""Analytic roofline cost model: expected FLOPs and HBM bytes per kernel.

Every hot path the engine dispatches — paged attention (bf16 or int8 KV),
ring-attention prefill, and the dense matmuls around them — gets a closed-
form cost as a function of the call's shapes and dtypes. The step profiler
(obs/profiler.py) folds these into per-step MFU / HBM-bandwidth-utilization
counters; tools/perf_report.py renders them as the docs/PERF.md scoreboard;
bench.py uses them to *predict* device numbers when the probe can only
reach a CPU.

Conventions (stated once, relied on by tests/test_perf_obs.py):

* FLOPs count matmul work only (2·M·N·K per dense contraction), the
  standard MFU accounting — softmax/normalization vector work is noise
  against the MXU terms for every real shape.
* Attention is charged for whole KV blocks (``ceil(kv_len / bs) · bs``
  context positions): that is what the kernel DMAs and feeds the MXU —
  masked in-block positions still burn the hardware.
* HBM bytes count reads + writes of tensors that round-trip HBM under the
  serving access pattern: weights stream once per step, activations are
  assumed resident (XLA fuses them), KV blocks stream per step.
* int8 KV halves the KV payload, packed int4 quarters it (two nibbles per
  byte — 0.5 bytes/elem), and both add the per-(block, head) f32 scales;
  int8 weights count 1 byte/elem (models/quant.py streams them packed).
* split-K (``num_splits > 1``) adds the combine step's traffic: each split
  writes f32 partial state (acc rows of head_dim plus the lane-padded m
  and l columns, 128 each) that the jnp combine reads back, plus its
  elementwise merge FLOPs — so MFU/BW-util stay honest when the kernel
  trades extra HBM round-trips for grid parallelism.

This module is dependency-free on purpose — no jax import — so the bench
parent process can compute predicted device numbers without touching a
device runtime.
"""

from __future__ import annotations

from dataclasses import dataclass, replace

from dynamo_tpu.models.config import ModelConfig

__all__ = [
    "HardwareSpec",
    "KernelCost",
    "HW_SPECS",
    "KV_DTYPES",
    "hw_spec_for",
    "auto_num_splits",
    "paged_attention_cost",
    "ring_attention_cost",
    "dense_matmul_cost",
    "model_step_cost",
    "decode_step_cost",
    "prefill_cost",
    "total_cost",
    "analytic_param_bytes",
    "predicted_decode_perf",
    "mfu",
    "bw_util",
    "roofline_fraction",
    "PrefixCacheCost",
    "kv_block_wire_bytes",
    "prefix_cache_cost",
    "RingPrefillDecision",
    "chunked_prefill_seconds",
    "mixed_step_cost",
    "mixed_step_seconds",
    "auto_prefill_chunk",
    "QOS_ITL_SLO_SCALE",
    "ring_prefill_seconds",
    "ring_vs_chunked_prefill",
    "ring_prefill_break_even_tokens",
    "SessionRetentionCost",
    "session_retention_cost",
]


@dataclass(frozen=True)
class HardwareSpec:
    """Peak numbers one chip can theoretically sustain."""

    name: str
    peak_flops: float   # bf16 matmul FLOP/s
    hbm_bw: float       # HBM bytes/s

    @property
    def ridge_intensity(self) -> float:
        """FLOPs/byte where the roofline bends: below it you are
        bandwidth-bound, above it compute-bound."""
        return self.peak_flops / self.hbm_bw


# Keyed by a lowercase substring of jax's ``device_kind``; first match wins
# (dict order), so the more specific "tpu v5p" precedes "tpu v5". The HBM
# numbers intentionally match bench.py's historical roofline table. The CPU
# entry is a deliberately rough stand-in for the fallback bench — a few
# AVX cores and one DDR channel-ish.
HW_SPECS: dict[str, HardwareSpec] = {
    "tpu v6": HardwareSpec("tpu-v6e", 918e12, 1638e9),
    "tpu v5p": HardwareSpec("tpu-v5p", 459e12, 2765e9),
    "tpu v5": HardwareSpec("tpu-v5e", 197e12, 819e9),
    "tpu v4": HardwareSpec("tpu-v4", 275e12, 1228e9),
    "cpu": HardwareSpec("cpu", 200e9, 50e9),
}


def hw_spec_for(device_kind: str) -> HardwareSpec:
    """Resolve a jax ``device_kind`` string (e.g. "TPU v5 lite") to a spec;
    unknown kinds fall back to the conservative CPU entry."""
    kind = (device_kind or "cpu").lower()
    for key, spec in HW_SPECS.items():
        if key in kind:
            return spec
    return HW_SPECS["cpu"]


@dataclass(frozen=True)
class KernelCost:
    """Expected work of one kernel invocation (or a sum of them)."""

    name: str
    flops: float = 0.0
    hbm_bytes: float = 0.0
    ici_bytes: float = 0.0  # interconnect traffic (ring attention hops)

    def __add__(self, other: "KernelCost") -> "KernelCost":
        return KernelCost(
            name=self.name if self.name == other.name else "total",
            flops=self.flops + other.flops,
            hbm_bytes=self.hbm_bytes + other.hbm_bytes,
            ici_bytes=self.ici_bytes + other.ici_bytes,
        )

    def scaled(self, k: float) -> "KernelCost":
        return replace(self, flops=self.flops * k,
                       hbm_bytes=self.hbm_bytes * k,
                       ici_bytes=self.ici_bytes * k)

    @property
    def intensity(self) -> float:
        """Arithmetic intensity, FLOPs per HBM byte."""
        return self.flops / self.hbm_bytes if self.hbm_bytes else float("inf")

    def time_bound(self, hw: HardwareSpec) -> float:
        """Roofline-bound execution time: max of the compute and bandwidth
        lower bounds (perfect overlap assumed — this is the floor)."""
        return max(self.flops / hw.peak_flops if hw.peak_flops else 0.0,
                   self.hbm_bytes / hw.hbm_bw if hw.hbm_bw else 0.0)

    def bound(self, hw: HardwareSpec) -> str:
        return "compute" if self.intensity >= hw.ridge_intensity else "bandwidth"


#: every KV storage mode the cache supports (engine/cache.py), in scoreboard
#: order — perf_report rows and the bench kv_dtype sweep iterate this.
KV_DTYPES = ("bfloat16", "int8", "int4")


def _kv_itemsize(kv_dtype: str) -> float:
    """KV payload bytes per element: bf16 2, int8 1, packed int4 0.5."""
    if kv_dtype == "int8":
        return 1.0
    if kv_dtype == "int4":
        return 0.5
    return 2.0


def _ceil_div(a: int, b: int) -> int:
    return -(-a // b)


def auto_num_splits(num_blocks: int, *, batch: int, q_chunks: int = 1,
                    core_count: int = 8, min_blocks_per_split: int = 4,
                    max_splits: int = 16) -> int:
    """Split-K split count for one paged-attention call (deterministic,
    jax-free — callable at trace time from ops/paged_attention.py).

    Picks the smallest split count that fills ``core_count`` parallel grid
    streams given the ``batch × q_chunks`` programs that already exist,
    without shrinking any split below ``min_blocks_per_split`` context
    blocks (below that the combine's extra HBM round-trip outweighs the
    latency win — each split's partial state costs ~(D + 256) f32 per row
    against the ~BS·KH·D·itemsize bytes a block walk reads).
    """
    if num_blocks <= min_blocks_per_split:
        return 1
    streams = max(1, batch * q_chunks)
    want = _ceil_div(core_count, streams)
    cap = max(1, num_blocks // min_blocks_per_split)
    return max(1, min(want, cap, max_splits))


def paged_attention_cost(
    *,
    batch: int,
    q_tokens: int,
    num_heads: int,
    num_kv_heads: int,
    head_dim: int,
    kv_len: int,
    block_size: int,
    kv_dtype: str = "bfloat16",
    act_bytes: int = 2,
    num_splits: int = 1,
) -> KernelCost:
    """One paged-attention invocation (Pallas kernel and the dense-gather
    fallback execute the same matmul volume over the same KV blocks).

    FLOPs: the QK^T and PV matmuls — ``4 · B · T · H · D · S`` with S the
    block-rounded context. HBM: Q read + output write (activation dtype),
    plus both K and V caches streamed once per invocation; int8 caches move
    half the payload, packed int4 a quarter, both plus the per-(block,
    kv-head) f32 scales.

    ``num_splits > 1`` (split-K flash decode) adds the combine step:
    per split and per query row (B·T·H of them) the kernel writes f32
    partial state — acc (head_dim) plus the lane-padded m and l columns
    (128 each) — which the combine reads back, so
    ``combine_bytes = 8 · NS · B · T · H · (D + 256)`` (4-byte elems,
    write + read). The merge's elementwise work is charged as
    ``combine_flops = NS · B · T · H · (2 · D + 8)`` (scale + sum of acc,
    plus the exp/max/l bookkeeping per row).
    """
    nblk = _ceil_div(max(kv_len, 1), block_size)
    s = nblk * block_size
    flops = 4.0 * batch * q_tokens * num_heads * head_dim * s
    q_bytes = batch * q_tokens * num_heads * head_dim * act_bytes
    kv_block = block_size * num_kv_heads * head_dim * _kv_itemsize(kv_dtype)
    if kv_dtype in ("int8", "int4"):
        kv_block += num_kv_heads * 4  # per-(block, head) f32 scale
    kv_bytes = 2.0 * batch * nblk * kv_block
    out_bytes = q_bytes
    hbm = q_bytes + kv_bytes + out_bytes
    if num_splits > 1:
        rows = batch * q_tokens * num_heads
        hbm += 8.0 * num_splits * rows * (head_dim + 256)
        flops += num_splits * rows * (2.0 * head_dim + 8)
    return KernelCost("paged_attention", flops, hbm)


def ring_attention_cost(
    *,
    batch: int,
    seq_len: int,
    num_heads: int,
    num_kv_heads: int,
    head_dim: int,
    sp: int = 1,
    act_bytes: int = 2,
) -> KernelCost:
    """Sequence-parallel prefill self-attention (ops/ring_attention.py):
    full causal-masked matmul volume over the chunk, KV shards rotating
    ``sp - 1`` hops over the interconnect."""
    flops = 4.0 * batch * seq_len * seq_len * num_heads * head_dim
    qkv = batch * seq_len * (num_heads + 2 * num_kv_heads) * head_dim * act_bytes
    out = batch * seq_len * num_heads * head_dim * act_bytes
    kv_shard = 2.0 * batch * seq_len * num_kv_heads * head_dim * act_bytes / max(sp, 1)
    ici = kv_shard * max(sp - 1, 0)
    return KernelCost("ring_attention", flops, qkv + out, ici_bytes=ici)


def dense_matmul_cost(m: int, n: int, k: int, *, act_bytes: int = 2,
                      weight_bytes: int = 2, name: str = "matmul") -> KernelCost:
    """[M,K] @ [K,N]: 2MNK FLOPs; activations + streamed weight + output."""
    flops = 2.0 * m * n * k
    hbm = m * k * act_bytes + k * n * weight_bytes + m * n * act_bytes
    return KernelCost(name, flops, hbm)


def _weight_itemsize(quantization: str) -> int:
    return 1 if quantization == "int8" else 2


def model_step_cost(
    cfg: ModelConfig,
    *,
    tokens: int,
    logit_rows: int,
    attn_q_ctx: float,
    kv_blocks: float,
    block_size: int,
    kv_dtype: str = "bfloat16",
    quantization: str = "none",
    attn_num_splits: int = 1,
) -> dict[str, KernelCost]:
    """Aggregate cost of ONE dispatched engine step, by phase.

    Aggregated inputs let the profiler charge a ragged batch in O(rows)
    host work (engine hot path):

    * ``tokens`` — total query tokens across rows (N),
    * ``logit_rows`` — rows projected to logits and sampled,
    * ``attn_q_ctx`` — Σ over rows of ``t_row · S_row`` with S_row the
      block-rounded context (the attention matmul volume per head-dim),
    * ``kv_blocks`` — Σ over rows of ``ceil(kv_len / bs)`` (blocks DMA'd
      per layer).

    Phase keys mirror the profiler's hooks: embed, scatter, attention,
    proj, mlp, logits, sampling. All per-layer terms are multiplied by
    ``cfg.num_layers``.
    """
    h, L = cfg.hidden_size, cfg.num_layers
    wb = _weight_itemsize(quantization)
    ab = 2  # bf16 activations
    n = tokens

    embed = KernelCost("embed", 0.0, n * h * (wb + ab))

    # Attention projections: wq, wk, wv, wo per layer; weights stream once
    # per step regardless of batch (the bandwidth-roofline assumption the
    # bench normalizes against).
    proj_flops = 2.0 * n * h * (2 * cfg.q_size + 2 * cfg.kv_size) * L
    proj_w = (h * cfg.q_size * 2 + h * cfg.kv_size * 2) * wb * L
    proj_act = (n * h * 2 + n * (cfg.q_size + 2 * cfg.kv_size)) * ab * L
    proj = KernelCost("proj", proj_flops, proj_w + proj_act)

    # KV scatter: the step's new K/V rows written at cache dtype; a
    # quantized cache (int8/int4) additionally re-reads + re-writes each
    # touched block to requant committed rows against the merged scale
    # (llama._scatter_kv_quant).
    kvb = _kv_itemsize(kv_dtype)
    scatter_bytes = 2.0 * n * cfg.kv_size * kvb * L
    if kv_dtype in ("int8", "int4"):
        blocks_touched = _ceil_div(n, block_size) + 1
        scatter_bytes += (2.0 * 2.0 * blocks_touched * block_size
                          * cfg.kv_size * kvb * L)
    scatter = KernelCost("scatter", 0.0, scatter_bytes)

    # Rebuild from the aggregated volumes: flops scale with attn_q_ctx,
    # KV bytes with kv_blocks, Q/out bytes with tokens.
    kv_block_bytes = block_size * cfg.num_kv_heads * cfg.head_dim * kvb
    if kv_dtype in ("int8", "int4"):
        kv_block_bytes += cfg.num_kv_heads * 4
    attn_flops = 4.0 * cfg.num_heads * cfg.head_dim * attn_q_ctx * L
    attn_bytes = (2.0 * n * cfg.q_size * ab
                  + 2.0 * kv_blocks * kv_block_bytes) * L
    if attn_num_splits > 1:
        # Split-K combine (same per-row formula as paged_attention_cost):
        # each query row's per-split f32 partial state round-trips HBM.
        rows = n * cfg.num_heads
        attn_bytes += 8.0 * attn_num_splits * rows * (cfg.head_dim + 256) * L
        attn_flops += attn_num_splits * rows * (2.0 * cfg.head_dim + 8) * L
    attention = KernelCost("paged_attention", attn_flops, attn_bytes)

    if cfg.is_moe:
        m = cfg.moe_intermediate_size
        k = max(cfg.num_experts_per_tok, 1)
        mlp_flops = (2.0 * n * h * cfg.num_experts  # router
                     + 6.0 * n * h * m * k) * L
        experts_touched = min(n * k, cfg.num_experts)
        mlp_w = (h * cfg.num_experts + 3 * h * m * experts_touched) * wb * L
        if cfg.num_shared_experts:
            sm = m * cfg.num_shared_experts
            mlp_flops += 6.0 * n * h * sm * L
            mlp_w += 3 * h * sm * wb * L
        mlp_act = n * h * 2 * ab * L
    else:
        i = cfg.intermediate_size
        mlp_flops = 6.0 * n * h * i * L
        mlp_w = 3 * h * i * wb * L
        mlp_act = (n * h * 2 + n * i) * ab * L
    mlp = KernelCost("mlp", mlp_flops, mlp_w + mlp_act)

    logits = dense_matmul_cost(logit_rows, cfg.vocab_size, h,
                               weight_bytes=wb, name="logits")
    # Sampling: vector work over [rows, V] logits — no matmul FLOPs, one
    # f32 read of the logits (argmax / top-k masking).
    sampling = KernelCost("sampling", 0.0, logit_rows * cfg.vocab_size * 4.0)

    return {"embed": embed, "scatter": scatter, "attention": attention,
            "proj": proj, "mlp": mlp, "logits": logits, "sampling": sampling}


def total_cost(phases: dict[str, KernelCost]) -> KernelCost:
    out = KernelCost("total")
    for c in phases.values():
        out = out + c
    return out


def decode_step_cost(
    cfg: ModelConfig,
    *,
    batch: int,
    kv_len: int,
    block_size: int,
    kv_dtype: str = "bfloat16",
    quantization: str = "none",
    attn_num_splits: int = 1,
) -> dict[str, KernelCost]:
    """Uniform-batch decode step (every row: 1 query token, same context) —
    the bench / perf_report / prediction entry point."""
    nblk = _ceil_div(max(kv_len, 1), block_size)
    return model_step_cost(
        cfg, tokens=batch, logit_rows=batch,
        attn_q_ctx=float(batch * nblk * block_size),
        kv_blocks=float(batch * nblk), block_size=block_size,
        kv_dtype=kv_dtype, quantization=quantization,
        attn_num_splits=attn_num_splits)


def prefill_cost(
    cfg: ModelConfig,
    *,
    batch: int,
    chunk: int,
    kv_len: int,
    block_size: int,
    kv_dtype: str = "bfloat16",
    quantization: str = "none",
) -> dict[str, KernelCost]:
    """Uniform prefill chunk: ``chunk`` query tokens per row attending a
    ``kv_len`` context (chunk end for fresh prompts)."""
    nblk = _ceil_div(max(kv_len, 1), block_size)
    return model_step_cost(
        cfg, tokens=batch * chunk, logit_rows=batch,
        attn_q_ctx=float(batch * chunk * nblk * block_size),
        kv_blocks=float(batch * nblk), block_size=block_size,
        kv_dtype=kv_dtype, quantization=quantization)


def analytic_param_bytes(cfg: ModelConfig, quantization: str = "none") -> int:
    """Model parameter bytes from shapes alone (mirrors models/llama.py
    init_params structure; matmul weights at the quantized itemsize, norms
    at bf16). The runtime twin is models/quant.py param_bytes(params)."""
    h, L = cfg.hidden_size, cfg.num_layers
    wb = _weight_itemsize(quantization)
    matmul = h * cfg.q_size * 2 + h * cfg.kv_size * 2  # wq wk wv wo
    norms = 2 * h
    if cfg.is_moe:
        m = cfg.moe_intermediate_size
        matmul += h * cfg.num_experts + cfg.num_experts * 3 * h * m
        if cfg.num_shared_experts:
            matmul += 3 * h * m * cfg.num_shared_experts
    else:
        matmul += 3 * h * cfg.intermediate_size
    total = L * (matmul * wb + norms * 2)
    total += cfg.vocab_size * h * wb   # embed
    total += h * 2                      # final norm
    if not cfg.tie_word_embeddings:
        total += h * cfg.vocab_size * wb
    return total


def predicted_decode_perf(
    cfg: ModelConfig,
    hw: HardwareSpec,
    *,
    batch: int,
    kv_len: int,
    block_size: int = 16,
    kv_dtype: str = "bfloat16",
    quantization: str = "none",
    attn_num_splits: int = 1,
) -> dict:
    """Roofline prediction for a decode config on ``hw`` — what bench.py
    attaches as the device forecast when only the CPU fallback could run."""
    phases = decode_step_cost(cfg, batch=batch, kv_len=kv_len,
                              block_size=block_size, kv_dtype=kv_dtype,
                              quantization=quantization,
                              attn_num_splits=attn_num_splits)
    cost = total_cost(phases)
    step_s = cost.time_bound(hw)
    tok_s = batch / step_s if step_s > 0 else 0.0
    return {
        "device": hw.name,
        "tok_s": round(tok_s, 1),
        "step_flops": cost.flops,
        "step_hbm_bytes": cost.hbm_bytes,
        "arithmetic_intensity": round(cost.intensity, 2),
        "bound": cost.bound(hw),
        "mfu_at_roofline": round(mfu(cost.flops, step_s, hw), 4),
        "bw_util_at_roofline": round(bw_util(cost.hbm_bytes, step_s, hw), 4),
    }


# ---------------------------------------------------------------------------
# Fleet-wide prefix cache: route-vs-pull break-even
# ---------------------------------------------------------------------------

#: Effective per-stream DCN bandwidth for pod-to-pod KV block pulls. One TCP
#: stream over the data-center network sustains far less than the NIC line
#: rate; this is the conservative planning number the router arbitrates
#: against (overridable per deployment via KvRouterConfig).
DCN_BYTES_PER_S = 12.5e9

#: Achieved MFU assumed for recompute-prefill when converting FLOPs to
#: seconds. Prefill runs compute-bound near the roofline on real batches;
#: 0.4 matches the scoreboard's achieved numbers rather than the peak.
PREFILL_MFU = 0.4


def kv_block_wire_bytes(*, num_layers: int, block_size: int,
                        num_kv_heads: int, head_dim: int,
                        kv_dtype: str = "bfloat16") -> float:
    """Bytes one KV block occupies on the wire in kvbm's host format
    (kvbm/transfer.py): K and V payload at the cache itemsize, plus the
    per-(layer, kv-head) f32 scale sidecar for quantized caches — the same
    accounting paged_attention_cost charges for the HBM stream."""
    elems = 2.0 * num_layers * block_size * num_kv_heads * head_dim
    nbytes = elems * _kv_itemsize(kv_dtype)
    if kv_dtype in ("int8", "int4"):
        nbytes += 2.0 * num_layers * num_kv_heads * 4
    return nbytes


@dataclass(frozen=True)
class PrefixCacheCost:
    """Route-vs-pull arbiter inputs for the fleet-wide prefix cache.

    Two ways to satisfy a shared prefix on a worker that doesn't hold it:

    * **recompute** — run prefill over the prefix tokens:
      ``tokens · flops_per_token / (peak_flops · prefill_mfu)`` seconds;
    * **pull** — fetch the packed KV blocks from the remote tier:
      ``overhead + blocks · wire_bytes_per_block / dcn_bytes_per_s``.

    Everything is plain floats so the router can arbitrate without a model
    runtime; build one from a ModelConfig with :func:`prefix_cache_cost`.
    """

    flops_per_token: float
    wire_bytes_per_block: float
    block_size: int
    peak_flops: float
    prefill_mfu: float = PREFILL_MFU
    dcn_bytes_per_s: float = DCN_BYTES_PER_S
    #: fixed per-import cost: remote-tier RTTs + the device scatter dispatch.
    import_overhead_s: float = 2e-3

    @property
    def seconds_per_token(self) -> float:
        eff = self.peak_flops * self.prefill_mfu
        return self.flops_per_token / eff if eff > 0 else 0.0

    def recompute_seconds(self, tokens: float) -> float:
        return max(tokens, 0.0) * self.seconds_per_token

    def pull_seconds(self, blocks: int) -> float:
        if blocks <= 0:
            return 0.0
        return (self.import_overhead_s
                + blocks * self.wire_bytes_per_block
                / max(self.dcn_bytes_per_s, 1.0))

    def break_even_blocks(self) -> float:
        """Prefix depth (blocks) above which pulling beats recomputing on an
        otherwise idle worker — the docs/PERF.md formula:
        ``pull_s(n) < recompute_s(n · bs)``."""
        per_block_pull = self.wire_bytes_per_block / max(self.dcn_bytes_per_s, 1.0)
        per_block_recompute = self.block_size * self.seconds_per_token
        gain = per_block_recompute - per_block_pull
        if gain <= 0:
            return float("inf")
        return self.import_overhead_s / gain


def prefix_cache_cost(
    cfg: ModelConfig,
    hw: HardwareSpec,
    *,
    block_size: int,
    kv_dtype: str = "bfloat16",
    quantization: str = "none",
    rep_prefix_tokens: int = 1024,
    dcn_bytes_per_s: float = DCN_BYTES_PER_S,
    prefill_mfu: float = PREFILL_MFU,
) -> PrefixCacheCost:
    """Linearized PrefixCacheCost for a model/device pair. Per-token prefill
    FLOPs are taken at a representative shared-prefix length (the attention
    term grows with context, so this slightly undercharges very long
    prefixes — i.e. the arbiter errs toward recompute, the safe side)."""
    n = max(rep_prefix_tokens, block_size)
    phases = prefill_cost(cfg, batch=1, chunk=n, kv_len=n,
                          block_size=block_size, kv_dtype=kv_dtype,
                          quantization=quantization)
    flops_per_token = total_cost(phases).flops / n
    return PrefixCacheCost(
        flops_per_token=flops_per_token,
        wire_bytes_per_block=kv_block_wire_bytes(
            num_layers=cfg.num_layers, block_size=block_size,
            num_kv_heads=cfg.num_kv_heads, head_dim=cfg.head_dim,
            kv_dtype=kv_dtype),
        block_size=block_size,
        peak_flops=hw.peak_flops,
        prefill_mfu=prefill_mfu,
        dcn_bytes_per_s=dcn_bytes_per_s,
    )


# ---------------------------------------------------------------------------
# Context-parallel ring prefill: ring-vs-chunked break-even
# ---------------------------------------------------------------------------

#: Per-hop ICI bandwidth one rotating KV shard sustains during ring
#: attention (a v5e 1D ring link, conservative). The ring overlaps the hop
#: with the block matmuls, so this only binds when the shard is large.
ICI_BYTES_PER_S = 4.5e10

#: Fixed cost of taking the ring path for one prompt: the whole-prompt
#: dispatch (one bucketed step fn at the full sequence length), the
#: seq-axis scatter of the prompt, and the paged-cache writeback gather.
RING_PREFILL_OVERHEAD_S = 1e-3


@dataclass(frozen=True)
class RingPrefillDecision:
    """Priced comparison of the two ways an sp>1 engine can prefill one
    prompt: ``ring`` (one seq-sharded whole-prompt chunk over ICI) vs
    ``chunked`` (the sequential prefill_chunk walk with the seq axis
    idle). ``use_ring`` is the auto-select verdict the engine applies when
    ``ring_prefill_threshold == 0``."""

    prompt_tokens: int
    sp: int
    ring_seconds: float
    chunked_seconds: float

    @property
    def use_ring(self) -> bool:
        return self.ring_seconds < self.chunked_seconds

    @property
    def speedup(self) -> float:
        return (self.chunked_seconds / self.ring_seconds
                if self.ring_seconds > 0 else float("inf"))


def chunked_prefill_seconds(
    cfg: ModelConfig,
    hw: HardwareSpec,
    *,
    prompt_tokens: int,
    chunk: int,
    block_size: int,
    kv_dtype: str = "bfloat16",
    quantization: str = "none",
    prefill_mfu: float = PREFILL_MFU,
) -> float:
    """Sequential chunked prefill of one prompt with the mesh's seq axis
    idle (every device repeats the same chunk): total FLOPs over the chunk
    walk at achieved prefill MFU on ONE device's peak."""
    eff = hw.peak_flops * prefill_mfu
    if eff <= 0 or prompt_tokens <= 0:
        return 0.0
    chunk = max(chunk, 1)
    flops = 0.0
    done = 0
    while done < prompt_tokens:
        c = min(chunk, prompt_tokens - done)
        phases = prefill_cost(cfg, batch=1, chunk=c, kv_len=done + c,
                              block_size=block_size, kv_dtype=kv_dtype,
                              quantization=quantization)
        flops += total_cost(phases).flops
        done += c
    return flops / eff


# ---------------------------------------------------------------------------
# Unified ragged mixed-phase steps: decode + prefill chunk in one launch
# ---------------------------------------------------------------------------

#: Per-QoS-class scale applied to the decode-ITL SLO budget that
#: auto_prefill_chunk sizes against — the same 1x/2x/4x degradation ladder
#: the stream-checkpoint cadence uses. Interactive streams tolerate the
#: smallest prefill-induced ITL inflation, batch the largest (so batch
#: traffic prefills in bigger, more efficient chunks).
QOS_ITL_SLO_SCALE = {"interactive": 1.0, "standard": 2.0, "batch": 4.0}


def mixed_step_cost(
    cfg: ModelConfig,
    *,
    decode_rows: int,
    decode_kv_len: int,
    chunk: int,
    chunk_kv_len: int,
    block_size: int,
    kv_dtype: str = "bfloat16",
    quantization: str = "none",
    attn_num_splits: int = 1,
) -> dict[str, KernelCost]:
    """One unified ragged mixed step: ``decode_rows`` decode rows (one live
    query token attending ``decode_kv_len`` context each) packed with one
    prefill-chunk row (``chunk`` live tokens attending ``chunk_kv_len``
    context — the chunk end for fresh prompts) in a SINGLE program. The
    ragged grid early-exits padded positions, so the live volume is exactly
    the sum of the two phases' volumes; the aggregate inputs below are the
    hand-checkable expansion (tests/test_perf_obs.py)."""
    nblk_d = _ceil_div(max(decode_kv_len, 1), block_size)
    nblk_p = _ceil_div(max(chunk_kv_len, 1), block_size)
    return model_step_cost(
        cfg,
        tokens=decode_rows + chunk,
        logit_rows=decode_rows + (1 if chunk > 0 else 0),
        attn_q_ctx=float(decode_rows * nblk_d * block_size
                         + chunk * nblk_p * block_size),
        kv_blocks=float(decode_rows * nblk_d + (nblk_p if chunk > 0 else 0)),
        block_size=block_size, kv_dtype=kv_dtype,
        quantization=quantization, attn_num_splits=attn_num_splits)


def mixed_step_seconds(
    cfg: ModelConfig,
    hw: HardwareSpec,
    *,
    decode_rows: int,
    decode_kv_len: int,
    chunk: int,
    chunk_kv_len: int,
    block_size: int,
    kv_dtype: str = "bfloat16",
    quantization: str = "none",
    attn_num_splits: int = 1,
    prefill_mfu: float = PREFILL_MFU,
) -> float:
    """Predicted wall time of one unified mixed step — decode ITL when a
    chunk rides along. Compute is derated to achieved prefill MFU (the
    chunk's matmuls dominate the FLOP side, consistent with
    chunked_prefill_seconds); bandwidth stays at peak (the decode side is
    a streaming KV read, consistent with the decode roofline). chunk=0
    prices the pure-decode step, so ``mixed - pure`` is the chunk's
    marginal ITL inflation the HOL attribution charges."""
    cost = total_cost(mixed_step_cost(
        cfg, decode_rows=decode_rows, decode_kv_len=decode_kv_len,
        chunk=chunk, chunk_kv_len=chunk_kv_len, block_size=block_size,
        kv_dtype=kv_dtype, quantization=quantization,
        attn_num_splits=attn_num_splits))
    eff = hw.peak_flops * prefill_mfu
    return max(cost.flops / eff if eff > 0 else 0.0,
               cost.hbm_bytes / hw.hbm_bw if hw.hbm_bw > 0 else 0.0)


def auto_prefill_chunk(
    cfg: ModelConfig,
    hw: HardwareSpec,
    *,
    itl_slo_s: float,
    decode_rows: int,
    decode_kv_len: int,
    block_size: int,
    max_chunk: int,
    kv_dtype: str = "bfloat16",
    quantization: str = "none",
    qos_class: str = "interactive",
    min_chunk: int = 16,
) -> int:
    """SLO-driven chunk sizing: the largest power-of-two chunk (the compile
    ledger's 16-doubling t ladder, so auto never mints new buckets) whose
    predicted mixed-step time stays inside the decode-ITL SLO budget for
    ``qos_class`` (budget × QOS_ITL_SLO_SCALE). Returns ``min_chunk`` even
    when the SLO is already blown by the pure-decode step — prefill must
    keep making forward progress."""
    budget = itl_slo_s * QOS_ITL_SLO_SCALE.get(qos_class, 1.0)
    best = min_chunk
    chunk = min_chunk
    while chunk <= max(max_chunk, min_chunk):
        predicted = mixed_step_seconds(
            cfg, hw, decode_rows=decode_rows, decode_kv_len=decode_kv_len,
            chunk=chunk, chunk_kv_len=chunk, block_size=block_size,
            kv_dtype=kv_dtype, quantization=quantization)
        if predicted <= budget:
            best = chunk
        chunk *= 2
    return min(best, max(max_chunk, min_chunk))


def ring_prefill_seconds(
    cfg: ModelConfig,
    hw: HardwareSpec,
    *,
    prompt_tokens: int,
    sp: int,
    block_size: int,
    kv_dtype: str = "bfloat16",
    quantization: str = "none",
    prefill_mfu: float = PREFILL_MFU,
    ici_bytes_per_s: float = ICI_BYTES_PER_S,
) -> float:
    """One seq-sharded whole-prompt ring prefill: the same matmul volume
    split ``sp`` ways, overlapped with the per-layer KV shard rotation over
    ICI, plus the fixed dispatch/writeback overhead."""
    eff = hw.peak_flops * prefill_mfu
    if eff <= 0 or prompt_tokens <= 0:
        return 0.0
    phases = prefill_cost(cfg, batch=1, chunk=prompt_tokens,
                          kv_len=prompt_tokens, block_size=block_size,
                          kv_dtype=kv_dtype, quantization=quantization)
    compute_s = total_cost(phases).flops / max(sp, 1) / eff
    ring = ring_attention_cost(
        batch=1, seq_len=prompt_tokens, num_heads=cfg.num_heads,
        num_kv_heads=cfg.num_kv_heads, head_dim=cfg.head_dim, sp=sp)
    ici_s = ring.ici_bytes * cfg.num_layers / max(ici_bytes_per_s, 1.0)
    return RING_PREFILL_OVERHEAD_S + max(compute_s, ici_s)


def ring_vs_chunked_prefill(
    cfg: ModelConfig,
    hw: HardwareSpec,
    *,
    prompt_tokens: int,
    sp: int,
    chunk: int,
    block_size: int,
    kv_dtype: str = "bfloat16",
    quantization: str = "none",
) -> RingPrefillDecision:
    """Price both prefill modes for one prompt; the engine's auto-select
    and tools/perf_report.py both read this one verdict."""
    return RingPrefillDecision(
        prompt_tokens=prompt_tokens,
        sp=sp,
        ring_seconds=ring_prefill_seconds(
            cfg, hw, prompt_tokens=prompt_tokens, sp=sp,
            block_size=block_size, kv_dtype=kv_dtype,
            quantization=quantization),
        chunked_seconds=chunked_prefill_seconds(
            cfg, hw, prompt_tokens=prompt_tokens, chunk=chunk,
            block_size=block_size, kv_dtype=kv_dtype,
            quantization=quantization),
    )


def ring_prefill_break_even_tokens(
    cfg: ModelConfig,
    hw: HardwareSpec,
    *,
    sp: int,
    chunk: int,
    block_size: int,
    kv_dtype: str = "bfloat16",
    quantization: str = "none",
    max_tokens: int = 1 << 20,
) -> int:
    """Smallest block-aligned prompt length where the ring path beats the
    chunked walk (the engine's auto threshold). Returns ``max_tokens`` when
    ring never wins in range (sp=1, or overhead dominates throughout) —
    callers treat that as "effectively off"."""
    if sp <= 1:
        return max_tokens

    def _ring_wins(tokens: int) -> bool:
        return ring_vs_chunked_prefill(
            cfg, hw, prompt_tokens=tokens, sp=sp, chunk=chunk,
            block_size=block_size, kv_dtype=kv_dtype,
            quantization=quantization).use_ring

    # Doubling probe for the first winning length, then bisect down to
    # block granularity (the verdict is monotone in tokens: the ring's
    # fixed overhead amortizes while its compute advantage grows).
    hi = block_size
    while hi < max_tokens and not _ring_wins(hi):
        hi *= 2
    if hi >= max_tokens:
        return max_tokens
    lo = hi // 2
    while hi - lo > block_size:
        mid = (lo + hi) // 2 // block_size * block_size
        if _ring_wins(mid):
            hi = mid
        else:
            lo = mid
    return hi


# ---------------------------------------------------------------------------
# Session-sticky KV retention: retained bytes vs re-prefill seconds
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class SessionRetentionCost:
    """The session-retention trade: holding one conversation's KV costs
    ``bytes_per_token`` of cache capacity per retained context token and
    buys back ``seconds_per_token`` of turn-N+1 prefill per token NOT
    recomputed. ``seconds_per_gb`` is the docs/PERF.md break-even figure:
    prefill seconds one retained gigabyte saves at achieved MFU."""

    bytes_per_token: float
    seconds_per_token: float

    def retained_bytes(self, tokens: float) -> float:
        return max(tokens, 0.0) * self.bytes_per_token

    def recompute_seconds(self, tokens: float) -> float:
        return max(tokens, 0.0) * self.seconds_per_token

    @property
    def seconds_per_gb(self) -> float:
        if self.bytes_per_token <= 0:
            return 0.0
        return self.seconds_per_token * (1 << 30) / self.bytes_per_token


def session_retention_cost(
    cfg: ModelConfig,
    hw: HardwareSpec,
    *,
    block_size: int,
    kv_dtype: str = "bfloat16",
    quantization: str = "none",
    rep_context_tokens: int = 1024,
    prefill_mfu: float = PREFILL_MFU,
) -> SessionRetentionCost:
    """Linearized retention trade for a model/device pair: per-token KV
    bytes from the cache layout (kv_block_wire_bytes over a block) and
    per-token prefill seconds at a representative context (same
    linearization — and the same err-toward-recompute bias — as
    prefix_cache_cost)."""
    per_block = kv_block_wire_bytes(
        num_layers=cfg.num_layers, block_size=block_size,
        num_kv_heads=cfg.num_kv_heads, head_dim=cfg.head_dim,
        kv_dtype=kv_dtype)
    n = max(rep_context_tokens, block_size)
    phases = prefill_cost(cfg, batch=1, chunk=n, kv_len=n,
                          block_size=block_size, kv_dtype=kv_dtype,
                          quantization=quantization)
    eff = hw.peak_flops * prefill_mfu
    return SessionRetentionCost(
        bytes_per_token=per_block / block_size,
        seconds_per_token=(total_cost(phases).flops / n / eff
                           if eff > 0 else 0.0),
    )


def mfu(flops: float, wall_s: float, hw: HardwareSpec) -> float:
    """Model-FLOPs utilization: achieved matmul FLOP/s over peak."""
    if wall_s <= 0 or hw.peak_flops <= 0:
        return 0.0
    return flops / wall_s / hw.peak_flops


def bw_util(hbm_bytes: float, wall_s: float, hw: HardwareSpec) -> float:
    """Achieved HBM bytes/s over peak bandwidth."""
    if wall_s <= 0 or hw.hbm_bw <= 0:
        return 0.0
    return hbm_bytes / wall_s / hw.hbm_bw


def roofline_fraction(cost: KernelCost, wall_s: float, hw: HardwareSpec) -> float:
    """Achieved fraction of the roofline floor: bound-time / wall (1.0 =
    running exactly at the roofline; > 1 means the model undercounts)."""
    if wall_s <= 0:
        return 0.0
    return cost.time_bound(hw) / wall_s
