"""Observability: always-on request tracing, flight recorder, and the
span-to-metrics bridge.

Fills the role of the reference's tracing layer
(reference: lib/runtime/src/logging.rs traceparent propagation plus the
per-phase serving metrics the SLA planner consumes): a dependency-free
Dapper-style tracer keyed off ``TraceContext``, a bounded in-process
flight recorder dumpable as JSONL or Chrome trace-event JSON
(Perfetto-loadable), and a bridge deriving ``dynamo_request_*``
Prometheus histograms from closed spans so operators get per-phase
aggregates without an external trace backend.
"""

from dynamo_tpu.obs.bridge import SpanMetricsBridge
from dynamo_tpu.obs.fleet import (
    DEFAULT_SLO_SPECS,
    EwmaAnomaly,
    FleetAggregator,
    SloEngine,
    SloSpec,
    parse_slo_specs,
)
from dynamo_tpu.obs.costmodel import (
    HardwareSpec,
    KernelCost,
    hw_spec_for,
)
from dynamo_tpu.obs.profiler import (
    PerfMetrics,
    StepPerfProfiler,
    capture_phases,
    get_perf_metrics,
    install_perf_metrics,
    phase,
)
from dynamo_tpu.obs.recorder import FlightRecorder, StepProfiler
from dynamo_tpu.obs.tracer import (
    TRACE_KEY,
    Span,
    Tracer,
    get_tracer,
    trace_context_of,
)

__all__ = [
    "DEFAULT_SLO_SPECS",
    "TRACE_KEY",
    "EwmaAnomaly",
    "FleetAggregator",
    "FlightRecorder",
    "SloEngine",
    "SloSpec",
    "parse_slo_specs",
    "HardwareSpec",
    "KernelCost",
    "PerfMetrics",
    "Span",
    "SpanMetricsBridge",
    "StepPerfProfiler",
    "StepProfiler",
    "Tracer",
    "capture_phases",
    "get_perf_metrics",
    "get_tracer",
    "hw_spec_for",
    "install_perf_metrics",
    "phase",
    "trace_context_of",
]
