"""Observability: always-on request tracing, flight recorder, and the
span-to-metrics bridge.

Fills the role of the reference's tracing layer
(reference: lib/runtime/src/logging.rs traceparent propagation plus the
per-phase serving metrics the SLA planner consumes): a dependency-free
Dapper-style tracer keyed off ``TraceContext``, a bounded in-process
flight recorder dumpable as JSONL or Chrome trace-event JSON
(Perfetto-loadable), and a bridge deriving ``dynamo_request_*``
Prometheus histograms from closed spans so operators get per-phase
aggregates without an external trace backend.
"""

from dynamo_tpu.obs.bridge import SpanMetricsBridge
from dynamo_tpu.obs.recorder import FlightRecorder, StepProfiler
from dynamo_tpu.obs.tracer import (
    TRACE_KEY,
    Span,
    Tracer,
    get_tracer,
    trace_context_of,
)

__all__ = [
    "TRACE_KEY",
    "FlightRecorder",
    "Span",
    "SpanMetricsBridge",
    "StepProfiler",
    "Tracer",
    "get_tracer",
    "trace_context_of",
]
