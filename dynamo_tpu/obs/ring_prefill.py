"""Prometheus family for the context-parallel ring prefill mode.

The engine's sp>1 prefill path (ops/ring_attention.py promoted to a
serving mode by engine/engine.py) is cost-model arbitrated: prompts past
the ring-vs-chunked break-even (obs/costmodel.py
``ring_prefill_break_even_tokens``) prefill as ONE seq-sharded ring chunk;
shorter prompts ride the normal chunked sequential path even on an sp>1
mesh. This family makes the arbitration visible on /metrics: how often
each side won and how many prompt tokens the ring path actually carried.

Same singleton/bind pattern as kvbm/metrics.py; names are cross-checked
by tools/lint_metrics.py RING_PREFILL_METRICS.
"""

from __future__ import annotations

from dynamo_tpu.utils.metrics import MetricsRegistry


class RingPrefillMetrics:
    """The dynamo_ring_prefill_* family (names cross-checked by
    tools/lint_metrics.py RING_PREFILL_METRICS)."""

    def __init__(self, registry: MetricsRegistry | None = None):
        self.bind(registry or MetricsRegistry())

    def bind(self, registry: MetricsRegistry) -> None:
        self.registry = registry
        self.invocations = registry.counter(
            "ring_prefill_invocations",
            "Prefill dispatches that ran the seq-sharded ring path")
        self.tokens = registry.counter(
            "ring_prefill_tokens",
            "Prompt tokens prefilled through ring attention")
        self.bypassed = registry.counter(
            "ring_prefill_bypassed",
            "Prefill dispatches on an sp>1 mesh that stayed on the "
            "chunked sequential path (below threshold or shape guard)")
        self.threshold_tokens = registry.gauge(
            "ring_prefill_threshold_tokens",
            "Engaged ring-vs-chunked token threshold (explicit knob or "
            "cost-model break-even)")


_metrics: RingPrefillMetrics | None = None


def get_ring_prefill_metrics() -> RingPrefillMetrics:
    global _metrics
    if _metrics is None:
        _metrics = RingPrefillMetrics()
    return _metrics


def install_ring_prefill_metrics(registry: MetricsRegistry) -> RingPrefillMetrics:
    """Re-home the singleton into a runtime registry (worker /metrics)."""
    m = get_ring_prefill_metrics()
    m.bind(registry)
    return m
