"""Scheduling ledger: per-step goodput, padding waste, HOL-stall attribution.

The compile ledger makes XLA stalls observable; this module does the same
for the *scheduler's* decisions. Every dispatched engine step files one
``SchedStepRecord``:

* **Goodput** — the fraction of scheduled (bucket-padded) FLOPs that were
  live tokens. The engine dispatches static-shape programs
  (``_bucket``/``_pow2_bucket`` geometry, engine/engine.py dispatch()); the
  gap between the ragged batch it planned and the padded batch it ran is
  pure waste, priced through the same analytic cost model the perf
  profiler uses (obs/costmodel.py) and exported as
  ``dynamo_sched_goodput_fraction`` plus cumulative padding FLOPs/bytes.
* **HOL interference** — when a prefill chunk shares a step with decode
  streams, every decode row's token delivery is delayed by the whole
  step's wall (outputs materialize only at finalize). Each victim stream
  accrues an ``engine.hol_stall`` span in its OWN trace carrying the
  culprit request id, aggregated into
  ``dynamo_sched_hol_stall_seconds{qos_class}`` and a per-step
  interference index (stalled-decode-row-seconds).
* **Admission & preemption causes** — why waiting seqs could not admit
  (no free blocks vs. batch full vs. WDRR lane gate) and how many tokens
  preemption forces back through prefill
  (``dynamo_sched_preempt_recompute_tokens_total{cause}``).

Disabled mode (``DYN_SCHED_LEDGER=0``) flips ``SchedLedger.enabled``; the
engine and scheduler gate on that flag BEFORE building any step info, so a
disabled ledger adds zero per-step work — the same contract as the
profiler's ``DYN_PERF_PROFILE`` gate.

The ``dynamo_sched_*`` family (lint-checked by tools/lint_metrics.py
SCHED_METRICS) installs on workers via ``install_sched_metrics`` and is
mirrored device-free by the mocker, so fleet scenarios exercise the
``decode_stall`` SLI without a TPU. ``/debug/sched`` (frontend + worker
status server) serves ``debug_info()``: the recent-step ring, the goodput
trend, and the top stall culprits.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from dataclasses import dataclass, field

from dynamo_tpu.obs.compile_ledger import _bucket, _pow2_bucket
from dynamo_tpu.utils.metrics import MetricsRegistry

SCHED_ENV = "DYN_SCHED_LEDGER"

#: Admission-block causes (engine/scheduler.py _try_admit / plan):
#: ``no_free_blocks`` — the pool (or its watermark) refused the prompt;
#: ``batch_full`` — no sampling slot / running at max_batch_size;
#: ``wdrr_gate`` — the WDRR-committed head lane blocks while other
#: non-empty lanes wait behind the commitment.
BLOCK_CAUSES = ("no_free_blocks", "batch_full", "wdrr_gate")

#: Preemption causes: ``blocks`` — recompute preemption reclaiming KV
#: blocks for a growing decode stream; ``qos`` — the reclaimed victim
#: belonged to a different QoS class than the stream that grew.
PREEMPT_CAUSES = ("blocks", "qos")

#: Victim stalls span one fused decode window (~ms) to a full 32k-prompt
#: prefill chunk on CPU fallback. (MetricsRegistry appends +Inf.)
_STALL_SECONDS_BUCKETS = (
    0.001, 0.005, 0.02, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 10.0,
)


def sched_enabled(default: bool = True) -> bool:
    """The module-level gate: DYN_SCHED_LEDGER=0 disables all per-step
    scheduling accounting (record paths return before any work)."""
    val = os.environ.get(SCHED_ENV, "")
    if val == "":
        return default
    return val not in ("0", "false", "no", "off")


# ---------------------------------------------------------------------------
# Prometheus family
# ---------------------------------------------------------------------------

class SchedMetrics:
    """The dynamo_sched_* family (names cross-checked by
    tools/lint_metrics.py SCHED_METRICS)."""

    def __init__(self, registry: MetricsRegistry | None = None):
        self.bind(registry or MetricsRegistry())

    def bind(self, registry: MetricsRegistry) -> None:
        self.registry = registry
        self.goodput = registry.gauge(
            "sched_goodput_fraction",
            "Live-token FLOPs over scheduled (bucket-padded) FLOPs for the "
            "last engine step (1.0 = zero padding waste)")
        self.budget_util = registry.gauge(
            "sched_token_budget_utilization",
            "Fraction of max_tokens_per_step the last step's planned rows "
            "actually used (decode window rows + prefill chunk tokens)")
        self.queue_depth = registry.gauge(
            "sched_queue_depth",
            "Waiting seqs per QoS class at the last step's record point "
            "(WDRR lane depths, qos_class label)")
        self.steps = registry.counter(
            "sched_steps_total",
            "Engine steps recorded by the scheduling ledger, by batch kind "
            "(prefill|decode|window|verify|guided|mixed; a multi-batch "
            "step counts once per kind it dispatched)")
        self.prefill_chunk = registry.gauge(
            "sched_prefill_chunk_tokens",
            "Effective prefill chunk size in tokens per QoS class "
            "(SLO-driven per-class when --prefill-chunk 0 auto mode is "
            "on, uniform otherwise), qos_class label")
        self.admission_blocked = registry.counter(
            "sched_admission_blocked_total",
            "Admission attempts blocked, by cause (no_free_blocks|"
            "batch_full|wdrr_gate)")
        self.preempt_recompute = registry.counter(
            "sched_preempt_recompute_tokens_total",
            "Tokens whose KV a preemption discarded and prefill must "
            "recompute, by cause (blocks|qos)")
        self.padding_flops = registry.counter(
            "sched_padding_flops_total",
            "Cumulative analytic FLOPs spent on bucket padding rather than "
            "live tokens (scheduled minus live)")
        self.padding_bytes = registry.counter(
            "sched_padding_hbm_bytes_total",
            "Cumulative analytic HBM bytes moved for bucket padding rather "
            "than live tokens (scheduled minus live)")
        self.hol_stall = registry.histogram(
            "sched_hol_stall_seconds",
            "Per-victim head-of-line stall: wall seconds one decode-ready "
            "stream's token delivery waited on a step that carried a "
            "prefill chunk, by qos_class",
            buckets=_STALL_SECONDS_BUCKETS)
        self.interference = registry.counter(
            "sched_interference_row_seconds_total",
            "Interference index: cumulative stalled-decode-row-seconds "
            "(per step, victims x stall wall)")


_metrics: SchedMetrics | None = None


def get_sched_metrics() -> SchedMetrics:
    global _metrics
    if _metrics is None:
        _metrics = SchedMetrics()
    return _metrics


def install_sched_metrics(registry: MetricsRegistry) -> SchedMetrics:
    """Re-home the singleton's metrics into ``registry`` (the worker's
    runtime registry) so the family is exposed on /metrics. Gauges are
    republished from the live ledger so an install that lands AFTER the
    engine recorded steps still exposes the current goodput; counters stay
    monotonic and are not replayed."""
    m = get_sched_metrics()
    m.bind(registry)
    led = get_sched_ledger()
    with led._lock:
        last = led.steps[-1] if led.steps else None
    if last is not None:
        m.goodput.set(last.goodput)
        m.budget_util.set(last.budget_util)
        for cls, d in last.queue_depths.items():
            m.queue_depth.set(float(d), qos_class=cls)
    for cls, chunk in led.prefill_chunks.items():
        m.prefill_chunk.set(float(chunk), qos_class=cls)
    return m


# ---------------------------------------------------------------------------
# Step records
# ---------------------------------------------------------------------------

@dataclass
class HolStall:
    """One step's head-of-line interference: the culprit prefill and the
    decode-ready streams whose token delivery its chunk delayed.

    ``stall_share`` scales the per-victim stall below the full step wall:
    under the unified mixed step the chunk is not a separate launch, so
    the engine passes the chunk's cost-model marginal share of the step
    (mixed minus pure-decode over mixed). None = legacy two-launch
    attribution (the whole wall)."""

    culprit: str                    # culprit request id (largest chunk)
    culprit_tokens: int             # prefill tokens the step carried
    victims: list = field(default_factory=list)  # (trace_ctx, rid, qos_class)
    stall_share: float | None = None  # chunk's marginal fraction of the wall


@dataclass
class SchedStepRecord:
    """One dispatched engine step as the scheduler saw it."""

    ts: float                       # record timestamp (epoch, at finalize)
    wall_s: float                   # dispatch-to-materialize wall
    kinds: tuple                    # batch kinds dispatched, in order
    prefill_rows: int = 0
    decode_rows: int = 0
    decode_window: int = 1
    live_tokens: int = 0            # tokens the plan actually needed
    sched_tokens: int = 0           # tokens the padded buckets computed
    live_flops: float = 0.0
    sched_flops: float = 0.0
    live_bytes: float = 0.0
    sched_bytes: float = 0.0
    goodput: float = 1.0            # live/sched FLOPs (token ratio fallback)
    budget_util: float = 0.0        # planned tokens / max_tokens_per_step
    queue_depths: dict = field(default_factory=dict)   # qos_class -> waiting
    blocked: dict = field(default_factory=dict)        # cause -> attempts
    preempt: dict = field(default_factory=dict)        # cause -> tokens
    hol_culprit: str = ""
    hol_victims: int = 0
    hol_stall_s: float = 0.0        # per-victim stall (wall x stall_share;
                                    # == full wall on the legacy path)
    interference_row_s: float = 0.0  # victims x stall

    def to_dict(self) -> dict:
        d = {
            "ts": self.ts,
            "wall_s": round(self.wall_s, 6),
            "kinds": list(self.kinds),
            "prefill_rows": self.prefill_rows,
            "decode_rows": self.decode_rows,
            "decode_window": self.decode_window,
            "live_tokens": self.live_tokens,
            "sched_tokens": self.sched_tokens,
            "goodput": round(self.goodput, 4),
            "budget_util": round(self.budget_util, 4),
        }
        if self.queue_depths:
            d["queue_depths"] = dict(self.queue_depths)
        if self.blocked:
            d["blocked"] = dict(self.blocked)
        if self.preempt:
            d["preempt_recompute_tokens"] = dict(self.preempt)
        if self.hol_victims:
            d["hol"] = {
                "culprit": self.hol_culprit,
                "victims": self.hol_victims,
                "stall_s": round(self.hol_stall_s, 6),
                "row_seconds": round(self.interference_row_s, 6),
            }
        return d


# ---------------------------------------------------------------------------
# The ledger
# ---------------------------------------------------------------------------

class SchedLedger:
    """Process-global per-step scheduling record.

    Thread-safe: the engine-core thread records steps/blocks/preempts
    while the asyncio side reads snapshots for stats/debug endpoints. The
    step ring is bounded (``cap``); totals stay exact past the cap."""

    _CULPRIT_CAP = 512  # trim the per-culprit stall table past this

    def __init__(self, cap: int = 2048):
        self._lock = threading.Lock()
        self.cap = cap
        self.enabled = sched_enabled()
        self.steps: deque[SchedStepRecord] = deque(maxlen=cap)
        self.steps_total = 0
        self.live_tokens_total = 0
        self.sched_tokens_total = 0
        self.padding_flops_total = 0.0
        self.padding_bytes_total = 0.0
        self.hol_stall_seconds_total = 0.0
        self.hol_victims_total = 0
        self.interference_row_seconds_total = 0.0
        self.blocked_totals: dict[str, int] = {}
        self.preempt_totals: dict[str, int] = {}
        # effective per-QoS prefill chunk sizes (engine publishes at init)
        self.prefill_chunks: dict[str, int] = {}
        # per-culprit {rid: (stall_seconds, victim_count)}
        self._culprits: dict[str, tuple[float, int]] = {}
        # accumulated between steps, flushed into the next record
        self._blocked_step: dict[str, int] = {}
        self._preempt_step: dict[str, int] = {}

    # -- configuration --------------------------------------------------
    def configure(self, enabled: bool | None = None) -> None:
        """Engine-startup hook: re-read the env gate (or force a value)."""
        with self._lock:
            self.enabled = sched_enabled() if enabled is None else enabled

    def reset(self) -> None:
        """Test hook: drop all records/totals (metrics counters are
        monotonic and keep their values)."""
        with self._lock:
            self.steps.clear()
            self.steps_total = 0
            self.live_tokens_total = 0
            self.sched_tokens_total = 0
            self.padding_flops_total = 0.0
            self.padding_bytes_total = 0.0
            self.hol_stall_seconds_total = 0.0
            self.hol_victims_total = 0
            self.interference_row_seconds_total = 0.0
            self.blocked_totals.clear()
            self.preempt_totals.clear()
            self._culprits.clear()
            self._blocked_step.clear()
            self._preempt_step.clear()
            self.prefill_chunks = {}

    def set_prefill_chunks(self, chunk_by_qos: dict) -> None:
        """Publish the effective per-QoS prefill chunk sizes (resolved at
        engine construction — SLO-driven in auto mode, uniform otherwise)
        to the dynamo_sched_prefill_chunk_tokens gauge."""
        if not self.enabled:
            return
        with self._lock:
            self.prefill_chunks = dict(chunk_by_qos)
        m = get_sched_metrics()
        for qos, chunk in chunk_by_qos.items():
            m.prefill_chunk.set(float(chunk), qos_class=qos)

    # -- recording ------------------------------------------------------
    def record_block(self, cause: str) -> None:
        """One blocked admission attempt (engine/scheduler.py)."""
        if not self.enabled:
            return
        with self._lock:
            self._blocked_step[cause] = self._blocked_step.get(cause, 0) + 1
            self.blocked_totals[cause] = self.blocked_totals.get(cause, 0) + 1
        get_sched_metrics().admission_blocked.inc(cause=cause)

    def record_preempt(self, tokens: int, cause: str = "blocks") -> None:
        """One preemption: ``tokens`` of KV discarded and due for
        recompute-prefill (read from seq.num_computed BEFORE the reset)."""
        if not self.enabled:
            return
        tokens = max(int(tokens), 0)
        with self._lock:
            self._preempt_step[cause] = (
                self._preempt_step.get(cause, 0) + tokens)
            self.preempt_totals[cause] = (
                self.preempt_totals.get(cause, 0) + tokens)
        if tokens:
            get_sched_metrics().preempt_recompute.inc(tokens, cause=cause)

    def record_step(
        self, *,
        wall_s: float,
        kinds: tuple | list,
        prefill_rows: int = 0,
        decode_rows: int = 0,
        decode_window: int = 1,
        live_tokens: int = 0,
        sched_tokens: int = 0,
        live_flops: float = 0.0,
        sched_flops: float = 0.0,
        live_bytes: float = 0.0,
        sched_bytes: float = 0.0,
        budget_util: float = 0.0,
        queue_depths: dict | None = None,
        hol: HolStall | None = None,
        ts: float | None = None,
    ) -> SchedStepRecord | None:
        """File one step record; returns it (None when disabled).

        HOL victims with a traced request additionally accrue a
        retroactive ``engine.hol_stall`` span in their own trace (start =
        end - wall, like the compile ledger's retro spans) carrying the
        culprit request id; untraced victims still count in the metrics."""
        if not self.enabled:
            return None
        end = ts if ts is not None else time.time()
        if sched_flops > 0:
            goodput = min(live_flops / sched_flops, 1.0)
        elif sched_tokens > 0:
            goodput = min(live_tokens / sched_tokens, 1.0)
        else:
            goodput = 1.0
        rec = SchedStepRecord(
            ts=end, wall_s=wall_s, kinds=tuple(kinds),
            prefill_rows=prefill_rows, decode_rows=decode_rows,
            decode_window=decode_window,
            live_tokens=live_tokens, sched_tokens=sched_tokens,
            live_flops=live_flops, sched_flops=sched_flops,
            live_bytes=live_bytes, sched_bytes=sched_bytes,
            goodput=goodput, budget_util=budget_util,
            queue_depths=dict(queue_depths or {}))
        m = get_sched_metrics()
        pad_f = max(sched_flops - live_flops, 0.0)
        pad_b = max(sched_bytes - live_bytes, 0.0)
        if hol is not None and hol.victims:
            # Every decode-ready stream in the step waited for its token
            # (outputs materialize at finalize). Legacy two-launch steps
            # charge the full step wall (the prefill program serialized
            # after decode); unified mixed steps charge only the chunk's
            # marginal share of the single launch.
            stall = (wall_s * hol.stall_share
                     if hol.stall_share is not None else wall_s)
            rec.hol_culprit = hol.culprit
            rec.hol_victims = len(hol.victims)
            rec.hol_stall_s = stall
            rec.interference_row_s = stall * len(hol.victims)
            tr = None
            for v_ctx, v_rid, v_cls in hol.victims:
                m.hol_stall.observe(stall, qos_class=v_cls)
                if v_ctx is None:
                    continue  # untraced stream: metrics only, no span
                if tr is None:
                    from dynamo_tpu.obs.tracer import get_tracer

                    tr = get_tracer()
                span = tr.start_span(
                    "engine.hol_stall", ctx=v_ctx, start=end - stall,
                    request_id=v_rid, culprit=hol.culprit,
                    culprit_tokens=hol.culprit_tokens, qos_class=v_cls)
                tr.end_span(span, end=end, seconds=round(stall, 6))
            m.interference.inc(rec.interference_row_s)
        with self._lock:
            rec.blocked, self._blocked_step = self._blocked_step, {}
            rec.preempt, self._preempt_step = self._preempt_step, {}
            self.steps.append(rec)
            self.steps_total += 1
            self.live_tokens_total += live_tokens
            self.sched_tokens_total += sched_tokens
            self.padding_flops_total += pad_f
            self.padding_bytes_total += pad_b
            if rec.hol_victims:
                self.hol_stall_seconds_total += rec.interference_row_s
                self.hol_victims_total += rec.hol_victims
                self.interference_row_seconds_total += rec.interference_row_s
                s, n = self._culprits.get(rec.hol_culprit, (0.0, 0))
                self._culprits[rec.hol_culprit] = (
                    s + rec.interference_row_s, n + rec.hol_victims)
                if len(self._culprits) > self._CULPRIT_CAP:
                    keep = sorted(self._culprits.items(),
                                  key=lambda kv: kv[1][0],
                                  reverse=True)[: self._CULPRIT_CAP // 2]
                    self._culprits = dict(keep)
        for k in rec.kinds:
            m.steps.inc(kind=k)
        m.goodput.set(goodput)
        m.budget_util.set(budget_util)
        if pad_f:
            m.padding_flops.inc(pad_f)
        if pad_b:
            m.padding_bytes.inc(pad_b)
        for cls, d in rec.queue_depths.items():
            m.queue_depth.set(float(d), qos_class=cls)
        return rec

    # -- accounting -----------------------------------------------------
    def top_culprits(self, top: int = 5) -> list[dict]:
        """Worst HOL offenders: [{request_id, stall_seconds, victims}]."""
        with self._lock:
            items = sorted(self._culprits.items(),
                           key=lambda kv: kv[1][0], reverse=True)[:top]
        return [{"request_id": rid, "stall_seconds": round(s, 6),
                 "victims": n} for rid, (s, n) in items]

    def snapshot(self, steps: bool = False) -> dict:
        """Compact dict for stats publishing / bench artifacts."""
        with self._lock:
            recent = list(self.steps)
            out = {
                "enabled": self.enabled,
                "steps_total": self.steps_total,
                "goodput_fraction": (recent[-1].goodput if recent else 1.0),
                "budget_utilization": (recent[-1].budget_util
                                       if recent else 0.0),
                "live_tokens_total": self.live_tokens_total,
                "sched_tokens_total": self.sched_tokens_total,
                "padding_flops_total": self.padding_flops_total,
                "padding_hbm_bytes_total": self.padding_bytes_total,
                "admission_blocked": dict(self.blocked_totals),
                "preempt_recompute_tokens": dict(self.preempt_totals),
                "hol_stall_seconds_total": round(
                    self.hol_stall_seconds_total, 6),
                "hol_victims_total": self.hol_victims_total,
                "interference_row_seconds_total": round(
                    self.interference_row_seconds_total, 6),
            }
            if self.prefill_chunks:
                out["prefill_chunk_tokens"] = dict(self.prefill_chunks)
        if recent:
            out["goodput_mean_recent"] = round(
                sum(r.goodput for r in recent) / len(recent), 4)
        out["top_culprits"] = self.top_culprits()
        if steps:
            out["steps"] = [r.to_dict() for r in recent[-64:]]
        return out

    def debug_info(self, recorder=None, limit: int = 64) -> dict:
        """The /debug/sched document: recent-step ring, goodput trend, top
        culprits — plus span-derived culprit aggregation when a
        FlightRecorder is given (the frontend's recorder holds hol spans
        INGESTED from workers, so a frontend that never ran an engine
        still attributes fleet-wide stalls)."""
        with self._lock:
            recent = list(self.steps)[-limit:]
        out = {
            "enabled": self.enabled,
            "env": SCHED_ENV,
            "totals": self.snapshot(),
            "recent_steps": [r.to_dict() for r in recent],
            "goodput_trend": [round(r.goodput, 4) for r in recent],
            "top_culprits": self.top_culprits(),
        }
        if recorder is not None:
            out["trace_culprits"] = hol_span_culprits(recorder)
        return out


def hol_span_culprits(recorder, top: int = 5) -> list[dict]:
    """Aggregate ``engine.hol_stall`` spans in a FlightRecorder by culprit
    — the cross-process view (workers ship victim spans on the wire)."""
    agg: dict[str, tuple[float, int]] = {}
    for span in recorder.iter_spans():
        if span.name != "engine.hol_stall":
            continue
        culprit = str(span.attrs.get("culprit", ""))
        s, n = agg.get(culprit, (0.0, 0))
        agg[culprit] = (s + span.duration, n + 1)
    items = sorted(agg.items(), key=lambda kv: kv[1][0], reverse=True)[:top]
    return [{"request_id": rid, "stall_seconds": round(s, 6),
             "victims": n} for rid, (s, n) in items]


_ledger: SchedLedger | None = None
_ledger_lock = threading.Lock()


def get_sched_ledger() -> SchedLedger:
    global _ledger
    with _ledger_lock:
        if _ledger is None:
            _ledger = SchedLedger()
        return _ledger


# ---------------------------------------------------------------------------
# Live-vs-scheduled step geometry — the SAME math as engine dispatch.
# ---------------------------------------------------------------------------

def step_geometry(model_cfg, engine_cfg, batches, *,
                  mixed_dec_rows: int = 0) -> dict:
    """Live and scheduled (bucket-padded) work for one finalized step.

    ``batches`` is PendingStep.batches: (kind, rows, sample_rows, toks,
    lps) with rows of (seq, start, length). The live walk mirrors
    StepPerfProfiler.measure exactly; the padded walk prices the bucket
    geometry dispatch() actually compiled (``_bucket``/``_pow2_bucket``
    over rows/t_max/nblk_need — without dispatch's len(block_ids) clamp,
    which can have shrunk by finalize time for finished seqs). Both sides
    run through obs/costmodel.model_step_cost, so goodput is a pure FLOPs
    ratio hand-computable at any known bucket geometry.

    Unified "mixed" batches (decode rows + prefill chunks in one launch)
    price as: live = per-row exact tokens/contexts, scheduled = the mixed
    program's b (DECODE row ladder) × t (prefill chunk ladder) envelope.
    ``mixed_dec_rows`` is the plan-time decode-row count of the step's
    mixed batch (leading rows), splitting prefill_rows/decode_rows.

    Returns {kinds, prefill_rows, decode_rows, live_tokens, sched_tokens,
    live_flops, sched_flops, live_bytes, sched_bytes}.
    """
    from dynamo_tpu.obs import costmodel as cm

    ec = engine_cfg
    bs = ec.block_size
    kv = ec.kv_dtype or "bfloat16"
    quant = ec.quantization or "none"
    max_nblk = -(-ec.max_model_len // bs)
    live = {"tokens": 0, "logit_rows": 0, "attn_q_ctx": 0.0, "kv_blocks": 0.0}
    sched = {"tokens": 0, "logit_rows": 0, "attn_q_ctx": 0.0, "kv_blocks": 0.0}
    kinds: list[str] = []
    pf_rows = dec_rows = 0
    for kind, rows, _sample_rows, toks, _lps in batches:
        if not rows:
            continue
        n = len(rows)
        window = toks.shape[1] if getattr(toks, "ndim", 1) == 2 else 1
        t_max = max(length for _, _, length in rows)
        # padded program geometry (engine/engine.py dispatch())
        if kind == "verify":
            b = _bucket(n, ec.decode_bucket)
            t = min(_pow2_bucket(t_max, 2, ec.spec_k + 1), ec.spec_k + 1)
            window = 1
        elif t_max == 1:
            # Includes degenerate "mixed" batches (every live row one token):
            # dispatch reclassifies those to the decode program.
            b, t = _bucket(n, ec.decode_bucket), 1
        elif kind == "mixed":
            # Unified step: decode-row ladder for b, prefill chunk ladder
            # for t — the envelope dispatch() compiles for mixed batches.
            b, t = _bucket(n, ec.decode_bucket), _pow2_bucket(
                t_max, 16, ec.prefill_chunk)
            window = 1
        else:
            b, t = _bucket(n, (1, 2, 4, 8)), _pow2_bucket(
                t_max, 16, ec.prefill_chunk)
            window = 1
        nblk_need = max(
            -(-(start + length + window - 1) // bs)
            for _s, start, length in rows)
        nblk = min(_pow2_bucket(max(nblk_need, 1), 4, max_nblk), max_nblk)
        if kind == "prefill":
            kinds.append("prefill")
            pf_rows += n
        elif kind == "mixed":
            # Leading rows of a mixed batch are decode/guided by
            # construction; the split is captured at plan time because
            # prefill_target() moves as finalize appends tokens.
            kinds.append("mixed" if t_max > 1 else "decode")
            d = min(mixed_dec_rows, n)
            dec_rows += d
            pf_rows += n - d
        elif kind == "verify":
            kinds.append("verify")
            dec_rows += n
        elif window > 1:
            kinds.append("window")
            dec_rows += n
        elif rows[0][0] is not None and getattr(
                rows[0][0], "guided", None) is not None:
            kinds.append("guided")
            dec_rows += n
        else:
            kinds.append("decode")
            dec_rows += n
        if kind == "decode":
            # live: each row decodes `window` positions
            for _seq, start, length in rows:
                live["tokens"] += window
                live["logit_rows"] += window
                for j in range(window):
                    nb = -(-(start + length + j) // bs)
                    live["attn_q_ctx"] += nb * bs
                    live["kv_blocks"] += nb
            # scheduled: b padded rows x window positions at the bucketed
            # block-table width
            sched["tokens"] += b * window
            sched["logit_rows"] += b * window
            sched["attn_q_ctx"] += b * window * nblk * bs
            sched["kv_blocks"] += b * window * nblk
        else:
            for _seq, start, length in rows:
                live["tokens"] += length
                live["logit_rows"] += 1
                nb = -(-(start + length) // bs)
                live["attn_q_ctx"] += length * nb * bs
                live["kv_blocks"] += nb
            sched["tokens"] += b * t
            sched["logit_rows"] += b
            sched["attn_q_ctx"] += b * t * nblk * bs
            sched["kv_blocks"] += b * nblk

    def _cost(agg: dict):
        phases = cm.model_step_cost(
            model_cfg, tokens=agg["tokens"], logit_rows=agg["logit_rows"],
            attn_q_ctx=agg["attn_q_ctx"], kv_blocks=agg["kv_blocks"],
            block_size=bs, kv_dtype=kv, quantization=quant)
        return cm.total_cost(phases)

    lc = _cost(live) if live["tokens"] else None
    sc = _cost(sched) if sched["tokens"] else None
    return {
        "kinds": tuple(kinds),
        "prefill_rows": pf_rows,
        "decode_rows": dec_rows,
        "live_tokens": live["tokens"],
        "sched_tokens": sched["tokens"],
        "live_flops": lc.flops if lc else 0.0,
        "sched_flops": sc.flops if sc else 0.0,
        "live_bytes": lc.hbm_bytes if lc else 0.0,
        "sched_bytes": sc.hbm_bytes if sc else 0.0,
    }
