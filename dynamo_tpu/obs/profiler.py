"""Step performance profiler: hardware counters for every engine step.

Three pieces:

* ``phase(name)`` — the lightweight hook models/llama.py and
  engine/engine.py wrap their phases in (scatter, gather, attention,
  logits, sampling). Outside a capture it is exactly ``jax.named_scope``:
  zero runtime ops (the scope only annotates the traced HLO, so XLA
  profiles group by phase), and since the model runs under ``jax.jit`` the
  context manager itself executes only at trace time. Inside
  ``capture_phases()`` (an eager/``jax.disable_jit`` profiling run) it
  additionally accumulates wall time per phase.

* ``StepPerfProfiler`` — folds the analytic cost model (obs/costmodel.py)
  over each dispatched step's batches and, with the measured step wall,
  derives tokens/s, MFU, HBM-bandwidth utilization, and the achieved
  roofline fraction. EngineCore calls ``measure()`` from its always-on
  step recording; the returned fields land in the FlightRecorder step ring
  (obs/recorder.py StepRecord) so /debug/traces carries hardware counters.
  Disabled (``DYN_PERF_PROFILE=0``) it returns ``{}`` before touching the
  cost model — zero extra ops, zero extra host math.

* ``PerfMetrics`` — the ``dynamo_engine_perf_*`` Prometheus family
  (lint-checked by tools/lint_metrics.py PERF_METRICS), re-homeable into a
  worker's runtime registry via ``install_perf_metrics`` exactly like the
  disagg KV-transfer family.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any

from dynamo_tpu.obs import costmodel as cm
from dynamo_tpu.utils.metrics import MetricsRegistry

PERF_ENV = "DYN_PERF_PROFILE"

# Engine steps span sub-ms fused-window decode on a chip to multi-second
# CPU-fallback prefill compiles. (MetricsRegistry appends the +Inf bucket.)
_STEP_SECONDS_BUCKETS = (
    0.001, 0.005, 0.02, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 10.0,
)


def perf_enabled(default: bool = True) -> bool:
    """The module-level gate: DYN_PERF_PROFILE=0 disables all per-step
    cost-model math (the phase hooks are free either way)."""
    val = os.environ.get(PERF_ENV, "")
    if val == "":
        return default
    return val not in ("0", "false", "no", "off")


# ---------------------------------------------------------------------------
# Phase hooks
# ---------------------------------------------------------------------------

_capture = threading.local()


class _TimedPhase:
    """Capture-mode phase: named_scope + wall accumulation. Wall times are
    trustworthy in eager/disable_jit profiling runs (each phase's dispatch
    is ~synchronous on CPU); under jit they fire at trace time and the
    capture dict records trace cost, which is why captures are explicit."""

    __slots__ = ("name", "_scope", "_t0")

    def __init__(self, name: str):
        self.name = name

    def __enter__(self):
        import jax
        self._scope = jax.named_scope(self.name)
        self._scope.__enter__()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        dt = time.perf_counter() - self._t0
        self._scope.__exit__(*exc)
        sink = getattr(_capture, "sink", None)
        if sink is not None:
            sink[self.name] = sink.get(self.name, 0.0) + dt
        return False


def phase(name: str):
    """Wrap one engine phase. No capture active → plain ``jax.named_scope``
    (annotation only, zero ops in the compiled program)."""
    if getattr(_capture, "sink", None) is None:
        import jax
        return jax.named_scope(name)
    return _TimedPhase(name)


class capture_phases:
    """Context manager enabling wall-time capture for ``phase()`` hooks on
    this thread; yields the {phase: seconds} dict. Use with
    ``jax.disable_jit()`` (or eager calls) for real per-phase walls."""

    def __enter__(self) -> dict[str, float]:
        self._prev = getattr(_capture, "sink", None)
        sink: dict[str, float] = {}
        _capture.sink = sink
        return sink

    def __exit__(self, *exc):
        _capture.sink = self._prev
        return False


# ---------------------------------------------------------------------------
# Prometheus family
# ---------------------------------------------------------------------------

class PerfMetrics:
    """The dynamo_engine_perf_* family (names cross-checked by
    tools/lint_metrics.py PERF_METRICS)."""

    def __init__(self, registry: MetricsRegistry | None = None):
        self.bind(registry or MetricsRegistry())

    def bind(self, registry: MetricsRegistry) -> None:
        self.registry = registry
        self.tok_s = registry.gauge(
            "engine_perf_tokens_per_second",
            "Generated tokens/s over recent engine steps (EWMA), by kind "
            "(decode|prefill) and kv_dtype (bfloat16|int8|int4) — label set "
            "declared in tools/lint_metrics.py PERF_METRIC_LABELS")
        self.mfu = registry.gauge(
            "engine_perf_mfu",
            "Model-FLOPs utilization over recent engine steps (EWMA): "
            "analytic matmul FLOP/s over the chip's peak")
        self.bw_util = registry.gauge(
            "engine_perf_hbm_bw_util",
            "HBM bandwidth utilization over recent engine steps (EWMA): "
            "analytic bytes/s over the chip's peak bandwidth")
        self.roofline = registry.gauge(
            "engine_perf_roofline_fraction",
            "Achieved fraction of the analytic roofline floor for recent "
            "engine steps (1.0 = running at the hardware bound)")
        self.flops_total = registry.counter(
            "engine_perf_model_flops_total",
            "Cumulative analytic model FLOPs dispatched by the engine")
        self.bytes_total = registry.counter(
            "engine_perf_hbm_bytes_total",
            "Cumulative analytic HBM bytes moved by engine steps")
        self.step_seconds = registry.histogram(
            "engine_perf_step_seconds",
            "Engine step wall time (dispatch to materialize)",
            buckets=_STEP_SECONDS_BUCKETS)


_metrics: PerfMetrics | None = None


def get_perf_metrics() -> PerfMetrics:
    global _metrics
    if _metrics is None:
        _metrics = PerfMetrics()
    return _metrics


def install_perf_metrics(registry: MetricsRegistry) -> PerfMetrics:
    """Re-home the singleton's metrics into ``registry`` (the worker's
    runtime registry) so the family is exposed on /metrics."""
    m = get_perf_metrics()
    m.bind(registry)
    return m


# ---------------------------------------------------------------------------
# Per-step measurement
# ---------------------------------------------------------------------------

class StepPerfProfiler:
    """Analytic per-step hardware counters for one EngineCore.

    ``measure(batches, wall_s)`` charges each dispatched batch via the cost
    model and returns the perf fields for the step ring; it also feeds the
    dynamo_engine_perf_* family. O(rows) host work per step; disabled it
    returns ``{}`` immediately.
    """

    _EWMA_ALPHA = 0.2

    def __init__(self, model_cfg, engine_cfg, device_kind: str | None = None,
                 enabled: bool | None = None):
        self.cfg = model_cfg
        self.block_size = engine_cfg.block_size
        self.kv_dtype = engine_cfg.kv_dtype or "bfloat16"
        self.quantization = engine_cfg.quantization or "none"
        self.enabled = perf_enabled() if enabled is None else enabled
        if device_kind is None:
            device_kind = _detect_device_kind()
        self.hw = cm.hw_spec_for(device_kind)
        self._ewma: dict[str, float] = {}

    def _smooth(self, key: str, value: float) -> float:
        prev = self._ewma.get(key)
        cur = value if prev is None else (
            prev + self._EWMA_ALPHA * (value - prev))
        self._ewma[key] = cur
        return cur

    def measure(self, batches: list, wall_s: float) -> dict[str, Any]:
        """Perf fields for one finalized step. ``batches`` is
        PendingStep.batches: (kind, rows, sample_rows, toks, lps) with rows
        of (seq, start, length)."""
        if not self.enabled or not batches:
            return {}
        bs = self.block_size
        tokens = logit_rows = 0
        attn_q_ctx = kv_blocks = 0.0
        dec_tokens = pf_tokens = 0
        for kind, rows, sample_rows, toks, _lps in batches:
            window = toks.shape[1] if getattr(toks, "ndim", 1) == 2 else 1
            for (seq, start, length) in rows:
                if kind == "decode" or (length == 1 and window > 1):
                    w = window
                    dec_tokens += w
                    tokens += w
                    logit_rows += w
                    for j in range(w):
                        nblk = -(-(start + length + j) // bs)
                        attn_q_ctx += nblk * bs
                        kv_blocks += nblk
                else:
                    tokens += length
                    logit_rows += 1
                    nblk = -(-(start + length) // bs)
                    attn_q_ctx += length * nblk * bs
                    kv_blocks += nblk
                    # Unified "mixed" batches carry both phases: multi-token
                    # rows are prefill chunks, single-token rows decode.
                    # (A 1-token prefill tail inside a mixed batch lands on
                    # the decode counter — one token of split drift; the
                    # aggregate volumes above stay exact.)
                    if kind == "prefill" or (kind == "mixed" and length > 1):
                        pf_tokens += length
                    else:
                        dec_tokens += length
        phases = cm.model_step_cost(
            self.cfg, tokens=tokens, logit_rows=logit_rows,
            attn_q_ctx=attn_q_ctx, kv_blocks=kv_blocks, block_size=bs,
            kv_dtype=self.kv_dtype, quantization=self.quantization)
        cost = cm.total_cost(phases)
        gen = dec_tokens if dec_tokens else tokens
        tok_s = gen / wall_s if wall_s > 0 else 0.0
        fields = {
            "decode_tokens": dec_tokens,
            "prefill_tokens": pf_tokens,
            "flops": cost.flops,
            "hbm_bytes": cost.hbm_bytes,
            "tok_s": tok_s,
            "mfu": cm.mfu(cost.flops, wall_s, self.hw),
            "bw_util": cm.bw_util(cost.hbm_bytes, wall_s, self.hw),
            "roofline_frac": cm.roofline_fraction(cost, wall_s, self.hw),
        }
        m = get_perf_metrics()
        kind = "decode" if dec_tokens >= pf_tokens else "prefill"
        m.tok_s.set(self._smooth(f"tok_s:{kind}", tok_s), kind=kind,
                    kv_dtype=self.kv_dtype)
        m.mfu.set(self._smooth("mfu", fields["mfu"]))
        m.bw_util.set(self._smooth("bw_util", fields["bw_util"]))
        m.roofline.set(self._smooth("roofline", fields["roofline_frac"]))
        m.flops_total.inc(cost.flops)
        m.bytes_total.inc(cost.hbm_bytes)
        m.step_seconds.observe(wall_s)
        return fields


def _detect_device_kind() -> str:
    try:
        import jax
        return getattr(jax.devices()[0], "device_kind", "cpu")
    except Exception:  # pragma: no cover - no runtime available
        return "cpu"
