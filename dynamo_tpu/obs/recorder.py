"""Flight recorder: bounded, thread-safe ring of completed request
timelines plus a fixed ring of engine step records.

Two export formats, both dependency-free:
  * JSONL — one span per line, consumed by ``tools/trace_report.py``.
  * Chrome trace-event JSON — ``{"traceEvents": [...]}`` with complete
    ("ph":"X") events in microseconds, loadable in Perfetto / chrome://tracing.
"""

from __future__ import annotations

import json
import threading
from collections import OrderedDict, deque
from dataclasses import dataclass
from typing import TYPE_CHECKING, Iterable

if TYPE_CHECKING:  # pragma: no cover - import cycle guard
    from dynamo_tpu.obs.tracer import Span


@dataclass
class StepRecord:
    """One engine step: wall time plus batch composition. Fixed-size
    fields only — recording is a deque append, always-on cheap.

    The perf fields (flops…roofline_frac) are the step profiler's analytic
    hardware counters (obs/profiler.py); they stay 0 when the profiler is
    disabled so the ring schema is stable either way."""

    ts: float
    wall_s: float
    num_prefill: int
    num_decode: int
    num_waiting: int
    num_preempted: int
    occupancy: float
    decode_tokens: int = 0
    prefill_tokens: int = 0
    flops: float = 0.0
    hbm_bytes: float = 0.0
    tok_s: float = 0.0
    mfu: float = 0.0
    bw_util: float = 0.0
    roofline_frac: float = 0.0

    def to_dict(self) -> dict:
        return {
            "ts": self.ts, "wall_s": self.wall_s,
            "num_prefill": self.num_prefill, "num_decode": self.num_decode,
            "num_waiting": self.num_waiting,
            "num_preempted": self.num_preempted,
            "occupancy": self.occupancy,
            "decode_tokens": self.decode_tokens,
            "prefill_tokens": self.prefill_tokens,
            "flops": self.flops, "hbm_bytes": self.hbm_bytes,
            "tok_s": self.tok_s, "mfu": self.mfu,
            "bw_util": self.bw_util, "roofline_frac": self.roofline_frac,
        }


class StepProfiler:
    """Ring of the last N engine step records (see StepRecord)."""

    def __init__(self, capacity: int = 2048):
        self._ring: deque[StepRecord] = deque(maxlen=capacity)
        self._lock = threading.Lock()

    def record(self, ts: float, wall_s: float, *, num_prefill: int = 0,
               num_decode: int = 0, num_waiting: int = 0,
               num_preempted: int = 0, occupancy: float = 0.0,
               decode_tokens: int = 0, prefill_tokens: int = 0,
               flops: float = 0.0, hbm_bytes: float = 0.0,
               tok_s: float = 0.0, mfu: float = 0.0, bw_util: float = 0.0,
               roofline_frac: float = 0.0) -> None:
        rec = StepRecord(ts, wall_s, num_prefill, num_decode, num_waiting,
                         num_preempted, occupancy, decode_tokens,
                         prefill_tokens, flops, hbm_bytes, tok_s, mfu,
                         bw_util, roofline_frac)
        with self._lock:
            self._ring.append(rec)

    def snapshot(self) -> list[StepRecord]:
        with self._lock:
            return list(self._ring)


class FlightRecorder:
    """Ring of the last ``capacity`` request timelines, keyed by
    trace_id. A timeline is the list of closed spans sharing a trace_id;
    eviction is LRU on trace insertion order (a trace that keeps
    receiving spans stays fresh)."""

    def __init__(self, capacity: int = 256, spans_per_trace: int = 512,
                 step_capacity: int = 2048):
        self.capacity = max(capacity, 1)
        self.spans_per_trace = spans_per_trace
        self._traces: "OrderedDict[str, list[Span]]" = OrderedDict()
        self._span_ids: dict[str, set[str]] = {}
        self._lock = threading.Lock()
        self.steps = StepProfiler(capacity=step_capacity)

    def record(self, span: "Span") -> bool:
        """File a closed span. Returns False on duplicate span_id (wire
        replays) or per-trace overflow."""
        with self._lock:
            spans = self._traces.get(span.trace_id)
            if spans is None:
                spans = []
                self._traces[span.trace_id] = spans
                self._span_ids[span.trace_id] = set()
                while len(self._traces) > self.capacity:
                    old, _ = self._traces.popitem(last=False)
                    del self._span_ids[old]
            else:
                self._traces.move_to_end(span.trace_id)
            ids = self._span_ids[span.trace_id]
            if span.span_id in ids or len(spans) >= self.spans_per_trace:
                return False
            ids.add(span.span_id)
            spans.append(span)
            return True

    def trace_ids(self) -> list[str]:
        with self._lock:
            return list(self._traces)

    def spans_for(self, trace_id: str) -> "list[Span]":
        with self._lock:
            return list(self._traces.get(trace_id, ()))

    def _snapshot(self, trace_id: str | None) -> "list[Span]":
        with self._lock:
            if trace_id is not None:
                return list(self._traces.get(trace_id, ()))
            return [s for spans in self._traces.values() for s in spans]

    # -- exporters ------------------------------------------------------
    def dump_jsonl(self, trace_id: str | None = None) -> str:
        spans = self._snapshot(trace_id)
        spans.sort(key=lambda s: (s.trace_id, s.start))
        return "".join(
            json.dumps(s.to_dict(), separators=(",", ":")) + "\n"
            for s in spans)

    def dump_chrome(self, trace_id: str | None = None,
                    include_steps: bool = True) -> dict:
        """Chrome trace-event JSON. pid = component (process row in the
        Perfetto UI), tid = short trace id (one track per request)."""
        events: list[dict] = []
        pids: dict[str, int] = {}
        tids: dict[str, int] = {}

        def _pid(comp: str) -> int:
            if comp not in pids:
                pids[comp] = len(pids) + 1
                events.append({
                    "ph": "M", "name": "process_name", "pid": pids[comp],
                    "tid": 0, "args": {"name": comp or "proc"}})
            return pids[comp]

        for s in self._snapshot(trace_id):
            key = (s.component, s.trace_id)
            pid = _pid(s.component)
            if key not in tids:
                tids[key] = len(tids) + 1
                events.append({
                    "ph": "M", "name": "thread_name", "pid": pid,
                    "tid": tids[key],
                    "args": {"name": f"trace {s.trace_id[:8]}"}})
            args = {"trace_id": s.trace_id, "span_id": s.span_id,
                    "status": s.status, **s.attrs}
            if s.parent_id:
                args["parent_id"] = s.parent_id
            events.append({
                "ph": "X", "name": s.name, "cat": s.component or "span",
                "pid": pid, "tid": tids[key],
                "ts": s.start * 1e6, "dur": s.duration * 1e6,
                "args": args,
            })
        if include_steps and trace_id is None:
            for rec in self.steps.snapshot():
                events.append({
                    "ph": "C", "name": "engine.batch", "pid": 0, "tid": 0,
                    "ts": rec.ts * 1e6,
                    "args": {"prefill": rec.num_prefill,
                             "decode": rec.num_decode,
                             "waiting": rec.num_waiting,
                             "tok_s": round(rec.tok_s, 1),
                             "mfu": round(rec.mfu, 4),
                             "bw_util": round(rec.bw_util, 4)}})
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def iter_spans(self) -> "Iterable[Span]":
        return self._snapshot(None)
